"""Gradient anomaly detection — failure-detection subsystem (SURVEY §2.9).

Reference counterpart: DL4J's FailureTestingListener checks +
ExecDebuggingListener / "gradient issues" diagnostics — catching NaN/Inf
gradients, explosions and dead layers DURING training rather than after a
wasted run. The score-level guard is ``nn.listeners.NanScoreWatchdog``;
this module adds per-parameter-group gradient statistics.

TPU-native shape: the statistics are computed INSIDE the jitted train step
(a handful of scalar reductions, fused into the backward pass by XLA — no
extra HBM traffic worth noticing), the step gates its own param/opt-state
update on grad finiteness (a poisoned batch is a no-op, not a lost run),
and only the tiny stats pytree comes back to host — fetched one step LATE
by the fit loops so dispatch pipelining survives — where the detector
applies thresholds and an EMA explosion test.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp


def grad_stats(grads) -> Dict[str, Dict[str, jnp.ndarray]]:
    """Jit-able: per top-level param-group {l2, max_abs, nonfinite_count}.

    Grouping is by the first pytree level (layer name in MLN/CG params), the
    granularity DL4J reports gradient issues at (per-layer).
    """
    out = {}
    for group, sub in grads.items():
        leaves = jax.tree_util.tree_leaves(sub)
        if not leaves:
            continue
        sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
        mx = jnp.max(jnp.stack([jnp.max(jnp.abs(l.astype(jnp.float32)))
                                for l in leaves]))
        nonfinite = sum(jnp.sum(~jnp.isfinite(l.astype(jnp.float32)))
                        for l in leaves)
        # element count is trace-time static — free, and it lets the
        # numerics plane derive rms = l2/sqrt(size) host-side (ISSUE 13)
        size = jnp.float32(sum(int(l.size) for l in leaves))
        out[str(group)] = {"l2": jnp.sqrt(sq), "max_abs": mx,
                           "nonfinite": nonfinite, "size": size}
    return out


def gate_on_finite(stats, *new_old_pairs):
    """Jit-able: if any gradient element is non-finite, return the old value
    of every (new, old) pytree pair — the whole step becomes a no-op (params,
    opt state AND layer state such as BN running stats), so a poisoned batch
    can be detected without losing the run."""
    ok = sum(s["nonfinite"] for s in stats.values()) == 0
    return tuple(
        jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), new, old)
        for new, old in new_old_pairs)


def stats_and_gate(grads, params, new_params, opt_state, new_opt_state,
                   states, new_states):
    """Jit-able one-stop wiring for step functions: compute grad stats and
    gate every piece of step output on grad finiteness. Used identically by
    MultiLayerNetwork, ComputationGraph and ParallelWrapper steps."""
    stats = grad_stats(grads)
    new_params, new_opt_state, new_states = gate_on_finite(
        stats, (new_params, params), (new_opt_state, opt_state),
        (new_states, states))
    return stats, new_params, new_opt_state, new_states


def maybe_stats_and_gate(gate, grads, params, new_params, opt_state,
                         new_opt_state, states, new_states):
    """Jit-able: :func:`stats_and_gate` when ``gate`` is set (policies
    that must leave a poisoned step bit-identical), plain
    :func:`grad_stats` with the step outputs passed through when it is
    not (observe-only detectors / sentinel policy "warn"). ``gate`` is
    a trace-time Python bool — the three step builders resolve it from
    the detector's ``gate_updates`` before compiling."""
    if gate:
        return stats_and_gate(grads, params, new_params, opt_state,
                              new_opt_state, states, new_states)
    return grad_stats(grads), new_params, new_opt_state, new_states


class DelayedAnomalyCheck:
    """Host-side: checks each step's stats ONE step late so the fit loop
    never blocks on the step it just dispatched (preserves async pipelining).
    Call push() after each step and flush() when the loop ends."""

    def __init__(self, detector: "GradientAnomalyDetector"):
        self.detector = detector
        self._pending = None

    def push(self, stats, iteration: int):
        if self._pending is not None:
            self.detector.check(jax.device_get(self._pending[0]), self._pending[1])
        self._pending = (stats, iteration)

    def flush(self):
        if self._pending is not None:
            self.detector.check(jax.device_get(self._pending[0]), self._pending[1])
            self._pending = None


@dataclass
class GradientAnomaly:
    kind: str        # "nonfinite" | "explosion" | "vanishing"
    layer: str
    iteration: int
    detail: str

    def __str__(self):
        return (f"[{self.kind}] layer '{self.layer}' at iteration "
                f"{self.iteration}: {self.detail}")


@dataclass
class GradientAnomalyDetector:
    """Host-side thresholds over the in-jit stats.

    - nonfinite: any NaN/Inf gradient element → always an anomaly.
    - explosion: per-layer grad L2 exceeding `explosion_abs`, or exceeding
      `explosion_ratio` × its own EMA (warmup-gated so init noise is ignored).
    - vanishing: per-layer max|g| below `vanishing_abs` for
      `vanishing_patience` consecutive checks (a dead/saturated layer).

    `strict=True` raises FloatingPointError on nonfinite/explosion;
    otherwise anomalies are recorded in `.anomalies` (listener-style).
    """

    explosion_abs: float = 1e4
    explosion_ratio: float = 100.0
    vanishing_abs: float = 1e-10
    vanishing_patience: int = 10
    ema_decay: float = 0.9
    warmup_iters: int = 5
    strict: bool = True
    anomalies: List[GradientAnomaly] = field(default_factory=list)
    _ema: Dict[str, float] = field(default_factory=dict)
    _seen: Dict[str, int] = field(default_factory=dict)
    _dead_streak: Dict[str, int] = field(default_factory=dict)

    def check(self, stats: Dict[str, Dict], iteration: int) -> List[GradientAnomaly]:
        """stats: host-fetched output of grad_stats. Returns new anomalies."""
        new: List[GradientAnomaly] = []
        for layer, s in stats.items():
            l2 = float(s["l2"]); mx = float(s["max_abs"])
            nf = int(s["nonfinite"])
            if nf > 0 or math.isnan(l2) or math.isinf(l2):
                new.append(GradientAnomaly(
                    "nonfinite", layer, iteration,
                    f"{nf} non-finite gradient elements (l2={l2})"))
                continue
            seen = self._seen.get(layer, 0)
            ema = self._ema.get(layer)
            exploded = l2 > self.explosion_abs or (
                ema is not None and seen >= self.warmup_iters
                and ema > 0 and l2 > self.explosion_ratio * ema)
            if exploded:
                new.append(GradientAnomaly(
                    "explosion", layer, iteration,
                    f"grad l2={l2:.3e} (ema={ema if ema is None else f'{ema:.3e}'}, "
                    f"abs threshold={self.explosion_abs:.0e})"))
            self._ema[layer] = l2 if ema is None else (
                self.ema_decay * ema + (1 - self.ema_decay) * l2)
            self._seen[layer] = seen + 1
            if mx < self.vanishing_abs:
                streak = self._dead_streak.get(layer, 0) + 1
                self._dead_streak[layer] = streak
                if streak == self.vanishing_patience:
                    new.append(GradientAnomaly(
                        "vanishing", layer, iteration,
                        f"max|g|={mx:.1e} for {streak} consecutive checks"))
            else:
                self._dead_streak[layer] = 0
        self.anomalies.extend(new)
        if self.strict:
            fatal = [a for a in new if a.kind in ("nonfinite", "explosion")]
            if fatal:
                raise FloatingPointError(
                    "gradient anomaly detected:\n  " + "\n  ".join(map(str, fatal)))
        return new
