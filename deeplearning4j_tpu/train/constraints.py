"""Parameter constraints — applied to weights after each updater step.

Reference parity: ``org.deeplearning4j.nn.conf.constraint.{MaxNormConstraint,
MinMaxNormConstraint, NonNegativeConstraint, UnitNormConstraint}`` and the
``Builder.constrainWeights/constrainBias/constrainAllParameters`` plumbing.

TPU-first: a constraint is a pure ``apply(w) -> w`` clamp that runs inside
the jitted train step right after ``optax.apply_updates`` — no host round
trip, fused into the update program by XLA.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence, Union

import jax.numpy as jnp

# param keys treated as biases / norm-statistics, excluded by constrain-weights
NON_WEIGHT_KEYS = ("b", "bias", "beta", "gamma", "mean", "var", "centers")


def _norm(w, dims):
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=dims, keepdims=True) + 1e-12)


@dataclass
class BaseConstraint:
    """dims: axes reduced when computing the per-unit norm (reference
    BaseConstraint.dimensions; default 0 = fan-in axis of a (nIn,nOut) W)."""

    dims: Union[int, Sequence[int]] = 0

    def apply(self, w):  # pragma: no cover — abstract
        raise NotImplementedError


@dataclass
class MaxNormConstraint(BaseConstraint):
    max_norm: float = 1.0

    def __init__(self, max_norm=1.0, dims=0):
        self.max_norm = float(max_norm)
        self.dims = dims

    def apply(self, w):
        n = _norm(w, self.dims)
        return w * jnp.minimum(n, self.max_norm) / n


@dataclass
class MinMaxNormConstraint(BaseConstraint):
    min_norm: float = 0.0
    max_norm: float = 1.0
    rate: float = 1.0

    def __init__(self, min_norm=0.0, max_norm=1.0, rate=1.0, dims=0):
        self.min_norm = float(min_norm)
        self.max_norm = float(max_norm)
        self.rate = float(rate)
        self.dims = dims

    def apply(self, w):
        n = _norm(w, self.dims)
        clipped = jnp.clip(n, self.min_norm, self.max_norm)
        target = self.rate * clipped + (1.0 - self.rate) * n
        return w * target / n


@dataclass
class NonNegativeConstraint(BaseConstraint):
    def apply(self, w):
        return jnp.maximum(w, 0.0)


@dataclass
class UnitNormConstraint(BaseConstraint):
    def apply(self, w):
        return w / _norm(w, self.dims)


def apply_constraints(layer_params: dict, constraints, *, weights=True,
                      biases=False):
    """Apply each constraint to the matching params of one layer's dict."""
    if not constraints:
        return layer_params
    out = {}
    for k, w in layer_params.items():
        is_bias = k in NON_WEIGHT_KEYS
        if isinstance(w, dict):
            out[k] = apply_constraints(w, constraints, weights=weights, biases=biases)
            continue
        if (is_bias and biases) or (not is_bias and weights):
            for c in constraints:
                w = c.apply(w)
        out[k] = w
    return out
