"""MultiLayerNetwork — the sequential-network API, redesigned for XLA.

Reference parity: ``org.deeplearning4j.nn.multilayer.MultiLayerNetwork``
(init/fit/output/score/evaluate/params/summary, listeners, masking).

TPU-first redesign: instead of the reference's per-layer activate/
backpropGradient interpreter loop with workspaces, the WHOLE training
iteration — forward, loss, backward, updater, parameter update — is one
jitted pure function with params/opt-state donated (HBM reuse). Gradients
come from jax.value_and_grad over the composed forward; the updater chain is
optax. Listeners run on host between steps.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
import optax

from ..train.updaters import NoOp, build_optimizer, gradient_normalization
from .conf import MultiLayerConfiguration
from .layers.base import Ctx, Layer
from .layers.wrappers import unwrap
from .layers.core import LossLayer, OCNNOutputLayer, OutputLayer
from .layers.samediff_layer import SameDiffOutputLayer
from .preprocessors import CnnToFeedForwardPreProcessor
from .weightnoise import maybe_apply_weight_noise


def _is_ff_layer(layer: Layer) -> bool:
    from .layers.core import (DenseLayer, ElementWiseMultiplicationLayer,
                              EmbeddingLayer)
    from .layers.recurrent import LastTimeStep
    layer = unwrap(layer)
    return isinstance(layer, (DenseLayer, ElementWiseMultiplicationLayer)) and \
        not isinstance(layer, EmbeddingLayer)


def _is_rnn_layer(layer: Layer) -> bool:
    from .layers.attention import (RecurrentAttentionLayer, SelfAttentionLayer)
    from .layers.core import RnnOutputLayer
    from .layers.recurrent import BaseRecurrent, Bidirectional
    layer = unwrap(layer)
    return isinstance(layer, (BaseRecurrent, Bidirectional, SelfAttentionLayer,
                              RecurrentAttentionLayer, RnnOutputLayer))


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: List[Layer] = conf.layers
        self._g = conf.globals_
        self.params: Dict[str, dict] = {}
        self.states: Dict[str, dict] = {}
        self._preprocessors: Dict[int, Any] = {}
        self._optimizer = None
        self._opt_state = None
        self._iters_per_epoch = 1
        self._step_count = 0
        self.epoch_count = 0
        self.listeners: List[Any] = []
        self.initialized = False
        self._train_step = None
        self._scan_epoch = None
        self._host_key = jax.random.PRNGKey(self._g.seed)
        # int n -> train-time forward runs as n jax.checkpoint segments
        # (activation remat; sequential analogue of
        # ComputationGraph.remat_segments)
        self.remat_segments = None

    @property
    def remat_segments(self):
        return self._remat_segments

    @remat_segments.setter
    def remat_segments(self, n):
        """Changing the remat policy invalidates every compiled step that
        traced the old forward."""
        if getattr(self, "_remat_segments", None) != n:
            self._train_step = None
            self._scan_epoch = None
            self._infer_fn = None
        self._remat_segments = n

    # ------------------------------------------------------------------ init
    def init(self, input_shape=None):
        """Resolve shapes layer-by-layer, create params (reference: init())."""
        if input_shape is None:
            if self.conf.input_type is not None:
                input_shape = tuple(self.conf.input_type[1])
            else:
                n_in = getattr(unwrap(self.layers[0]), "n_in", None)
                if not n_in:
                    raise ValueError("Provide input_shape or set_input_type on the config")
                input_shape = (int(n_in),)
        key = jax.random.PRNGKey(self._g.seed)
        shape = tuple(input_shape)
        self._init_input_shape = shape      # for TransferLearningHelper et al
        for i, layer in enumerate(self.layers):
            # auto preprocessor: conv/rnn activations into a flat FF layer
            if _is_ff_layer(layer) and len(shape) in (3, 4):  # cnn or cnn3d
                pp = CnnToFeedForwardPreProcessor()
                self._preprocessors[i] = pp
                shape = pp.out_shape(shape)
            if isinstance(unwrap(layer), OutputLayer) and not _is_rnn_layer(layer) and len(shape) in (3, 4):
                pp = CnnToFeedForwardPreProcessor()
                self._preprocessors[i] = pp
                shape = pp.out_shape(shape)
            key, sub = jax.random.split(key)
            p, s, shape = layer.init(sub, shape)
            self.params[f"layer_{i}"] = p
            self.states[f"layer_{i}"] = s
        self.output_shape = shape
        self.initialized = True
        return self

    # -------------------------------------------------------------- forward
    def _apply_one(self, i, params, states, h, new_states, *, train, rng,
                   fmask, lmask, stop_before_output):
        """Apply layer ``i`` to ``h``; returns (h, stopped). ``i`` keys the
        per-layer rng (fold_in), so segmented execution reproduces the
        monolithic walk's dropout/weight-noise draws exactly."""
        layer = self.layers[i]
        if stop_before_output and i == len(self.layers) - 1 and isinstance(
                unwrap(layer),
                (OutputLayer, LossLayer, SameDiffOutputLayer,
                 OCNNOutputLayer)):
            new_states[f"layer_{i}"] = states[f"layer_{i}"]
            return h, True
        if i in self._preprocessors:
            h = self._preprocessors[i](h)
        lrng = jax.random.fold_in(rng, i) if rng is not None else None
        ctx = Ctx(train=train, rng=lrng, mask=fmask, label_mask=lmask)
        # named scope = the profiler's layer map at the XLA level: the
        # fused executable's ops carry layer_i.<Type> in their metadata
        # (tensorboard xprof groups by it; trace-time only, zero runtime
        # cost). Same naming as obs.profiler's span attribution.
        with jax.named_scope(f"layer_{i}.{type(unwrap(layer)).__name__}"):
            if train and layer.dropout > 0.0 and lrng is not None:
                keep = 1.0 - layer.dropout
                dk = jax.random.fold_in(lrng, 997)
                m = jax.random.bernoulli(dk, keep, h.shape)
                h = jnp.where(m, h / keep, 0.0).astype(h.dtype)
            p_i = maybe_apply_weight_noise(layer, params[f"layer_{i}"],
                                           lrng, train)
            h, s_new = layer.apply(p_i, states[f"layer_{i}"], h, ctx)
        new_states[f"layer_{i}"] = s_new
        return h, False

    def _forward(self, params, states, x, *, train, rng, fmask=None, lmask=None,
                 stop_before_output=False):
        """Pure forward. Returns (activation, new_states)."""
        if train and getattr(self, "remat_segments", None):
            return self._forward_remat(
                params, states, x, train=train, rng=rng, fmask=fmask,
                lmask=lmask, stop_before_output=stop_before_output)
        new_states = {}
        h = x
        for i in range(len(self.layers)):
            h, stopped = self._apply_one(
                i, params, states, h, new_states, train=train, rng=rng,
                fmask=fmask, lmask=lmask,
                stop_before_output=stop_before_output)
            if stopped:
                break
        return h, new_states

    def _forward_remat(self, params, states, x, *, train, rng, fmask=None,
                      lmask=None, stop_before_output=False):
        """_forward with contiguous layer chunks under ``jax.checkpoint``:
        only chunk-boundary activations are saved for backward; in-chunk
        activations recompute. The sequential counterpart of
        ComputationGraph._forward_remat (single carried tensor, so the
        segment plan is just an even index split)."""
        n = len(self.layers)
        if int(self.remat_segments) > n:
            import warnings
            warnings.warn(
                f"remat_segments={int(self.remat_segments)} exceeds what "
                f"this {n}-layer net supports; using {n} checkpoint "
                "segments (activation footprint will be larger than "
                "configured)", stacklevel=3)
        nseg = max(1, min(int(self.remat_segments), n))
        bounds = [round(k * n / nseg) for k in range(nseg + 1)]
        h = x
        new_states = {}
        for a, b in zip(bounds[:-1], bounds[1:]):
            if a == b:
                continue

            def seg_fn(p, s, hh, rng_, fmask_, lmask_, _a=a, _b=b):
                ns = {}
                for i in range(_a, _b):
                    hh, stopped = self._apply_one(
                        i, p, s, hh, ns, train=train, rng=rng_,
                        fmask=fmask_, lmask=lmask_,
                        stop_before_output=stop_before_output)
                    if stopped:
                        break
                return hh, ns

            seg_params = {f"layer_{i}": params[f"layer_{i}"]
                          for i in range(a, b)}
            seg_states = {f"layer_{i}": states[f"layer_{i}"]
                          for i in range(a, b)}
            h, ns = jax.checkpoint(seg_fn)(seg_params, seg_states, h, rng,
                                           fmask, lmask)
            new_states.update(ns)
        return h, new_states

    def output(self, x, train: bool = False):
        """Inference forward (reference: output()). Jit-cached."""
        x = jnp.asarray(x)
        fn = self._get_infer_fn()
        return fn(self.params, self.states, x)

    def _get_infer_fn(self):
        if not hasattr(self, "_infer_fn") or self._infer_fn is None:
            def infer(params, states, x):
                y, _ = self._forward(params, states, x, train=False, rng=None)
                return y
            self._infer_fn = jax.jit(infer)
        return self._infer_fn

    def feed_forward(self, x, train: bool = False):
        """Per-layer activations list (reference: feedForward())."""
        x = jnp.asarray(x)
        acts = [x]
        h = x
        for i, layer in enumerate(self.layers):
            if i in self._preprocessors:
                h = self._preprocessors[i](h)
            ctx = Ctx(train=train, rng=None)
            h, _ = layer.apply(self.params[f"layer_{i}"], self.states[f"layer_{i}"], h, ctx)
            acts.append(h)
        return acts

    # ----------------------------------------------------------------- loss
    def _loss(self, params, states, x, y, rng, fmask, lmask):
        h, new_states = self._forward(params, states, x, train=True, rng=rng,
                                      fmask=fmask, lmask=lmask, stop_before_output=True)
        out_layer = unwrap(self.layers[-1])
        i = len(self.layers) - 1
        # the output layer's work happens HERE (the forward stops before
        # it) — scope it like _apply_one scopes every other layer
        with jax.named_scope(
                f"layer_{i}.{type(out_layer).__name__}.loss"):
            return self._loss_tail(out_layer, i, params, states, new_states,
                                   h, y, lmask)

    def _loss_tail(self, out_layer, i, params, states, new_states, h, y,
                   lmask):
        if isinstance(out_layer, OutputLayer):
            if i in self._preprocessors:
                h = self._preprocessors[i](h)
            from .layers.core import CenterLossOutputLayer
            if isinstance(out_layer, CenterLossOutputLayer):
                loss = out_layer.compute_loss(params[f"layer_{i}"], h, y, mask=lmask,
                                              state=states[f"layer_{i}"])
                new_states[f"layer_{i}"] = out_layer.update_state(
                    states[f"layer_{i}"], jax.lax.stop_gradient(h), y)
            else:
                loss = out_layer.compute_loss(params[f"layer_{i}"], h, y, mask=lmask)
        elif isinstance(out_layer, SameDiffOutputLayer):
            if i in self._preprocessors:
                h = self._preprocessors[i](h)
            loss = out_layer.compute_loss(params[f"layer_{i}"], h, y, mask=lmask)
        elif isinstance(out_layer, OCNNOutputLayer):
            if i in self._preprocessors:
                h = self._preprocessors[i](h)
            loss = out_layer.compute_loss(params[f"layer_{i}"], h, y, mask=lmask,
                                          state=states[f"layer_{i}"])
            new_states[f"layer_{i}"] = out_layer.update_state(
                states[f"layer_{i}"], h, params[f"layer_{i}"])
        elif isinstance(out_layer, LossLayer):
            loss = out_layer.compute_loss(h, y, mask=lmask)
        else:
            raise ValueError("Last layer must be an OutputLayer or LossLayer for fit()")
        loss = loss + self._reg_score(params)
        return loss, new_states

    def _reg_score(self, params):
        reg = 0.0
        for i, layer in enumerate(self.layers):
            if layer.l1 == 0.0 and layer.l2 == 0.0:
                continue
            for k, w in params[f"layer_{i}"].items():
                if k in ("b", "beta", "mean", "var"):
                    continue
                if layer.l1:
                    reg = reg + layer.l1 * jnp.sum(jnp.abs(w))
                if layer.l2:
                    reg = reg + 0.5 * layer.l2 * jnp.sum(jnp.square(w))
        return reg

    # ------------------------------------------------------------ optimizer
    def _param_labels(self):
        labels = {}
        has_override = False
        for i, layer in enumerate(self.layers):
            if layer.frozen:
                lab = "__frozen__"
                has_override = True
            elif layer.updater is not None:
                lab = f"__layer_{i}__"
                has_override = True
            else:
                lab = "__default__"
            labels[f"layer_{i}"] = jax.tree_util.tree_map(lambda _: lab, self.params[f"layer_{i}"])
        return (labels if has_override else None)

    def _build_optimizer(self, iters_per_epoch=1):
        g = self._g
        labels = self._param_labels()
        per_label = None
        if labels is not None:
            per_label = {"__default__": g.updater, "__frozen__": NoOp()}
            for i, layer in enumerate(self.layers):
                if layer.updater is not None and not layer.frozen:
                    per_label[f"__layer_{i}__"] = layer.updater
        # l1/l2 handled inside loss (reg term differentiates through); don't
        # double-apply in the optimizer chain.
        self._optimizer = build_optimizer(
            g.updater, grad_norm=g.grad_norm, grad_norm_threshold=g.grad_norm_threshold,
            iters_per_epoch=iters_per_epoch,
            param_labels=labels, per_label_updaters=per_label)
        self._opt_state = self._optimizer.init(self.params)
        upstream = getattr(self, "_upstream_adam_state", None)
        if upstream is not None:  # resume from an upstream DL4J zip — graft
            # here so EVERY optimizer consumer (fit/fit_scanned/
            # ParallelWrapper) picks the restored m/v/count up
            from ..serde.upstream_dl4j import graft_adam_state
            self._opt_state = graft_adam_state(self._opt_state, upstream)
            self._upstream_adam_state = None

    def _apply_constraints(self, params):
        from ..train.constraints import apply_constraints
        for i, layer in enumerate(self.layers):
            if layer.frozen:      # frozen params must stay bit-identical
                continue
            if layer.constraints:
                params[f"layer_{i}"] = apply_constraints(
                    params[f"layer_{i}"], layer.constraints, weights=True)
            if layer.bias_constraints:
                params[f"layer_{i}"] = apply_constraints(
                    params[f"layer_{i}"], layer.bias_constraints,
                    weights=False, biases=True)
        return params

    def _get_train_step(self):
        if self._train_step is None:
            optimizer = self._optimizer
            with_stats = getattr(self, "_anomaly_detector", None) is not None
            # numerics sentinel (ISSUE 13): a detector with
            # gate_updates=False (policy "warn") observes grad stats
            # WITHOUT the in-jit finiteness gate — the poisoned update
            # is applied, which is exactly what "warn" promises
            gate = with_stats and getattr(self._anomaly_detector,
                                          "gate_updates", True)

            def step(params, states, opt_state, x, y, rng, fmask, lmask):
                # the per-step key split happens INSIDE the jitted step and
                # the next chain key rides the outputs: the fit loop never
                # dispatches a separate host-side split per batch (a real
                # extra device launch per step, costly through the tunnel)
                use_rng, next_rng = jax.random.split(rng)
                (loss, new_states), grads = jax.value_and_grad(
                    self._loss, has_aux=True)(params, states, x, y, use_rng,
                                              fmask, lmask)
                updates, new_opt_state = optimizer.update(grads, opt_state, params)
                new_params = self._apply_constraints(
                    optax.apply_updates(params, updates))
                stats = None
                if with_stats:
                    # A non-finite batch becomes a whole-step no-op (params,
                    # opt state, BN running stats) so the detector can raise
                    # without the run already being poisoned.
                    from ..train.anomaly import maybe_stats_and_gate
                    stats, new_params, new_opt_state, new_states = \
                        maybe_stats_and_gate(
                            gate, grads, params, new_params, opt_state,
                            new_opt_state, states, new_states)
                return new_params, new_states, new_opt_state, loss, stats, next_rng

            # compile sentinel (ISSUE 12): counts/times every compile of
            # the donated step and warns on post-warmup retraces — the
            # wrapper is transparent (fit_scanned's `.__wrapped__` and
            # floor probes' `.lower` delegate through)
            from ..obs.compiles import CompileSentinel
            self._train_step = CompileSentinel(
                "mln_train_step",
                jax.jit(step, donate_argnums=(0, 1, 2)))
        return self._train_step

    def enable_gradient_anomaly_detection(self, detector=None):
        """Failure detection (SURVEY §2.9): per-layer gradient stats computed
        inside the jitted step, checked host-side each iteration. Pass a
        configured ``train.anomaly.GradientAnomalyDetector`` or None for
        defaults. Call with detector=False to disable."""
        from ..train.anomaly import GradientAnomalyDetector
        if detector is False:
            self._anomaly_detector = None
        else:
            self._anomaly_detector = detector or GradientAnomalyDetector()
        self._train_step = None  # rebuild with/without stats
        self._scan_epoch = None
        return self

    # ------------------------------------------------------------------ fit
    def fit(self, data, labels=None, *, epochs: int = 1):
        """fit(DataSetIterator) | fit(DataSet) | fit(features, labels).

        Reference: MultiLayerNetwork.fit — one optimizer step per minibatch,
        listeners invoked per iteration, epoch counter maintained.
        """
        from ..data.dataset import DataSet
        if labels is not None:
            data = DataSet(jnp.asarray(data), jnp.asarray(labels))
        if isinstance(data, DataSet):
            iterator = [data]
        else:
            iterator = data
        if not self.initialized:
            first = next(iter(iterator))
            self.init(tuple(np.asarray(first.features).shape[1:]))
            if hasattr(iterator, "reset"):
                iterator.reset()
        if self._optimizer is None:
            try:
                ipe = len(iterator)
            except TypeError:
                ipe = 1
            self._iters_per_epoch = max(int(ipe), 1)
            self._build_optimizer(self._iters_per_epoch)
            restored = getattr(self, "_restored_opt_state", None)
            if restored is not None:  # resume updater state from checkpoint
                self._opt_state = jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(self._opt_state),
                    jax.tree_util.tree_leaves(restored))
                self._restored_opt_state = None
        step_fn = self._get_train_step()
        last = None
        anomaly_check = None
        if getattr(self, "_anomaly_detector", None) is not None:
            from ..train.anomaly import DelayedAnomalyCheck
            anomaly_check = DelayedAnomalyCheck(self._anomaly_detector)

        # DL4J's fit wraps the source in an AsyncDataSetIterator so batch
        # prep runs on a background thread while the device computes; do
        # the same when the iterator opts in (async_supported).
        from ..data.async_iter import maybe_wrap_async
        run_iter, wrapped = maybe_wrap_async(iterator)

        # Listener score fetches are deferred ONE iteration when every
        # attached listener opts in (`deferred_score_ok`, the pure logging
        # ones): float(loss) blocks until the step finishes, so fetching
        # step k-1's loss while step k is in flight keeps the device
        # pipeline full. Listeners that read model state at the reported
        # iteration (checkpointing, eval, NaN watchdog) keep the exact
        # synchronous semantics — params must match the (step, score) pair.
        defer_ok = all(getattr(ls, "deferred_score_ok", False)
                       for ls in self.listeners)
        pending = None

        def flush_pending():
            nonlocal pending
            if pending is not None:
                loss_d, si, ei = pending
                pending = None
                lv = float(loss_d)
                for listener in self.listeners:
                    listener.iteration_done(self, si, ei, lv)

        try:
            for e in range(epochs):
                for ds in run_iter:
                    x = jnp.asarray(ds.features)
                    y = jnp.asarray(ds.labels)
                    # examples-throughput telemetry (MetricsListener)
                    self._last_batch_size = int(x.shape[0])
                    fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
                    lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
                    (self.params, self.states, self._opt_state, loss, gstats,
                     self._host_key) = step_fn(
                        self.params, self.states, self._opt_state, x, y,
                        self._host_key, fmask, lmask)
                    self._step_count += 1
                    if anomaly_check is not None and gstats is not None:
                        anomaly_check.push(gstats, self._step_count)
                    last = loss
                    if self.listeners:
                        if defer_ok:
                            flush_pending()
                            pending = (loss, self._step_count,
                                       self.epoch_count)
                        else:
                            lv = float(loss)
                            for listener in self.listeners:
                                listener.iteration_done(
                                    self, self._step_count, self.epoch_count,
                                    lv)
                self.epoch_count += 1
                if e < epochs - 1:
                    if hasattr(run_iter, "reset"):
                        run_iter.reset()
                elif wrapped is not None:
                    # final epoch: close the wrapper FIRST so reset doesn't
                    # spin up a producer whose prefetch is thrown away
                    wrapped.close()
                    wrapped = None
                    if hasattr(iterator, "reset"):
                        iterator.reset()
                elif hasattr(run_iter, "reset"):
                    run_iter.reset()
                flush_pending()   # all iteration_done before on_epoch_end
                for listener in self.listeners:
                    if hasattr(listener, "on_epoch_end"):
                        listener.on_epoch_end(self)
        finally:
            # a mid-epoch exception must still deliver the completed step's
            # deferred callback (scores would end one step short) — but it
            # must never MASK the original error, and runs before close()
            try:
                flush_pending()
            except Exception:  # noqa: BLE001 — original exception wins
                pass
            if wrapped is not None:
                wrapped.close()
        if anomaly_check is not None:
            anomaly_check.flush()
        return None if last is None else float(last)

    def fit_scanned(self, data, *, epochs: int = 1):
        """TPU-idiomatic epoch loop: ONE jit dispatch per epoch.

        Stacks the epoch's minibatches to (K, B, ...) and runs the train
        step as a ``lax.scan`` over them, so per-step dispatch overhead
        (pytree flatten + launch latency — milliseconds through a relay,
        and comparable to the whole step for small models) is paid once
        per EPOCH instead of once per batch. Semantics vs :meth:`fit`:
        identical parameter trajectory (same step math, same rng chain);
        listeners fire per-iteration AFTER the epoch's dispatch from the
        scanned loss history (one device fetch for all K losses), so
        listeners that inspect model state mid-epoch (checkpointing,
        evaluative) see the post-epoch model and are rejected loudly.

        Requires equally-shaped, mask-free minibatches (the stacked scan
        is a single compiled program). The reference has no analogue —
        this is what an XLA-native training loop looks like.

        TPU-targeted: XLA:CPU lowers conv/matmul inside loop bodies to a
        slow generic path (measured 14x vs the per-step loop for a conv
        step), so on CPU prefer fit(); on TPU loop bodies get the same
        MXU codegen as straight-line code and the dispatch saving is the
        whole point.
        """
        from ..data.dataset import DataSet
        if isinstance(data, DataSet):
            batches = [data]
        else:
            batches = list(data)
        if not batches:
            return None
        if any(b.features_mask is not None or b.labels_mask is not None
               for b in batches):
            raise ValueError("fit_scanned does not support masked batches; "
                             "use fit()")
        shapes = {(np.asarray(b.features).shape, np.asarray(b.labels).shape)
                  for b in batches}
        if len(shapes) > 1:
            raise ValueError(f"fit_scanned needs equally-shaped batches, "
                             f"got {sorted(shapes)}; use fit()")
        from ._scan_common import check_scan_listeners
        check_scan_listeners(self)
        if not self.initialized:
            self.init(tuple(np.asarray(batches[0].features).shape[1:]))
        if self._optimizer is None:
            self._iters_per_epoch = len(batches)
            self._build_optimizer(self._iters_per_epoch)
        xs = jnp.stack([jnp.asarray(b.features) for b in batches])
        ys = jnp.stack([jnp.asarray(b.labels) for b in batches])
        step_fn = self._get_train_step()

        if self._scan_epoch is None:
            def scan_epoch(params, states, opt_state, rng, xs, ys):
                def body(carry, xy):
                    p, s, o, k = carry
                    x, y = xy
                    p, s, o, loss, _, k = step_fn.__wrapped__(
                        p, s, o, x, y, k, None, None)
                    return (p, s, o, k), loss
                (params, states, opt_state, rng), losses = lax.scan(
                    body, (params, states, opt_state, rng), (xs, ys))
                return params, states, opt_state, rng, losses
            self._scan_epoch = jax.jit(scan_epoch, donate_argnums=(0, 1, 2))
        losses = None
        for _ in range(epochs):
            (self.params, self.states, self._opt_state, self._host_key,
             losses) = self._scan_epoch(self.params, self.states,
                                        self._opt_state, self._host_key,
                                        xs, ys)
            self._step_count += len(batches)
            self.epoch_count += 1
            from ._scan_common import replay_scan_listeners
            replay_scan_listeners(self, losses, len(batches))
        return float(np.asarray(losses)[-1])

    # ---------------------------------------------------------------- score
    def score(self, dataset=None):
        """Loss (incl. regularization) on a DataSet (reference: score())."""
        if dataset is None:
            raise ValueError("score() requires a DataSet")
        x = jnp.asarray(dataset.features)
        y = jnp.asarray(dataset.labels)
        fmask = None if dataset.features_mask is None else jnp.asarray(dataset.features_mask)
        lmask = None if dataset.labels_mask is None else jnp.asarray(dataset.labels_mask)
        loss, _ = self._loss(self.params, self.states, x, y, None, fmask, lmask)
        return float(loss)

    def gradient_and_score(self, dataset):
        """(gradients pytree, score) — reference computeGradientAndScore()."""
        x = jnp.asarray(dataset.features)
        y = jnp.asarray(dataset.labels)
        (loss, _), grads = jax.value_and_grad(self._loss, has_aux=True)(
            self.params, self.states, x, y, None, None, None)
        return grads, float(loss)

    # ------------------------------------------------------------- evaluate
    def evaluate(self, iterator, top_n: int = 1):
        from ..eval.classification import Evaluation
        ev = Evaluation(top_n=top_n)
        for ds in iterator:
            preds = self.output(jnp.asarray(ds.features))
            mask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
            ev.eval(jnp.asarray(ds.labels), preds, mask=mask)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return ev

    def evaluate_regression(self, iterator):
        from ..eval.regression import RegressionEvaluation
        ev = RegressionEvaluation()
        for ds in iterator:
            preds = self.output(jnp.asarray(ds.features))
            ev.eval(jnp.asarray(ds.labels), preds)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return ev

    def evaluate_roc(self, iterator, threshold_steps: int = 0):
        from ..eval.roc import ROC
        roc = ROC(threshold_steps)
        for ds in iterator:
            preds = self.output(jnp.asarray(ds.features))
            roc.eval(jnp.asarray(ds.labels), preds)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return roc

    # ------------------------------------------------------------ listeners
    # ------------------------------------------------- streaming inference
    def rnn_time_step(self, x):
        """Stateful streaming inference — reference rnnTimeStep: feed one
        step (B, C) or a chunk (B, T, C); every recurrent layer's hidden
        state persists across calls until rnn_clear_previous_state(). One
        jitted scan per chunk; the carry pytree lives on device between
        calls (no host round-trip in a generation loop)."""
        from .layers.recurrent import (BaseRecurrent, Bidirectional,
                                       LastTimeStep)
        from .layers.wrappers import TimeDistributedLayer
        for layer in self.layers:
            if isinstance(unwrap(layer), (Bidirectional, LastTimeStep,
                                          TimeDistributedLayer)):
                raise NotImplementedError(
                    f"rnn_time_step cannot stream through "
                    f"{type(unwrap(layer)).__name__}: it needs the full "
                    f"sequence (reference rnnTimeStep has the same limit)")
        x = jnp.asarray(x)
        # 2-D *integer* input is a (B, T) token-id chunk for embedding-fronted
        # models, NOT a single (B, C) feature step; only float 2-D is a step.
        integer = jnp.issubdtype(x.dtype, jnp.integer)
        single = (x.ndim == 2 and not integer) or (x.ndim == 1 and integer)
        if single:
            x = x[:, None] if x.ndim == 1 else x[:, None, :]
        batch = x.shape[0]

        def carry_dtype(ul):
            # must match what the cell emits: the post-cast compute dtype
            if ul.compute_dtype is not None:
                return ul.compute_dtype
            return x.dtype if jnp.issubdtype(x.dtype, jnp.floating) \
                else self._g.param_dtype

        old = getattr(self, "_rnn_carries", None) or {}
        if getattr(self, "_rnn_carry_batch", None) != batch:
            old = {}  # batch changed: stale state is meaningless
        carries = {}
        for i, layer in enumerate(self.layers):
            ul = unwrap(layer)
            if isinstance(ul, BaseRecurrent):
                key = f"layer_{i}"
                carries[key] = old.get(key)
                if carries[key] is None:  # keep rnn_set_previous_state values
                    carries[key] = ul.init_carry(batch, carry_dtype(ul))
        self._rnn_carry_batch = batch

        if getattr(self, "_rnn_stream_fn", None) is None:
            def stream(params, states, carries, xs):
                def step(cs, xt):
                    h = xt
                    new_cs = {}
                    for i, layer in enumerate(self.layers):
                        key = f"layer_{i}"
                        if i in self._preprocessors:  # same as _forward
                            h = self._preprocessors[i](h)
                        ul = unwrap(layer)
                        if isinstance(ul, BaseRecurrent):
                            h, c = ul.step_apply(params[key], cs[key], h,
                                                 Ctx(train=False))
                            new_cs[key] = c
                        else:
                            h, _ = layer.apply(params[key], states[key], h,
                                               Ctx(train=False))
                    return new_cs, h
                cs, ys = jax.lax.scan(step, carries, xs.swapaxes(0, 1))
                return ys.swapaxes(0, 1), cs
            self._rnn_stream_fn = jax.jit(stream)

        y, carries = self._rnn_stream_fn(self.params, self.states, carries, x)
        self._rnn_carries = carries
        return y[:, 0] if single else y

    def rnn_clear_previous_state(self):
        """Reference rnnClearPreviousState: drop all streaming state."""
        self._rnn_carries = None
        self._rnn_carry_batch = None

    def rnn_get_previous_state(self, layer_idx: int):
        carries = getattr(self, "_rnn_carries", None) or {}
        return carries.get(f"layer_{layer_idx}")

    def rnn_set_previous_state(self, layer_idx: int, state):
        carries = dict(getattr(self, "_rnn_carries", None) or {})
        carries[f"layer_{layer_idx}"] = state
        self._rnn_carries = carries
        # record the batch the injected state implies so the next
        # rnn_time_step keeps it instead of re-initializing
        leaf = jax.tree_util.tree_leaves(state)[0]
        self._rnn_carry_batch = leaf.shape[0]

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)

    # ----------------------------------------------------------- params API
    def num_params(self) -> int:
        return sum(int(p.size) for p in jax.tree_util.tree_leaves(self.params))

    def get_param(self, layer_idx: int, name: str):
        return self.params[f"layer_{layer_idx}"][name]

    def set_param(self, layer_idx: int, name: str, value):
        self.params[f"layer_{layer_idx}"][name] = jnp.asarray(value)
        self._invalidate()

    def params_flat(self):
        """Single flat vector, reference INDArray params() order: layer order."""
        leaves = jax.tree_util.tree_leaves(self.params)
        return jnp.concatenate([l.ravel() for l in leaves]) if leaves else jnp.zeros((0,))

    def set_params_flat(self, flat):
        leaves, treedef = jax.tree_util.tree_flatten(self.params)
        out, off = [], 0
        for l in leaves:
            n = int(l.size)
            out.append(jnp.asarray(flat[off:off + n]).reshape(l.shape).astype(l.dtype))
            off += n
        self.params = jax.tree_util.tree_unflatten(treedef, out)
        self._invalidate()

    def _invalidate(self):
        self._infer_fn = None
        self._train_step = None
        self._scan_epoch = None
        self._rnn_stream_fn = None

    def clone(self):
        import copy
        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        net.remat_segments = self.remat_segments
        if self.initialized:
            # REAL copies: fit() donates param buffers, so sharing arrays
            # would let the clone's training invalidate the source's
            net.params = jax.tree_util.tree_map(jnp.copy, self.params)
            net.states = jax.tree_util.tree_map(jnp.copy, self.states)
            net._preprocessors = dict(self._preprocessors)
            # a net restored without input_type has params but never ran
            # shape resolution — clone what exists
            if hasattr(self, "output_shape"):
                net.output_shape = self.output_shape
            net.initialized = True
        return net

    # -------------------------------------------------------------- summary
    def summary(self) -> str:
        lines = ["=" * 72,
                 f"{'LayerName (idx)':<28}{'Output Shape':<20}{'Param Count':<12}",
                 "=" * 72]
        total = 0
        for i, layer in enumerate(self.layers):
            p = self.params.get(f"layer_{i}", {})
            n = sum(int(v.size) for v in jax.tree_util.tree_leaves(p))
            total += n
            name = layer.name or type(layer).__name__
            lines.append(f"{name + f' ({i})':<28}{'-':<20}{n:<12}")
        lines += ["=" * 72, f"Total params: {total}", "=" * 72]
        return "\n".join(lines)

    # ----------------------------------------------------------------- save
    def save(self, path, save_updater: bool = False):
        from ..serde.model_serializer import save_model
        save_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path):
        from ..serde.model_serializer import load_model
        return load_model(path)
