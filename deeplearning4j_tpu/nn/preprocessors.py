"""Input preprocessors — shape adapters between layer families.

Reference parity: ``org.deeplearning4j.nn.conf.preprocessor.{CnnToFeedForward,
FeedForwardToCnn, RnnToFeedForward, FeedForwardToRnn, CnnToRnn, RnnToCnn}
PreProcessor``. Pure reshapes — free under XLA (layout changes fuse away).
Auto-inserted by MultiLayerNetwork when adjacent shape kinds differ, like the
reference's ``setInputType`` logic.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Tuple


@dataclass
class CnnToFeedForwardPreProcessor:
    def out_shape(self, s):
        return (int(math.prod(s)),)

    def __call__(self, x):
        return x.reshape(x.shape[0], -1)


@dataclass
class FeedForwardToCnnPreProcessor:
    height: int = 0
    width: int = 0
    channels: int = 0

    def out_shape(self, s):
        return (self.height, self.width, self.channels)

    def __call__(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)


@dataclass
class RnnToFeedForwardPreProcessor:
    """(B,T,C) → (B*T, C); pairs with FeedForwardToRnn to restore."""

    def out_shape(self, s):
        return (s[-1],)

    def __call__(self, x):
        return x.reshape(-1, x.shape[-1])


@dataclass
class FeedForwardToRnnPreProcessor:
    timesteps: int = 0

    def out_shape(self, s):
        return (self.timesteps, s[-1])

    def __call__(self, x):
        return x.reshape(-1, self.timesteps, x.shape[-1])


@dataclass
class CnnToRnnPreProcessor:
    """(B,H,W,C) → (B, H, W*C) treating H as time, or flatten spatial to T."""

    def out_shape(self, s):
        h, w, c = s
        return (h, w * c)

    def __call__(self, x):
        b, h, w, c = x.shape
        return x.reshape(b, h, w * c)


@dataclass
class RnnToCnnPreProcessor:
    height: int = 0
    width: int = 0
    channels: int = 0

    def out_shape(self, s):
        return (self.height, self.width, self.channels)

    def __call__(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)
