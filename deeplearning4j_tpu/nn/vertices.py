"""Graph vertices — parity with ``org.deeplearning4j.nn.conf.graph.*Vertex``.

MergeVertex, ElementWiseVertex, SubsetVertex, StackVertex, UnstackVertex,
L2NormalizeVertex, L2Vertex, ScaleVertex, ShiftVertex, ReshapeVertex,
PreprocessorVertex. A vertex is param-free (LayerVertex wraps Layers);
``apply(inputs: list) -> array`` and ``out_shape(shapes: list) -> shape``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import jax.numpy as jnp


class GraphVertex:
    def out_shape(self, shapes: List[Tuple]) -> Tuple:
        raise NotImplementedError

    def apply(self, inputs: List, ctx=None):
        raise NotImplementedError


@dataclass
class MergeVertex(GraphVertex):
    """Concat along feature (last) axis."""

    axis: int = -1

    def out_shape(self, shapes):
        base = list(shapes[0])
        base[-1] = sum(s[-1] for s in shapes)
        return tuple(base)

    def apply(self, inputs, ctx=None):
        return jnp.concatenate(inputs, axis=self.axis)


@dataclass
class ElementWiseVertex(GraphVertex):
    """op in {add, sub, mul, avg, max} (reference ElementWiseVertex.Op)."""

    op: str = "add"

    def out_shape(self, shapes):
        return shapes[0]

    def apply(self, inputs, ctx=None):
        x = inputs[0]
        if self.op == "add":
            for y in inputs[1:]:
                x = x + y
        elif self.op == "sub":
            x = x - inputs[1]
        elif self.op == "mul":
            for y in inputs[1:]:
                x = x * y
        elif self.op == "avg":
            x = sum(inputs) / len(inputs)
        elif self.op == "max":
            for y in inputs[1:]:
                x = jnp.maximum(x, y)
        else:
            raise ValueError(self.op)
        return x


@dataclass
class SubsetVertex(GraphVertex):
    """Feature-axis slice [lo, hi] inclusive (reference semantics)."""

    lo: int = 0
    hi: int = 0

    def out_shape(self, shapes):
        s = list(shapes[0])
        s[-1] = self.hi - self.lo + 1
        return tuple(s)

    def apply(self, inputs, ctx=None):
        return inputs[0][..., self.lo:self.hi + 1]


@dataclass
class StackVertex(GraphVertex):
    """Stack along batch axis (reference StackVertex)."""

    def out_shape(self, shapes):
        return shapes[0]

    def apply(self, inputs, ctx=None):
        return jnp.concatenate(inputs, axis=0)


@dataclass
class UnstackVertex(GraphVertex):
    from_index: int = 0
    stack_size: int = 1

    def out_shape(self, shapes):
        return shapes[0]

    def apply(self, inputs, ctx=None):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_index * n:(self.from_index + 1) * n]


@dataclass
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def out_shape(self, shapes):
        return shapes[0]

    def apply(self, inputs, ctx=None):
        x = inputs[0]
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
        return x / jnp.maximum(n, self.eps)


@dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs → (B, 1)."""

    eps: float = 1e-8

    def out_shape(self, shapes):
        return (1,)

    def apply(self, inputs, ctx=None):
        a, b = inputs
        d = a.reshape(a.shape[0], -1) - b.reshape(b.shape[0], -1)
        return jnp.sqrt(jnp.sum(jnp.square(d), axis=-1, keepdims=True) + self.eps)


@dataclass
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def out_shape(self, shapes):
        return shapes[0]

    def apply(self, inputs, ctx=None):
        return inputs[0] * self.scale


@dataclass
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def out_shape(self, shapes):
        return shapes[0]

    def apply(self, inputs, ctx=None):
        return inputs[0] + self.shift


@dataclass
class ReshapeVertex(GraphVertex):
    new_shape: Tuple = ()  # excluding batch

    def out_shape(self, shapes):
        return tuple(self.new_shape)

    def apply(self, inputs, ctx=None):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.new_shape))


@dataclass
class PreprocessorVertex(GraphVertex):
    preprocessor: Any = None

    def out_shape(self, shapes):
        return self.preprocessor.out_shape(shapes[0])

    def apply(self, inputs, ctx=None):
        return self.preprocessor(inputs[0])
