"""Training listeners — parity with ``org.deeplearning4j.optimize.listeners``.

ScoreIterationListener, PerformanceListener, EvaluativeListener,
CheckpointListener, TimeIterationListener, CollectScoresListener, plus a
NaN watchdog (failure detection) and a TensorBoard StatsListener analogue.
Listeners run on host between jitted steps; they never touch the hot path.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any, Callable, List, Optional


class TrainingListener:
    def iteration_done(self, model, iteration: int, epoch: int, score: float):
        pass

    def on_epoch_end(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    """Print score every N iterations (reference ScoreIterationListener)."""
    deferred_score_ok = True  # pure logging: fit() may report the
    # (step, score) pair one dispatch late to keep the device busy


    def __init__(self, print_iterations: int = 10, log_fn: Callable = print):
        self.print_iterations = max(1, print_iterations)
        self.log_fn = log_fn

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.print_iterations == 0:
            self.log_fn(f"Score at iteration {iteration} is {score}")


class PerformanceListener(TrainingListener):
    """Throughput reporting: iterations/sec + examples/sec."""
    deferred_score_ok = True  # pure logging: fit() may report the
    # (step, score) pair one dispatch late to keep the device busy


    def __init__(self, frequency: int = 10, report_batch: bool = True, log_fn: Callable = print):
        self.frequency = max(1, frequency)
        self.report_batch = report_batch
        self.log_fn = log_fn
        self._last_time = None
        self._last_iter = 0

    def iteration_done(self, model, iteration, epoch, score):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time, self._last_iter = now, iteration
            return
        if iteration - self._last_iter >= self.frequency:
            dt = now - self._last_time
            its = (iteration - self._last_iter) / dt
            self.log_fn(f"iteration {iteration}; iterations/sec: {its:.2f}; score: {score:.5f}")
            self._last_time, self._last_iter = now, iteration


class TimeIterationListener(TrainingListener):
    """ETA logging based on expected total iteration count."""
    deferred_score_ok = True  # pure logging: fit() may report the
    # (step, score) pair one dispatch late to keep the device busy


    def __init__(self, total_iterations: int, frequency: int = 100, log_fn: Callable = print):
        self.total = total_iterations
        self.frequency = max(1, frequency)
        self.log_fn = log_fn
        self._start = time.perf_counter()

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.perf_counter() - self._start
            rate = iteration / elapsed
            remaining = (self.total - iteration) / rate if rate > 0 else float("inf")
            self.log_fn(f"iteration {iteration}/{self.total}; ETA {remaining:.0f}s")


class CollectScoresListener(TrainingListener):
    deferred_score_ok = True  # pure logging: fit() may report the
    # (step, score) pair one dispatch late to keep the device busy

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.iterations: List[int] = []
        self.scores: List[float] = []

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency == 0:
            self.iterations.append(iteration)
            self.scores.append(score)


class EvaluativeListener(TrainingListener):
    """Periodically evaluate on a held-out iterator (reference EvaluativeListener)."""

    def __init__(self, iterator, frequency: int = 100, log_fn: Callable = print):
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.log_fn = log_fn
        self.last_evaluation = None

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency == 0:
            self.last_evaluation = model.evaluate(self.iterator)
            self.log_fn(f"Evaluation at iteration {iteration}: "
                        f"accuracy={self.last_evaluation.accuracy():.4f}")


class CheckpointListener(TrainingListener):
    """Periodic checkpoints with retention (reference CheckpointListener).

    save_every_n_iterations / save_every_n_epochs; keep_last + keep_every.
    """

    def __init__(self, model_dir, save_every_n_iterations: Optional[int] = None,
                 save_every_n_epochs: Optional[int] = None, keep_last: int = 3):
        self.dir = Path(model_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self._saved: List[Path] = []

    def _save(self, model, tag: str):
        path = self.dir / f"checkpoint_{tag}.zip"
        model.save(path, save_updater=True)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            try:
                os.remove(old)
            except OSError:
                pass

    def iteration_done(self, model, iteration, epoch, score):
        if self.every_iter and iteration % self.every_iter == 0:
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model):
        if self.every_epoch and model.epoch_count % self.every_epoch == 0:
            self._save(model, f"epoch_{model.epoch_count}")


class NanScoreWatchdog(TrainingListener):
    """Failure detection: abort (or callback) on NaN/Inf score — the
    reference's FailureTestingListener / InvalidScoreIterationTerminationCondition."""

    def __init__(self, on_failure: Optional[Callable] = None):
        self.on_failure = on_failure
        self.triggered = False

    def iteration_done(self, model, iteration, epoch, score):
        import math
        if math.isnan(score) or math.isinf(score):
            self.triggered = True
            if self.on_failure is not None:
                self.on_failure(model, iteration, score)
            else:
                raise FloatingPointError(
                    f"NaN/Inf score at iteration {iteration}: {score}")


class MetricsListener(TrainingListener):
    """Telemetry-plane listener: feeds the process-wide ``obs`` registry
    (step-time histogram, loss, examples/s, device memory) so a running
    fit is scrapeable at ``GET /metrics`` on the UI server.

    Budgeted: the whole body is increments + one histogram observe on
    host between steps (~µs); its own cumulative cost is exported as
    ``dl4j_obs_overhead_seconds_total`` and tests/test_obs.py pins it
    under 2% of the step time on the tier-1 CPU path. Device-memory
    stats are polled every ``memory_frequency`` iterations only (the
    one call that can cost >µs, and None off-TPU)."""

    deferred_score_ok = True  # pure metrics: fit() may report the
    # (step, score) pair one dispatch late to keep the device busy

    def __init__(self, registry=None, memory_frequency: int = 50):
        from ..obs import get_registry
        reg = registry or get_registry()
        self.registry = reg
        self.memory_frequency = max(1, memory_frequency)
        self._step_seconds = reg.histogram(
            "dl4j_train_step_seconds",
            "Wall time between training iterations (host-observed)")
        self._iterations = reg.counter(
            "dl4j_train_iterations_total", "Optimizer steps taken")
        self._examples = reg.counter(
            "dl4j_train_examples_total", "Training examples consumed")
        self._epochs = reg.counter(
            "dl4j_train_epochs_total", "Epochs completed")
        self._loss = reg.gauge("dl4j_train_loss", "Last reported score")
        self._eps = reg.gauge(
            "dl4j_train_examples_per_second",
            "Examples/s over the last inter-iteration interval")
        self._mem = reg.gauge(
            "dl4j_device_memory_bytes",
            "jax device memory stats (polled every memory_frequency "
            "iterations; absent on backends without memory_stats)",
            labelnames=("stat",))
        self._overhead = reg.counter(
            "dl4j_obs_overhead_seconds_total",
            "Cumulative host time spent inside MetricsListener "
            "(budget: <2% of step time, tests/test_obs.py)")
        self._last_t: Optional[float] = None

    @property
    def overhead_seconds(self) -> float:
        return self._overhead.value()

    def _poll_memory(self, model=None):
        """Device-memory poll + component census (ISSUE 12).

        The allocator stats feed ``dl4j_device_memory_bytes{stat=}``
        where the backend has them; on CPU ``memory_stats()`` is absent
        and this used to export NOTHING — the tier-1 suite ran memory-
        blind. Now the pytree census always runs: params / optimizer /
        states bytes land in ``dl4j_mem_component_bytes{component,}``
        regardless of backend, so a dryrun sizes the same attribution a
        chip run does."""
        try:
            from ..obs import memory as obs_memory
        except Exception:  # noqa: BLE001 — memory stats are decoration
            return
        stats = obs_memory.device_memory_stats()
        if stats:
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit"):
                if key in stats:
                    self._mem.set(float(stats[key]), stat=key)
        if model is None:
            return
        components = {}
        if getattr(model, "params", None) is not None:
            components["params"] = model.params
        if getattr(model, "_opt_state", None) is not None:
            components["optimizer"] = model._opt_state
        if getattr(model, "states", None) is not None:
            components["states"] = model.states
        if components:
            try:
                # per_replica: the gauge's replica label ALWAYS means
                # "bytes this device holds" — the same semantics the
                # ParallelWrapper census writes, so the two emitters
                # agree on a sharded net instead of clobbering each
                # other's replica="0" row (on one device, shard bytes
                # == the full tree)
                obs_memory.emit_census(components, source="train",
                                       registry=self.registry,
                                       per_replica=True)
            except Exception:  # noqa: BLE001 — census is decoration
                pass

    def iteration_done(self, model, iteration, epoch, score):
        t0 = time.perf_counter()
        batch = getattr(model, "_last_batch_size", None)
        if self._last_t is not None:
            dt = t0 - self._last_t
            self._step_seconds.observe(dt)
            if batch and dt > 0:
                self._eps.set(batch / dt)
        self._last_t = t0
        self._iterations.inc()
        if batch:
            self._examples.inc(batch)
        self._loss.set(float(score))
        if iteration % self.memory_frequency == 0:
            self._poll_memory(model)
        self._overhead.inc(time.perf_counter() - t0)

    def on_epoch_end(self, model):
        self._epochs.inc()
        self._last_t = None  # epoch boundary work is not a step interval


class NumericsListener(TrainingListener):
    """Numerics-plane listener (ISSUE 13): every iteration feeds the
    loss to the attached :class:`~..obs.numerics.NumericsSentinel`
    (non-finite-loss trip + rolling z-score spike detector), and every
    ``frequency`` iterations samples the model's params (and the
    step's in-jit grad stats, when the sentinel is wired into the
    train step) through the jitted one-pass stat engine into
    ``dl4j_num_*{layer, kind}`` gauges.

    Budgeted like MetricsListener: the per-iteration body is a float
    check + a deque append (~µs, self-timed via the sentinel +
    ``overhead_seconds``); the stat sampling pays one fused reduction
    pass + one small host fetch per ``frequency`` steps.

    ``attach(net)`` is the one-call setup: adds this listener AND
    installs the sentinel as the net's gradient-anomaly detector, so
    grad stats are computed inside the jitted step and the
    ``skip_step`` / ``raise`` policies gate the update in-jit
    (bit-identical no-op on a poisoned batch).

    NOT deferred_score_ok: the sentinel's stat-tree dump reads live
    params, so the (step, score, params) triple must stay synchronous
    — a deferred score would snapshot the step AFTER the offender.
    """

    def __init__(self, sentinel=None, frequency: int = 25,
                 registry=None, source: str = "train",
                 replica: str = "0", sample_params: bool = True):
        from ..obs.numerics import NumericsSentinel
        self.sentinel = sentinel if sentinel is not None \
            else NumericsSentinel()
        self.frequency = max(1, int(frequency))
        self.registry = registry
        self.source = str(source)
        self.replica = str(replica)
        self.sample_params = bool(sample_params)
        self._overhead = 0.0

    @property
    def overhead_seconds(self) -> float:
        """Listener + sentinel bookkeeping cost (the <2%-of-step
        budget tests/test_numerics.py pins)."""
        return self._overhead + self.sentinel.overhead_seconds

    def attach(self, net) -> "NumericsListener":
        """Wire the whole plane onto ``net``: listener + in-step grad
        stats/gating via the sentinel. The net has ONE anomaly-detector
        slot — replacing a configured detector drops its explosion/
        vanishing thresholds (the sentinel only watches finiteness), so
        that replacement is warned, never silent."""
        existing = getattr(net, "_anomaly_detector", None)
        if existing is not None and existing is not self.sentinel:
            import warnings
            warnings.warn(
                f"NumericsListener.attach replaces the net's existing "
                f"{type(existing).__name__} gradient-anomaly detector "
                "with the numerics sentinel — explosion/vanishing "
                "detection stops; keep the old detector by wiring the "
                "listener alone (net.add_listeners) and leaving "
                "enable_gradient_anomaly_detection as it was",
                RuntimeWarning, stacklevel=2)
        net.add_listeners(self)
        net.enable_gradient_anomaly_detection(self.sentinel)
        return self

    def iteration_done(self, model, iteration, epoch, score):
        import time as _time
        self.sentinel.observe_loss(model, iteration, score)  # self-times
        t0 = _time.perf_counter()
        sample = iteration % self.frequency == 0
        if sample:
            from ..obs import numerics as obs_numerics
            import math as _math
            if _math.isfinite(float(score)):
                try:
                    obs_numerics.record_stats(
                        {"loss": {"mean": float(score),
                                  "nonfinite": 0.0}},
                        "loss", source=self.source,
                        replica=self.replica, registry=self.registry)
                except Exception:  # noqa: BLE001 — stats are decoration
                    pass
            if self.sample_params and \
                    getattr(model, "params", None):
                try:
                    obs_numerics.emit_stats(
                        model.params, "params", source=self.source,
                        replica=self.replica, registry=self.registry)
                except Exception:  # noqa: BLE001 — stats are decoration
                    pass
            gs = self.sentinel.last_grad_stats
            if gs:
                try:
                    obs_numerics.record_stats(
                        gs, "grads", source=self.source,
                        replica=self.replica, registry=self.registry)
                except Exception:  # noqa: BLE001 — stats are decoration
                    pass
        self._overhead += _time.perf_counter() - t0


class ProfilingListener(TrainingListener):
    """Per-layer time attribution (ISSUE 7): every ``frequency``
    iterations, run one ``obs.profiler`` attribution pass over
    ``probe_data`` — forward + backward per layer, each timed in a named
    ``Span`` — and feed the ``dl4j_layer_time_ms`` histogram (labels:
    layer, direction) plus optional JSONL span export.

    Unlike MetricsListener this is NOT hot-path-budgeted: a profile pass
    costs roughly one un-fused train step (per-layer dispatch), which is
    why it runs every `frequency` steps, off by default. ``probe_data``
    is a DataSet/MultiDataSet shaped like the training batches (same
    idiom as EvaluativeListener holding its own iterator); without one
    the listener only profiles on explicit ``profile(model, ds)`` calls.

    Reports accumulate on ``self.reports`` (total_ms / accounted_ms /
    accounted_frac / per-layer rows) — the unit-test contract is
    accounted_frac ≥ 0.9 on a CPU test model."""

    deferred_score_ok = True  # profiling reads probe_data, not the
    # live (step, score, params) triple — deferral is safe

    def __init__(self, probe_data=None, frequency: int = 100,
                 registry=None, tracer=None, jsonl_path=None,
                 max_reports: int = 50):
        self.probe_data = probe_data
        self.frequency = max(1, frequency)
        self._registry = registry
        self._tracer = tracer
        self.jsonl_path = jsonl_path
        self.max_reports = max_reports
        self.reports: List[dict] = []

    def profile(self, model, ds=None):
        from ..obs import profiler
        ds = ds if ds is not None else self.probe_data
        if ds is None:
            return None
        report = profiler.profile_step(model, ds, tracer=self._tracer)
        profiler.observe_report(report, registry=self._registry)
        # append exactly THIS pass's spans (the tracer ring also holds
        # every earlier pass — re-exporting it would duplicate records)
        recs = report.pop("span_records", [])
        if self.jsonl_path is not None and recs:
            p = Path(self.jsonl_path)
            p.parent.mkdir(parents=True, exist_ok=True)
            with open(p, "a") as f:
                for r in recs:
                    f.write(json.dumps(r) + "\n")
        self.reports.append(report)
        del self.reports[:-self.max_reports]
        return report

    def iteration_done(self, model, iteration, epoch, score):
        if self.probe_data is not None and \
                iteration % self.frequency == 0:
            self.profile(model)


class StatsListener(TrainingListener):
    """Training-UI analogue (reference StatsListener + UIServer): score,
    learning rate and per-layer update:param ratios — DL4J's headline
    training-health chart. Writes TensorBoard scalars when available AND
    always a JSONL stream that ``deeplearning4j_tpu.ui`` renders in the
    terminal. Ratio computation snapshots params every `frequency` steps
    (off the hot path; a few tiny reductions per report).

    NOT deferred_score_ok: _ratios reads live model params, so the
    (step, score, params) triple must stay synchronous."""

    def __init__(self, log_dir="runs/dl4j_tpu", frequency: int = 10,
                 report_ratios: bool = True, tensorboard: bool = True):
        self.frequency = max(1, frequency)
        self.report_ratios = report_ratios
        self.log_dir = Path(log_dir)
        self.log_dir.mkdir(parents=True, exist_ok=True)
        self._writer = None
        if tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter  # torch cpu baked in
                self._writer = SummaryWriter(str(self.log_dir))
            except Exception:  # noqa: BLE001
                pass
        self._jsonl = open(self.log_dir / "stats.jsonl", "a")
        # run delimiter: the dashboard charts only the records after the
        # last one of these, so appended logs never splice two runs. The
        # leading newline terminates any torn line a crashed run left
        # behind (an empty line is skipped by the parser).
        prefix = "\n" if self._jsonl.tell() > 0 else ""
        self._jsonl.write(prefix + json.dumps({"run_start": time.time()}) + "\n")
        self._jsonl.flush()
        self._prev_params = None

    @staticmethod
    def _current_lr(model, iteration):
        try:
            upd = model._g.updater
            lr = upd._lr(getattr(model, "_iters_per_epoch", 1) or 1)
            return float(lr(iteration)) if callable(lr) else float(lr)
        except Exception:  # noqa: BLE001 — lr is best-effort decoration
            return None

    def _ratios(self, model):
        """Per-layer ||Δparam|| / ||param|| since the previous report.

        The snapshot is copied to HOST: the train step donates params, so
        holding the device arrays across a step is use-after-donate (see
        utils/race.py) — their buffers die with the next dispatch."""
        import jax
        import numpy as _np
        params = jax.device_get(model.params)
        if self._prev_params is None:
            self._prev_params = params
            return None
        out = {}
        for group, sub in params.items():
            prev = self._prev_params.get(group)
            if prev is None:
                continue
            leaves_n = jax.tree_util.tree_leaves(sub)
            leaves_p = jax.tree_util.tree_leaves(prev)
            if not leaves_n:
                continue
            dn = sum(float(_np.sum(_np.square(
                _np.asarray(n, _np.float32) - _np.asarray(p, _np.float32))))
                for n, p in zip(leaves_n, leaves_p))
            pn = sum(float(_np.sum(_np.square(_np.asarray(p, _np.float32))))
                     for p in leaves_p)
            out[str(group)] = dn ** 0.5 / (pn ** 0.5 + 1e-12)
        self._prev_params = params
        return out or None

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency:
            return
        if not getattr(self, "_static_written", False):
            # run-level static info (upstream StatsStorage staticInfo):
            # written once, lets the UI label historical sessions
            self._static_written = True
            try:
                info = {"model": type(model).__name__}
                if hasattr(model, "num_params"):
                    info["num_params"] = int(model.num_params())
                self._jsonl.write(json.dumps({"static": info}) + "\n")
                self._jsonl.flush()
            except Exception:  # noqa: BLE001 — decoration only
                pass
        rec = {"iter": iteration, "epoch": epoch, "score": score,
               "ts": time.time()}
        lr = self._current_lr(model, iteration)
        if lr is not None:
            rec["lr"] = lr
        if self.report_ratios and hasattr(model, "params"):
            ratios = self._ratios(model)
            if ratios:
                rec["update_ratios"] = ratios
        if self._writer is not None:
            self._writer.add_scalar("score", score, iteration)
            if lr is not None:
                self._writer.add_scalar("lr", lr, iteration)
            for layer, v in rec.get("update_ratios", {}).items():
                self._writer.add_scalar(f"update_ratio/{layer}", v, iteration)
        self._jsonl.write(json.dumps(rec) + "\n")
        self._jsonl.flush()

    def close(self):
        if self._writer is not None:
            self._writer.close()
        self._jsonl.close()
