"""NeuralNetConfiguration — fluent builder parity.

Reference parity: ``org.deeplearning4j.nn.conf.NeuralNetConfiguration.Builder``
→ ``.list()`` → ``ListBuilder`` → ``MultiLayerConfiguration``, and
``.graphBuilder()`` → ``ComputationGraphConfiguration`` (see graph.py).

Global values (updater, weightInit, activation, l1/l2, dropout, dtype policy)
are defaults that individual layers may override — same precedence as the
reference. The dtype policy adds a TPU essential the reference lacks:
params in f32, compute in bf16 (`.data_type(param_dtype, compute_dtype)`).
"""

from __future__ import annotations

import copy
import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, List, Optional

import jax.numpy as jnp

from ..train.updaters import Sgd, Updater
from .layers.base import InputType, Layer


@dataclass
class GlobalConf:
    seed: int = 12345
    updater: Updater = field(default_factory=lambda: Sgd(1e-1))
    bias_updater: Optional[Updater] = None
    weight_init: Any = "xavier"
    activation: Any = None
    l1: float = 0.0
    l2: float = 0.0
    weight_decay: float = 0.0
    dropout: float = 0.0
    weight_noise: Any = None          # IWeightNoise (WeightNoise/DropConnect)
    grad_norm: str = "none"
    grad_norm_threshold: float = 1.0
    param_dtype: Any = jnp.float32
    compute_dtype: Any = None         # e.g. jnp.bfloat16 for mixed precision
    mini_batch: bool = True
    max_num_line_search_iterations: int = 5  # accepted for config parity; unused
    weight_constraints: Any = None    # constrainWeights(...)
    bias_constraints: Any = None      # constrainBias(...)


class NeuralNetConfiguration:
    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._g = GlobalConf()

    # --- fluent setters (reference names, snake_case) ----------------------
    def seed(self, s):
        self._g.seed = int(s)
        return self

    def updater(self, u):
        self._g.updater = u
        return self

    def bias_updater(self, u):
        self._g.bias_updater = u
        return self

    def weight_init(self, wi):
        self._g.weight_init = wi
        return self

    def activation(self, a):
        self._g.activation = a
        return self

    def l1(self, v):
        self._g.l1 = float(v)
        return self

    def l2(self, v):
        self._g.l2 = float(v)
        return self

    def weight_decay(self, v):
        self._g.weight_decay = float(v)
        return self

    def drop_out(self, retain_prob):
        """DL4J semantics: argument is the RETAIN probability."""
        self._g.dropout = 1.0 - float(retain_prob)
        return self

    def dropout_rate(self, rate):
        self._g.dropout = float(rate)
        return self

    def weight_noise(self, wn):
        """DL4J Builder.weightNoise(IWeightNoise) — WeightNoise/DropConnect."""
        self._g.weight_noise = wn
        return self

    def gradient_normalization(self, gn):
        self._g.grad_norm = gn
        return self

    def gradient_normalization_threshold(self, t):
        self._g.grad_norm_threshold = float(t)
        return self

    def data_type(self, param_dtype, compute_dtype=None):
        self._g.param_dtype = param_dtype
        self._g.compute_dtype = compute_dtype
        return self

    def mini_batch(self, b):
        self._g.mini_batch = bool(b)
        return self

    def constrain_weights(self, *constraints):
        self._g.weight_constraints = list(constraints)
        return self

    def constrain_bias(self, *constraints):
        self._g.bias_constraints = list(constraints)
        return self

    def constrain_all_parameters(self, *constraints):
        self._g.weight_constraints = list(constraints)
        self._g.bias_constraints = list(constraints)
        return self

    # no-op parity shims (accepted, irrelevant under XLA)
    def optimization_algo(self, *_):
        return self

    def cache_mode(self, *_):
        return self

    def cudnn_algo_mode(self, *_):
        return self

    def list(self) -> "ListBuilder":
        return ListBuilder(self._g)

    def graph_builder(self):
        from .graph import GraphBuilder
        return GraphBuilder(self._g)


class ListBuilder:
    def __init__(self, g: GlobalConf):
        self._g = g
        self._layers: List[Layer] = []
        self._input_type = None

    def layer(self, *args):
        """.layer(L) or .layer(index, L) (index must be append-order)."""
        lyr = args[-1]
        self._layers.append(lyr)
        return self

    def set_input_type(self, it):
        self._input_type = it
        return self

    input_type = set_input_type

    def backprop_type(self, *_):
        return self

    def t_bptt_length(self, *_):
        return self

    def build(self) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(self._g, [copy.deepcopy(l) for l in self._layers],
                                       self._input_type)


def resolve_layer_defaults(layer: Layer, g: GlobalConf) -> Layer:
    """Apply global defaults where the layer didn't specify (reference
    precedence: layer > global)."""
    if layer.weight_init is None:
        layer.weight_init = g.weight_init
    if getattr(layer, "activation", "__missing__") is None:
        layer.activation = g.activation or "identity"
    if layer.l1 == 0.0 and g.l1:
        layer.l1 = g.l1
    if layer.l2 == 0.0 and g.l2:
        layer.l2 = g.l2
    if layer.dropout == 0.0 and g.dropout and layer.has_params():
        layer.dropout = g.dropout
    if layer.weight_noise is None and g.weight_noise is not None \
            and layer.has_params():
        layer.weight_noise = g.weight_noise
    if layer.constraints is None and g.weight_constraints:
        layer.constraints = list(g.weight_constraints)
    if layer.bias_constraints is None and g.bias_constraints:
        layer.bias_constraints = list(g.bias_constraints)
    layer.dtype = g.param_dtype if layer.dtype is jnp.float32 else layer.dtype
    if layer.compute_dtype is None and g.compute_dtype is not None:
        layer.compute_dtype = g.compute_dtype
    # wrap nested layers (Bidirectional/LastTimeStep/TimeDistributed)
    for attr in ("fwd", "inner"):
        sub = getattr(layer, attr, None)
        if isinstance(sub, Layer):
            resolve_layer_defaults(sub, g)
    return layer


@dataclass
class MultiLayerConfiguration:
    globals_: GlobalConf
    layers: List[Layer]
    input_type: Any = None

    def __post_init__(self):
        for lyr in self.layers:
            resolve_layer_defaults(lyr, self.globals_)

    def to_json(self) -> str:
        def enc(o):
            if dataclasses.is_dataclass(o) and not isinstance(o, type):
                d = {"__class__": type(o).__name__}
                for f in dataclasses.fields(o):
                    d[f.name] = enc(getattr(o, f.name))
                return d
            if isinstance(o, (list, tuple)):
                return [enc(v) for v in o]
            if isinstance(o, dict):
                return {k: enc(v) for k, v in o.items()}
            if hasattr(o, "dtype") and hasattr(o, "shape"):
                return {"__array__": True}
            if isinstance(o, type) or (hasattr(o, "name") and hasattr(o, "itemsize")):
                return {"__dtype__": jnp.dtype(o).name}
            try:
                return jnp.dtype(o).name if hasattr(o, "kind") else o
            except Exception:  # noqa: BLE001
                return str(o)
        return json.dumps({"globals": enc(self.globals_), "input_type": self.input_type,
                           "layers": [enc(l) for l in self.layers]}, indent=2, default=str)

    def to_upstream_json(self) -> str:
        """Upstream ``MultiLayerConfiguration.toJson()``-format JSON —
        loadable by DL4J tooling (serde/upstream_dl4j.py, supported-layer
        subset)."""
        from ..serde.upstream_dl4j import mln_conf_to_upstream_json
        return mln_conf_to_upstream_json(self)

    @staticmethod
    def from_upstream_json(data: str) -> "MultiLayerConfiguration":
        """Upstream ``MultiLayerConfiguration.fromJson()`` analogue."""
        from ..serde.upstream_dl4j import mln_conf_from_upstream_json
        return mln_conf_from_upstream_json(data)

    fromJson = from_upstream_json      # reference naming
