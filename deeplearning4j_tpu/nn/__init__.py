"""deeplearning4j_tpu.nn — layer API (DL4J-NN analogue)."""

from . import activations, losses, weights
from .conf import MultiLayerConfiguration, NeuralNetConfiguration
from .layers.attention import (AttentionVertex, LearnedSelfAttentionLayer,
                               RecurrentAttentionLayer, SelfAttentionLayer)
from .layers.base import Ctx, InputType, Layer
from .layers.conv import (Convolution1DLayer, Convolution3DLayer,
                          ConvolutionLayer, Cropping1D, Cropping2D,
                          Cropping3D, Deconvolution2D, Deconvolution3D,
                          DepthToSpaceLayer,
                          DepthwiseConvolution2D, GlobalPoolingLayer,
                          LocallyConnected1D, LocallyConnected2D, PoolingType,
                          SeparableConvolution2D, SpaceToDepthLayer,
                          Subsampling1DLayer, Subsampling3DLayer,
                          SubsamplingLayer, Upsampling1D, Upsampling2D,
                          Upsampling3D, ZeroPadding1DLayer,
                          ZeroPadding3DLayer, ZeroPaddingLayer)
from .layers.capsule import (CapsuleLayer, CapsuleStrengthLayer,
                             PrimaryCapsules)
from .layers.core import (ActivationLayer, AlphaDropout,
                          CenterLossOutputLayer, CnnLossLayer, DenseLayer,
                          DropoutLayer, ElementWiseMultiplicationLayer,
                          EmbeddingLayer, EmbeddingSequenceLayer,
                          GaussianDropout, GaussianNoise, LossLayer,
                          MaskLayer, OCNNOutputLayer, OutputLayer, PReLULayer,
                          PermuteLayer, ReshapeLayer, RnnOutputLayer,
                          SpatialDropout)
from .layers.objdetect import (DetectedObject, Yolo2OutputLayer,
                               get_predicted_objects, nms)
from .layers.samediff_layer import (SameDiffLambdaLayer, SameDiffLambdaVertex,
                                    SameDiffLayer, SameDiffOutputLayer,
                                    SameDiffVertex, SDLayerParams)
from .layers.variational import VariationalAutoencoder
from .layers.wrappers import (FrozenLayer, FrozenLayerWithBackprop,
                              MaskZeroLayer, RepeatVector,
                              TimeDistributedLayer)
from .layers.norm import (BatchNormalization, LayerNormalization,
                          LocalResponseNormalization, RMSNorm)
from .layers.recurrent import (GRU, LSTM, BaseRecurrent, Bidirectional,
                               BidirectionalMode, ConvLSTM2D,
                               GravesBidirectionalLSTM, GravesLSTM,
                               LastTimeStep, SimpleRnn, TimeDistributed)
from .listeners import (CheckpointListener, CollectScoresListener,
                        EvaluativeListener, NanScoreWatchdog,
                        PerformanceListener, ProfilingListener,
                        ScoreIterationListener, StatsListener,
                        TimeIterationListener)
from .losses import Loss
from .computation_graph import ComputationGraph
from .multi_layer_network import MultiLayerNetwork
from .vertices import (ElementWiseVertex, L2NormalizeVertex, L2Vertex,
                       MergeVertex, PreprocessorVertex, ReshapeVertex,
                       ScaleVertex, ShiftVertex, StackVertex, SubsetVertex,
                       UnstackVertex)
from .transfer import (FineTuneConfiguration, TransferLearning,
                       TransferLearningHelper)
from .weightnoise import (BernoulliDistribution, DropConnect,
                          NormalDistribution, UniformDistribution,
                          WeightNoise)
from .weights import WeightInit
