"""Weight initialization — parity with ``org.deeplearning4j.nn.weights.WeightInit``.

Each initializer is `fn(key, shape, fan_in, fan_out, dtype) -> array`.
Resolve via `get(name)`; names match the DL4J enum, lowercase.
DL4J fan semantics: for dense W of shape (nIn, nOut), fan_in=nIn, fan_out=nOut;
for convs (kh,kw,cin,cout): fan_in=kh*kw*cin, fan_out=kh*kw*cout.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def compute_fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = math.prod(shape[:-2])
    return shape[-2] * receptive, shape[-1] * receptive


def zero(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def one(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def constant(value):
    def init(key, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.full(shape, value, dtype)
    return init


def normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    """DL4J NORMAL: N(0, 1/sqrt(fanIn))."""
    return jax.random.normal(key, shape, dtype) / jnp.asarray(math.sqrt(fan_in), dtype)


def gaussian(key, shape, fan_in, fan_out, dtype=jnp.float32):
    """DL4J (legacy) DISTRIBUTION-free gaussian: N(0,1)."""
    return jax.random.normal(key, shape, dtype)


def truncated_normal(key, shape, fan_in, fan_out, dtype=jnp.float32, std=1.0):
    return std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)


def uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    """DL4J UNIFORM: U(-a, a), a = sqrt(3/fanIn)."""
    a = math.sqrt(3.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, -a, a)


def xavier(key, shape, fan_in, fan_out, dtype=jnp.float32):
    """DL4J XAVIER: N(0, 2/(fanIn+fanOut))."""
    std = math.sqrt(2.0 / (fan_in + fan_out))
    return std * jax.random.normal(key, shape, dtype)


def xavier_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -a, a)


def xavier_fan_in(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) / jnp.asarray(math.sqrt(fan_in), dtype)


def xavier_legacy(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = math.sqrt(1.0 / (fan_in + fan_out))
    return std * jax.random.normal(key, shape, dtype)


def relu_init(key, shape, fan_in, fan_out, dtype=jnp.float32):
    """DL4J RELU == He normal: N(0, 2/fanIn)."""
    return math.sqrt(2.0 / fan_in) * jax.random.normal(key, shape, dtype)


def relu_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(6.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, -a, a)


he_normal = relu_init
he_uniform = relu_uniform


def lecun_normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return math.sqrt(1.0 / fan_in) * jax.random.normal(key, shape, dtype)


def lecun_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(3.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, -a, a)


def sigmoid_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -a, a)


def orthogonal(key, shape, fan_in, fan_out, dtype=jnp.float32, gain=1.0):
    if len(shape) < 2:
        return jax.random.normal(key, shape, dtype)
    rows = math.prod(shape[:-1])
    cols = shape[-1]
    a = jax.random.normal(key, (max(rows, cols), min(rows, cols)), jnp.float32)
    q, r = jnp.linalg.qr(a)
    q = q * jnp.sign(jnp.diagonal(r))
    q = q.T if rows < cols else q
    return (gain * q[:rows, :cols]).reshape(shape).astype(dtype)


def identity_init(key, shape, fan_in, fan_out, dtype=jnp.float32):
    if len(shape) == 2:
        return jnp.eye(shape[0], shape[1], dtype=dtype)
    # conv identity: delta kernel at spatial center
    w = jnp.zeros(shape, dtype)
    ctr = tuple(s // 2 for s in shape[:-2])
    eye = jnp.eye(shape[-2], shape[-1], dtype=dtype)
    return w.at[ctr].set(eye)


def var_scaling(scale=1.0, mode="fan_in", distribution="truncated_normal"):
    """VAR_SCALING_* family."""
    def init(key, shape, fan_in, fan_out, dtype=jnp.float32):
        if mode == "fan_in":
            n = fan_in
        elif mode == "fan_out":
            n = fan_out
        else:
            n = (fan_in + fan_out) / 2.0
        variance = scale / max(1.0, n)
        if distribution == "truncated_normal":
            std = math.sqrt(variance) / 0.8796256610342398  # correct truncation
            return std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
        if distribution == "normal":
            return math.sqrt(variance) * jax.random.normal(key, shape, dtype)
        a = math.sqrt(3.0 * variance)
        return jax.random.uniform(key, shape, dtype, -a, a)
    return init


_REGISTRY = {
    "zero": zero, "ones": one, "one": one,
    "normal": normal, "gaussian": gaussian, "truncated_normal": truncated_normal,
    "uniform": uniform,
    "xavier": xavier, "xavier_uniform": xavier_uniform,
    "xavier_fan_in": xavier_fan_in, "xavier_legacy": xavier_legacy,
    "relu": relu_init, "relu_uniform": relu_uniform,
    "he_normal": he_normal, "he_uniform": he_uniform,
    "lecun_normal": lecun_normal, "lecun_uniform": lecun_uniform,
    "sigmoid_uniform": sigmoid_uniform,
    "orthogonal": orthogonal, "identity": identity_init,
    "var_scaling_normal_fan_in": var_scaling(1.0, "fan_in", "normal"),
    "var_scaling_normal_fan_out": var_scaling(1.0, "fan_out", "normal"),
    "var_scaling_normal_fan_avg": var_scaling(1.0, "fan_avg", "normal"),
    "var_scaling_uniform_fan_in": var_scaling(1.0, "fan_in", "uniform"),
    "var_scaling_uniform_fan_out": var_scaling(1.0, "fan_out", "uniform"),
    "var_scaling_uniform_fan_avg": var_scaling(1.0, "fan_avg", "uniform"),
}


class WeightInit:
    """DL4J-style enum constants: WeightInit.XAVIER etc. (string-valued)."""

    ZERO = "zero"
    ONES = "ones"
    NORMAL = "normal"
    TRUNCATED_NORMAL = "truncated_normal"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    XAVIER_LEGACY = "xavier_legacy"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    HE_NORMAL = "he_normal"
    HE_UNIFORM = "he_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    ORTHOGONAL = "orthogonal"
    IDENTITY = "identity"


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown weight init '{name_or_fn}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
