"""Shared pieces of the fit_scanned contract (MLN / CG / ParallelWrapper):
the listener/anomaly gate and the post-epoch listener replay. One copy —
a change to scanned-loop listener semantics must not be applied three
times."""

from __future__ import annotations

import numpy as np


def check_scan_listeners(net):
    """Scanned epochs fetch losses after the dispatch: only listeners that
    opted into deferred scores may run, and per-step anomaly gating cannot."""
    for ls in net.listeners:
        if not getattr(ls, "deferred_score_ok", False):
            raise ValueError(
                f"listener {type(ls).__name__} needs exact per-"
                "iteration model state; use fit()")
    if getattr(net, "_anomaly_detector", None) is not None:
        raise ValueError("gradient anomaly detection gates per step; "
                         "use fit()")


def replay_scan_listeners(net, losses, n_batches):
    """Fire per-iteration listeners from the scanned loss history (ONE
    device fetch for all K losses), then epoch-end hooks."""
    if not net.listeners:
        return
    host_losses = np.asarray(losses)
    base = net._step_count - n_batches
    for i, lv in enumerate(host_losses):
        for listener in net.listeners:
            listener.iteration_done(net, base + i + 1,
                                    net.epoch_count - 1, float(lv))
    for listener in net.listeners:
        if hasattr(listener, "on_epoch_end"):
            listener.on_epoch_end(net)
