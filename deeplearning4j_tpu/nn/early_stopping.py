"""Early stopping — parity with ``org.deeplearning4j.earlystopping``.

EarlyStoppingConfiguration + EarlyStoppingTrainer with epoch/iteration
termination conditions (MaxEpochs, ScoreImprovementEpochs patience, MaxTime,
MaxScore, InvalidScore) and score calculators (loss or evaluation-based on a
held-out iterator). Restores the best model like the reference.
"""

from __future__ import annotations

import copy
import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp


# --- termination conditions -------------------------------------------------

class MaxEpochsTerminationCondition:
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score, history) -> bool:
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition:
    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement

    def terminate(self, epoch, score, history) -> bool:
        if len(history) <= self.patience:
            return False
        best_older = min(history[:-self.patience])
        best_recent = min(history[-self.patience:])
        return best_recent > best_older - self.min_improvement


class MaxTimeTerminationCondition:
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = time.monotonic()

    def terminate(self, epoch, score, history) -> bool:
        return (time.monotonic() - self._start) > self.max_seconds


class MaxScoreTerminationCondition:
    """Terminate (failure) when score exceeds a bound — divergence guard."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, epoch, score, history) -> bool:
        return score > self.max_score


class InvalidScoreTerminationCondition:
    def terminate(self, epoch, score, history) -> bool:
        return math.isnan(score) or math.isinf(score)


# --- score calculators ------------------------------------------------------

class DataSetLossCalculator:
    """Average loss over an iterator (reference DataSetLossCalculator)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        for ds in self.iterator:
            total += model.score(ds) * ds.num_examples()
            n += ds.num_examples()
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        return total / max(n, 1)


class ClassificationScoreCalculator:
    """1 - accuracy so that lower is better (consistent with loss)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        return 1.0 - model.evaluate(self.iterator).accuracy()


@dataclass
class EarlyStoppingConfiguration:
    epoch_termination_conditions: List[Any] = field(default_factory=list)
    iteration_termination_conditions: List[Any] = field(default_factory=list)
    score_calculator: Any = None
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any
    score_vs_epoch: dict = field(default_factory=dict)


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, model, train_iterator):
        self.config = config
        self.model = model
        self.iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        best_score = math.inf
        best_epoch = -1
        best_params = None
        best_states = None
        history: List[float] = []
        scores = {}
        epoch = 0
        reason, details = "MaxEpochs", ""
        while True:
            self._fit_epoch()
            if (epoch + 1) % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.model) \
                    if cfg.score_calculator else self._train_score()
                history.append(score)
                scores[epoch] = score
                if score < best_score:
                    best_score, best_epoch = score, epoch
                    # real copies, not references: the next epoch's jitted
                    # step DONATES the current param buffers (no-op on CPU,
                    # but on TPU a bare reference would be a deleted array)
                    best_params = jax.tree_util.tree_map(
                        jnp.copy, self.model.params)
                    best_states = jax.tree_util.tree_map(
                        jnp.copy, self.model.states)
                stop = False
                for cond in cfg.epoch_termination_conditions:
                    if cond.terminate(epoch, score, history):
                        reason = type(cond).__name__
                        details = f"epoch={epoch} score={score}"
                        stop = True
                        break
                if stop:
                    break
            epoch += 1
        best_model = self.model
        if best_params is not None and not cfg.save_last_model:
            best_model = self.model.clone() if hasattr(self.model, "clone") else self.model
            best_model.params = best_params
            best_model.states = best_states
        return EarlyStoppingResult(reason, details, best_epoch, best_score,
                                   epoch + 1, best_model, scores)

    def _fit_epoch(self):
        self.model.fit(self.iterator, epochs=1)

    def _train_score(self):
        ds = next(iter(self.iterator))
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        return self.model.score(ds)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping around a multi-device trainer (reference:
    ``org.deeplearning4j.earlystopping.trainer.EarlyStoppingParallelTrainer``
    wrapping ParallelWrapper). Accepts any trainer with
    ``fit(iterator, epochs=1)`` and a ``.net`` (ParallelWrapper,
    ParameterAveragingTrainer); scoring/condition logic runs on the wrapped
    net whose params the trainer keeps in sync."""

    def __init__(self, config: EarlyStoppingConfiguration, trainer,
                 train_iterator):
        if not hasattr(trainer, "net") or not hasattr(trainer, "fit"):
            raise TypeError("trainer must expose .net and .fit (e.g. "
                            "ParallelWrapper / ParameterAveragingTrainer)")
        super().__init__(config, trainer.net, train_iterator)
        self.trainer = trainer

    def _fit_epoch(self):
        self.trainer.fit(self.iterator, epochs=1)
