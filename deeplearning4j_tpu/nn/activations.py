"""Activation functions — parity with DL4J's ``org.nd4j.linalg.activations.Activation`` enum.

All are pure elementwise fns (XLA fuses them into adjacent matmuls/convs, so
unlike the reference there is no separate "activation op" cost on TPU).
Resolve by name via `get(name)`; names match the DL4J enum, lowercase.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_SQRT_2_OVER_PI = 0.7978845608028654


def identity(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jax.nn.relu6(x)


def leakyrelu(x, alpha=0.01):
    return jax.nn.leaky_relu(x, alpha)


def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


def selu(x):
    return jax.nn.selu(x)


def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


def gelu(x):
    """DL4J ActivationGELU (tanh approximation is its default path)."""
    return jax.nn.gelu(x, approximate=True)


def gelu_exact(x):
    return jax.nn.gelu(x, approximate=False)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def logsoftmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def tanh(x):
    return jnp.tanh(x)


def rationaltanh(x):
    """DL4J ActivationRationalTanh: 1.7159 * tanh(2x/3) rational approximation."""
    ax = jnp.abs(x)
    a = 1.0 + ax + x * x + 1.41645 * x * x * x * x
    return jnp.sign(x) * (1.0 - 1.0 / a) * 1.7159


def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def swish(x):
    return jax.nn.silu(x)


def mish(x):
    return jax.nn.mish(x)


def cube(x):
    return x * x * x


def thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


def gumbel_softmax(x, tau=1.0, axis=-1):
    return jax.nn.softmax(x / tau, axis=axis)


_REGISTRY = {
    "identity": identity, "linear": identity,
    "relu": relu, "relu6": relu6, "leakyrelu": leakyrelu, "elu": elu,
    "selu": selu, "celu": celu, "gelu": gelu, "gelu_exact": gelu_exact,
    "sigmoid": sigmoid, "hardsigmoid": hardsigmoid,
    "softmax": softmax, "logsoftmax": logsoftmax,
    "tanh": tanh, "rationaltanh": rationaltanh, "rectifiedtanh": rectifiedtanh,
    "hardtanh": hardtanh, "softplus": softplus, "softsign": softsign,
    "swish": swish, "silu": swish, "mish": mish, "cube": cube,
    "thresholdedrelu": thresholdedrelu, "gumbel_softmax": gumbel_softmax,
}


def get(name_or_fn):
    """Resolve an activation by DL4J enum name (case-insensitive) or pass through."""
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown activation '{name_or_fn}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def names():
    return sorted(_REGISTRY)
