"""Weight noise — train-time parameter perturbation.

Reference parity: ``org.deeplearning4j.nn.conf.weightnoise.{IWeightNoise,
WeightNoise, DropConnect}`` and the ``org.nd4j.linalg.api.rng.distribution``
samplers they take.

TPU-first redesign: the reference mutates a cached noisy copy of each
parameter inside Layer.preOutput; here noise is a PURE function
``params -> noisy_params`` applied at the network-forward call site, inside
jit, keyed off the per-layer fold of the step rng. Gradients flow through the
noise exactly as in the reference (noise applied to the weight used in the
forward; the gradient w.r.t. the clean parameter follows by chain rule — for
additive noise and DropConnect masks that is the masked/unit gradient).

Parameter classification: leaves with ndim >= 2 are weights (W, RW, conv
kernels, embeddings); 1-d/0-d leaves (b, gamma, beta, running stats live in
state, not params) are bias-like and only touched when ``apply_to_bias``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------- samplers
@dataclass
class NormalDistribution:
    """org.nd4j...impl.NormalDistribution(mean, std)."""

    mean: float = 0.0
    std: float = 1.0

    def sample(self, key, shape, dtype):
        return (self.mean
                + self.std * jax.random.normal(key, shape)).astype(dtype)


@dataclass
class UniformDistribution:
    """org.nd4j...impl.UniformDistribution(lower, upper)."""

    lower: float = 0.0
    upper: float = 1.0

    def sample(self, key, shape, dtype):
        return jax.random.uniform(key, shape, minval=self.lower,
                                  maxval=self.upper).astype(dtype)


@dataclass
class BernoulliDistribution:
    """org.nd4j...impl.BernoulliDistribution(p) — samples {0, 1}."""

    p: float = 0.5

    def sample(self, key, shape, dtype):
        return jax.random.bernoulli(key, self.p, shape).astype(dtype)


# ------------------------------------------------------------ noise configs
class IWeightNoise:
    """Contract: ``apply(params, key) -> params`` (pure, jit-safe)."""

    def apply(self, params, key):
        raise NotImplementedError

    # -- shared traversal ---------------------------------------------------
    def _map_leaves(self, params, key, fn):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(key, max(len(leaves), 1))
        out = [fn(k, leaf) for k, leaf in zip(keys, leaves)]
        return jax.tree_util.tree_unflatten(treedef, out)


@dataclass
class WeightNoise(IWeightNoise):
    """Additive or multiplicative distribution noise on weights
    (reference WeightNoise(Distribution, applyToBias, additive))."""

    distribution: Any = None
    apply_to_bias: bool = False
    additive: bool = True

    def __post_init__(self):
        if self.distribution is None:
            self.distribution = NormalDistribution(0.0, 0.01)

    def apply(self, params, key):
        def one(k, w):
            if not hasattr(w, "ndim") or not jnp.issubdtype(
                    jnp.asarray(w).dtype, jnp.floating):
                return w
            if w.ndim < 2 and not self.apply_to_bias:
                return w
            noise = self.distribution.sample(k, w.shape, w.dtype)
            return w + noise if self.additive else w * noise

        return self._map_leaves(params, key, one)


@dataclass
class DropConnect(IWeightNoise):
    """Bernoulli weight masking (reference DropConnect(weightRetainProb)):
    each weight kept with prob p and scaled 1/p (inverted, so inference
    needs no rescale — matches the reference's DropOut op semantics)."""

    weight_retain_prob: float = 0.5
    apply_to_bias: bool = False

    def apply(self, params, key):
        p = self.weight_retain_prob

        def one(k, w):
            if not hasattr(w, "ndim") or not jnp.issubdtype(
                    jnp.asarray(w).dtype, jnp.floating):
                return w
            if w.ndim < 2 and not self.apply_to_bias:
                return w
            mask = jax.random.bernoulli(k, p, w.shape)
            return jnp.where(mask, w / p, 0.0).astype(w.dtype)

        return self._map_leaves(params, key, one)


def _effective_noise(layer):
    """Weight noise set on a layer nested inside a wrapper
    (TimeDistributed/MaskZero/Frozen/Bidirectional) must still fire: walk
    the wrapper chain. Wrappers delegate init(), so the wrapper-level params
    ARE the inner layer's params and the noise map applies directly (for
    Bidirectional it covers both directions — intended: the reference
    resolves noise per underlying layer the same way)."""
    seen = set()
    while layer is not None and id(layer) not in seen:
        wn = getattr(layer, "weight_noise", None)
        if wn is not None:
            return wn
        seen.add(id(layer))
        layer = (getattr(layer, "layer", None) or getattr(layer, "fwd", None)
                 or getattr(layer, "inner", None))
    return None


def maybe_apply_weight_noise(layer, params, rng, train):
    """Network-forward hook: returns the (possibly noisy) params to apply
    the layer with. No-op unless the layer has weight noise, training is on,
    and an rng is threaded."""
    if not train or rng is None:
        return params
    wn = _effective_noise(layer)
    if wn is None:
        return params
    # Fold constant far outside the dropout stream's 997+j range so a
    # many-input vertex can never alias its dropout key with this one.
    return wn.apply(params, jax.random.fold_in(rng, 100003))
