"""ComputationGraphConfiguration builder.

Reference parity: ``org.deeplearning4j.nn.conf.ComputationGraphConfiguration
.GraphBuilder`` — addInputs / addLayer / addVertex / setOutputs /
setInputTypes. The DAG is validated and topologically sorted at build time;
at run time the whole topology traces into ONE jaxpr (no per-vertex
interpreter like the reference's ComputationGraph.topologicalOrder loop).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .conf import GlobalConf, resolve_layer_defaults
from .layers.base import Layer
from .vertices import GraphVertex


@dataclass
class NodeDef:
    name: str
    op: Any                      # Layer | GraphVertex
    inputs: List[str]


@dataclass
class ComputationGraphConfiguration:
    globals_: GlobalConf
    inputs: List[str]
    outputs: List[str]
    nodes: Dict[str, NodeDef]
    topo_order: List[str]
    input_types: Optional[List] = None

    def to_upstream_json(self) -> str:
        """Upstream ``ComputationGraphConfiguration.toJson()``-format JSON
        (serde/upstream_dl4j.py, supported layer/vertex subset)."""
        from ..serde.upstream_dl4j import cg_conf_to_upstream_json
        return cg_conf_to_upstream_json(self)

    @staticmethod
    def from_upstream_json(data: str) -> "ComputationGraphConfiguration":
        """Upstream ``ComputationGraphConfiguration.fromJson()`` analogue."""
        from ..serde.upstream_dl4j import cg_conf_from_upstream_json
        return cg_conf_from_upstream_json(data)

    fromJson = from_upstream_json      # reference naming


class GraphBuilder:
    def __init__(self, g: GlobalConf):
        self._g = g
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._nodes: Dict[str, NodeDef] = {}
        self._input_types = None

    def add_inputs(self, *names) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs) -> "GraphBuilder":
        if name in self._nodes or name in self._inputs:
            raise ValueError(f"duplicate node name {name}")
        lyr = copy.deepcopy(layer)
        lyr.name = name
        resolve_layer_defaults(lyr, self._g)
        self._nodes[name] = NodeDef(name, lyr, list(inputs))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs) -> "GraphBuilder":
        if name in self._nodes or name in self._inputs:
            raise ValueError(f"duplicate node name {name}")
        self._nodes[name] = NodeDef(name, vertex, list(inputs))
        return self

    def set_outputs(self, *names) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def set_input_types(self, *types) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def build(self) -> ComputationGraphConfiguration:
        known = set(self._inputs)
        for n, node in self._nodes.items():
            for inp in node.inputs:
                if inp not in self._inputs and inp not in self._nodes:
                    raise ValueError(f"node '{n}' references unknown input '{inp}'")
        # Kahn topological sort
        order: List[str] = []
        placed = set(self._inputs)
        pending = dict(self._nodes)
        while pending:
            progress = False
            for name in list(pending):
                if all(i in placed for i in pending[name].inputs):
                    order.append(name)
                    placed.add(name)
                    del pending[name]
                    progress = True
            if not progress:
                raise ValueError(f"cycle in graph involving {sorted(pending)}")
        for out in self._outputs:
            if out not in self._nodes:
                raise ValueError(f"output '{out}' is not a node")
        return ComputationGraphConfiguration(
            self._g, list(self._inputs), list(self._outputs),
            self._nodes, order, self._input_types)
