"""Custom layers defined through the SameDiff graph API.

Reference parity: ``org.deeplearning4j.nn.conf.layers.samediff`` —
`SameDiffLayer` (defineLayer/defineParameters/initializeParameters),
`SameDiffLambdaLayer`, `SameDiffOutputLayer` (defineLayer returns the loss,
activationsVertexName selects the inference output), `SameDiffVertex` and
`SameDiffLambdaVertex` (multi-input ComputationGraph vertices).

TPU-first redesign: the user's `define_layer` builds a `SameDiff` graph once
(ops are shape-polymorphic jnp closures), which lowers via
`SameDiff.make_function` to a pure fn and traces into the surrounding
network's single jaxpr — no separate execution session, no graph-runtime
boundary, and `jax.grad` differentiates straight through the user graph
(replaces the reference's doDiff plumbing for custom layers).
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ...autodiff.samediff import SameDiff
from .base import Ctx, Layer
from ..vertices import GraphVertex


class SDLayerParams:
    """Parameter-shape registry handed to `define_parameters`.

    Reference: ``SDLayerParams.addWeightParam/addBiasParam``. Weights get the
    layer's weight_init; biases get bias_init.
    """

    def __init__(self):
        self.weight_shapes: Dict[str, Tuple[int, ...]] = {}
        self.bias_shapes: Dict[str, Tuple[int, ...]] = {}

    def add_weight_param(self, name: str, *shape):
        self.weight_shapes[name] = tuple(int(s) for s in shape)

    def add_bias_param(self, name: str, *shape):
        self.bias_shapes[name] = tuple(int(s) for s in shape)

    # pythonic aliases
    add_weight = add_weight_param
    add_bias = add_bias_param


def _build_graph(define, param_names, *, n_inputs=1, with_mask=False,
                 with_labels=False):
    """Build the user graph once and lower it to a pure function
    fn(var_values, *feeds); feeds order is inputs, then labels, then mask."""
    sd = SameDiff.create()
    inputs = [sd.placeholder(f"input{i}" if n_inputs > 1 else "input")
              for i in range(n_inputs)]
    pvars = {n: sd.var(n, value=jnp.zeros(())) for n in param_names}
    labels = sd.placeholder("labels") if with_labels else None
    mask = sd.placeholder("mask") if with_mask else None
    out = define(sd, inputs, pvars, labels, mask)
    placeholders = [v.name for v in inputs]
    if with_labels:
        placeholders.append("labels")
    if with_mask:
        placeholders.append("mask")
    outs = out if isinstance(out, (list, tuple)) else [out]
    return sd.make_function(list(outs), placeholders)


@dataclass
class _SDGraphModule(Layer):
    """Shared machinery: param registry, default init, pickle-safe fn cache."""

    def define_parameters(self, params: SDLayerParams) -> None:
        pass

    def initialize_parameters(self, key, name, shape, kind):
        if kind == "bias":
            return jnp.full(shape, self.bias_init, self.dtype)
        return self._make_weight(key, shape)

    def __getstate__(self):
        # the lowered-graph cache holds closures — rebuilt lazily after load
        d = dict(self.__dict__)
        d.pop("_sd_fns", None)
        return d

    def _param_shapes(self) -> Dict[str, Tuple[Tuple[int, ...], str]]:
        reg = SDLayerParams()
        self.define_parameters(reg)
        shapes = {n: (s, "weight") for n, s in reg.weight_shapes.items()}
        shapes.update({n: (s, "bias") for n, s in reg.bias_shapes.items()})
        return shapes

    def _init_params(self, key):
        params = {}
        for name, (shape, kind) in sorted(self._param_shapes().items()):
            key, sub = jax.random.split(key)
            params[name] = self.initialize_parameters(sub, name, shape, kind)
        return params

    def _fn_cache(self):
        return self.__dict__.setdefault("_sd_fns", {})


@dataclass
class SameDiffLayer(_SDGraphModule):
    """Base for user-defined layers built from a SameDiff graph.

    Subclass and override:
      - ``define_parameters(params: SDLayerParams)`` — declare param shapes
      - ``define_layer(sd, layer_input, params, mask=None) -> SDVariable``
      - optionally ``initialize_parameters(key, name, shape, kind)`` per-param
    """

    def define_layer(self, sd: SameDiff, layer_input, params, mask=None):
        raise NotImplementedError

    def _accepts_mask(self) -> bool:
        return "mask" in inspect.signature(self.define_layer).parameters

    def _fn(self, masked: bool):
        cache = self._fn_cache()
        key = ("layer", masked)
        if key not in cache:
            names = list(self._param_shapes())

            def define(sd, inputs, pvars, labels, mask):
                if masked:
                    return self.define_layer(sd, inputs[0], pvars, mask=mask)
                return self.define_layer(sd, inputs[0], pvars)

            cache[key] = _build_graph(define, names, with_mask=masked)
        return cache[key]

    def init(self, key, input_shape):
        params = self._init_params(key)
        fn = self._fn(masked=False)
        out = jax.eval_shape(
            lambda p, x: fn(p, x), params,
            jax.ShapeDtypeStruct((2,) + tuple(input_shape), self.dtype))
        return params, {}, tuple(out.shape[1:])

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        # a define_layer without a mask= parameter ignores the feature mask —
        # the same semantics as built-in layers (DenseLayer etc. leave masks
        # to the loss) and the reference's null-mask defineLayer contract
        if ctx.mask is not None and self._accepts_mask():
            y = self._fn(masked=True)(params, x, ctx.mask)
        else:
            y = self._fn(masked=False)(params, x)
        return y, state


@dataclass
class SameDiffLambdaLayer(SameDiffLayer):
    """Param-free SameDiff layer from a ``fn(sd, layer_input)`` callable
    (or override ``define_layer``). Reference: SameDiffLambdaLayer.
    Note: to survive ModelSerializer pickling, pass a module-level function,
    not a lambda."""

    fn: Optional[Callable] = None

    def define_layer(self, sd, layer_input, params, mask=None):
        if self.fn is None:
            raise NotImplementedError(
                "pass fn=lambda sd, x: ... or override define_layer")
        return self.fn(sd, layer_input)

    def has_params(self):
        return False


@dataclass
class SameDiffOutputLayer(_SDGraphModule):
    """Output layer whose loss is a SameDiff graph.

    Override ``define_layer(sd, layer_input, labels, params)`` (optionally
    with a ``mask=None`` kwarg to receive the labels mask) returning a scalar
    loss SDVariable, and ``activations_vertex_name() -> str`` naming the
    graph variable that `output()` should return (it must not depend on
    labels). Reference: SameDiffOutputLayer.
    """

    def define_layer(self, sd, layer_input, labels, params):  # -> loss var
        raise NotImplementedError

    def activations_vertex_name(self) -> str:
        raise NotImplementedError

    def _accepts_mask(self) -> bool:
        return "mask" in inspect.signature(self.define_layer).parameters

    def _out_fns(self, masked: bool = False):
        cache = self._fn_cache()
        key = ("out", masked)
        if key not in cache:
            names = list(self._param_shapes())
            holder = {}

            def define(sd, inputs, pvars, labels, mask):
                if masked:
                    loss = self.define_layer(sd, inputs[0], labels, pvars,
                                             mask=mask)
                else:
                    loss = self.define_layer(sd, inputs[0], labels, pvars)
                act = sd.get_variable(self.activations_vertex_name())
                holder["act"] = act
                return [loss, act]

            fn = _build_graph(define, names, with_labels=True,
                              with_mask=masked)
            # activations-only function over the same graph: the labels/mask
            # placeholders are never traced because activations can't depend
            # on them
            act_fn = holder["act"].sd.make_function([holder["act"]], ["input"])
            cache[key] = (fn, act_fn)
        return cache[key]

    def init(self, key, input_shape):
        params = self._init_params(key)
        _, act_fn = self._out_fns()
        out = jax.eval_shape(
            lambda p, x: act_fn(p, x), params,
            jax.ShapeDtypeStruct((2,) + tuple(input_shape), self.dtype))
        return params, {}, tuple(out.shape[1:])

    def apply(self, params, state, x, ctx: Ctx):
        _, act_fn = self._out_fns()
        return act_fn(params, self._cast_in(x)), state

    def compute_loss(self, params, x, labels, mask=None):
        if mask is not None:
            if not self._accepts_mask():
                raise ValueError(
                    f"{type(self).__name__}: a labels mask was supplied but "
                    "define_layer has no mask= parameter — add one to handle "
                    "masked losses (silently ignoring it would train wrong)")
            fn, _ = self._out_fns(masked=True)
            loss, _ = fn(params, self._cast_in(x), labels, mask)
            return loss
        fn, _ = self._out_fns()
        loss, _ = fn(params, self._cast_in(x), labels)
        return loss


@dataclass
class SameDiffVertex(_SDGraphModule):
    """Multi-input, parameterized ComputationGraph vertex defined via a
    SameDiff graph. Override ``define_parameters`` and
    ``define_vertex(sd, inputs: list, params) -> SDVariable``.
    Reference: SameDiffVertex."""

    multi_input = True

    def define_vertex(self, sd, inputs: List, params):
        raise NotImplementedError

    def _fn(self, n_inputs: int):
        cache = self._fn_cache()
        if n_inputs not in cache:
            names = list(self._param_shapes())

            def define(sd, inputs, pvars, labels, mask):
                return self.define_vertex(sd, list(inputs), pvars)

            cache[n_inputs] = _build_graph(define, names, n_inputs=n_inputs)
        return cache[n_inputs]

    def init(self, key, input_shapes):
        # input_shapes: list of per-input shapes (batch-less)
        if input_shapes and not isinstance(input_shapes[0], (tuple, list)):
            input_shapes = [input_shapes]
        params = self._init_params(key)
        fn = self._fn(len(input_shapes))
        outs = jax.eval_shape(
            lambda p, *xs: fn(p, *xs), params,
            *[jax.ShapeDtypeStruct((2,) + tuple(s), self.dtype)
              for s in input_shapes])
        return params, {}, tuple(outs.shape[1:])

    def apply(self, params, state, xs, ctx: Ctx):
        if not isinstance(xs, (list, tuple)):
            xs = [xs]
        xs = [self._cast_in(x) for x in xs]
        return self._fn(len(xs))(params, *xs), state


class SameDiffLambdaVertex(GraphVertex):
    """Param-free multi-input vertex from ``fn(sd, *inputs)``.
    Reference: SameDiffLambdaVertex."""

    def __init__(self, fn: Callable):
        self.fn = fn
        self._fns = {}

    def __getstate__(self):
        return {"fn": self.fn}

    def __setstate__(self, d):
        self.fn = d["fn"]
        self._fns = {}

    def _fn(self, n_inputs):
        if n_inputs not in self._fns:
            def define(sd, inputs, pvars, labels, mask):
                return self.fn(sd, *inputs)

            self._fns[n_inputs] = _build_graph(define, [], n_inputs=n_inputs)
        return self._fns[n_inputs]

    def out_shape(self, shapes):
        fn = self._fn(len(shapes))
        out = jax.eval_shape(
            lambda *xs: fn({}, *xs),
            *[jax.ShapeDtypeStruct((2,) + tuple(s), jnp.float32)
              for s in shapes])
        return tuple(out.shape[1:])

    def apply(self, inputs, ctx=None):
        return self._fn(len(inputs))({}, *inputs)
