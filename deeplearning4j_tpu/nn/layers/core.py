"""Core feed-forward layers — DenseLayer, OutputLayer, Embedding, Dropout, etc.

Reference parity: ``org.deeplearning4j.nn.conf.layers.{DenseLayer,
OutputLayer, RnnOutputLayer, LossLayer, EmbeddingLayer,
EmbeddingSequenceLayer, DropoutLayer, ActivationLayer,
ElementWiseMultiplicationLayer, PReLULayer, CenterLossOutputLayer}``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .. import activations as _act
from .. import losses as _losses
from .base import Ctx, Layer, apply_time_mask


@dataclass
class DenseLayer(Layer):
    """Fully connected: y = act(x @ W + b). W: (nIn, nOut) like the reference."""

    n_in: Optional[int] = None
    n_out: int = 0
    activation: Any = "identity"
    has_bias: bool = True

    def init(self, key, input_shape):
        n_in = self.n_in or input_shape[-1]
        params = {"W": self._make_weight(key, (n_in, self.n_out), n_in, self.n_out)}
        if self.has_bias:
            params["b"] = self._make_bias((self.n_out,))
        return params, {}, input_shape[:-1] + (self.n_out,)

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        w = params["W"].astype(x.dtype)
        y = x @ w
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state


@dataclass
class ActivationLayer(Layer):
    activation: Any = "relu"

    def init(self, key, input_shape):
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        return self.activation_fn()(x), state

    def has_params(self):
        return False


@dataclass
class DropoutLayer(Layer):
    """Inverted dropout; `rate` is KEEP probability complement?  No —

    DL4J convention: `dropOut(0.5)` RETAINS with p=0.5. Here `rate` is the
    DROP probability (modern convention); `retain_prob` accepted for parity.
    """

    rate: float = 0.5

    @classmethod
    def from_retain(cls, retain_prob):
        return cls(rate=1.0 - retain_prob)

    def init(self, key, input_shape):
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        if not ctx.train or self.rate <= 0.0:
            return x, state
        k = ctx.split_rng()
        keep = 1.0 - self.rate
        m = jax.random.bernoulli(k, keep, x.shape)
        return jnp.where(m, x / keep, 0.0).astype(x.dtype), state

    def has_params(self):
        return False


@dataclass
class GaussianDropout(Layer):
    rate: float = 0.5

    def init(self, key, input_shape):
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        if not ctx.train or self.rate <= 0.0:
            return x, state
        k = ctx.split_rng()
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + std * jax.random.normal(k, x.shape, x.dtype)
        return x * noise, state

    def has_params(self):
        return False


@dataclass
class GaussianNoise(Layer):
    stddev: float = 0.1

    def init(self, key, input_shape):
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        if not ctx.train:
            return x, state
        k = ctx.split_rng()
        return x + self.stddev * jax.random.normal(k, x.shape, x.dtype), state

    def has_params(self):
        return False


@dataclass
class AlphaDropout(Layer):
    """SELU-compatible dropout (keeps self-normalizing property)."""

    rate: float = 0.1

    def init(self, key, input_shape):
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        if not ctx.train or self.rate <= 0.0:
            return x, state
        alpha_p = -1.7580993408473766
        keep = 1.0 - self.rate
        k = ctx.split_rng()
        m = jax.random.bernoulli(k, keep, x.shape)
        a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
        b = -a * alpha_p * (1 - keep)
        return (a * jnp.where(m, x, alpha_p) + b).astype(x.dtype), state

    def has_params(self):
        return False


@dataclass
class SpatialDropout(Layer):
    """Drops whole channels (B,...,C). DL4J SpatialDropout."""

    rate: float = 0.5

    def init(self, key, input_shape):
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        if not ctx.train or self.rate <= 0.0:
            return x, state
        k = ctx.split_rng()
        keep = 1.0 - self.rate
        shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        m = jax.random.bernoulli(k, keep, shape)
        return jnp.where(m, x / keep, 0.0).astype(x.dtype), state

    def has_params(self):
        return False


@dataclass
class EmbeddingLayer(Layer):
    """Index → vector. Input (B,) int ids; output (B, nOut)."""

    n_in: Optional[int] = None   # vocab size
    n_out: int = 0
    has_bias: bool = False
    activation: Any = "identity"

    def init(self, key, input_shape):
        params = {"W": self._make_weight(key, (self.n_in, self.n_out), self.n_in, self.n_out)}
        if self.has_bias:
            params["b"] = self._make_bias((self.n_out,))
        return params, {}, (self.n_out,)

    def apply(self, params, state, x, ctx: Ctx):
        ids = x.astype(jnp.int32)
        if ids.ndim > 1 and ids.shape[-1] == 1:
            ids = ids[..., 0]
        y = jnp.take(params["W"], ids, axis=0)
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@dataclass
class EmbeddingSequenceLayer(EmbeddingLayer):
    """Sequence of ids (B, T) → (B, T, nOut) [NTC]."""

    def init(self, key, input_shape):
        params, state, _ = super().init(key, input_shape)
        t = input_shape[0] if input_shape else None
        return params, state, (t, self.n_out)


@dataclass
class ElementWiseMultiplicationLayer(Layer):
    """y = act(x * w + b), elementwise learned scaling (nIn == nOut)."""

    n_in: Optional[int] = None
    n_out: int = 0
    activation: Any = "identity"

    def init(self, key, input_shape):
        n = self.n_out or self.n_in or input_shape[-1]
        return ({"W": jnp.ones((n,), self.dtype), "b": self._make_bias((n,))},
                {}, input_shape[:-1] + (n,))

    def apply(self, params, state, x, ctx: Ctx):
        return self.activation_fn()(x * params["W"] + params["b"]), state


@dataclass
class PReLULayer(Layer):
    """Parametric ReLU with learned per-feature alpha."""

    alpha_init: float = 0.0
    shared_axes: tuple = ()

    def init(self, key, input_shape):
        shape = tuple(1 if (i in self.shared_axes) else s
                      for i, s in enumerate(input_shape))
        return {"alpha": jnp.full(shape, self.alpha_init, self.dtype)}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        a = params["alpha"]
        return jnp.where(x >= 0, x, a * x), state


@dataclass
class LossLayer(Layer):
    """No params: applies activation + computes loss vs labels (LossLayer)."""

    activation: Any = "identity"
    loss: Any = "mse"

    def init(self, key, input_shape):
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        return self.activation_fn()(x), state

    def compute_loss(self, pre_activation, labels, mask=None):
        lf = str(self.loss).lower() if not callable(self.loss) else None
        if lf in _losses.LOGITS_VARIANTS and str(self.activation).lower() in ("softmax", "sigmoid"):
            return _losses.LOGITS_VARIANTS[lf](labels, pre_activation, mask=mask)
        fn = _losses.get(self.loss)
        return fn(labels, self.activation_fn()(pre_activation), mask=mask)

    def has_params(self):
        return False


@dataclass
class CnnLossLayer(LossLayer):
    """Per-pixel loss over (B,H,W,C) activations, no params (CnnLossLayer).

    Labels are (B,H,W,C); mask (B,H,W) zeroes excluded pixels. The loss
    flattens space into the batch dim so every loss fn sees (N, C).
    """

    def compute_loss(self, pre_activation, labels, mask=None):
        c = pre_activation.shape[-1]
        flat = pre_activation.reshape(-1, c)
        flat_labels = labels.reshape(-1, labels.shape[-1])
        flat_mask = mask.reshape(-1) if mask is not None else None
        return super().compute_loss(flat, flat_labels, mask=flat_mask)


@dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (org.deeplearning4j.nn.conf.layers.OutputLayer).

    `apply` returns activated predictions; the training path calls
    `pre_activation` + `compute_loss` so softmax/sigmoid losses fuse with
    logits for numerical stability (replaces the reference's
    LossMCXENT+softmax special-casing).
    """

    loss: Any = "mcxent"
    activation: Any = "softmax"

    def pre_activation(self, params, x):
        y = x @ params["W"].astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return y

    def compute_loss(self, params, x, labels, mask=None):
        logits = self.pre_activation(params, x)
        lf = str(self.loss).lower() if not callable(self.loss) else None
        if lf in _losses.LOGITS_VARIANTS and str(self.activation).lower() in ("softmax", "sigmoid"):
            return _losses.LOGITS_VARIANTS[lf](labels, logits, mask=mask)
        fn = _losses.get(self.loss)
        return fn(labels, self.activation_fn()(logits), mask=mask)


@dataclass
class RnnOutputLayer(OutputLayer):
    """Per-timestep output head: (B,T,nIn) → (B,T,nOut), time-distributed.

    Masking: label_mask (B,T) zeroes padded steps in the loss (reference:
    RnnOutputLayer + LossFunction masking).
    """

    def init(self, key, input_shape):
        params, state, _ = super().init(key, input_shape)
        t = input_shape[0] if len(input_shape) == 2 else None
        return params, state, (t, self.n_out)

    def apply(self, params, state, x, ctx: Ctx):
        y, state = DenseLayer.apply(self, params, state, x, ctx)
        return apply_time_mask(y, ctx.mask), state

    def compute_loss(self, params, x, labels, mask=None):
        logits = self.pre_activation(params, x)  # (B,T,C)
        lf = str(self.loss).lower() if not callable(self.loss) else None
        if lf in _losses.LOGITS_VARIANTS and str(self.activation).lower() in ("softmax", "sigmoid"):
            b, t = logits.shape[0], logits.shape[1]
            flat_mask = mask.reshape(b * t) if mask is not None else None
            return _losses.LOGITS_VARIANTS[lf](
                labels.reshape(b * t, -1) if labels.ndim == 3 else labels.reshape(b * t),
                logits.reshape(b * t, -1), mask=flat_mask)
        fn = _losses.get(self.loss)
        return fn(labels, self.activation_fn()(logits), mask=mask)


@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (intra-class compactness). Keeps per-class
    centers in `state`, updated with EMA like the reference's alpha."""

    alpha: float = 0.05
    lambda_: float = 2e-4

    def init(self, key, input_shape):
        params, state, out = super().init(key, input_shape)
        n_in = self.n_in or input_shape[-1]
        state = dict(state)
        state["centers"] = jnp.zeros((self.n_out, n_in), self.dtype)
        return params, state, out

    def compute_loss(self, params, x, labels, mask=None, state=None):
        base = super().compute_loss(params, x, labels, mask)
        if state is None:
            return base
        cls = jnp.argmax(labels, axis=-1)
        centers = state["centers"]
        diff = x - centers[cls]
        center_loss = 0.5 * jnp.mean(jnp.sum(jnp.square(diff), axis=-1))
        return base + self.lambda_ * center_loss

    def update_state(self, state, x, labels):
        cls = jnp.argmax(labels, axis=-1)
        centers = state["centers"]
        diff = centers[cls] - x
        counts = jnp.zeros((self.n_out,), x.dtype).at[cls].add(1.0)
        delta = jnp.zeros_like(centers).at[cls].add(diff)
        delta = delta / (1.0 + counts)[:, None]
        return {**state, "centers": centers - self.alpha * delta}


@dataclass
class MaskLayer(Layer):
    """Zeroes activations at masked timesteps and otherwise passes through
    (org.deeplearning4j.nn.conf.layers.util.MaskLayer). Useful after layers
    that pollute padded steps (e.g. bidirectional RNNs)."""

    def init(self, key, input_shape):
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        if ctx.mask is None:
            return x, state
        if x.ndim == 3:
            return apply_time_mask(x, ctx.mask), state
        return x * ctx.mask.reshape(ctx.mask.shape[0],
                                    *([1] * (x.ndim - 1))).astype(x.dtype), state

    def has_params(self):
        return False


@dataclass
class OCNNOutputLayer(Layer):
    """One-class neural network output layer for anomaly detection
    (org.deeplearning4j.nn.conf.ocnn.OCNNOutputLayer; Chalapathy et al. 2018).

    score(x) = w . act(V x); loss = 0.5||V||^2 + 0.5||w||^2
               + (1/nu) * mean(relu(r - score)) - r.
    The margin r tracks the nu-quantile of scores via an EMA held in state
    (the reference recomputes r from a score window every epoch; an in-jit
    EMA of the batch quantile is the streaming TPU-friendly equivalent).
    `labels` are ignored (one-class training uses only inliers) — evaluate
    with `score < r` => anomaly.
    """

    n_in: Optional[int] = None
    hidden_size: int = 32
    nu: float = 0.04
    activation: Any = "sigmoid"
    window_size: int = 10000      # kept for reference-API compatibility
    initial_r_value: float = 0.1
    r_update_rate: float = 0.1    # EMA rate for the quantile target

    def init(self, key, input_shape):
        n_in = self.n_in or input_shape[-1]
        k1, k2 = jax.random.split(key)
        params = {"V": self._make_weight(k1, (n_in, self.hidden_size)),
                  "w": self._make_weight(k2, (self.hidden_size, 1))}
        state = {"r": jnp.asarray(self.initial_r_value, self.dtype)}
        return params, state, (1,)

    def ocnn_score(self, params, x):
        h = self.activation_fn()(x @ params["V"])
        return (h @ params["w"])[..., 0]

    def apply(self, params, state, x, ctx: Ctx):
        return self.ocnn_score(params, x)[:, None], state

    def compute_loss(self, params, x, labels, mask=None, state=None):
        score = self.ocnn_score(params, x)
        r = state["r"] if state is not None else jnp.asarray(
            self.initial_r_value, score.dtype)
        reg = 0.5 * jnp.sum(jnp.square(params["V"])) \
            + 0.5 * jnp.sum(jnp.square(params["w"]))
        hinge = jnp.mean(jax.nn.relu(r - score)) / self.nu
        return reg + hinge - r

    def update_state(self, state, x, params):
        score = jax.lax.stop_gradient(self.ocnn_score(params, x))
        q = jnp.quantile(score, self.nu)
        r = state["r"] * (1.0 - self.r_update_rate) + self.r_update_rate * q
        return {**state, "r": r.astype(state["r"].dtype)}


@dataclass
class ReshapeLayer(Layer):
    """Reshape per-example activations (keras Reshape / reference
    ReshapeVertex as a sequential layer). target_shape excludes batch."""

    target_shape: Any = None

    def init(self, key, input_shape):
        import numpy as _npm
        if self.target_shape is None:
            raise ValueError("target_shape required")
        tgt = tuple(int(t) for t in self.target_shape)
        n_in = int(_npm.prod(input_shape))
        if tgt.count(-1) > 1:
            raise ValueError(f"at most one -1 wildcard allowed, got {tgt}")
        if -1 in tgt:                       # keras Reshape wildcard
            known = int(-_npm.prod(tgt))    # product of the fixed dims
            if known == 0 or n_in % known:
                raise ValueError(f"cannot reshape {input_shape} -> {tgt}")
            tgt = tuple(n_in // known if t == -1 else t for t in tgt)
        elif int(_npm.prod(tgt)) != n_in:
            raise ValueError(f"cannot reshape {input_shape} -> {tgt}")
        return {}, {}, tgt

    def apply(self, params, state, x, ctx: Ctx):
        return x.reshape((x.shape[0],) + tuple(self.target_shape)), state

    def has_params(self):
        return False


@dataclass
class PermuteLayer(Layer):
    """Permute per-example dims, 1-indexed like keras Permute((2, 1))."""

    dims: Any = None

    def init(self, key, input_shape):
        if self.dims is None:
            raise ValueError("dims required")
        d = tuple(int(i) for i in self.dims)
        if sorted(d) != list(range(1, len(input_shape) + 1)):
            raise ValueError(f"dims {d} must permute 1..{len(input_shape)}")
        return {}, {}, tuple(input_shape[i - 1] for i in d)

    def apply(self, params, state, x, ctx: Ctx):
        return x.transpose((0,) + tuple(self.dims)), state

    def has_params(self):
        return False
