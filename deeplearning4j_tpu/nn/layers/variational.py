"""Variational autoencoder layer.

Reference parity: ``org.deeplearning4j.nn.conf.layers.variational.
VariationalAutoencoder`` (+ reconstruction distributions
``GaussianReconstructionDistribution`` / ``BernoulliReconstructionDistribution``)
and the pretrain path in ``o.d.nn.layers.variational.VariationalAutoencoder``.

TPU-first: encoder/decoder are fused MLP stacks inside one jitted ELBO
function; the reparameterisation trick uses explicit PRNG keys. As in the
reference, when used inside a net the layer's forward pass outputs the mean
of q(z|x); pretraining maximises the ELBO via ``elbo_loss``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import activations as _act
from .base import Ctx, Layer


@dataclass
class VariationalAutoencoder(Layer):
    """VAE as a (pretrainable) layer: nIn -> encoder -> z (nOut) -> decoder -> nIn."""

    n_in: int = None
    n_out: int = 32                                   # latent size
    encoder_layer_sizes: Sequence[int] = (256,)
    decoder_layer_sizes: Sequence[int] = (256,)
    activation: Any = "leakyrelu"
    pzx_activation: Any = "identity"                  # on the q(z|x) mean head
    reconstruction_distribution: str = "gaussian"     # or "bernoulli"
    num_samples: int = 1

    def _mlp_init(self, key, sizes, n_in):
        params = []
        for i, n in enumerate(sizes):
            key, k = jax.random.split(key)
            params.append({"W": self._make_weight(k, (n_in, n)),
                           "b": self._make_bias((n,))})
            n_in = n
        return params, n_in, key

    def init(self, key, input_shape):
        n_in = self.n_in or input_shape[-1]
        self.n_in = n_in
        enc, h, key = self._mlp_init(key, self.encoder_layer_sizes, n_in)
        key, k1, k2 = jax.random.split(key, 3)
        mean_head = {"W": self._make_weight(k1, (h, self.n_out)),
                     "b": self._make_bias((self.n_out,))}
        logvar_head = {"W": self._make_weight(k2, (h, self.n_out)),
                       "b": self._make_bias((self.n_out,))}
        dec, h2, key = self._mlp_init(key, self.decoder_layer_sizes, self.n_out)
        key, k3 = jax.random.split(key)
        out_dim = n_in * (2 if self.reconstruction_distribution == "gaussian" else 1)
        recon_head = {"W": self._make_weight(k3, (h2, out_dim)),
                      "b": self._make_bias((out_dim,))}
        params = {"encoder": enc, "mean": mean_head, "logvar": logvar_head,
                  "decoder": dec, "recon": recon_head}
        return params, {}, (self.n_out,)

    # ---- pieces ------------------------------------------------------------
    def _mlp(self, layers, x):
        f = _act.get(self.activation)
        for p in layers:
            x = f(x @ p["W"].astype(x.dtype) + p["b"].astype(x.dtype))
        return x

    def encode(self, params, x):
        h = self._mlp(params["encoder"], x)
        mean = _act.get(self.pzx_activation)(
            h @ params["mean"]["W"] + params["mean"]["b"])
        logvar = h @ params["logvar"]["W"] + params["logvar"]["b"]
        return mean, logvar

    def decode(self, params, z):
        h = self._mlp(params["decoder"], z)
        return h @ params["recon"]["W"] + params["recon"]["b"]

    def apply(self, params, state, x, ctx: Ctx):
        mean, _ = self.encode(params, self._cast_in(x))
        return mean, state

    # ---- ELBO (pretrain objective) ----------------------------------------
    def _recon_log_prob(self, recon_raw, x):
        if self.reconstruction_distribution == "bernoulli":
            logits = recon_raw
            return -jnp.sum(jnp.maximum(logits, 0) - logits * x
                            + jnp.log1p(jnp.exp(-jnp.abs(logits))), axis=-1)
        mu, logvar = jnp.split(recon_raw, 2, axis=-1)
        return -0.5 * jnp.sum(logvar + jnp.square(x - mu) / jnp.exp(logvar)
                              + jnp.log(2 * jnp.pi), axis=-1)

    def elbo_loss(self, params, x, rng):
        """Negative ELBO (to minimise): recon NLL + KL(q(z|x) || N(0,1))."""
        x = x.reshape(x.shape[0], -1)
        mean, logvar = self.encode(params, x)
        kl = 0.5 * jnp.sum(jnp.exp(logvar) + jnp.square(mean) - 1.0 - logvar, axis=-1)
        nll = 0.0
        for i in range(self.num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, i), mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            nll = nll - self._recon_log_prob(self.decode(params, z), x)
        return jnp.mean(nll / self.num_samples + kl)

    # ---- reference API: reconstruction / generation ------------------------
    def reconstruct(self, params, x, rng=None):
        mean, logvar = self.encode(params, x.reshape(x.shape[0], -1))
        z = mean if rng is None else \
            mean + jnp.exp(0.5 * logvar) * jax.random.normal(rng, mean.shape)
        raw = self.decode(params, z)
        if self.reconstruction_distribution == "bernoulli":
            return jax.nn.sigmoid(raw)
        return jnp.split(raw, 2, axis=-1)[0]

    def generate_given_z(self, params, z):
        raw = self.decode(params, z)
        if self.reconstruction_distribution == "bernoulli":
            return jax.nn.sigmoid(raw)
        return jnp.split(raw, 2, axis=-1)[0]

    def reconstruction_probability(self, params, x, rng, num_samples=5):
        """Mean log p(x|z) over samples of q(z|x) (reconstructionLogProbability)."""
        x = x.reshape(x.shape[0], -1)
        mean, logvar = self.encode(params, x)
        total = 0.0
        for i in range(num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, i), mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            total = total + self._recon_log_prob(self.decode(params, z), x)
        return total / num_samples

    def pretrain_fit(self, params, x_batches, updater=None, rng=None,
                     epochs: int = 1):
        """Layerwise pretraining loop (reference MultiLayerNetwork.pretrain)."""
        from ...train.updaters import Adam
        import optax
        opt = (updater or Adam(1e-3)).to_optax()
        opt_state = opt.init(params)
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        @jax.jit
        def step(params, opt_state, x, key):
            loss, grads = jax.value_and_grad(self.elbo_loss)(params, x, key)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        loss = None
        for _ in range(epochs):
            for x in x_batches:
                rng, k = jax.random.split(rng)
                params, opt_state, loss = step(params, opt_state, jnp.asarray(x), k)
        return params, loss
