"""Recurrent layers — SimpleRnn, LSTM, GravesLSTM (peepholes), GRU,
Bidirectional, LastTimeStep.

Reference parity: ``org.deeplearning4j.nn.conf.layers.{LSTM, GravesLSTM,
GravesBidirectionalLSTM, SimpleRnn, recurrent.Bidirectional,
recurrent.LastTimeStep}``. The reference runs these through cuDNN RNN
helpers; the TPU-native design is a single ``lax.scan`` over time with the
input projection hoisted OUT of the scan — one big (B*T, 4H) matmul on the
MXU up front, then only the small recurrent matmul inside the loop. Layout
is NTC (batch, time, channels); the reference's NCW is converted at the
data layer.

Masking: `ctx.mask` (B, T) freezes hidden state on padded steps, matching
the reference's masked RNN semantics (output at padded steps is zeroed by
downstream mask application).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .base import Ctx, Layer, apply_time_mask


def _split_key(key, n):
    return jax.random.split(key, n)


@dataclass
class BaseRecurrent(Layer):
    n_in: Optional[int] = None
    n_out: int = 0
    activation: Any = "tanh"

    def _gates(self):
        raise NotImplementedError

    # ---- streaming single-step API (reference rnnTimeStep) ---------------
    # Subclasses implement _cell(params, carry, xproj); apply()'s scan and
    # step_apply() share it, so the cell math lives once. _cell returns
    # either h_new (carry == output) or (new_carry, y) (e.g. LSTM).

    def init_carry(self, batch: int, dtype):
        return jnp.zeros((batch, self.n_out), dtype)

    def step_apply(self, params, carry, xt, ctx: Ctx):
        """One timestep of stateful inference: xt (B, C) → (y (B, H), carry).
        The TPU analogue of MultiLayerNetwork.rnnTimeStep's per-layer state."""
        xt = self._cast_in(xt)
        xproj = xt @ params["W"].astype(xt.dtype) + params["b"].astype(xt.dtype)
        out = self._cell(params, carry, xproj)
        if isinstance(out, tuple):
            new_carry, y = out
        else:
            new_carry = y = out
        # keep the carry dtype stable across steps (lax.scan requires it)
        new_carry = jax.tree_util.tree_map(
            lambda n, o: n.astype(o.dtype), new_carry, carry)
        return y, new_carry


@dataclass
class SimpleRnn(BaseRecurrent):
    """h_t = act(x_t W + h_{t-1} R + b)."""

    def init(self, key, input_shape):
        t, c = input_shape
        c = self.n_in or c
        k1, k2 = _split_key(key, 2)
        params = {
            "W": self._make_weight(k1, (c, self.n_out), c, self.n_out),
            "RW": self._make_weight(k2, (self.n_out, self.n_out), self.n_out, self.n_out),
            "b": self._make_bias((self.n_out,)),
        }
        return params, {}, (t, self.n_out)

    def _cell(self, params, h_prev, xproj):
        """xproj = x_t @ W + b already applied; returns h_new."""
        return self.activation_fn()(xproj + h_prev @ params["RW"].astype(xproj.dtype))

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        w, b = params["W"].astype(x.dtype), params["b"].astype(x.dtype)
        xw = x @ w + b  # (B,T,H) — hoisted MXU matmul
        mask = ctx.mask
        h0 = jnp.zeros((x.shape[0], self.n_out), x.dtype)

        def step(h, inp):
            xt, mt = inp
            h_new = self._cell(params, h, xt)
            if mt is not None:
                h_new = jnp.where(mt[:, None] > 0, h_new, h)
            return h_new, h_new

        ms = mask.swapaxes(0, 1) if mask is not None else None
        xs = xw.swapaxes(0, 1)  # (T,B,H)
        if ms is None:
            _, hs = lax.scan(lambda h, xt: step(h, (xt, None)), h0, xs)
        else:
            _, hs = lax.scan(step, h0, (xs, ms))
        y = hs.swapaxes(0, 1)
        return apply_time_mask(y, mask), state


@dataclass
class LSTM(BaseRecurrent):
    """Standard LSTM (no peepholes) — gate order [i, f, o, g] like the reference.

    forget_gate_bias: DL4J initializes forget bias to 1.0 by default.
    """

    forget_gate_bias: float = 1.0
    gate_activation: Any = "sigmoid"
    # Fused pallas whole-sequence kernel policy. "auto" now resolves to
    # the lax.scan path: the r5 on-chip A/B (process-isolated arms,
    # scripts/diag_attn_r5_out.json, 2026-08-01, b256×T60×h256) measured
    # scan ahead of the kernel in BOTH dtypes — bf16 11.0M vs 5.0M
    # tokens/s, f32 4.4M vs 2.5M. Same verdict as the fused-BN kernel
    # (docs/PERF.md): XLA's scan fusion beats the hand kernel at these
    # recurrent shapes, where per-grid-step overhead dominates the tiny
    # (B,4H) gate matmuls. True forces the kernel (interpret mode
    # off-TPU — how CI covers it); False always uses lax.scan.
    fused: Any = "auto"

    def _has_peepholes(self):
        return False

    def _can_fuse(self, mask) -> bool:
        if self.fused is False or mask is not None:
            return False
        if self.activation != "tanh" or self.gate_activation != "sigmoid":
            return False
        # only an explicit True engages the kernel — "auto" = scan (see
        # the `fused` field comment for the measured adjudication)
        return self.fused is True

    def init(self, key, input_shape):
        t, c = input_shape
        c = self.n_in or c
        k1, k2, k3 = _split_key(key, 3)
        h = self.n_out
        b = jnp.zeros((4 * h,), self.dtype)
        b = b.at[h:2 * h].set(self.forget_gate_bias)
        params = {
            "W": self._make_weight(k1, (c, 4 * h), c, h),
            "RW": self._make_weight(k2, (h, 4 * h), h, h),
            "b": b,
        }
        if self._has_peepholes():
            params["pI"] = jnp.zeros((h,), self.dtype)
            params["pF"] = jnp.zeros((h,), self.dtype)
            params["pO"] = jnp.zeros((h,), self.dtype)
        return params, {}, (t, h)

    def _cell(self, params, carry, xproj):
        """xproj = x_t @ W + b; carry (h, c); returns ((h', c'), h')."""
        h = self.n_out
        act = self.activation_fn()
        from .. import activations as _a
        gate_act = _a.get(self.gate_activation)
        h_prev, c_prev = carry
        rw = params["RW"].astype(xproj.dtype)
        z = xproj + h_prev @ rw
        zi, zf, zo, zg = z[:, :h], z[:, h:2 * h], z[:, 2 * h:3 * h], z[:, 3 * h:]
        if self._has_peepholes():
            zi = zi + c_prev * params["pI"].astype(xproj.dtype)
            zf = zf + c_prev * params["pF"].astype(xproj.dtype)
        i = gate_act(zi)
        f = gate_act(zf)
        g = act(zg)
        c_new = f * c_prev + i * g
        if self._has_peepholes():
            zo = zo + c_new * params["pO"].astype(xproj.dtype)
        o = gate_act(zo)
        h_new = o * act(c_new)
        return (h_new, c_new), h_new

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        h = self.n_out
        w, b = params["W"].astype(x.dtype), params["b"].astype(x.dtype)
        xw = x @ w + b  # hoisted (B,T,4H) MXU matmul
        mask = ctx.mask
        b0 = x.shape[0]
        from ...kernels.fused_lstm import fits_vmem
        if self._can_fuse(mask) and fits_vmem(b0, h, x.dtype.itemsize):
            from ...kernels.fused_lstm import fused_lstm_seq
            rw = params["RW"].astype(x.dtype)
            if self._has_peepholes():
                peep = jnp.stack([params["pI"], params["pF"], params["pO"]]
                                 ).astype(jnp.float32)
            else:
                peep = jnp.zeros((3, h), jnp.float32)
            z0 = jnp.zeros((b0, h), x.dtype)
            # interpret=None → kernels/_common.interpret_default: compiled
            # on a real TPU, interpret mode elsewhere (how CI covers it)
            y = fused_lstm_seq(xw, rw, peep, z0, z0, None)
            return y, state
        carry0 = (jnp.zeros((b0, h), x.dtype), jnp.zeros((b0, h), x.dtype))

        def step(carry, inp):
            xt, mt = inp
            (h_new, c_new), _ = self._cell(params, carry, xt)
            if mt is not None:
                keep = mt[:, None] > 0
                h_new = jnp.where(keep, h_new, carry[0])
                c_new = jnp.where(keep, c_new, carry[1])
            return (h_new, c_new), h_new

        xs = xw.swapaxes(0, 1)
        if mask is None:
            _, hs = lax.scan(lambda cr, xt: step(cr, (xt, None)), carry0, xs)
        else:
            _, hs = lax.scan(step, carry0, (xs, mask.swapaxes(0, 1)))
        y = hs.swapaxes(0, 1)
        return apply_time_mask(y, mask), state

    def init_carry(self, batch, dtype):
        return (jnp.zeros((batch, self.n_out), dtype),
                jnp.zeros((batch, self.n_out), dtype))


@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013) — the reference's
    GravesLSTM. Same scan, plus diagonal cell→gate weights."""

    def _has_peepholes(self):
        return True


@dataclass
class GRU(BaseRecurrent):
    """GRU — gate order [r, z, n].

    reset_after=True (default, keras v3 semantics): n uses r * (h @ RWn),
    one fused (h, 3H) recurrent matmul per step. reset_after=False (classic
    GRU / keras v1): n uses (r * h) @ RWn — the reset gate applies BEFORE
    the matmul, so the candidate matmul can't fuse with the gate matmul."""

    gate_activation: Any = "sigmoid"
    reset_after: bool = True

    def init(self, key, input_shape):
        t, c = input_shape
        c = self.n_in or c
        k1, k2 = _split_key(key, 2)
        h = self.n_out
        params = {
            "W": self._make_weight(k1, (c, 3 * h), c, h),
            "RW": self._make_weight(k2, (h, 3 * h), h, h),
            "b": jnp.zeros((3 * h,), self.dtype),
        }
        return params, {}, (t, h)

    def _cell(self, params, h_prev, xproj):
        """xproj = x_t @ W + b; returns h_new."""
        h = self.n_out
        act = self.activation_fn()
        from .. import activations as _a
        gate_act = _a.get(self.gate_activation)
        rw = params["RW"].astype(xproj.dtype)
        # optional recurrent bias (keras GRU reset_after=True import): applied
        # inside the reset gate's product, so it can't fold into `b`
        rb = params["rb"].astype(xproj.dtype) if "rb" in params else None
        if self.reset_after:
            hr = h_prev @ rw
            if rb is not None:
                hr = hr + rb
            r = gate_act(xproj[:, :h] + hr[:, :h])
            z = gate_act(xproj[:, h:2 * h] + hr[:, h:2 * h])
            n = act(xproj[:, 2 * h:] + r * hr[:, 2 * h:])
        else:
            hg = h_prev @ rw[:, :2 * h]
            r = gate_act(xproj[:, :h] + hg[:, :h])
            z = gate_act(xproj[:, h:2 * h] + hg[:, h:2 * h])
            n = act(xproj[:, 2 * h:] + (r * h_prev) @ rw[:, 2 * h:])
        return (1 - z) * n + z * h_prev

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        h = self.n_out
        w, b = params["W"].astype(x.dtype), params["b"].astype(x.dtype)
        xw = x @ w + b
        mask = ctx.mask
        h0 = jnp.zeros((x.shape[0], h), x.dtype)

        def step(h_prev, inp):
            xt, mt = inp
            h_new = self._cell(params, h_prev, xt)
            if mt is not None:
                h_new = jnp.where(mt[:, None] > 0, h_new, h_prev)
            return h_new, h_new

        xs = xw.swapaxes(0, 1)
        if mask is None:
            _, hs = lax.scan(lambda hh, xt: step(hh, (xt, None)), h0, xs)
        else:
            _, hs = lax.scan(step, h0, (xs, mask.swapaxes(0, 1)))
        y = hs.swapaxes(0, 1)
        return apply_time_mask(y, mask), state


class BidirectionalMode:
    CONCAT = "concat"
    ADD = "add"
    MUL = "mul"
    AVERAGE = "average"


@dataclass
class Bidirectional(Layer):
    """Wraps any recurrent layer; runs forward + time-reversed copies.

    Reference: ``recurrent.Bidirectional(Mode, layer)``. Mask-aware reversal
    flips only the valid prefix of each sequence.
    """

    fwd: Any = None
    mode: str = BidirectionalMode.CONCAT

    # last_step=True reproduces keras Bidirectional(return_sequences=False):
    # merge(fwd state at t=T-1, bwd state after its full reverse pass). That
    # bwd state sits at t=0 of the re-aligned bwd sequence, so it is NOT the
    # same as LastTimeStep over the merged sequence.
    last_step: bool = False

    def __init__(self, fwd=None, mode=BidirectionalMode.CONCAT,
                 last_step=False, **kw):
        super().__init__(**kw)
        self.fwd = fwd
        self.mode = mode
        self.last_step = last_step

    def init(self, key, input_shape):
        k1, k2 = _split_key(key, 2)
        pf, sf, out = self.fwd.init(k1, input_shape)
        pb, sb, _ = self.fwd.init(k2, input_shape)
        t, h = out
        h_out = 2 * h if self.mode == BidirectionalMode.CONCAT else h
        out = (h_out,) if self.last_step else (t, h_out)
        return {"fwd": pf, "bwd": pb}, {"fwd": sf, "bwd": sb}, out

    def _reverse(self, x, mask):
        if mask is None:
            return jnp.flip(x, axis=1)
        # flip valid prefix: index t -> (len-1-t) for t < len
        lengths = jnp.sum(mask > 0, axis=1).astype(jnp.int32)  # (B,)
        t_idx = jnp.arange(x.shape[1])
        rev_idx = jnp.clip(lengths[:, None] - 1 - t_idx[None, :], 0, x.shape[1] - 1)
        return jnp.take_along_axis(x, rev_idx[:, :, None], axis=1)

    def apply(self, params, state, x, ctx: Ctx):
        yf, sf = self.fwd.apply(params["fwd"], state["fwd"], x, ctx)
        xr = self._reverse(x, ctx.mask)
        yb, sb = self.fwd.apply(params["bwd"], state["bwd"], xr, ctx)
        yb = self._reverse(yb, ctx.mask)
        if self.last_step:
            if ctx.mask is None:
                yf = yf[:, -1]
            else:  # last VALID fwd step
                lengths = jnp.sum(ctx.mask > 0, axis=1).astype(jnp.int32)
                yf = jnp.take_along_axis(
                    yf, jnp.clip(lengths - 1, 0)[:, None, None], axis=1)[:, 0]
            yb = yb[:, 0]  # bwd state after its full pass sits at t=0
        if self.mode == BidirectionalMode.CONCAT:
            y = jnp.concatenate([yf, yb], axis=-1)
        elif self.mode == BidirectionalMode.ADD:
            y = yf + yb
        elif self.mode == BidirectionalMode.MUL:
            y = yf * yb
        else:
            y = 0.5 * (yf + yb)
        return y, {"fwd": sf, "bwd": sb}


@dataclass
class GravesBidirectionalLSTM(Bidirectional):
    """Convenience parity alias: Bidirectional(CONCAT, GravesLSTM)."""

    def __init__(self, n_in=None, n_out=0, activation="tanh", **kw):
        super().__init__(fwd=GravesLSTM(n_in=n_in, n_out=n_out, activation=activation),
                         mode=BidirectionalMode.CONCAT, **kw)


@dataclass
class LastTimeStep(Layer):
    """Wraps a recurrent layer, returning only the last (unmasked) step."""

    inner: Any = None

    def __init__(self, inner=None, **kw):
        super().__init__(**kw)
        self.inner = inner

    def init(self, key, input_shape):
        p, s, out = self.inner.init(key, input_shape)
        return p, s, (out[-1],)

    def apply(self, params, state, x, ctx: Ctx):
        y, s = self.inner.apply(params, state, x, ctx)
        if ctx.mask is not None:
            lengths = jnp.sum(ctx.mask > 0, axis=1).astype(jnp.int32)
            idx = jnp.clip(lengths - 1, 0, y.shape[1] - 1)
            out = jnp.take_along_axis(y, idx[:, None, None], axis=1)[:, 0]
        else:
            out = y[:, -1]
        return out, s


@dataclass
class TimeDistributed(Layer):
    """Applies a feed-forward layer independently at each timestep."""

    inner: Any = None

    def __init__(self, inner=None, **kw):
        super().__init__(**kw)
        self.inner = inner

    def init(self, key, input_shape):
        t = input_shape[0]
        p, s, out = self.inner.init(key, input_shape[1:])
        return p, s, (t,) + out

    def apply(self, params, state, x, ctx: Ctx):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y, s = self.inner.apply(params, state, flat, ctx)
        return y.reshape((b, t) + y.shape[1:]), s


@dataclass
class ConvLSTM2D(Layer):
    """Convolutional LSTM (Shi et al. 2015) over (B, T, H, W, C) sequences.

    Reference parity: the keras ``ConvLSTM2D`` layer that upstream imports
    via ``KerasConvLSTM2D`` (deeplearning4j keras-import). TPU-native
    design mirrors the dense LSTM here: the input convolution over ALL
    timesteps is hoisted out of the scan as one batched (B*T) conv on the
    MXU; only the small recurrent conv (stride 1, same-padded on the output
    grid) runs inside the ``lax.scan``. Gate order [i, f, o, g] like our
    LSTM, so keras [i, f, c, o] kernels are reordered at import.

    ``return_sequences=True`` yields (B, T, H', W', F); False yields the
    (masked) last step (B, H', W', F).
    """

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    convolution_mode: str = "same"   # "same" | "truncate" (keras "valid")
    activation: Any = "tanh"
    gate_activation: Any = "sigmoid"
    forget_gate_bias: float = 1.0
    return_sequences: bool = True
    has_bias: bool = True

    def _pair(self, v):
        from .conv import _pair
        return _pair(v)

    def _out_hw(self, h, w):
        kh, kw = self._pair(self.kernel_size)
        sh, sw = self._pair(self.stride)
        if self.convolution_mode == "same":
            return -(-h // sh), -(-w // sw)
        return (h - kh) // sh + 1, (w - kw) // sw + 1

    def init(self, key, input_shape):
        t, h, w, c = input_shape
        c = self.n_in or c
        kh, kw = self._pair(self.kernel_size)
        f = self.n_out
        k1, k2 = _split_key(key, 2)
        b = jnp.zeros((4 * f,), self.dtype)
        b = b.at[f:2 * f].set(self.forget_gate_bias)
        params = {
            "W": self._make_weight(k1, (kh, kw, c, 4 * f),
                                   kh * kw * c, kh * kw * f),
            "RW": self._make_weight(k2, (kh, kw, f, 4 * f),
                                    kh * kw * f, kh * kw * f),
        }
        if self.has_bias:
            params["b"] = b
        ho, wo = self._out_hw(h, w)
        out = (t, ho, wo, f) if self.return_sequences else (ho, wo, f)
        return params, {}, out

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        bsz, t = x.shape[0], x.shape[1]
        f = self.n_out
        w = params["W"].astype(x.dtype)
        pad = "SAME" if self.convolution_mode == "same" else "VALID"
        # hoisted input conv over all timesteps at once
        xw = lax.conv_general_dilated(
            x.reshape((bsz * t,) + x.shape[2:]), w,
            window_strides=self._pair(self.stride), padding=pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            xw = xw + params["b"].astype(x.dtype)
        ho, wo = xw.shape[1], xw.shape[2]
        xw = xw.reshape(bsz, t, ho, wo, 4 * f)
        rw = params["RW"].astype(x.dtype)
        from .. import activations as _a
        act, gate_act = self.activation_fn(), _a.get(self.gate_activation)
        mask = ctx.mask

        def cell(carry, xt):
            h_prev, c_prev = carry
            z = xt + lax.conv_general_dilated(
                h_prev, rw, window_strides=(1, 1), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            i = gate_act(z[..., :f])
            fg = gate_act(z[..., f:2 * f])
            o = gate_act(z[..., 2 * f:3 * f])
            g = act(z[..., 3 * f:])
            c_new = fg * c_prev + i * g
            h_new = o * act(c_new)
            return h_new, c_new

        def step(carry, inp):
            xt, mt = inp
            h_new, c_new = cell(carry, xt)
            if mt is not None:
                keep = mt[:, None, None, None] > 0
                h_new = jnp.where(keep, h_new, carry[0])
                c_new = jnp.where(keep, c_new, carry[1])
            return (h_new, c_new), h_new

        z0 = jnp.zeros((bsz, ho, wo, f), x.dtype)
        xs = xw.swapaxes(0, 1)  # (T, B, H', W', 4F)
        if mask is None:
            (hT, _), hs = lax.scan(
                lambda cr, xt: step(cr, (xt, None)), (z0, z0), xs)
        else:
            (hT, _), hs = lax.scan(step, (z0, z0), (xs, mask.swapaxes(0, 1)))
        if not self.return_sequences:
            return hT, state  # masked steps froze the state -> hT is last valid
        y = hs.swapaxes(0, 1)
        if mask is not None:
            y = y * mask[:, :, None, None, None].astype(y.dtype)
        return y, state
