"""Attention layers — SelfAttention (MHA), LearnedSelfAttention,
RecurrentAttention.

Reference parity: ``org.deeplearning4j.nn.conf.layers.{SelfAttentionLayer,
LearnedSelfAttentionLayer, RecurrentAttentionLayer}`` (built on SameDiff
MultiHeadDotProductAttention). TPU-first: the core is
``jax.nn.dot_product_attention`` which XLA lowers to a fused (flash-style)
kernel; a Pallas flash-attention path plugs in via `impl="pallas"` (see
`deeplearning4j_tpu.kernels.flash_attention`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .base import Ctx, Layer, apply_time_mask


def _mha_params(layer, key, n_in, n_out, n_heads, head_dim):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    proj = n_heads * head_dim
    return {
        "Wq": layer._make_weight(k1, (n_in, proj), n_in, proj),
        "Wk": layer._make_weight(k2, (n_in, proj), n_in, proj),
        "Wv": layer._make_weight(k3, (n_in, proj), n_in, proj),
        "Wo": layer._make_weight(k4, (proj, n_out), proj, n_out),
    }


def multi_head_attention(params, q_in, kv_in, n_heads, head_dim, mask=None,
                         is_causal=False, impl=None, dtype=None, v_in=None):
    """q_in (B,Tq,C), kv_in (B,Tk,C) → (B,Tq,nOut). mask: (B,Tk) key mask.
    ``v_in`` (B,Tk,Cv) lets values come from a different input than keys
    (AttentionVertex's 3-input form); defaults to kv_in."""
    dt = dtype or q_in.dtype
    b, tq, _ = q_in.shape
    tk = kv_in.shape[1]
    v_src = kv_in if v_in is None else v_in
    q = (q_in @ params["Wq"].astype(dt)).reshape(b, tq, n_heads, head_dim)
    k = (kv_in @ params["Wk"].astype(dt)).reshape(b, tk, n_heads, head_dim)
    v = (v_src @ params["Wv"].astype(dt)).reshape(b, tk, n_heads, head_dim)
    # pallas kernel needs self-attention (Tq == Tk), no key mask, and real TPU
    # hardware ("pallas_interpret" forces interpreter mode for tests/debug)
    use_pallas = (impl == "pallas_interpret"
                  or (impl == "pallas" and jax.default_backend() == "tpu"))
    if use_pallas and mask is None and tq == tk:
        from ...kernels.flash_attention import flash_attention_ntc
        out = flash_attention_ntc(
            q, k, v, causal=is_causal,
            interpret=True if impl == "pallas_interpret" else None)
    else:
        kw = {}
        if mask is not None:
            kw["key_value_seq_lengths"] = None
            amask = mask[:, None, None, :].astype(bool)  # (B,1,1,Tk) -> broadcast (B,H,Tq,Tk)
            kw["mask"] = jnp.broadcast_to(amask, (b, n_heads, tq, tk))
        out = jax.nn.dot_product_attention(q, k, v, is_causal=is_causal, **kw)
    out = out.reshape(b, tq, n_heads * head_dim)
    return out @ params["Wo"].astype(dt)


@dataclass
class SelfAttentionLayer(Layer):
    """Multi-head self attention over (B,T,C) [NTC]."""

    n_in: Optional[int] = None
    n_out: int = 0
    n_heads: int = 1
    head_size: Optional[int] = None
    project_input: bool = True
    is_causal: bool = False
    impl: Optional[str] = None  # None → XLA fused; "pallas" → our kernel

    def _head_dim(self, n_in):
        return self.head_size or (self.n_out or n_in) // self.n_heads

    def init(self, key, input_shape):
        t, c = input_shape
        c = self.n_in or c
        n_out = self.n_out or c
        params = _mha_params(self, key, c, n_out, self.n_heads, self._head_dim(c))
        return params, {}, (t, n_out)

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        y = multi_head_attention(params, x, x, self.n_heads, self._head_dim(x.shape[-1]),
                                 mask=ctx.mask, is_causal=self.is_causal, impl=self.impl)
        return apply_time_mask(y, ctx.mask), state


@dataclass
class LearnedSelfAttentionLayer(Layer):
    """Attention with nQueries learned query vectors → fixed-size output
    (B, nQueries, nOut) regardless of sequence length."""

    n_in: Optional[int] = None
    n_out: int = 0
    n_heads: int = 1
    head_size: Optional[int] = None
    n_queries: int = 1
    impl: Optional[str] = None

    def init(self, key, input_shape):
        t, c = input_shape
        c = self.n_in or c
        n_out = self.n_out or c
        kq, kp = jax.random.split(key)
        hd = self.head_size or n_out // self.n_heads
        params = _mha_params(self, kp, c, n_out, self.n_heads, hd)
        params["Q"] = self._make_weight(kq, (self.n_queries, c), c, c)
        return params, {}, (self.n_queries, n_out)

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        b = x.shape[0]
        q = jnp.broadcast_to(params["Q"].astype(x.dtype), (b,) + params["Q"].shape)
        hd = self.head_size or (self.n_out or x.shape[-1]) // self.n_heads
        y = multi_head_attention(params, q, x, self.n_heads, hd, mask=ctx.mask, impl=self.impl)
        return y, state


@dataclass
class AttentionVertex(Layer):
    """Multi-head dot-product attention as a ComputationGraph vertex
    (reference ``org.deeplearning4j.nn.conf.graph.AttentionVertex``).

    Inputs (all NTC): 1 → self-attention (q = k = v); 2 → (queries,
    keys-and-values); 3 → (queries, keys, values). With
    ``project_input=False`` (requires ``n_heads == 1``) raw scaled
    dot-product attention runs without projections, like the reference.
    """

    multi_input = True

    n_out: int = 0
    n_heads: int = 1
    head_size: Optional[int] = None
    project_input: bool = True
    n_in_queries: Optional[int] = None
    n_in_keys: Optional[int] = None
    n_in_values: Optional[int] = None

    @staticmethod
    def _norm_shapes(input_shapes):
        if input_shapes and not isinstance(input_shapes[0], (tuple, list)):
            input_shapes = [input_shapes]
        if len(input_shapes) == 1:
            input_shapes = input_shapes * 3
        elif len(input_shapes) == 2:
            input_shapes = [input_shapes[0], input_shapes[1], input_shapes[1]]
        elif len(input_shapes) != 3:
            raise ValueError(
                f"AttentionVertex takes 1-3 inputs, got {len(input_shapes)}")
        return input_shapes

    def init(self, key, input_shapes):
        (tq, cq), (_, ck), (_, cv) = self._norm_shapes(input_shapes)
        cq = self.n_in_queries or cq
        ck = self.n_in_keys or ck
        cv = self.n_in_values or cv
        if not self.project_input:
            if self.n_heads != 1:
                raise ValueError(
                    "AttentionVertex(project_input=False) requires "
                    f"n_heads == 1, got {self.n_heads}")
            if cq != ck:
                raise ValueError(
                    "AttentionVertex(project_input=False): query size "
                    f"{cq} must equal key size {ck}")
            if self.n_out and self.n_out != cv:
                raise ValueError(
                    "AttentionVertex(project_input=False) outputs the value "
                    f"width {cv}; n_out={self.n_out} needs project_input="
                    "True (there is no projection to change the width)")
            return {}, {}, (tq, self.n_out or cv)
        n_out = self.n_out or cv
        hd = self.head_size or n_out // self.n_heads
        proj = self.n_heads * hd
        k1, k2, k3, k4 = jax.random.split(key, 4)
        params = {
            "Wq": self._make_weight(k1, (cq, proj), cq, proj),
            "Wk": self._make_weight(k2, (ck, proj), ck, proj),
            "Wv": self._make_weight(k3, (cv, proj), cv, proj),
            "Wo": self._make_weight(k4, (proj, n_out), proj, n_out),
        }
        return params, {}, (tq, n_out)

    def apply(self, params, state, xs, ctx: Ctx):
        if not isinstance(xs, (list, tuple)):
            xs = [xs]
        xs = [self._cast_in(x) for x in xs]
        if len(xs) == 1:
            q_in = k_in = v_src = xs[0]
        elif len(xs) == 2:
            q_in, k_in = xs
            v_src = k_in
        else:
            q_in, k_in, v_src = xs
        mask = ctx.mask
        if mask is not None and (mask.ndim != 2
                                 or mask.shape[1] != k_in.shape[1]):
            mask = None  # feature mask doesn't span the key axis
        if not self.project_input:
            scale = 1.0 / jnp.sqrt(jnp.asarray(q_in.shape[-1], q_in.dtype))
            scores = jnp.einsum("bqc,bkc->bqk", q_in, k_in) * scale
            if mask is not None:
                scores = jnp.where(mask[:, None, :] > 0, scores,
                                   jnp.finfo(scores.dtype).min)
            y = jax.nn.softmax(scores, axis=-1) @ v_src
            return y, state
        n_out = self.n_out or v_src.shape[-1]
        hd = self.head_size or n_out // self.n_heads
        y = multi_head_attention(params, q_in, k_in, self.n_heads, hd,
                                 mask=mask, v_in=v_src)
        return y, state


@dataclass
class RecurrentAttentionLayer(Layer):
    """SimpleRnn cell whose input at each step is augmented with attention
    over the full input sequence (reference RecurrentAttentionLayer)."""

    n_in: Optional[int] = None
    n_out: int = 0
    n_heads: int = 1
    activation: Any = "tanh"

    def init(self, key, input_shape):
        t, c = input_shape
        c = self.n_in or c
        k1, k2, k3, k4 = jax.random.split(key, 4)
        hd = self.n_out // self.n_heads
        params = _mha_params(self, k1, c, self.n_out, self.n_heads, max(hd, 1))
        params["W"] = self._make_weight(k2, (c, self.n_out), c, self.n_out)
        params["RW"] = self._make_weight(k3, (self.n_out, self.n_out), self.n_out, self.n_out)
        params["Wa"] = self._make_weight(k4, (self.n_out, self.n_out), self.n_out, self.n_out)
        params["b"] = self._make_bias((self.n_out,))
        return params, {}, (t, self.n_out)

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        act = self.activation_fn()
        hd = max(self.n_out // self.n_heads, 1)
        # attention context per step computed from x (keys/values static per seq)
        attn = multi_head_attention(params, x, x, self.n_heads, hd, mask=ctx.mask)
        w, rw, wa, b = (params[k].astype(x.dtype) for k in ("W", "RW", "Wa", "b"))
        xw = x @ w + b
        aw = attn @ wa
        h0 = jnp.zeros((x.shape[0], self.n_out), x.dtype)

        def step(h, inp):
            xt, at, mt = inp
            h_new = act(xt + at + h @ rw)
            if mt is not None:
                h_new = jnp.where(mt[:, None] > 0, h_new, h)
            return h_new, h_new

        xs, ats = xw.swapaxes(0, 1), aw.swapaxes(0, 1)
        if ctx.mask is None:
            _, hs = jax.lax.scan(lambda h, i: step(h, (i[0], i[1], None)), h0, (xs, ats))
        else:
            _, hs = jax.lax.scan(step, h0, (xs, ats, ctx.mask.swapaxes(0, 1)))
        y = hs.swapaxes(0, 1)
        return apply_time_mask(y, ctx.mask), state
