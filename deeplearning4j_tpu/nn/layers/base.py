"""Layer protocol + InputType — the TPU-native redesign of DL4J's Layer API.

Reference parity: ``org.deeplearning4j.nn.conf.layers.Layer`` +
``org.deeplearning4j.nn.api.Layer`` (activate/backpropGradient) and
``InputType`` (setInputType/getOutputType shape inference).

TPU-first redesign: a layer is a *config dataclass* with two pure functions —
``init(key, input_shape) -> (params, state, output_shape)`` and
``apply(params, state, x, ctx) -> (y, new_state)``. No backpropGradient:
reverse-mode comes from jax.grad over the composed forward. Params/state are
plain dicts of jax arrays (pytrees), named like the reference ("W", "b",
"gamma", ...) so checkpoints translate 1:1.

Shape convention (batch dim excluded everywhere):
  feed-forward: (nIn,)            — DL4J InputType.feedForward(nIn)
  recurrent:    (T, nIn)  [NTC]   — DL4J uses NCW; NTC is the TPU-native layout
  convolutional:(H, W, C) [NHWC]  — DL4J uses NCHW; NHWC is the TPU-native layout
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .. import activations as _act
from .. import weights as _winit


@dataclass
class Ctx:
    """Per-call context threaded through apply(): train flag, rng, masks."""

    train: bool = False
    rng: Any = None
    mask: Any = None          # feature/time mask (B,) or (B, T)
    label_mask: Any = None

    def split_rng(self):
        if self.rng is None:
            return None
        self.rng, sub = jax.random.split(self.rng)
        return sub


class InputType:
    """DL4J InputType factory — plain shape tuples + kind tags."""

    @staticmethod
    def feed_forward(n):
        return ("ff", (int(n),))

    @staticmethod
    def recurrent(n, timesteps=None):
        return ("rnn", (timesteps, int(n)))

    @staticmethod
    def convolutional(height, width, channels):
        """NHWC output shape (TPU-native); accepts DL4J's (h, w, c) argument order."""
        return ("cnn", (int(height), int(width), int(channels)))

    @staticmethod
    def convolutional_3d(d, h, w, c):
        return ("cnn3d", (int(d), int(h), int(w), int(c)))


@dataclass
class Layer:
    """Base layer config. Subclasses define init/apply; everything is pure."""

    name: Optional[str] = None
    dtype: Any = jnp.float32          # parameter dtype
    compute_dtype: Any = None         # if set, inputs cast before apply (bf16 policy)
    weight_init: Any = None           # None → inherit global default (xavier)
    bias_init: float = 0.0
    l1: float = 0.0                   # per-layer overrides picked up by the net
    l2: float = 0.0
    updater: Any = None               # per-layer updater override
    frozen: bool = False
    dropout: float = 0.0              # input dropout (DL4J layer dropOut)
    weight_noise: Any = None          # IWeightNoise (WeightNoise/DropConnect)
    constraints: Any = None           # weight constraints (constrainWeights)
    bias_constraints: Any = None      # bias constraints (constrainBias)

    def __post_init__(self):
        # Fail fast on config typos — apply-time is too late to learn an
        # activation or weight-init name is wrong.
        act = getattr(self, "activation", None)
        if act is not None:
            _act.get(act)
        if self.weight_init is not None:
            _winit.get(self.weight_init)

    # ---- to be overridden -------------------------------------------------
    def init(self, key, input_shape):
        """Returns (params: dict, state: dict, output_shape)."""
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        return x, state

    # ---- helpers ----------------------------------------------------------
    def _winit_fn(self):
        return _winit.get(self.weight_init or "xavier")

    def _make_weight(self, key, shape, fan_in=None, fan_out=None):
        fi, fo = _winit.compute_fans(shape)
        fn = self._winit_fn()
        return fn(key, shape, fan_in or fi, fan_out or fo, self.dtype)

    def _make_bias(self, shape):
        return jnp.full(shape, self.bias_init, self.dtype)

    def _cast_in(self, x):
        if self.compute_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(self.compute_dtype)
        return x

    def activation_fn(self):
        return _act.get(getattr(self, "activation", "identity"))

    def has_params(self):
        return True

    def n_params(self, input_shape):
        params, _, _ = self.init(jax.random.PRNGKey(0), input_shape)
        return sum(p.size for p in jax.tree_util.tree_leaves(params))


def apply_time_mask(y, mask):
    """Zero padded timesteps: y (B,T,C), mask (B,T) → masked y."""
    if mask is None:
        return y
    return y * mask[..., None].astype(y.dtype)
