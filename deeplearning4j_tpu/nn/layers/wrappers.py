"""Wrapper layers — frozen, time-distributed, mask-zero, repeat.

Reference parity: ``org.deeplearning4j.nn.conf.layers.misc.{FrozenLayer,
FrozenLayerWithBackprop}``, ``...recurrent.TimeDistributed``,
``...util.MaskZeroLayer``, ``...RepeatVector``.

TPU-first: freezing = ``lax.stop_gradient`` on the wrapped params plus a NoOp
updater label (the nets already route ``frozen`` params to NoOp); no separate
"backprop vs not" machinery is needed because reverse-mode is derived from the
forward function.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

from .base import Ctx, Layer


def unwrap(layer):
    """Peel wrapper layers to the innermost config (for type dispatch)."""
    while isinstance(layer, BaseWrapperLayer):
        layer = layer.layer
    return layer


@dataclass
class BaseWrapperLayer(Layer):
    """Delegates init/apply to ``layer``; subclasses adjust in/out."""

    layer: Any = None

    def init(self, key, input_shape):
        return self.layer.init(key, input_shape)

    def apply(self, params, state, x, ctx: Ctx):
        return self.layer.apply(params, state, x, ctx)

    def has_params(self):
        return self.layer.has_params()

    def activation_fn(self):
        return self.layer.activation_fn()


@dataclass
class FrozenLayer(BaseWrapperLayer):
    """Wrapped layer runs forward but its params get no gradient and no
    updates (FrozenLayer / FrozenLayerWithBackprop — with jax.grad the
    distinction vanishes: upstream gradients always flow through)."""

    def __post_init__(self):
        super().__post_init__()
        self.frozen = True
        if self.layer is not None:
            self.layer.frozen = True

    def apply(self, params, state, x, ctx: Ctx):
        params = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.layer.apply(params, state, x, ctx)


# Alias: with functional autodiff the two reference classes coincide.
FrozenLayerWithBackprop = FrozenLayer


@dataclass
class TimeDistributedLayer(BaseWrapperLayer):
    """Applies any per-sample layer independently per timestep by folding
    time into batch: (B, T, *S) -> (B*T, *S) -> layer -> (B, T, *S')
    (TimeDistributed). Works for feed-forward AND spatial inners (Conv2D
    per frame etc.) — the fold is shape-generic."""

    def init(self, key, input_shape):
        t = input_shape[0]
        params, state, out = self.layer.init(key, tuple(input_shape[1:]))
        out_t = tuple(out) if isinstance(out, tuple) else (out,)
        return params, state, (t,) + out_t

    def apply(self, params, state, x, ctx: Ctx):
        b, t = x.shape[0], x.shape[1]
        y, state = self.layer.apply(
            params, state, x.reshape((b * t,) + x.shape[2:]), ctx)
        return y.reshape((b, t) + y.shape[1:]), state


@dataclass
class MaskZeroLayer(BaseWrapperLayer):
    """Zeroes masked timesteps on the way *into* the wrapped recurrent layer
    (MaskZeroLayer); mask comes from ctx.mask (B,T)."""

    mask_value: float = 0.0

    def apply(self, params, state, x, ctx: Ctx):
        if ctx.mask is not None:
            keep = ctx.mask[..., None].astype(x.dtype)
            x = x * keep + self.mask_value * (1.0 - keep)
        return self.layer.apply(params, state, x, ctx)


@dataclass
class RepeatVector(Layer):
    """(B,C) -> (B,T,C), repeating the input T times (RepeatVector)."""

    n: int = 1

    def init(self, key, input_shape):
        return {}, {}, (self.n, input_shape[-1])

    def apply(self, params, state, x, ctx: Ctx):
        return jnp.repeat(x[:, None, :], self.n, axis=1), state

    def has_params(self):
        return False
