"""Object-detection output layer — YOLOv2 loss + box decode/NMS.

Reference parity: ``org.deeplearning4j.nn.conf.layers.objdetect.Yolo2OutputLayer``
and ``org.deeplearning4j.nn.layers.objdetect.{Yolo2OutputLayer, YoloUtils}``.

TPU-first redesign: the whole YOLOv2 loss — responsible-anchor selection by
IOU, coordinate/confidence/class terms — is one fused, fully-vectorised jax
function over the (B, H, W, A, 5+C) activation volume; no per-cell Java loops.
Decode/NMS runs on host (numpy) like the reference's CPU-side YoloUtils.

Layouts (TPU-native NHWC, vs the reference's NCHW):
  activations: (B, gridH, gridW, A*(5+C))  — A anchors, C classes
  labels:      (B, gridH, gridW, 4+C)      — [x1,y1,x2,y2] in grid units + one-hot
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import Ctx
from .core import LossLayer


def _box_iou_wh(wh1, wh2):
    """IOU of two boxes sharing a center, given (w, h) each. Shapes broadcast."""
    inter = jnp.minimum(wh1[..., 0], wh2[..., 0]) * jnp.minimum(wh1[..., 1], wh2[..., 1])
    union = wh1[..., 0] * wh1[..., 1] + wh2[..., 0] * wh2[..., 1] - inter
    return inter / jnp.maximum(union, 1e-9)


def _box_iou_xyxy(a, b):
    """IOU of boxes in (x1,y1,x2,y2); broadcasts over leading dims."""
    x1 = jnp.maximum(a[..., 0], b[..., 0])
    y1 = jnp.maximum(a[..., 1], b[..., 1])
    x2 = jnp.minimum(a[..., 2], b[..., 2])
    y2 = jnp.minimum(a[..., 3], b[..., 3])
    inter = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-9)


@dataclass
class Yolo2OutputLayer(LossLayer):
    """YOLOv2 detection loss head (no params; pure loss over conv activations).

    ``anchors``: sequence of (w, h) priors in grid units, one per anchor box.
    Loss = lambda_coord * position + confidence (IOU target) + class XENT,
    matching the reference's Yolo2OutputLayer.computeScore term structure.
    """

    anchors: Sequence[Tuple[float, float]] = field(default_factory=lambda: [(1.0, 1.0)])
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    @property
    def n_anchors(self):
        return len(self.anchors)

    def init(self, key, input_shape):
        return {}, {}, input_shape

    # -- activation: sigmoid xy/conf, exp wh * anchor, softmax classes -------
    def _split(self, x):
        """(B,H,W,A*(5+C)) -> dict of activated prediction tensors."""
        b, h, w, ch = x.shape
        a = self.n_anchors
        c = ch // a - 5
        x = x.reshape(b, h, w, a, 5 + c).astype(jnp.float32)
        txy, twh, tconf, tcls = x[..., 0:2], x[..., 2:4], x[..., 4], x[..., 5:]
        xy = jax.nn.sigmoid(txy)                       # offset within cell [0,1)
        wh = jnp.exp(twh) * jnp.asarray(self.anchors, jnp.float32)  # grid units
        conf = jax.nn.sigmoid(tconf)
        cls = jax.nn.softmax(tcls, axis=-1)
        return xy, wh, conf, cls, tcls

    def apply(self, params, state, x, ctx: Ctx):
        xy, wh, conf, cls, _ = self._split(x)
        b, h, w, a, c = cls.shape
        out = jnp.concatenate([xy, wh, conf[..., None], cls], axis=-1)
        return out.reshape(b, h, w, a * (5 + c)), state

    def compute_loss(self, pre_activation, labels, mask=None):
        xy, wh, conf, cls, tcls = self._split(pre_activation)
        b, h, w, a, c = cls.shape
        labels = labels.astype(jnp.float32)
        gt_xyxy = labels[..., 0:4]                     # (B,H,W,4) grid units
        gt_cls = labels[..., 4:]                       # (B,H,W,C)
        obj = (jnp.sum(gt_cls, axis=-1) > 0).astype(jnp.float32)  # (B,H,W)

        gt_wh = jnp.stack([gt_xyxy[..., 2] - gt_xyxy[..., 0],
                           gt_xyxy[..., 3] - gt_xyxy[..., 1]], axis=-1)
        gt_center = 0.5 * (gt_xyxy[..., 0:2] + gt_xyxy[..., 2:4])
        # fractional offset of the gt center inside its cell
        gt_off = gt_center - jnp.floor(gt_center)

        # responsible anchor per cell: prior shape with max IOU vs gt shape
        # (reference: YoloUtils IOU over anchor boxes)
        anc = jnp.asarray(self.anchors, jnp.float32)   # (A,2)
        shape_iou = _box_iou_wh(gt_wh[..., None, :], anc)        # (B,H,W,A)
        resp = jax.nn.one_hot(jnp.argmax(shape_iou, axis=-1), a)  # (B,H,W,A)
        resp = resp * obj[..., None]

        # predicted boxes in grid units (for the confidence IOU target)
        cell_x = jnp.arange(w, dtype=jnp.float32)[None, None, :, None]
        cell_y = jnp.arange(h, dtype=jnp.float32)[None, :, None, None]
        px = xy[..., 0] + cell_x
        py = xy[..., 1] + cell_y
        pred_xyxy = jnp.stack([px - wh[..., 0] / 2, py - wh[..., 1] / 2,
                               px + wh[..., 0] / 2, py + wh[..., 1] / 2], axis=-1)
        iou = _box_iou_xyxy(pred_xyxy, gt_xyxy[..., None, :])    # (B,H,W,A)
        iou = jax.lax.stop_gradient(iou)

        n_obj = jnp.maximum(jnp.sum(obj), 1.0)
        # position: squared error on cell offsets + sqrt sizes (resp anchors only)
        pos = (jnp.sum(jnp.square(xy - gt_off[..., None, :]), axis=-1)
               + jnp.sum(jnp.square(jnp.sqrt(jnp.maximum(wh, 1e-9))
                                    - jnp.sqrt(jnp.maximum(gt_wh[..., None, :], 1e-9))),
                         axis=-1))
        pos_loss = self.lambda_coord * jnp.sum(pos * resp) / n_obj
        # confidence: target IOU at responsible anchors, 0 elsewhere
        conf_loss = (jnp.sum(jnp.square(conf - iou) * resp)
                     + self.lambda_no_obj * jnp.sum(jnp.square(conf) * (1.0 - resp))) / n_obj
        # class: XENT at responsible anchors
        logp = jax.nn.log_softmax(tcls, axis=-1)
        cls_loss = -jnp.sum(jnp.sum(gt_cls[..., None, :] * logp, axis=-1) * resp) / n_obj
        return pos_loss + conf_loss + cls_loss

    def has_params(self):
        return False


@dataclass
class DetectedObject:
    """One decoded detection (reference: o.d.nn.layers.objdetect.DetectedObject)."""

    center_x: float
    center_y: float
    width: float
    height: float
    predicted_class: int
    confidence: float
    class_probs: np.ndarray

    @property
    def xyxy(self):
        return (self.center_x - self.width / 2, self.center_y - self.height / 2,
                self.center_x + self.width / 2, self.center_y + self.height / 2)


def get_predicted_objects(layer: Yolo2OutputLayer, activations,
                          threshold: float = 0.5) -> List[List[DetectedObject]]:
    """YoloUtils.getPredictedObjects: decode raw activations to detections."""
    xy, wh, conf, cls, _ = layer._split(jnp.asarray(activations))
    xy, wh, conf, cls = (np.asarray(t) for t in (xy, wh, conf, cls))
    b, h, w, a, c = cls.shape
    out = []
    for bi in range(b):
        dets = []
        score = conf[bi]                               # (H,W,A)
        ys, xs, ans = np.nonzero(score > threshold)
        for y, x, an in zip(ys, xs, ans):
            cw, ch_ = wh[bi, y, x, an]
            dets.append(DetectedObject(
                center_x=float(xy[bi, y, x, an, 0] + x),
                center_y=float(xy[bi, y, x, an, 1] + y),
                width=float(cw), height=float(ch_),
                predicted_class=int(np.argmax(cls[bi, y, x, an])),
                confidence=float(score[y, x, an]),
                class_probs=cls[bi, y, x, an]))
        out.append(dets)
    return out


def nms(detections: List[DetectedObject], iou_threshold: float = 0.45):
    """Greedy per-class non-max suppression (YoloUtils.nms)."""
    kept = []
    by_cls = {}
    for d in detections:
        by_cls.setdefault(d.predicted_class, []).append(d)
    for dets in by_cls.values():
        dets = sorted(dets, key=lambda d: -d.confidence)
        while dets:
            best = dets.pop(0)
            kept.append(best)
            ba = np.asarray(best.xyxy)

            def iou_np(d):
                o = np.asarray(d.xyxy)
                x1, y1 = max(ba[0], o[0]), max(ba[1], o[1])
                x2, y2 = min(ba[2], o[2]), min(ba[3], o[3])
                inter = max(x2 - x1, 0.0) * max(y2 - y1, 0.0)
                ua = ((ba[2] - ba[0]) * (ba[3] - ba[1])
                      + (o[2] - o[0]) * (o[3] - o[1]) - inter)
                return inter / max(ua, 1e-9)

            dets = [d for d in dets if iou_np(d) < iou_threshold]
    return sorted(kept, key=lambda d: -d.confidence)
