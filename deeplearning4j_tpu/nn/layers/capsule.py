"""Capsule network layers — primary capsules, dynamic-routing capsules,
capsule strength.

Reference parity: ``org.deeplearning4j.nn.conf.layers.{CapsuleLayer,
PrimaryCapsules, CapsuleStrengthLayer}`` (the reference implements these as
SameDiff layers; here they are plain jax — routing is a statically-unrolled
3-iteration loop, fully fused by XLA).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from .base import Ctx, Layer
from .conv import ConvolutionLayer


def squash(s, axis=-1, eps=1e-9):
    """v = |s|^2/(1+|s|^2) * s/|s| — the capsule nonlinearity."""
    sq = jnp.sum(jnp.square(s), axis=axis, keepdims=True)
    return (sq / (1.0 + sq)) * s / jnp.sqrt(sq + eps)


@dataclass
class PrimaryCapsules(Layer):
    """Conv2D -> reshape to (B, nCaps, capDim) -> squash (PrimaryCapsules)."""

    capsules: int = 8            # capsule channels (conv filters = capsules*cap_dim)
    capsule_dimensions: int = 8
    kernel_size: Tuple = (9, 9)
    stride: Tuple = (2, 2)

    def init(self, key, input_shape):
        self._conv = ConvolutionLayer(
            n_out=self.capsules * self.capsule_dimensions,
            kernel_size=self.kernel_size, stride=self.stride,
            convolution_mode="truncate", activation="identity",
            dtype=self.dtype, weight_init=self.weight_init)
        params, state, (h, w, c) = self._conv.init(key, input_shape)
        self._n_caps = h * w * self.capsules
        return params, state, (self._n_caps, self.capsule_dimensions)

    def apply(self, params, state, x, ctx: Ctx):
        y, state = self._conv.apply(params, state, x, ctx)
        b = y.shape[0]
        y = y.reshape(b, -1, self.capsule_dimensions)
        return squash(y), state


@dataclass
class CapsuleLayer(Layer):
    """Fully-connected capsules with dynamic routing (CapsuleLayer).

    Input (B, nIn, dIn) -> predictions u_hat via per-pair weight tensor ->
    ``routings`` iterations of softmax agreement routing -> (B, nOut, dOut).
    """

    capsules: int = 10
    capsule_dimensions: int = 16
    routings: int = 3

    def init(self, key, input_shape):
        n_in, d_in = input_shape
        w = jax.random.normal(key, (1, n_in, self.capsules,
                                    self.capsule_dimensions, d_in),
                              self.dtype) * 0.01
        return {"W": w}, {}, (self.capsules, self.capsule_dimensions)

    def apply(self, params, state, x, ctx: Ctx):
        # u_hat[b,i,o,:] = W[i,o] @ x[b,i]; W[0]: (nIn,nOut,dOut,dIn), x: (B,nIn,dIn)
        u_hat = jnp.einsum("iokd,bid->biok", params["W"][0], x)
        logits = jnp.zeros(u_hat.shape[:3], u_hat.dtype)   # (B, nIn, nOut)
        u_detached = jax.lax.stop_gradient(u_hat)
        v = None
        for r in range(self.routings):
            c = jax.nn.softmax(logits, axis=2)[..., None]
            uh = u_hat if r == self.routings - 1 else u_detached
            v = squash(jnp.sum(c * uh, axis=1))            # (B, nOut, dOut)
            if r < self.routings - 1:
                logits = logits + jnp.sum(u_detached * v[:, None], axis=-1)
        return v, state


@dataclass
class CapsuleStrengthLayer(Layer):
    """(B, nCaps, dim) -> per-capsule L2 norm (B, nCaps) (CapsuleStrengthLayer)."""

    def init(self, key, input_shape):
        return {}, {}, (input_shape[0],)

    def apply(self, params, state, x, ctx: Ctx):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=-1) + 1e-9), state

    def has_params(self):
        return False
