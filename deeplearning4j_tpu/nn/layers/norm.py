"""Normalization layers — BatchNorm, LayerNorm, RMSNorm, LRN.

Reference parity: ``org.deeplearning4j.nn.conf.layers.BatchNormalization``
(cuDNN BatchNormalizationHelper path → fused XLA here),
``LocalResponseNormalization``. LayerNorm/RMSNorm are the reference's
SameDiff ops surfaced as layers (transformer path).

BatchNorm keeps running mean/var in layer `state` (the functional analogue of
the reference's mutable global stats arrays) — threaded through train steps
and used verbatim at inference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .base import Ctx, Layer


@dataclass
class BatchNormalization(Layer):
    """Normalizes the trailing (channel) axis — works for FF (B,C) and
    conv NHWC (B,H,W,C) inputs alike."""

    n_out: Optional[int] = None  # channels; inferred
    decay: float = 0.9           # DL4J's `decay` for running stats EMA
    eps: float = 1e-5
    gamma_init: float = 1.0
    beta_init: float = 0.0
    lock_gamma_beta: bool = False
    use_log_std: bool = False
    # DL4J BatchNormalization inherits activation from FeedForwardLayer;
    # at inference the whole BN+act collapses into the fused pallas
    # scale-shift-act kernel ("auto": on TPU; True forces interpret mode)
    activation: Any = "identity"
    fused: Any = "auto"

    def _fuse_ok(self, supported) -> bool:
        """Shared fused/auto/backend gating; `supported` is the kernel's
        activation predicate (inference and training support differ)."""
        if self.fused is False or not supported(self.activation):
            return False
        if self.fused is True:
            return True
        # "auto" fuses only when there IS an activation to fuse — plain
        # identity BN gains nothing over XLA's own fusion, so don't route
        # every existing BN through the kernel by default
        return self.activation != "identity" \
            and jax.default_backend() == "tpu"

    def _can_fuse(self) -> bool:
        from ...kernels.fused_ops import supported_activation
        return self._fuse_ok(supported_activation)

    def _can_fuse_train(self) -> bool:
        # OPT-IN ONLY (fused=True), never "auto": on-chip measurement
        # (scripts/diag_resnet_out.json, r4) showed the pallas training
        # BN regresses ResNet-50 b128 from MFU 0.35 to 0.22 — the kernel
        # materializes its input/output at HBM and blocks XLA from fusing
        # the BN+act chain into the producing convolution's epilogue.
        # The XLA path with one-pass shifted stats is the fast default.
        if self.fused is not True:
            return False
        from ...kernels.fused_ops import supported_train_activation
        return self._fuse_ok(supported_train_activation)

    def init(self, key, input_shape):
        c = self.n_out or input_shape[-1]
        params = {}
        if not self.lock_gamma_beta:
            params = {"gamma": jnp.full((c,), self.gamma_init, self.dtype),
                      "beta": jnp.full((c,), self.beta_init, self.dtype)}
        state = {"mean": jnp.zeros((c,), jnp.float32),
                 "var": jnp.ones((c,), jnp.float32)}
        return params, state, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        axes = tuple(range(x.ndim - 1))
        if ctx.train:
            c = lax.stop_gradient(state["mean"])
            if self._can_fuse_train():
                # fused pallas training BN (kernels/fused_ops.py): shifted
                # one-pass stats sweep + normalize-act sweep, custom-VJP
                # backward with fused reductions — the cuDNN
                # BatchNormalizationForwardTraining/Backward regime
                from ...kernels.fused_ops import fused_bn_act_train
                ch = x.shape[-1]
                gamma = (jnp.ones((ch,), jnp.float32)
                         if self.lock_gamma_beta else params["gamma"])
                beta = (jnp.zeros((ch,), jnp.float32)
                        if self.lock_gamma_beta else params["beta"])
                y, mean, var = fused_bn_act_train(
                    x.reshape(-1, ch), gamma, beta, c, self.eps,
                    self.activation,
                    True if self.fused is True else None)
                new_state = {
                    "mean": self.decay * state["mean"]
                            + (1 - self.decay) * lax.stop_gradient(mean),
                    "var": self.decay * state["var"]
                           + (1 - self.decay) * lax.stop_gradient(var),
                }
                return y.reshape(x.shape), new_state
            # One-pass stats: jnp.var's two-pass form costs an extra full
            # HBM sweep of the activation per BN; the fused single sweep
            # measured +8.6% whole-model ResNet-50 throughput on v5e.
            # Shift by the RUNNING mean c (per-channel f32 state) before
            # squaring — var = E[(x−c)²] − (E[x]−c)² — so the subtraction
            # cancels (std² + drift²) − drift², not the catastrophic
            # E[x²] − mean² of the naive form: once c tracks the channel
            # mean this is as accurate as two-pass even for large-offset
            # channels. The clamp guards first-batch roundoff while c is
            # still cold.
            xf = x.astype(jnp.float32)
            d = xf - c
            dmean = jnp.mean(d, axis=axes)
            d2mean = jnp.mean(d * d, axis=axes)
            mean = c + dmean
            var = jnp.maximum(d2mean - dmean * dmean, 0.0)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
            if self._can_fuse():
                # inference BN+act folds to act(x*scale + shift): one
                # bandwidth-bound pallas pass (kernels/fused_ops.py)
                from ...kernels.fused_ops import fused_bn_act
                inv = lax.rsqrt(var + self.eps)
                scale, shift = inv, -mean * inv
                if not self.lock_gamma_beta:
                    g32 = params["gamma"].astype(jnp.float32)
                    scale = inv * g32
                    shift = params["beta"].astype(jnp.float32) - mean * scale
                c = x.shape[-1]
                y = fused_bn_act(x.reshape(-1, c), scale, shift,
                                 self.activation,
                                 True if self.fused is True else None)
                return y.reshape(x.shape), new_state
        # normalize as one fused multiply-add: fold mean/gamma/beta into
        # per-channel scale/shift vectors (C-sized math) instead of two
        # full-tensor passes
        inv = lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta:
            scale = inv * params["gamma"].astype(jnp.float32)
            shift = params["beta"].astype(jnp.float32) - mean * scale
        else:
            scale, shift = inv, -mean * inv
        y = x.astype(jnp.float32) * scale + shift
        if self.activation != "identity":
            from .. import activations as _a
            y = _a.get(self.activation)(y)
        return y.astype(x.dtype), new_state


@dataclass
class LayerNormalization(Layer):
    """LayerNorm over the channel axis (SameDiff standardize + gain/bias)."""

    eps: float = 1e-5
    use_bias: bool = True

    def init(self, key, input_shape):
        c = input_shape[-1]
        params = {"gamma": jnp.ones((c,), self.dtype)}
        if self.use_bias:
            params["beta"] = jnp.zeros((c,), self.dtype)
        return params, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + self.eps)
        y = y * params["gamma"].astype(jnp.float32)
        if self.use_bias:
            y = y + params["beta"].astype(jnp.float32)
        return y.astype(x.dtype), state


@dataclass
class RMSNorm(Layer):
    """RMS normalization (no mean subtraction) — transformer staple."""

    eps: float = 1e-6

    def init(self, key, input_shape):
        return {"gamma": jnp.ones((input_shape[-1],), self.dtype)}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        xf = x.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * lax.rsqrt(ms + self.eps) * params["gamma"].astype(jnp.float32)
        return y.astype(x.dtype), state


@dataclass
class LocalResponseNormalization(Layer):
    """LRN across channels (AlexNet-era). NHWC; pure elementwise+window — XLA fuses."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def init(self, key, input_shape):
        return {}, {}, input_shape

    def apply(self, params, state, x, ctx: Ctx):
        xf = x.astype(jnp.float32)
        sq = jnp.square(xf)
        half = self.n // 2
        # sum over a window of channels via padded cumulative trick
        pad = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
        win = sum(lax.slice_in_dim(pad, i, i + x.shape[-1], axis=x.ndim - 1)
                  for i in range(self.n))
        y = xf / jnp.power(self.k + self.alpha * win, self.beta)
        return y.astype(x.dtype), state

    def has_params(self):
        return False
