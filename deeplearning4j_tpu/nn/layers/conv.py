"""Convolution family — NHWC, lax.conv_general_dilated (MXU path).

Reference parity: ``org.deeplearning4j.nn.conf.layers.{ConvolutionLayer,
Convolution1DLayer, Convolution3D, Deconvolution2D, SeparableConvolution2D,
DepthwiseConvolution2D, SubsamplingLayer, Subsampling1DLayer,
Subsampling3DLayer, Upsampling1D/2D/3D, ZeroPaddingLayer, Cropping2D,
SpaceToDepthLayer, DepthToSpace, LocallyConnected1D/2D}``.

The reference dispatches these to cuDNN kernels (libnd4j ConvolutionUtils);
here XLA lowers them onto the MXU directly with bf16 inputs (the MXU
accumulates products in f32 internally on TPU; on non-TPU backends bf16
convs accumulate at native precision). Layout is NHWC / HWIO — the TPU
native layout — instead of the reference's NCHW.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from .base import Ctx, Layer


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def _triple(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v, v)


def _padding(pad, kernel, mode):
    """DL4J ConvolutionMode → lax padding. mode: 'same'|'truncate'|'valid'+explicit."""
    if isinstance(pad, str):
        return pad.upper()
    if mode == "same":
        return "SAME"
    pads = _pair(pad) if len(kernel) == 2 else _triple(pad)
    return tuple((p, p) for p in pads)


@dataclass
class ConvolutionLayer(Layer):
    """2D conv. Kernel stored HWIO ("W": (kh,kw,cin/groups,cout)), bias (cout,)."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = 0
    dilation: Any = (1, 1)
    groups: int = 1
    convolution_mode: str = "truncate"   # DL4J ConvolutionMode.{Same,Truncate}
    activation: Any = "identity"
    has_bias: bool = True

    def _kernel_shape(self, c_in):
        kh, kw = _pair(self.kernel_size)
        return (kh, kw, c_in // self.groups, self.n_out)

    def init(self, key, input_shape):
        h, w, c = input_shape
        c = self.n_in or c
        kshape = self._kernel_shape(c)
        fan_in = kshape[0] * kshape[1] * kshape[2]
        fan_out = kshape[0] * kshape[1] * self.n_out
        params = {"W": self._make_weight(key, kshape, fan_in, fan_out)}
        if self.has_bias:
            params["b"] = self._make_bias((self.n_out,))
        oh, ow = self._out_hw(h, w)
        return params, {}, (oh, ow, self.n_out)

    def _out_hw(self, h, w):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        if self.convolution_mode == "same":
            return -(-h // sh), -(-w // sw)
        ph, pw = _pair(self.padding)
        eh, ew = dh * (kh - 1) + 1, dw * (kw - 1) + 1
        return (h + 2 * ph - eh) // sh + 1, (w + 2 * pw - ew) // sw + 1

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        w = params["W"].astype(x.dtype)
        y = lax.conv_general_dilated(
            x, w, window_strides=_pair(self.stride),
            padding=_padding(self.padding, _pair(self.kernel_size), self.convolution_mode),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.groups)
        y = y.astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state


@dataclass
class Convolution1DLayer(Layer):
    """1D conv over (B, T, C) [NTC]."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: Any = 0
    dilation: int = 1
    convolution_mode: str = "same"
    activation: Any = "identity"
    has_bias: bool = True

    def init(self, key, input_shape):
        t, c = input_shape
        c = self.n_in or c
        k = self.kernel_size if not isinstance(self.kernel_size, (tuple, list)) else self.kernel_size[0]
        kshape = (k, c, self.n_out)
        params = {"W": self._make_weight(key, kshape, k * c, k * self.n_out)}
        if self.has_bias:
            params["b"] = self._make_bias((self.n_out,))
        if self.convolution_mode == "same":
            ot = None if t is None else -(-t // self.stride)
        else:
            p = self.padding if isinstance(self.padding, int) else self.padding[0]
            e = self.dilation * (k - 1) + 1
            ot = None if t is None else (t + 2 * p - e) // self.stride + 1
        return params, {}, (ot, self.n_out)

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        w = params["W"].astype(x.dtype)
        if self.convolution_mode == "same":
            pad = "SAME"
        elif isinstance(self.padding, str):
            pad = self.padding.upper()
        else:
            p = self.padding if isinstance(self.padding, int) else self.padding[0]
            pad = ((p, p),)
        y = lax.conv_general_dilated(
            x, w, window_strides=(self.stride,), padding=pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NTC", "TIO", "NTC"))
        y = y.astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state


@dataclass
class Convolution3DLayer(Layer):
    """3D conv over (B, D, H, W, C) [NDHWC]."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: Any = (3, 3, 3)
    stride: Any = (1, 1, 1)
    padding: Any = 0
    dilation: Any = (1, 1, 1)
    convolution_mode: str = "same"
    activation: Any = "identity"
    has_bias: bool = True

    def init(self, key, input_shape):
        d, h, w, c = input_shape
        c = self.n_in or c
        kd, kh, kw = _triple(self.kernel_size)
        kshape = (kd, kh, kw, c, self.n_out)
        fan_in = kd * kh * kw * c
        params = {"W": self._make_weight(key, kshape, fan_in, kd * kh * kw * self.n_out)}
        if self.has_bias:
            params["b"] = self._make_bias((self.n_out,))
        sd, sh, sw = _triple(self.stride)
        if self.convolution_mode == "same":
            out = (-(-d // sd), -(-h // sh), -(-w // sw), self.n_out)
        else:
            pd, ph, pw = _triple(self.padding)
            dd, dh, dw = _triple(self.dilation)
            out = ((d + 2 * pd - (dd * (kd - 1) + 1)) // sd + 1,
                   (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1,
                   (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1, self.n_out)
        return params, {}, out

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        w = params["W"].astype(x.dtype)
        y = lax.conv_general_dilated(
            x, w, window_strides=_triple(self.stride),
            padding=_padding(self.padding, _triple(self.kernel_size), self.convolution_mode),
            rhs_dilation=_triple(self.dilation),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        y = y.astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state


@dataclass
class Deconvolution2D(ConvolutionLayer):
    """Transposed conv (Deconvolution2D)."""

    def init(self, key, input_shape):
        h, w, c = input_shape
        c = self.n_in or c
        kh, kw = _pair(self.kernel_size)
        kshape = (kh, kw, c, self.n_out)  # lax.conv_transpose uses HWIO
        params = {"W": self._make_weight(key, kshape, kh * kw * c, kh * kw * self.n_out)}
        if self.has_bias:
            params["b"] = self._make_bias((self.n_out,))
        sh, sw = _pair(self.stride)
        if self.convolution_mode == "same":
            out = (None if h is None else h * sh, None if w is None else w * sw, self.n_out)
        else:
            ph, pw = _pair(self.padding)
            out = (None if h is None else sh * (h - 1) + kh - 2 * ph,
                   None if w is None else sw * (w - 1) + kw - 2 * pw, self.n_out)
        return params, {}, out

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        w = params["W"].astype(x.dtype)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            ph, pw = _pair(self.padding)
            kh, kw = _pair(self.kernel_size)
            pad = ((kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw))
        y = lax.conv_transpose(
            x, w, strides=_pair(self.stride), padding=pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = y.astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state


@dataclass
class Deconvolution3D(Convolution3DLayer):
    """Transposed 3-D conv over (B, D, H, W, C) [NDHWC].

    Reference parity: ``org.deeplearning4j.nn.conf.layers.Deconvolution3D``
    (the reference runs NCDHW through cuDNN; here one XLA
    ``lax.conv_transpose`` in the TPU-native NDHWC layout).
    """

    def init(self, key, input_shape):
        d, h, w, c = input_shape
        c = self.n_in or c
        kd, kh, kw = _triple(self.kernel_size)
        kshape = (kd, kh, kw, c, self.n_out)  # DHWIO for conv_transpose
        params = {"W": self._make_weight(key, kshape, kd * kh * kw * c,
                                         kd * kh * kw * self.n_out)}
        if self.has_bias:
            params["b"] = self._make_bias((self.n_out,))
        sd, sh, sw = _triple(self.stride)
        if self.convolution_mode == "same":
            out = (d * sd, h * sh, w * sw, self.n_out)
        else:
            pd, ph, pw = _triple(self.padding)
            out = (sd * (d - 1) + kd - 2 * pd,
                   sh * (h - 1) + kh - 2 * ph,
                   sw * (w - 1) + kw - 2 * pw, self.n_out)
        return params, {}, out

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        w = params["W"].astype(x.dtype)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            pd, ph, pw = _triple(self.padding)
            kd, kh, kw = _triple(self.kernel_size)
            pad = ((kd - 1 - pd, kd - 1 - pd), (kh - 1 - ph, kh - 1 - ph),
                   (kw - 1 - pw, kw - 1 - pw))
        y = lax.conv_transpose(
            x, w, strides=_triple(self.stride), padding=pad,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        y = y.astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state


@dataclass
class DepthwiseConvolution2D(Layer):
    n_in: Optional[int] = None
    depth_multiplier: int = 1
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = 0
    convolution_mode: str = "same"
    activation: Any = "identity"
    has_bias: bool = True

    def init(self, key, input_shape):
        h, w, c = input_shape
        c = self.n_in or c
        kh, kw = _pair(self.kernel_size)
        n_out = c * self.depth_multiplier
        kshape = (kh, kw, 1, n_out)
        params = {"W": self._make_weight(key, kshape, kh * kw, kh * kw * self.depth_multiplier)}
        if self.has_bias:
            params["b"] = self._make_bias((n_out,))
        sh, sw = _pair(self.stride)
        if self.convolution_mode == "same":
            out = (-(-h // sh), -(-w // sw), n_out)
        else:
            ph, pw = _pair(self.padding)
            out = ((h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1, n_out)
        return params, {}, out

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        c = x.shape[-1]
        w = params["W"].astype(x.dtype)
        y = lax.conv_general_dilated(
            x, w, window_strides=_pair(self.stride),
            padding=_padding(self.padding, _pair(self.kernel_size), self.convolution_mode),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c)
        y = y.astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state


@dataclass
class SeparableConvolution2D(Layer):
    """Depthwise + pointwise (SeparableConvolution2D)."""

    n_in: Optional[int] = None
    n_out: int = 0
    depth_multiplier: int = 1
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = 0
    convolution_mode: str = "same"
    activation: Any = "identity"
    has_bias: bool = True

    def init(self, key, input_shape):
        h, w, c = input_shape
        c = self.n_in or c
        kh, kw = _pair(self.kernel_size)
        k1, k2 = jax.random.split(key)
        dshape = (kh, kw, 1, c * self.depth_multiplier)
        pshape = (1, 1, c * self.depth_multiplier, self.n_out)
        params = {
            "dW": self._make_weight(k1, dshape, kh * kw, kh * kw * self.depth_multiplier),
            "pW": self._make_weight(k2, pshape, c * self.depth_multiplier, self.n_out),
        }
        if self.has_bias:
            params["b"] = self._make_bias((self.n_out,))
        sh, sw = _pair(self.stride)
        if self.convolution_mode == "same":
            out = (-(-h // sh), -(-w // sw), self.n_out)
        else:
            ph, pw = _pair(self.padding)
            out = ((h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1, self.n_out)
        return params, {}, out

    def apply(self, params, state, x, ctx: Ctx):
        x = self._cast_in(x)
        c = x.shape[-1]
        y = lax.conv_general_dilated(
            x, params["dW"].astype(x.dtype), window_strides=_pair(self.stride),
            padding=_padding(self.padding, _pair(self.kernel_size), self.convolution_mode),
            dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c).astype(x.dtype)
        y = lax.conv_general_dilated(
            y, params["pW"].astype(x.dtype), window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC")).astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state


class PoolingType:
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


@dataclass
class SubsamplingLayer(Layer):
    """Pooling (SubsamplingLayer). NHWC."""

    kernel_size: Any = (2, 2)
    stride: Any = None
    padding: Any = 0
    pooling_type: str = PoolingType.MAX
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def init(self, key, input_shape):
        h, w, c = input_shape
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride if self.stride is not None else self.kernel_size)
        if self.convolution_mode == "same":
            out = (-(-h // sh), -(-w // sw), c)
        else:
            ph, pw = _pair(self.padding)
            out = ((h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1, c)
        return {}, {}, out

    def apply(self, params, state, x, ctx: Ctx):
        kh, kw = _pair(self.kernel_size)
        stride = _pair(self.stride if self.stride is not None else self.kernel_size)
        if self.convolution_mode == "same":
            pad = "SAME"
        elif isinstance(self.padding, str):
            pad = self.padding.upper()
        else:
            ph, pw = _pair(self.padding)
            pad = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        window = (1, kh, kw, 1)
        strides = (1, *stride, 1)
        if self.pooling_type == PoolingType.MAX:
            init_val = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            y = lax.reduce_window(x, init_val, lax.max, window, strides, pad)
        elif self.pooling_type == PoolingType.AVG:
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad) / (kh * kw)
        elif self.pooling_type == PoolingType.SUM:
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        else:  # pnorm
            p = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pad) ** (1.0 / p)
        return y.astype(x.dtype), state

    def has_params(self):
        return False


@dataclass
class Subsampling1DLayer(Layer):
    kernel_size: int = 2
    stride: int = None
    padding: int = 0
    pooling_type: str = PoolingType.MAX
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def init(self, key, input_shape):
        t, c = input_shape
        k = self.kernel_size
        s = self.stride or k
        if t is None:
            return {}, {}, (None, c)
        if self.convolution_mode == "same":
            return {}, {}, (-(-t // s), c)
        return {}, {}, ((t + 2 * self.padding - k) // s + 1, c)

    def apply(self, params, state, x, ctx: Ctx):
        k, s = self.kernel_size, self.stride or self.kernel_size
        pad = "SAME" if self.convolution_mode == "same" else ((0, 0), (self.padding, self.padding), (0, 0))
        window, strides = (1, k, 1), (1, s, 1)
        if self.pooling_type == PoolingType.MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad)
        elif self.pooling_type == PoolingType.PNORM:
            p = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pad) ** (1.0 / p)
        else:
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            if self.pooling_type == PoolingType.AVG:
                y = y / k
        return y.astype(x.dtype), state

    def has_params(self):
        return False


@dataclass
class Upsampling2D(Layer):
    size: Any = (2, 2)

    def init(self, key, input_shape):
        h, w, c = input_shape
        sh, sw = _pair(self.size)
        return {}, {}, (None if h is None else h * sh, None if w is None else w * sw, c)

    def apply(self, params, state, x, ctx: Ctx):
        sh, sw = _pair(self.size)
        y = jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
        return y, state

    def has_params(self):
        return False


@dataclass
class Upsampling1D(Layer):
    size: int = 2

    def init(self, key, input_shape):
        t, c = input_shape
        return {}, {}, (None if t is None else t * self.size, c)

    def apply(self, params, state, x, ctx: Ctx):
        return jnp.repeat(x, self.size, axis=1), state

    def has_params(self):
        return False


@dataclass
class Upsampling3D(Layer):
    size: Any = (2, 2, 2)

    def init(self, key, input_shape):
        d, h, w, c = input_shape
        sd, sh, sw = _triple(self.size)
        return {}, {}, (d * sd, h * sh, w * sw, c)

    def apply(self, params, state, x, ctx: Ctx):
        sd, sh, sw = _triple(self.size)
        y = jnp.repeat(jnp.repeat(jnp.repeat(x, sd, 1), sh, 2), sw, 3)
        return y, state

    def has_params(self):
        return False


@dataclass
class Subsampling3DLayer(Layer):
    """3-D pooling (Subsampling3DLayer). NDHWC, matching Convolution3DLayer."""

    kernel_size: Any = (2, 2, 2)
    stride: Any = None
    padding: Any = 0
    pooling_type: str = PoolingType.MAX
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def init(self, key, input_shape):
        d, h, w, c = input_shape
        kd, kh, kw = _triple(self.kernel_size)
        sd, sh, sw = _triple(self.stride if self.stride is not None
                             else self.kernel_size)
        if self.convolution_mode == "same":
            out = (-(-d // sd), -(-h // sh), -(-w // sw), c)
        else:
            pd, ph, pw = _triple(self.padding)
            out = ((d + 2 * pd - kd) // sd + 1, (h + 2 * ph - kh) // sh + 1,
                   (w + 2 * pw - kw) // sw + 1, c)
        return {}, {}, out

    def apply(self, params, state, x, ctx: Ctx):
        kd, kh, kw = _triple(self.kernel_size)
        stride = _triple(self.stride if self.stride is not None
                         else self.kernel_size)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            pd, ph, pw = _triple(self.padding)
            pad = ((0, 0), (pd, pd), (ph, ph), (pw, pw), (0, 0))
        window = (1, kd, kh, kw, 1)
        strides = (1, *stride, 1)
        if self.pooling_type == PoolingType.MAX:
            init_val = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
                else jnp.iinfo(x.dtype).min
            y = lax.reduce_window(x, init_val, lax.max, window, strides, pad)
        elif self.pooling_type == PoolingType.AVG:
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad) \
                / (kd * kh * kw)
        elif self.pooling_type == PoolingType.SUM:
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        else:  # pnorm, matching the 1D/2D layers
            p = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window,
                                  strides, pad) ** (1.0 / p)
        return y.astype(x.dtype), state

    def has_params(self):
        return False


@dataclass
class ZeroPaddingLayer(Layer):
    padding: Any = (1, 1)  # (ph, pw) or ((pt,pb),(pl,pr))

    def _pads(self):
        p = self.padding
        if isinstance(p, int):
            return (p, p), (p, p)
        if isinstance(p[0], (tuple, list)):
            return tuple(p[0]), tuple(p[1])
        return (p[0], p[0]), (p[1], p[1])

    def init(self, key, input_shape):
        h, w, c = input_shape
        (pt, pb), (pl, pr) = self._pads()
        return {}, {}, (h + pt + pb, w + pl + pr, c)

    def apply(self, params, state, x, ctx: Ctx):
        (pt, pb), (pl, pr) = self._pads()
        return jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0))), state

    def has_params(self):
        return False


@dataclass
class Cropping2D(Layer):
    cropping: Any = (1, 1)

    def _crops(self):
        c = self.cropping
        if isinstance(c, int):
            return (c, c), (c, c)
        if isinstance(c[0], (tuple, list)):
            return tuple(c[0]), tuple(c[1])
        return (c[0], c[0]), (c[1], c[1])

    def init(self, key, input_shape):
        h, w, c = input_shape
        (ct, cb), (cl, cr) = self._crops()
        return {}, {}, (h - ct - cb, w - cl - cr, c)

    def apply(self, params, state, x, ctx: Ctx):
        (ct, cb), (cl, cr) = self._crops()
        return x[:, ct:x.shape[1] - cb, cl:x.shape[2] - cr, :], state

    def has_params(self):
        return False


def _amount_pair(v):
    """int → symmetric pair; else pass through as (before, after)."""
    return (v, v) if isinstance(v, int) else tuple(v)


def _amount_triple(v):
    """int / (a,b,c) / ((a0,a1),(b0,b1),(c0,c1)) → 3 (before, after) pairs."""
    if isinstance(v, int):
        return ((v, v),) * 3
    if isinstance(v[0], (tuple, list)):
        return tuple(tuple(q) for q in v)
    return tuple((q, q) for q in v)


@dataclass
class ZeroPadding1DLayer(Layer):
    """(B, T, C) sequence padding (ZeroPadding1DLayer)."""

    padding: Any = 1  # int or (left, right)

    def init(self, key, input_shape):
        t, c = input_shape
        pl_, pr = _amount_pair(self.padding)
        return {}, {}, (t + pl_ + pr, c)

    def apply(self, params, state, x, ctx: Ctx):
        pl_, pr = _amount_pair(self.padding)
        return jnp.pad(x, ((0, 0), (pl_, pr), (0, 0))), state

    def has_params(self):
        return False


@dataclass
class ZeroPadding3DLayer(Layer):
    """NDHWC padding (ZeroPadding3DLayer)."""

    padding: Any = 1  # int, (pd, ph, pw) or ((df,db),(ht,hb),(wl,wr))

    def init(self, key, input_shape):
        d, h, w, c = input_shape
        (df, db), (ht, hb), (wl, wr) = _amount_triple(self.padding)
        return {}, {}, (d + df + db, h + ht + hb, w + wl + wr, c)

    def apply(self, params, state, x, ctx: Ctx):
        (df, db), (ht, hb), (wl, wr) = _amount_triple(self.padding)
        return jnp.pad(x, ((0, 0), (df, db), (ht, hb), (wl, wr), (0, 0))), state

    def has_params(self):
        return False


@dataclass
class Cropping1D(Layer):
    """(B, T, C) sequence cropping (Cropping1D)."""

    cropping: Any = 1  # int or (left, right)

    def init(self, key, input_shape):
        t, c = input_shape
        cl, cr = _amount_pair(self.cropping)
        return {}, {}, (t - cl - cr, c)

    def apply(self, params, state, x, ctx: Ctx):
        cl, cr = _amount_pair(self.cropping)
        return x[:, cl:x.shape[1] - cr, :], state

    def has_params(self):
        return False


@dataclass
class Cropping3D(Layer):
    """NDHWC cropping (Cropping3D)."""

    cropping: Any = 1

    def init(self, key, input_shape):
        d, h, w, c = input_shape
        (df, db), (ht, hb), (wl, wr) = _amount_triple(self.cropping)
        return {}, {}, (d - df - db, h - ht - hb, w - wl - wr, c)

    def apply(self, params, state, x, ctx: Ctx):
        (df, db), (ht, hb), (wl, wr) = _amount_triple(self.cropping)
        return x[:, df:x.shape[1] - db, ht:x.shape[2] - hb,
                 wl:x.shape[3] - wr, :], state

    def has_params(self):
        return False


@dataclass
class SpaceToDepthLayer(Layer):
    block_size: int = 2

    def init(self, key, input_shape):
        h, w, c = input_shape
        b = self.block_size
        return {}, {}, (h // b, w // b, c * b * b)

    def apply(self, params, state, x, ctx: Ctx):
        n, h, w, c = x.shape
        b = self.block_size
        y = x.reshape(n, h // b, b, w // b, b, c)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b, c * b * b)
        return y, state

    def has_params(self):
        return False


@dataclass
class DepthToSpaceLayer(Layer):
    block_size: int = 2

    def init(self, key, input_shape):
        h, w, c = input_shape
        b = self.block_size
        return {}, {}, (h * b, w * b, c // (b * b))

    def apply(self, params, state, x, ctx: Ctx):
        n, h, w, c = x.shape
        b = self.block_size
        y = x.reshape(n, h, w, b, b, c // (b * b))
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(n, h * b, w * b, c // (b * b))
        return y, state

    def has_params(self):
        return False


@dataclass
class GlobalPoolingLayer(Layer):
    """Global pooling over spatial/time dims (GlobalPoolingLayer).

    Supports masked mean/max for RNN inputs (B,T,C) with mask (B,T).
    """

    pooling_type: str = PoolingType.AVG
    pnorm: int = 2
    collapse_dimensions: bool = True

    def init(self, key, input_shape):
        return {}, {}, (input_shape[-1],)

    def apply(self, params, state, x, ctx: Ctx):
        axes = tuple(range(1, x.ndim - 1))
        mask = ctx.mask
        if mask is not None and x.ndim == 3:
            m = mask[..., None].astype(x.dtype)
            if self.pooling_type == PoolingType.MAX:
                y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
            elif self.pooling_type == PoolingType.SUM:
                y = jnp.sum(x * m, axis=1)
            elif self.pooling_type == PoolingType.PNORM:
                p = float(self.pnorm)
                y = jnp.sum((jnp.abs(x) * m) ** p, axis=1) ** (1.0 / p)
            else:
                y = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
            return y, state
        if self.pooling_type == PoolingType.MAX:
            y = jnp.max(x, axis=axes)
        elif self.pooling_type == PoolingType.SUM:
            y = jnp.sum(x, axis=axes)
        elif self.pooling_type == PoolingType.PNORM:
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            y = jnp.mean(x, axis=axes)
        return y, state

    def has_params(self):
        return False


@dataclass
class LocallyConnected2D(Layer):
    """Per-position filters (no weight sharing). Implemented as patch
    extraction + per-position einsum — MXU-friendly batched matmul."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    activation: Any = "identity"
    has_bias: bool = True

    def init(self, key, input_shape):
        h, w, c = input_shape
        c = self.n_in or c
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        kshape = (oh, ow, kh * kw * c, self.n_out)
        params = {"W": self._make_weight(key, kshape, kh * kw * c, self.n_out)}
        if self.has_bias:
            params["b"] = self._make_bias((oh, ow, self.n_out))
        return params, {}, (oh, ow, self.n_out)

    def apply(self, params, state, x, ctx: Ctx):
        kh, kw = _pair(self.kernel_size)
        patches = lax.conv_general_dilated_patches(
            x, (kh, kw), _pair(self.stride), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = jnp.einsum("nhwp,hwpo->nhwo", patches, params["W"].astype(x.dtype))
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state


@dataclass
class LocallyConnected1D(Layer):
    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    activation: Any = "identity"
    has_bias: bool = True

    def init(self, key, input_shape):
        t, c = input_shape
        c = self.n_in or c
        k = self.kernel_size
        ot = (t - k) // self.stride + 1
        params = {"W": self._make_weight(key, (ot, k * c, self.n_out), k * c, self.n_out)}
        if self.has_bias:
            params["b"] = self._make_bias((ot, self.n_out))
        return params, {}, (ot, self.n_out)

    def apply(self, params, state, x, ctx: Ctx):
        k = self.kernel_size
        patches = lax.conv_general_dilated_patches(
            x, (k,), (self.stride,), "VALID", dimension_numbers=("NTC", "TIO", "NTC"))
        y = jnp.einsum("ntp,tpo->nto", patches, params["W"].astype(x.dtype))
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self.activation_fn()(y), state
