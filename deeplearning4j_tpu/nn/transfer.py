"""Transfer learning — parity with ``org.deeplearning4j.nn.transferlearning``.

``TransferLearning.Builder(net)``: fine_tune_configuration,
set_feature_extractor (freeze up to layer), nout_replace, remove_output_layer,
remove_layers_from_output, add_layer. Frozen layers get zero updates via the
optimizer's multi_transform (no FrozenLayer wrapper interpreting at runtime —
the freeze is free at train time under jit).
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .conf import GlobalConf, MultiLayerConfiguration, resolve_layer_defaults
from .layers.base import Ctx, Layer
from .multi_layer_network import MultiLayerNetwork


class FineTuneConfiguration:
    """Subset of global config overridable at transfer time."""

    def __init__(self, updater=None, seed=None, l1=None, l2=None,
                 dropout=None, weight_init=None):
        self.updater = updater
        self.seed = seed
        self.l1 = l1
        self.l2 = l2
        self.dropout = dropout
        self.weight_init = weight_init

    def apply_to(self, g: GlobalConf):
        if self.updater is not None:
            g.updater = self.updater
        if self.seed is not None:
            g.seed = self.seed
        if self.l1 is not None:
            g.l1 = self.l1
        if self.l2 is not None:
            g.l2 = self.l2
        if self.dropout is not None:
            g.dropout = self.dropout
        if self.weight_init is not None:
            g.weight_init = self.weight_init


def _copy_if_compatible(src_p, dst_p, src_s):
    """(params, states) deep COPIES when tree structure + leaf shapes match,
    else None. Copies (jnp.array), never aliases: the train step donates its
    params/states buffers, so aliasing would let the transferred net's first
    fit() delete the SOURCE network's arrays."""
    import jax.numpy as jnp
    if jax.tree_util.tree_structure(src_p) != \
            jax.tree_util.tree_structure(dst_p):
        return None
    if not all(a.shape == b.shape for a, b in zip(
            jax.tree_util.tree_leaves(src_p),
            jax.tree_util.tree_leaves(dst_p))):
        return None
    return (jax.tree_util.tree_map(jnp.array, src_p),
            jax.tree_util.tree_map(jnp.array, src_s))


class TransferLearning:
    class GraphBuilder:
        """ComputationGraph transfer — parity with the reference's
        ``TransferLearning.GraphBuilder``: freeze up to named vertices
        (ancestors included), nOutReplace by layer name, remove vertices
        with their connections, graft new layers/vertices, re-point
        outputs. Retained, shape-compatible weights are copied over."""

        def __init__(self, net):
            from .computation_graph import ComputationGraph
            if not isinstance(net, ComputationGraph) or not net.initialized:
                raise ValueError("source must be an initialized "
                                 "ComputationGraph")
            self._src = net
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_at: List[str] = []
            self._nout_replace: List = []
            self._removed: List[str] = []
            self._added: List = []          # (name, op, inputs, is_layer)
            self._outputs: Optional[List[str]] = None
            self._input_shapes = None

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, *vertex_names: str):
            """Freeze the named vertices AND everything feeding them
            (reference setFeatureExtractor semantics)."""
            self._freeze_at.extend(vertex_names)
            return self

        def nout_replace(self, layer_name: str, n_out: int, weight_init=None):
            self._nout_replace.append((layer_name, n_out, weight_init))
            return self

        def remove_vertex_and_connections(self, name: str):
            self._removed.append(name)
            return self

        def add_layer(self, name: str, layer: Layer, *inputs: str):
            self._added.append((name, layer, list(inputs), True))
            return self

        def add_vertex(self, name: str, vertex, *inputs: str):
            self._added.append((name, vertex, list(inputs), False))
            return self

        def set_outputs(self, *names: str):
            self._outputs = list(names)
            return self

        def set_input_shapes(self, *shapes):
            self._input_shapes = [tuple(s) for s in shapes]
            return self

        def _ancestors(self, nodes, names):
            out = set()
            stack = list(names)
            while stack:
                n = stack.pop()
                if n in out or n not in nodes:
                    continue
                out.add(n)
                stack.extend(nodes[n].inputs)
            return out

        def build(self):
            from .computation_graph import ComputationGraph
            from .graph import GraphBuilder as ConfBuilder
            src = self._src
            g = copy.deepcopy(src.conf.globals_)
            if self._fine_tune is not None:
                self._fine_tune.apply_to(g)

            kept = {n: copy.deepcopy(d) for n, d in src.conf.nodes.items()
                    if n not in self._removed}
            # a removed name that is re-added (grafting a replacement under
            # the same name) is not dangling — DL4J's standard workflow
            readded = {n for n, _, _, _ in self._added}
            gone = set(self._removed) - readded
            dangling = [n for n, d in kept.items()
                        if any(i in gone for i in d.inputs)]
            if dangling:
                raise ValueError(
                    f"nodes {dangling} still consume removed vertices — "
                    "remove them too or re-point their inputs via add_*")

            frozen = self._ancestors(kept, self._freeze_at)
            missing = [n for n in self._freeze_at if n not in kept]
            if missing:
                raise ValueError(f"unknown feature-extractor nodes {missing}")
            invalid = set()                 # nodes whose weights can't copy

            def touch_consumers(name, n_out):
                """Invalidate consumers of `name`; direct Layer consumers
                get the exact new n_in, Layers reached THROUGH vertices get
                n_in=None so init re-infers the fan-in from the real shape
                (a vertex may change the width, e.g. a concat)."""
                for n, d in kept.items():
                    if name not in d.inputs:
                        continue
                    invalid.add(n)
                    if isinstance(d.op, Layer):
                        if getattr(d.op, "n_in", None) is not None:
                            d.op = dataclasses.replace(d.op, n_in=n_out)
                    else:                   # vertex: recurse; its Layer
                        touch_consumers(n, None)   # consumers re-infer n_in

            for lname, n_out, winit in self._nout_replace:
                if lname not in kept or not isinstance(kept[lname].op, Layer):
                    raise ValueError(f"nout_replace: no layer '{lname}'")
                kept[lname].op = dataclasses.replace(kept[lname].op,
                                                     n_out=n_out)
                if winit is not None:
                    kept[lname].op.weight_init = winit
                invalid.add(lname)
                touch_consumers(lname, n_out)

            b = ConfBuilder(g)
            b.add_inputs(*src.conf.inputs)
            for name in src.conf.topo_order:
                if name not in kept:
                    continue
                d = kept[name]
                if isinstance(d.op, Layer):
                    if name in frozen:
                        d.op.frozen = True
                    b.add_layer(name, d.op, *d.inputs)
                else:
                    b.add_vertex(name, d.op, *d.inputs)
            for name, op, inputs, is_layer in self._added:
                (b.add_layer if is_layer else b.add_vertex)(name, op, *inputs)
            outputs = self._outputs if self._outputs is not None else [
                o for o in src.conf.outputs if o not in gone]
            if not outputs:
                raise ValueError("no outputs left — set_outputs() required")
            b.set_outputs(*outputs)
            if src.conf.input_types is not None:
                b.set_input_types(*src.conf.input_types)

            net = ComputationGraph(b.build())
            shapes = self._input_shapes or getattr(src, "_init_shapes", None)
            net.init(shapes)
            for name in kept:
                if name in invalid or name not in net.params \
                        or name not in src.params:
                    continue
                copied = _copy_if_compatible(src.params[name],
                                             net.params[name],
                                             src.states[name])
                if copied is not None:
                    net.params[name], net.states[name] = copied
            return net

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            if not net.initialized:
                raise ValueError("source network must be initialized")
            self._src = net
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            self._nout_replace: List = []
            self._remove_from: Optional[int] = None
            self._added: List[Layer] = []
            self._input_shape = None

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (reference setFeatureExtractor)."""
            self._freeze_until = layer_idx
            return self

        def nout_replace(self, layer_idx: int, n_out: int, weight_init=None):
            self._nout_replace.append((layer_idx, n_out, weight_init))
            return self

        def remove_output_layer(self):
            self._remove_from = len(self._src.layers) - 1
            return self

        def remove_layers_from_output(self, n: int):
            self._remove_from = len(self._src.layers) - n
            return self

        def add_layer(self, layer: Layer):
            self._added.append(layer)
            return self

        def set_input_shape(self, shape):
            self._input_shape = tuple(shape)
            return self

        def build(self) -> MultiLayerNetwork:
            src = self._src
            g = copy.deepcopy(src.conf.globals_)
            if self._fine_tune is not None:
                self._fine_tune.apply_to(g)
            keep_n = self._remove_from if self._remove_from is not None else len(src.layers)
            layers = [copy.deepcopy(l) for l in src.layers[:keep_n]]
            replaced_from = len(layers)  # layers >= this index get fresh params
            for idx, n_out, winit in self._nout_replace:
                layers[idx] = dataclasses.replace(layers[idx], n_out=n_out)
                if winit is not None:
                    layers[idx].weight_init = winit
                replaced_from = min(replaced_from, idx)
            for i, lyr in enumerate(layers):
                if self._freeze_until is not None and i <= self._freeze_until:
                    lyr.frozen = True
                resolve_layer_defaults(lyr, g)
            new_layers = layers + [copy.deepcopy(l) for l in self._added]
            for lyr in new_layers[len(layers):]:
                resolve_layer_defaults(lyr, g)
            conf = MultiLayerConfiguration(g, new_layers, src.conf.input_type)
            net = MultiLayerNetwork(conf)
            in_shape = self._input_shape
            if in_shape is None and src.conf.input_type is not None:
                in_shape = tuple(src.conf.input_type[1])
            if in_shape is None:    # the source net recorded its init shape
                in_shape = getattr(src, "_init_input_shape", None)
            if in_shape is None:
                raise ValueError("set_input_shape() required when source conf has no input type")
            net.init(in_shape)
            # copy weights for retained, un-replaced layers (nOut change at
            # idx invalidates idx and idx+1 like the reference)
            invalid = set()
            for idx, _, _ in self._nout_replace:
                invalid.add(idx)
                invalid.add(idx + 1)
            for i in range(keep_n):
                if i in invalid:
                    continue
                copied = _copy_if_compatible(src.params[f"layer_{i}"],
                                             net.params[f"layer_{i}"],
                                             src.states[f"layer_{i}"])
                if copied is not None:
                    net.params[f"layer_{i}"], net.states[f"layer_{i}"] = copied
            return net


class TransferLearningHelper:
    """Featurized transfer learning (reference:
    ``org.deeplearning4j.nn.transferlearning.TransferLearningHelper``).

    Splits a MultiLayerNetwork at the frozen boundary: ``featurize`` runs
    the frozen trunk once per DataSet (one jitted forward — the expensive
    pretrained conv stack is never re-executed during head training),
    ``fit_featurized`` trains only the unfrozen head, and trained head
    params write back into the source network.
    """

    def __init__(self, net: MultiLayerNetwork, frozen_till: Optional[int] = None):
        if not net.initialized:
            raise ValueError("initialize the network first (net.init(...))")
        if frozen_till is None:
            if not net.layers[0].frozen:
                raise ValueError(
                    "no frozen PREFIX: layer 0 is trainable — pass "
                    "frozen_till explicitly or freeze a prefix "
                    "(TransferLearning builder / FrozenLayer)")
            frozen_till = 0
            while (frozen_till + 1 < len(net.layers)
                   and net.layers[frozen_till + 1].frozen):
                frozen_till += 1
        self._src = net
        self._k = int(frozen_till) + 1
        if not 0 < self._k < len(net.layers):
            raise ValueError(f"frozen_till={frozen_till} must leave at least "
                             "one frozen and one trainable layer")

        def trunk(params, states, x):
            h = x
            for i in range(self._k):
                if i in net._preprocessors:
                    h = net._preprocessors[i](h)
                h, _ = net.layers[i].apply(params[f"layer_{i}"],
                                           states[f"layer_{i}"], h,
                                           Ctx(train=False))
            return h
        self._trunk = jax.jit(trunk)

        # head network over the unfrozen tail (fresh conf, shared weights)
        g = copy.deepcopy(net.conf.globals_)
        head_layers = [copy.deepcopy(l) for l in net.layers[self._k:]]
        for l in head_layers:
            l.frozen = False
        feat_shape = self._feature_shape()
        conf = MultiLayerConfiguration(g, head_layers, None)
        self._head = MultiLayerNetwork(conf).init(feat_shape)
        for i in range(len(head_layers)):
            self._head.params[f"layer_{i}"] = net.params[f"layer_{self._k + i}"]
            self._head.states[f"layer_{i}"] = net.states[f"layer_{self._k + i}"]

    def _feature_shape(self):
        net = self._src
        in_shape = getattr(net, "_init_input_shape", None)
        if in_shape is None and net.conf.input_type is not None:
            in_shape = tuple(net.conf.input_type[1])
        if in_shape is None:
            raise ValueError("source net has no recorded input shape")
        out = jax.eval_shape(
            lambda p, s, x: self._trunk(p, s, x), net.params, net.states,
            jax.ShapeDtypeStruct((1,) + tuple(in_shape), jnp.float32))
        return tuple(out.shape[1:])

    # ------------------------------------------------------------------- api
    def featurize(self, ds):
        """DataSet -> DataSet whose features are the frozen trunk's output
        (reference featurize)."""
        from ..data.dataset import DataSet
        feats = self._trunk(self._src.params, self._src.states,
                            jnp.asarray(ds.features))
        return DataSet(np.asarray(feats), ds.labels,
                       features_mask=ds.features_mask,
                       labels_mask=ds.labels_mask)

    def fit_featurized(self, data, *, epochs: int = 1):
        """Train the head on featurized DataSets/iterators; head params
        write back into the source network (reference fitFeaturized)."""
        out = self._head.fit(data, epochs=epochs)
        for i in range(len(self._head.layers)):
            self._src.params[f"layer_{self._k + i}"] = \
                self._head.params[f"layer_{i}"]
            self._src.states[f"layer_{self._k + i}"] = \
                self._head.states[f"layer_{i}"]
        self._src._invalidate()
        return out

    def output_from_featurized(self, feats):
        return self._head.output(feats)

    def unfrozen_mln(self) -> MultiLayerNetwork:
        """The trainable submodel (reference unfrozenMLN)."""
        return self._head
