"""Transfer learning — parity with ``org.deeplearning4j.nn.transferlearning``.

``TransferLearning.Builder(net)``: fine_tune_configuration,
set_feature_extractor (freeze up to layer), nout_replace, remove_output_layer,
remove_layers_from_output, add_layer. Frozen layers get zero updates via the
optimizer's multi_transform (no FrozenLayer wrapper interpreting at runtime —
the freeze is free at train time under jit).
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, List, Optional

import jax

from .conf import GlobalConf, MultiLayerConfiguration, resolve_layer_defaults
from .layers.base import Layer
from .multi_layer_network import MultiLayerNetwork


class FineTuneConfiguration:
    """Subset of global config overridable at transfer time."""

    def __init__(self, updater=None, seed=None, l1=None, l2=None,
                 dropout=None, weight_init=None):
        self.updater = updater
        self.seed = seed
        self.l1 = l1
        self.l2 = l2
        self.dropout = dropout
        self.weight_init = weight_init

    def apply_to(self, g: GlobalConf):
        if self.updater is not None:
            g.updater = self.updater
        if self.seed is not None:
            g.seed = self.seed
        if self.l1 is not None:
            g.l1 = self.l1
        if self.l2 is not None:
            g.l2 = self.l2
        if self.dropout is not None:
            g.dropout = self.dropout
        if self.weight_init is not None:
            g.weight_init = self.weight_init


class TransferLearning:
    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            if not net.initialized:
                raise ValueError("source network must be initialized")
            self._src = net
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            self._nout_replace: List = []
            self._remove_from: Optional[int] = None
            self._added: List[Layer] = []
            self._input_shape = None

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (reference setFeatureExtractor)."""
            self._freeze_until = layer_idx
            return self

        def nout_replace(self, layer_idx: int, n_out: int, weight_init=None):
            self._nout_replace.append((layer_idx, n_out, weight_init))
            return self

        def remove_output_layer(self):
            self._remove_from = len(self._src.layers) - 1
            return self

        def remove_layers_from_output(self, n: int):
            self._remove_from = len(self._src.layers) - n
            return self

        def add_layer(self, layer: Layer):
            self._added.append(layer)
            return self

        def set_input_shape(self, shape):
            self._input_shape = tuple(shape)
            return self

        def build(self) -> MultiLayerNetwork:
            src = self._src
            g = copy.deepcopy(src.conf.globals_)
            if self._fine_tune is not None:
                self._fine_tune.apply_to(g)
            keep_n = self._remove_from if self._remove_from is not None else len(src.layers)
            layers = [copy.deepcopy(l) for l in src.layers[:keep_n]]
            replaced_from = len(layers)  # layers >= this index get fresh params
            for idx, n_out, winit in self._nout_replace:
                layers[idx] = dataclasses.replace(layers[idx], n_out=n_out)
                if winit is not None:
                    layers[idx].weight_init = winit
                replaced_from = min(replaced_from, idx)
            for i, lyr in enumerate(layers):
                if self._freeze_until is not None and i <= self._freeze_until:
                    lyr.frozen = True
                resolve_layer_defaults(lyr, g)
            new_layers = layers + [copy.deepcopy(l) for l in self._added]
            for lyr in new_layers[len(layers):]:
                resolve_layer_defaults(lyr, g)
            conf = MultiLayerConfiguration(g, new_layers, src.conf.input_type)
            net = MultiLayerNetwork(conf)
            in_shape = self._input_shape
            if in_shape is None and src.conf.input_type is not None:
                in_shape = tuple(src.conf.input_type[1])
            if in_shape is None:
                raise ValueError("set_input_shape() required when source conf has no input type")
            net.init(in_shape)
            # copy weights for retained, un-replaced layers (nOut change at
            # idx invalidates idx and idx+1 like the reference)
            invalid = set()
            for idx, _, _ in self._nout_replace:
                invalid.add(idx)
                invalid.add(idx + 1)
            for i in range(keep_n):
                if i in invalid:
                    continue
                src_p = src.params[f"layer_{i}"]
                dst_p = net.params[f"layer_{i}"]
                if jax.tree_util.tree_structure(src_p) == jax.tree_util.tree_structure(dst_p):
                    ok = all(a.shape == b.shape for a, b in zip(
                        jax.tree_util.tree_leaves(src_p), jax.tree_util.tree_leaves(dst_p)))
                    if ok:
                        net.params[f"layer_{i}"] = jax.tree_util.tree_map(lambda a: a, src_p)
                        net.states[f"layer_{i}"] = jax.tree_util.tree_map(
                            lambda a: a, src.states[f"layer_{i}"])
            return net
