"""Loss functions — parity with ``org.nd4j.linalg.lossfunctions.LossFunctions``.

Every loss is `fn(labels, preds, weights=None, mask=None) -> scalar` plus a
`per_example` variant returning (batch,) scores (used by masking, per-output
weighting, and `MultiLayerNetwork.scoreExamples`). `preds` are the layer's
*activated* outputs (DL4J convention) except the `*_with_logits` variants.

DL4J reduction convention: score = sum over output units, mean over (unmasked)
examples — matched here so numbers line up with the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-7


def _weighted(per_unit, weights):
    if weights is not None:
        per_unit = per_unit * weights
    return per_unit


def _reduce(per_unit, mask):
    """Sum over trailing dims → per-example score. Masking of individual
    units/timesteps already happened in _apply_mask; _mean handles the
    example-level weighting."""
    return per_unit.reshape(per_unit.shape[0], -1).sum(axis=1)


def _mean(per_ex, mask):
    if mask is None:
        return per_ex.mean()
    m = mask.reshape(mask.shape[0], -1).max(axis=1)  # example present at all?
    return (per_ex * m).sum() / jnp.maximum(m.sum(), 1.0)


def _apply_mask(per_unit, mask):
    """Mask shape (B,) / (B,T) / full — broadcast against per-unit scores."""
    if mask is None:
        return per_unit
    m = mask
    while m.ndim < per_unit.ndim:
        m = m[..., None]
    return per_unit * m


# --- classification --------------------------------------------------------

def mcxent_per_unit(labels, preds, weights=None, mask=None):
    p = jnp.clip(preds, _EPS, 1.0 - _EPS)
    per_unit = -labels * jnp.log(p)
    return _apply_mask(_weighted(per_unit, weights), mask)


def mcxent(labels, preds, weights=None, mask=None):
    """Multi-class cross entropy vs softmax output (LossMCXENT)."""
    per_unit = mcxent_per_unit(labels, preds, weights, mask)
    return _mean(_reduce(per_unit, mask), mask)


negative_log_likelihood = mcxent  # DL4J NEGATIVELOGLIKELIHOOD == MCXENT vs softmax


def sparse_mcxent(labels, preds, weights=None, mask=None):
    """Labels are int class ids (SparseMCXENT)."""
    p = jnp.clip(jnp.take_along_axis(preds, labels[..., None].astype(jnp.int32), -1), _EPS, 1.0)
    per_unit = -jnp.log(p)[..., 0]
    if weights is not None:
        per_unit = per_unit * jnp.take(weights, labels)
    per_unit = _apply_mask(per_unit, mask)
    if per_unit.ndim == 1:
        per_ex = per_unit
    else:
        per_ex = per_unit.reshape(per_unit.shape[0], -1).sum(axis=1)
    return _mean(per_ex, mask)


def softmax_cross_entropy_with_logits(labels, logits, weights=None, mask=None):
    """Numerically-stable fused path (what our OutputLayer actually uses)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    per_unit = _apply_mask(_weighted(-labels * logp, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def sparse_softmax_cross_entropy_with_logits(labels, logits, weights=None, mask=None):
    logp = jax.nn.log_softmax(logits, axis=-1)
    per_unit = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), -1)[..., 0]
    per_unit = _apply_mask(per_unit, mask)
    per_ex = per_unit if per_unit.ndim == 1 else per_unit.reshape(per_unit.shape[0], -1).sum(axis=1)
    return _mean(per_ex, mask)


def binary_xent(labels, preds, weights=None, mask=None):
    """LossBinaryXENT vs sigmoid output."""
    p = jnp.clip(preds, _EPS, 1.0 - _EPS)
    per_unit = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))
    per_unit = _apply_mask(_weighted(per_unit, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def sigmoid_cross_entropy_with_logits(labels, logits, weights=None, mask=None):
    z = jax.nn.relu(logits) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    per_unit = _apply_mask(_weighted(z, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def hinge(labels, preds, weights=None, mask=None):
    """Labels in {-1,1} (LossHinge)."""
    per_unit = jax.nn.relu(1.0 - labels * preds)
    per_unit = _apply_mask(_weighted(per_unit, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def squared_hinge(labels, preds, weights=None, mask=None):
    per_unit = jnp.square(jax.nn.relu(1.0 - labels * preds))
    per_unit = _apply_mask(_weighted(per_unit, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def fmeasure(labels, preds, beta=1.0, weights=None, mask=None):
    """LossFMeasure — differentiable soft-F_beta (binary). Returns 1 - F."""
    preds = _apply_mask(preds, mask)
    labels = _apply_mask(labels, mask)
    tp = jnp.sum(labels * preds)
    fp = jnp.sum((1.0 - labels) * preds)
    fn = jnp.sum(labels * (1.0 - preds))
    b2 = beta * beta
    f = (1.0 + b2) * tp / jnp.maximum((1.0 + b2) * tp + b2 * fn + fp, _EPS)
    return 1.0 - f


# --- regression ------------------------------------------------------------

def mse(labels, preds, weights=None, mask=None):
    per_unit = _apply_mask(_weighted(jnp.square(preds - labels), weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


l2 = mse  # DL4J LossL2 = sum of squares (no mean over units); score matches via _reduce


def rmse(labels, preds, weights=None, mask=None):
    return jnp.sqrt(mse(labels, preds, weights, mask))


def mae(labels, preds, weights=None, mask=None):
    per_unit = _apply_mask(_weighted(jnp.abs(preds - labels), weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


l1 = mae


def msle(labels, preds, weights=None, mask=None):
    per_unit = jnp.square(jnp.log1p(jnp.maximum(preds, -1 + _EPS)) - jnp.log1p(jnp.maximum(labels, -1 + _EPS)))
    per_unit = _apply_mask(_weighted(per_unit, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def mape(labels, preds, weights=None, mask=None):
    per_unit = 100.0 * jnp.abs((preds - labels) / jnp.clip(jnp.abs(labels), _EPS, None))
    per_unit = _apply_mask(_weighted(per_unit, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def kl_divergence(labels, preds, weights=None, mask=None):
    p = jnp.clip(labels, _EPS, 1.0)
    q = jnp.clip(preds, _EPS, 1.0)
    per_unit = _apply_mask(_weighted(p * (jnp.log(p) - jnp.log(q)), weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def poisson(labels, preds, weights=None, mask=None):
    per_unit = preds - labels * jnp.log(jnp.clip(preds, _EPS, None))
    per_unit = _apply_mask(_weighted(per_unit, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def cosine_proximity(labels, preds, weights=None, mask=None):
    ln = labels / jnp.maximum(jnp.linalg.norm(labels, axis=-1, keepdims=True), _EPS)
    pn = preds / jnp.maximum(jnp.linalg.norm(preds, axis=-1, keepdims=True), _EPS)
    per_unit = _apply_mask(_weighted(-ln * pn, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def wasserstein(labels, preds, weights=None, mask=None):
    """LossWasserstein: mean(labels * preds) — critic loss for WGAN."""
    per_unit = _apply_mask(_weighted(labels * preds, weights), mask)
    return _mean(_reduce(per_unit, mask), mask)


def mixture_density(labels, preds, n_mixtures, weights=None, mask=None):
    """LossMixtureDensity: negative log-likelihood of a GMM head.

    preds packs [alpha_logits(K), mu(K*D), log_sigma(K)] along the last axis.
    """
    d = labels.shape[-1]
    k = n_mixtures
    alpha = jax.nn.log_softmax(preds[..., :k], axis=-1)
    mu = preds[..., k:k + k * d].reshape(*preds.shape[:-1], k, d)
    log_sigma = preds[..., k + k * d:k + k * d + k]
    y = labels[..., None, :]
    sq = jnp.sum(jnp.square(y - mu), axis=-1)
    log_prob = alpha - 0.5 * sq / jnp.exp(2.0 * log_sigma) \
        - d * (log_sigma + 0.5 * jnp.log(2.0 * jnp.pi))
    nll = -jax.scipy.special.logsumexp(log_prob, axis=-1)
    nll = _apply_mask(_weighted(nll, weights), mask)
    per_ex = nll if nll.ndim == 1 else nll.reshape(nll.shape[0], -1).sum(axis=1)
    return _mean(per_ex, mask)


def multi_label(labels, preds, weights=None, mask=None):
    """LossMultiLabel (reference ``LossMultiLabel``): pairwise ranking loss
    over (positive, negative) label pairs per example —
    ``(1/(|Y||Ybar|)) * sum_{k in Y, l in Ybar} exp(o_l - o_k)``.

    Vectorized in LOG space via the factorization
    ``exp(logsumexp_l(o_l) + logsumexp_k(-o_k))`` so the result is finite
    whenever the true pairwise sum is representable (a naive max-shift
    product overflows when the logit spread exceeds ~88 in f32). Examples
    with an empty positive OR negative set contribute 0 (the reference
    skips them); a per-output mask shrinks the label sets, a per-example
    (B,) mask drops whole examples."""
    if weights is not None:
        raise ValueError(
            "multi_label has no per-output weighting (pairwise ranking has "
            "no per-unit term; upstream LossMultiLabel takes no weights)")
    pos = (labels > 0.5).astype(preds.dtype)
    neg = 1.0 - pos
    ex_mask = None
    if mask is not None:
        if mask.ndim == preds.ndim:        # per-output mask: shrink the sets
            pos = pos * mask.astype(preds.dtype)
            neg = neg * mask.astype(preds.dtype)
        else:                              # (B,)-style example mask
            ex_mask = mask
    lse_neg = jax.scipy.special.logsumexp(preds, axis=-1, b=neg)
    lse_pos = jax.scipy.special.logsumexp(-preds, axis=-1, b=pos)
    n_pairs = jnp.sum(pos, axis=-1) * jnp.sum(neg, axis=-1)
    log_loss = lse_neg + lse_pos - jnp.log(jnp.maximum(n_pairs, 1.0))
    per_ex = jnp.where(n_pairs > 0, jnp.exp(log_loss), 0.0)
    if per_ex.ndim > 1:  # time-distributed (B, T) -> sum over time
        per_ex = per_ex.reshape(per_ex.shape[0], -1).sum(axis=1)
    return _mean(per_ex, ex_mask)


class Loss:
    """DL4J-style enum: LossFunctions.LossFunction.* (string-valued)."""

    MCXENT = "mcxent"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    SPARSE_MCXENT = "sparse_mcxent"
    XENT = "binary_xent"  # DL4J XENT = binary cross entropy
    MSE = "mse"
    SQUARED_LOSS = "mse"
    L1 = "l1"
    MAE = "mae"
    L2 = "l2"
    RMSE = "rmse"
    MSLE = "msle"
    MAPE = "mape"
    KL_DIVERGENCE = "kl_divergence"
    RECONSTRUCTION_CROSSENTROPY = "binary_xent"
    POISSON = "poisson"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    COSINE_PROXIMITY = "cosine_proximity"
    WASSERSTEIN = "wasserstein"
    FMEASURE = "fmeasure"
    MIXTURE_DENSITY = "mixture_density"
    MULTI_LABEL = "multi_label"


_REGISTRY = {
    "mcxent": mcxent, "negativeloglikelihood": negative_log_likelihood,
    "sparse_mcxent": sparse_mcxent, "binary_xent": binary_xent, "xent": binary_xent,
    "mse": mse, "l2": l2, "rmse": rmse, "mae": mae, "l1": l1,
    "msle": msle, "mape": mape, "kl_divergence": kl_divergence,
    "poisson": poisson, "hinge": hinge, "squared_hinge": squared_hinge,
    "cosine_proximity": cosine_proximity, "wasserstein": wasserstein,
    "fmeasure": fmeasure, "mixture_density": mixture_density,
    "multi_label": multi_label, "multilabel": multi_label,
}

# losses whose stable fused-logits variant exists; OutputLayer uses these
LOGITS_VARIANTS = {
    "mcxent": softmax_cross_entropy_with_logits,
    "negativeloglikelihood": softmax_cross_entropy_with_logits,
    "sparse_mcxent": sparse_softmax_cross_entropy_with_logits,
    "binary_xent": sigmoid_cross_entropy_with_logits,
    "xent": sigmoid_cross_entropy_with_logits,
}


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss '{name_or_fn}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
