"""ComputationGraph — DAG network runtime.

Reference parity: ``org.deeplearning4j.nn.graph.ComputationGraph``
(init/fit/output/score/evaluate on multi-input multi-output DAGs).
The topological order traces into one jaxpr; multi-output losses sum with
per-output weights like the reference. Shares the train-step design of
MultiLayerNetwork (one jitted donated step).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
import optax

from ..train.updaters import NoOp, build_optimizer
from .graph import ComputationGraphConfiguration
from .layers.base import Ctx, Layer
from .layers.wrappers import unwrap
from .layers.core import LossLayer, OutputLayer
from .layers.samediff_layer import SameDiffOutputLayer
from .preprocessors import CnnToFeedForwardPreProcessor
from .vertices import GraphVertex
from .weightnoise import maybe_apply_weight_noise


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self._g = conf.globals_
        self.params: Dict[str, dict] = {}
        self.states: Dict[str, dict] = {}
        self._preprocessors: Dict[str, Any] = {}
        self._optimizer = None
        self._opt_state = None
        self.listeners: List[Any] = []
        self.initialized = False
        self._train_step = None
        self._scan_epoch = None
        self._infer_fn = None
        self.epoch_count = 0
        self._step_count = 0
        self._host_key = jax.random.PRNGKey(self._g.seed)
        self.output_loss_weights = {name: 1.0 for name in conf.outputs}
        # int n -> train-time forward runs as n jax.checkpoint segments
        # (activation rematerialization; see _forward_remat)
        self.remat_segments = None

    @property
    def remat_segments(self):
        return self._remat_segments

    @remat_segments.setter
    def remat_segments(self, n):
        """Changing the remat policy invalidates every compiled step that
        traced the old forward (same staleness rule as
        enable_gradient_anomaly_detection)."""
        if getattr(self, "_remat_segments", None) != n:
            self._invalidate()
            self._remat_plan_cache = {}
        self._remat_segments = n

    def _invalidate(self):
        """Drop every compiled function that closed over params/topology
        (mirrors MultiLayerNetwork._invalidate)."""
        self._train_step = None
        self._scan_epoch = None
        self._infer_fn = None
        self._rnn_stream_fn = None

    # ------------------------------------------------------------------ init
    def init(self, input_shapes=None):
        if input_shapes is None:
            if self.conf.input_types is None:
                raise ValueError("Provide input_shapes or set_input_types")
            input_shapes = [tuple(t[1]) for t in self.conf.input_types]
        self._init_shapes = [tuple(s) for s in input_shapes]  # for transfer
        shapes = {name: tuple(s) for name, s in zip(self.conf.inputs, input_shapes)}
        key = jax.random.PRNGKey(self._g.seed)
        for name in self.conf.topo_order:
            node = self.conf.nodes[name]
            in_shapes = [shapes[i] for i in node.inputs]
            if isinstance(node.op, Layer):
                from .multi_layer_network import _is_ff_layer
                if getattr(node.op, "multi_input", False):
                    key, sub = jax.random.split(key)
                    p, st, out = node.op.init(sub, in_shapes)
                    self.params[name] = p
                    self.states[name] = st
                    shapes[name] = out
                    continue
                s = in_shapes[0]
                if (_is_ff_layer(node.op) or isinstance(unwrap(node.op), OutputLayer)) \
                        and len(s) == 3:
                    pp = CnnToFeedForwardPreProcessor()
                    self._preprocessors[name] = pp
                    s = pp.out_shape(s)
                key, sub = jax.random.split(key)
                p, st, out = node.op.init(sub, s)
                self.params[name] = p
                self.states[name] = st
                shapes[name] = out
            else:
                shapes[name] = node.op.out_shape(in_shapes)
                self.params[name] = {}
                self.states[name] = {}
        self.output_shapes = {o: shapes[o] for o in self.conf.outputs}
        self.initialized = True
        return self

    # -------------------------------------------------------------- forward
    def _apply_node(self, idx, name, params, states, acts, pre_acts,
                    new_states, *, train, rng, fmask, lmask,
                    stop_at_output_preact):
        """Apply one topo-order node, writing into acts/pre_acts/new_states.

        ``idx`` is the GLOBAL topo position (the per-node rng is
        ``fold_in(rng, idx)``), so segmented execution reproduces the exact
        dropout/weight-noise draws of the monolithic walk."""
        node = self.conf.nodes[name]
        xs = [acts[i] for i in node.inputs]
        # named scope: the node's ops carry <name>.<Type> in the fused
        # executable's metadata (xprof layer map; trace-time only) —
        # mirrors MultiLayerNetwork._apply_one and obs.profiler naming
        scope = jax.named_scope(
            f"{name}.{type(unwrap(node.op)).__name__}".replace("/", "_"))
        with scope:
            self._apply_node_inner(
                name, node, xs, params, states, acts, pre_acts, new_states,
                train=train, rng=rng, idx=idx, fmask=fmask, lmask=lmask,
                stop_at_output_preact=stop_at_output_preact)

    def _apply_node_inner(self, name, node, xs, params, states, acts,
                          pre_acts, new_states, *, train, rng, idx, fmask,
                          lmask, stop_at_output_preact):
        if isinstance(node.op, Layer):
            if getattr(node.op, "multi_input", False):
                lrng = None if rng is None else jax.random.fold_in(rng, idx)
                ctx = Ctx(train=train, rng=lrng, mask=fmask, label_mask=lmask)
                if train and node.op.dropout > 0.0 and lrng is not None:
                    keep = 1.0 - node.op.dropout
                    dropped = []
                    for j, h in enumerate(xs):
                        m = jax.random.bernoulli(
                            jax.random.fold_in(lrng, 997 + j), keep, h.shape)
                        dropped.append(
                            jnp.where(m, h / keep, 0.0).astype(h.dtype))
                    xs = dropped
                p_n = maybe_apply_weight_noise(node.op, params[name],
                                               lrng, train)
                h, s_new = node.op.apply(p_n, states[name], xs, ctx)
                new_states[name] = s_new
                acts[name] = h
                return
            h = xs[0]
            if name in self._preprocessors:
                h = self._preprocessors[name](h)
            lrng = None if rng is None else jax.random.fold_in(rng, idx)
            ctx = Ctx(train=train, rng=lrng, mask=fmask, label_mask=lmask)
            if train and node.op.dropout > 0.0 and lrng is not None:
                keep = 1.0 - node.op.dropout
                m = jax.random.bernoulli(jax.random.fold_in(lrng, 997), keep, h.shape)
                h = jnp.where(m, h / keep, 0.0).astype(h.dtype)
            if stop_at_output_preact and name in self.conf.outputs and \
                    isinstance(unwrap(node.op),
                               (OutputLayer, LossLayer, SameDiffOutputLayer)):
                pre_acts[name] = h
                new_states[name] = states[name]
                acts[name] = h
                return
            p_n = maybe_apply_weight_noise(node.op, params[name],
                                           lrng, train)
            h, s_new = node.op.apply(p_n, states[name], h, ctx)
            new_states[name] = s_new
            acts[name] = h
        else:
            acts[name] = node.op.apply(xs)
            new_states[name] = states[name]

    def _as_input_dict(self, inputs):
        """Accept {name: arr}, [arr, ...] (zipped with conf.inputs), or a
        bare array (single-input graphs) — the MLN-compatible calling
        convention ParallelWrapper/ParallelInference use."""
        if isinstance(inputs, dict):
            return inputs
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != len(self.conf.inputs):
                raise ValueError(
                    f"got {len(inputs)} feature arrays for a graph with "
                    f"{len(self.conf.inputs)} inputs {self.conf.inputs}")
            return {n: v for n, v in zip(self.conf.inputs, inputs)}
        return {self.conf.inputs[0]: inputs}

    def _as_label_dict(self, labels):
        if isinstance(labels, dict):
            return labels
        if isinstance(labels, (list, tuple)):
            if len(labels) != len(self.conf.outputs):
                raise ValueError(
                    f"got {len(labels)} label arrays for a graph with "
                    f"{len(self.conf.outputs)} outputs {self.conf.outputs}")
            return {n: v for n, v in zip(self.conf.outputs, labels)}
        return {self.conf.outputs[0]: labels}

    def _forward(self, params, states, inputs, *, train, rng,
                 fmask=None, lmask=None, stop_at_output_preact=False):
        inputs = self._as_input_dict(inputs)
        if train and getattr(self, "remat_segments", None):
            return self._forward_remat(
                params, states, inputs, train=train, rng=rng, fmask=fmask,
                lmask=lmask, stop_at_output_preact=stop_at_output_preact)
        acts = dict(inputs)
        new_states = {}
        pre_acts = {}
        for idx, name in enumerate(self.conf.topo_order):
            self._apply_node(idx, name, params, states, acts, pre_acts,
                             new_states, train=train, rng=rng, fmask=fmask,
                             lmask=lmask,
                             stop_at_output_preact=stop_at_output_preact)
        return acts, pre_acts, new_states

    # ------------------------------------------------------- segmented remat
    def _segment_plan(self, n_segments, input_names):
        """Partition topo_order into ``n_segments`` contiguous segments,
        cutting where the cross-boundary live set is smallest.

        Liveness: an activation is live after position i if its producer is
        at <= i and some consumer is at > i (graph outputs live to the end).
        Each cut carries exactly the live set, so ANY cut position is
        semantically valid — the live-set size only decides how much the
        checkpoint saves. For chain-of-blocks topologies (ResNet bottleneck
        stacks) the minimal-live cuts land on block boundaries where exactly
        one tensor crosses."""
        order = self.conf.topo_order
        n = len(order)
        last_use = {}
        for idx, name in enumerate(order):
            for i in self.conf.nodes[name].inputs:
                last_use[i] = idx
        for o in self.conf.outputs:
            last_use[o] = n
        producers = list(input_names) + order
        pos = {a: -1 for a in input_names}
        pos.update({name: idx for idx, name in enumerate(order)})

        def live_after(idx):
            return [a for a in producers
                    if pos[a] <= idx and last_use.get(a, -1) > idx]

        cuts = []
        span = n / n_segments
        for k in range(1, n_segments):
            ideal = int(round(k * span)) - 1
            lo = max((cuts[-1] + 1) if cuts else 0, int(ideal - span // 2))
            hi = min(n - 2, int(ideal + span // 2))
            if lo > hi:
                continue
            best = min(range(lo, hi + 1),
                       key=lambda i: (len(live_after(i)), abs(i - ideal)))
            cuts.append(best)
        if len(cuts) + 1 < n_segments:
            import warnings
            warnings.warn(
                f"remat_segments={n_segments} exceeds what this "
                f"{n}-node graph supports; using {len(cuts) + 1} "
                "checkpoint segments (activation footprint will be larger "
                "than configured)", stacklevel=3)
        bounds = [-1] + cuts + [n - 1]
        segments = []
        for a, b in zip(bounds[:-1], bounds[1:]):
            nodes = [(i, order[i]) for i in range(a + 1, b + 1)]
            carry_in = sorted(live_after(a)) if a >= 0 else sorted(input_names)
            carry_out = sorted(live_after(b)) if b < n - 1 else \
                sorted(set(self.conf.outputs))
            segments.append({"nodes": nodes, "carry_in": carry_in,
                             "carry_out": carry_out})
        return segments

    def _forward_remat(self, params, states, inputs, *, train, rng,
                      fmask=None, lmask=None, stop_at_output_preact=False):
        """_forward with each segment under ``jax.checkpoint``: only the
        cross-segment live activations are saved for the backward pass;
        everything inside a segment is recomputed. Trades (otherwise idle,
        on an HBM-bound step) MXU cycles for activation traffic — the same
        lever as the transformer's remat-full policy."""
        key = (int(self.remat_segments), tuple(sorted(inputs)))
        cache = getattr(self, "_remat_plan_cache", None)
        if cache is None:
            cache = self._remat_plan_cache = {}
        plan = cache.get(key)
        if plan is None:
            plan = cache[key] = self._segment_plan(self.remat_segments,
                                                   sorted(inputs))
        acts = dict(inputs)
        pre_acts = {}
        new_states = {}
        for seg in plan:
            seg_names = [nm for _, nm in seg["nodes"]]
            seg_params = {nm: params[nm] for nm in seg_names}
            seg_states = {nm: states[nm] for nm in seg_names}

            def seg_fn(p, s, carry, rng_, fmask_, lmask_, _seg=seg):
                a = dict(carry)
                pre = {}
                ns = {}
                for idx, nm in _seg["nodes"]:
                    self._apply_node(
                        idx, nm, p, s, a, pre, ns, train=train, rng=rng_,
                        fmask=fmask_, lmask=lmask_,
                        stop_at_output_preact=stop_at_output_preact)
                return ({k: a[k] for k in _seg["carry_out"] if k in a},
                        ns, pre)

            carry_in = {k: acts[k] for k in seg["carry_in"]}
            out, ns, pre = jax.checkpoint(seg_fn)(
                seg_params, seg_states, carry_in, rng, fmask, lmask)
            acts.update(out)
            new_states.update(ns)
            pre_acts.update(pre)
        return acts, pre_acts, new_states

    def output(self, *inputs):
        if self._infer_fn is None:
            def infer(params, states, inputs):
                acts, _, _ = self._forward(params, states, inputs, train=False, rng=None)
                return [acts[o] for o in self.conf.outputs]
            self._infer_fn = jax.jit(infer)
        ins = {n: jnp.asarray(x) for n, x in zip(self.conf.inputs, inputs)}
        outs = self._infer_fn(self.params, self.states, ins)
        return outs[0] if len(outs) == 1 else outs

    # ------------------------------------------------------- rnn streaming
    def rnn_time_step(self, *inputs):
        """Streaming inference through the DAG (reference:
        ComputationGraph.rnnTimeStep): feed a (B, T, C) chunk — or a (B, C)
        float single step — per graph input; recurrent layer carries
        persist on device across calls until rnn_clear_previous_state().
        Same one-jitted-scan design as MultiLayerNetwork.rnn_time_step."""
        from .layers.recurrent import (BaseRecurrent, Bidirectional,
                                       LastTimeStep)
        from .layers.wrappers import TimeDistributedLayer
        for name in self.conf.topo_order:
            op = self.conf.nodes[name].op
            if isinstance(op, Layer) and isinstance(
                    unwrap(op), (Bidirectional, LastTimeStep,
                                 TimeDistributedLayer)):
                raise NotImplementedError(
                    f"rnn_time_step cannot stream through node '{name}' "
                    f"({type(unwrap(op)).__name__}): it needs the full "
                    "sequence (reference rnnTimeStep has the same limit)")
        xs = [jnp.asarray(x) for x in inputs]
        integer = jnp.issubdtype(xs[0].dtype, jnp.integer)
        single = (xs[0].ndim == 2 and not integer) or \
            (xs[0].ndim == 1 and integer)
        if single:
            xs = [x[:, None] if x.ndim == 1 else x[:, None, :] for x in xs]
        batch = xs[0].shape[0]

        old = getattr(self, "_rnn_carries", None) or {}
        if getattr(self, "_rnn_carry_batch", None) != batch:
            old = {}
        carries = {}
        for name in self.conf.topo_order:
            op = self.conf.nodes[name].op
            ul = unwrap(op) if isinstance(op, Layer) else None
            if isinstance(ul, BaseRecurrent):
                carries[name] = old.get(name)
                if carries[name] is None:
                    dtype = ul.compute_dtype or (
                        xs[0].dtype if jnp.issubdtype(xs[0].dtype,
                                                      jnp.floating)
                        else self._g.param_dtype)
                    carries[name] = ul.init_carry(batch, dtype)
        self._rnn_carry_batch = batch

        if getattr(self, "_rnn_stream_fn", None) is None:
            def stream(params, states, carries, ins):
                def step(cs, xt):
                    acts = dict(xt)
                    new_cs = {}
                    for name in self.conf.topo_order:
                        node = self.conf.nodes[name]
                        vals = [acts[i] for i in node.inputs]
                        if isinstance(node.op, Layer):
                            h = vals if getattr(node.op, "multi_input",
                                                False) else vals[0]
                            if name in self._preprocessors:
                                h = self._preprocessors[name](h)
                            ul = unwrap(node.op)
                            if isinstance(ul, BaseRecurrent):
                                h, c = ul.step_apply(params[name], cs[name],
                                                     h, Ctx(train=False))
                                new_cs[name] = c
                            else:
                                h, _ = node.op.apply(params[name],
                                                     states[name], h,
                                                     Ctx(train=False))
                            acts[name] = h
                        else:
                            acts[name] = node.op.apply(vals)
                    return new_cs, [acts[o] for o in self.conf.outputs]

                cs, ys = jax.lax.scan(
                    step, carries,
                    {n: v.swapaxes(0, 1) for n, v in ins.items()})
                return [y.swapaxes(0, 1) for y in ys], cs

            self._rnn_stream_fn = jax.jit(stream)

        ins = {n: x for n, x in zip(self.conf.inputs, xs)}
        ys, carries = self._rnn_stream_fn(self.params, self.states,
                                          carries, ins)
        self._rnn_carries = carries
        ys = [y[:, 0] for y in ys] if single else ys
        return ys[0] if len(ys) == 1 else ys

    def rnn_clear_previous_state(self):
        self._rnn_carries = None
        self._rnn_carry_batch = None

    # ----------------------------------------------------------------- loss
    def _loss(self, params, states, inputs, labels, rng, fmask, lmask):
        labels = self._as_label_dict(labels)
        acts, pre_acts, new_states = self._forward(
            params, states, inputs, train=True, rng=rng, fmask=fmask, lmask=lmask,
            stop_at_output_preact=True)
        total = 0.0
        for name in self.conf.outputs:
            op = unwrap(self.conf.nodes[name].op)
            y = labels[name]
            w = self.output_loss_weights.get(name, 1.0)
            # output-node work happens here (forward stops at its
            # pre-activation) — scope it like _apply_node scopes the rest
            with jax.named_scope(
                    f"{name}.{type(op).__name__}.loss".replace("/", "_")):
                if isinstance(op, (OutputLayer, SameDiffOutputLayer)):
                    total = total + w * op.compute_loss(
                        params[name], pre_acts[name], y, mask=lmask)
                elif isinstance(op, LossLayer):
                    total = total + w * op.compute_loss(
                        pre_acts[name], y, mask=lmask)
                else:
                    raise ValueError(
                        f"output node '{name}' is not an output/loss layer")
        total = total + self._reg_score(params)
        return total, new_states

    def _reg_score(self, params):
        reg = 0.0
        for name, node in self.conf.nodes.items():
            op = node.op
            if not isinstance(op, Layer) or (op.l1 == 0.0 and op.l2 == 0.0):
                continue
            for k, w in params[name].items():
                if k in ("b", "beta", "mean", "var"):
                    continue
                if op.l1:
                    reg = reg + op.l1 * jnp.sum(jnp.abs(w))
                if op.l2:
                    reg = reg + 0.5 * op.l2 * jnp.sum(jnp.square(w))
        return reg

    # ------------------------------------------------------------ optimizer
    def _build_optimizer(self, ipe=1):
        g = self._g
        labels = {}
        has_override = False
        per_label = {"__default__": g.updater, "__frozen__": NoOp()}
        for name, node in self.conf.nodes.items():
            if isinstance(node.op, Layer) and node.op.frozen:
                lab = "__frozen__"
                has_override = True
            elif isinstance(node.op, Layer) and node.op.updater is not None:
                lab = f"__{name}__"
                per_label[lab] = node.op.updater
                has_override = True
            else:
                lab = "__default__"
            labels[name] = jax.tree_util.tree_map(lambda _: lab, self.params[name])
        self._optimizer = build_optimizer(
            g.updater, grad_norm=g.grad_norm, grad_norm_threshold=g.grad_norm_threshold,
            iters_per_epoch=ipe,
            param_labels=labels if has_override else None,
            per_label_updaters=per_label if has_override else None)
        self._opt_state = self._optimizer.init(self.params)
        upstream = getattr(self, "_upstream_adam_state", None)
        if upstream is not None:  # resume from an upstream DL4J zip
            from ..serde.upstream_dl4j import graft_adam_state
            self._opt_state = graft_adam_state(self._opt_state, upstream)
            self._upstream_adam_state = None

    def _apply_constraints(self, params):
        from ..train.constraints import apply_constraints
        for name, node in self.conf.nodes.items():
            op = node.op
            if not isinstance(op, Layer) or op.frozen:
                continue
            if op.constraints:
                params[name] = apply_constraints(params[name], op.constraints,
                                                 weights=True)
            if op.bias_constraints:
                params[name] = apply_constraints(params[name], op.bias_constraints,
                                                 weights=False, biases=True)
        return params

    def _get_train_step(self):
        if self._train_step is None:
            optimizer = self._optimizer

            with_stats = getattr(self, "_anomaly_detector", None) is not None
            # numerics sentinel (ISSUE 13) — see MLN._get_train_step
            gate = with_stats and getattr(self._anomaly_detector,
                                          "gate_updates", True)

            def step(params, states, opt_state, inputs, labels, rng, fmask, lmask):
                # split inside jit; next key rides the outputs (no separate
                # host-side split dispatch per batch — see MLN._get_train_step)
                use_rng, next_rng = jax.random.split(rng)
                (loss, new_states), grads = jax.value_and_grad(
                    self._loss, has_aux=True)(params, states, inputs, labels,
                                              use_rng, fmask, lmask)
                updates, new_opt_state = optimizer.update(grads, opt_state, params)
                new_params = self._apply_constraints(
                    optax.apply_updates(params, updates))
                stats = None
                if with_stats:
                    from ..train.anomaly import maybe_stats_and_gate
                    stats, new_params, new_opt_state, new_states = \
                        maybe_stats_and_gate(
                            gate, grads, params, new_params, opt_state,
                            new_opt_state, states, new_states)
                return new_params, new_states, new_opt_state, loss, stats, next_rng

            # compile sentinel (ISSUE 12) — see MLN._get_train_step
            from ..obs.compiles import CompileSentinel
            self._train_step = CompileSentinel(
                "cg_train_step",
                jax.jit(step, donate_argnums=(0, 1, 2)))
        return self._train_step

    def enable_gradient_anomaly_detection(self, detector=None):
        """See MultiLayerNetwork.enable_gradient_anomaly_detection."""
        from ..train.anomaly import GradientAnomalyDetector
        if detector is False:
            self._anomaly_detector = None
        else:
            self._anomaly_detector = detector or GradientAnomalyDetector()
        self._train_step = None
        self._scan_epoch = None
        return self

    # ------------------------------------------------------------------ fit
    def fit(self, data, *, epochs: int = 1):
        """fit(MultiDataSetIterator | MultiDataSet | DataSet | iterator)."""
        from ..data.dataset import DataSet, MultiDataSet
        if isinstance(data, (DataSet, MultiDataSet)):
            iterator = [data]
        else:
            iterator = data
        if not self.initialized:
            first = next(iter(iterator))
            feats = first.features if isinstance(first, MultiDataSet) else [first.features]
            self.init([tuple(np.asarray(f).shape[1:]) for f in feats])
            if hasattr(iterator, "reset"):
                iterator.reset()
        if self._optimizer is None:
            try:
                ipe = len(iterator)
            except TypeError:
                ipe = 1
            self._build_optimizer(max(int(ipe), 1))
        step_fn = self._get_train_step()
        last = None
        anomaly_check = None
        if getattr(self, "_anomaly_detector", None) is not None:
            from ..train.anomaly import DelayedAnomalyCheck
            anomaly_check = DelayedAnomalyCheck(self._anomaly_detector)
        # async batch prep on a background thread, like MultiLayerNetwork.fit
        # (DL4J wraps both fit entry points the same way)
        from ..data.async_iter import maybe_wrap_async
        run_iter, wrapped = maybe_wrap_async(iterator)
        try:
            last = self._fit_epochs(run_iter, iterator, wrapped, epochs,
                                    step_fn, anomaly_check)
        finally:
            if wrapped is not None:
                wrapped.close()
        if anomaly_check is not None:
            anomaly_check.flush()
        return None if last is None else float(last)

    def fit_scanned(self, data, *, epochs: int = 1):
        """One jit dispatch per epoch: ``lax.scan`` of the train step over
        the stacked minibatches — same contract as
        ``MultiLayerNetwork.fit_scanned`` (bit-identical trajectory to
        ``fit``, equally-shaped mask-free batches, listeners replayed from
        the scanned loss history)."""
        from ..data.dataset import DataSet, MultiDataSet
        if isinstance(data, (DataSet, MultiDataSet)):
            batches = [data]
        else:
            batches = list(data)
        if not batches:
            return None

        def unpack(ds):
            if isinstance(ds, MultiDataSet):
                if ds.features_masks is not None or ds.labels_masks is not None:
                    raise ValueError("fit_scanned does not support masked "
                                     "batches; use fit()")
                return ds.features, ds.labels
            if ds.features_mask is not None or ds.labels_mask is not None:
                raise ValueError("fit_scanned does not support masked "
                                 "batches; use fit()")
            return [ds.features], [ds.labels]

        pairs = [unpack(ds) for ds in batches]
        shapes = {tuple(np.asarray(f).shape for f in fs)
                  + tuple(np.asarray(l).shape for l in ls)
                  for fs, ls in pairs}
        if len(shapes) > 1:
            raise ValueError("fit_scanned needs equally-shaped batches; "
                             "use fit()")
        from ._scan_common import check_scan_listeners
        check_scan_listeners(self)
        if not self.initialized:
            self.init([tuple(np.asarray(f).shape[1:])
                       for f in pairs[0][0]])
        if self._optimizer is None:
            self._build_optimizer(max(len(batches), 1))
        xs = {n: jnp.stack([jnp.asarray(fs[i]) for fs, _ in pairs])
              for i, n in enumerate(self.conf.inputs)}
        ys = {n: jnp.stack([jnp.asarray(ls[i]) for _, ls in pairs])
              for i, n in enumerate(self.conf.outputs)}
        step_fn = self._get_train_step()

        if self._scan_epoch is None:
            def scan_epoch(params, states, opt_state, rng, xs, ys):
                def body(carry, xy):
                    p, s, o, k = carry
                    x, y = xy
                    p, s, o, loss, _, k = step_fn.__wrapped__(
                        p, s, o, x, y, k, None, None)
                    return (p, s, o, k), loss
                (params, states, opt_state, rng), losses = lax.scan(
                    body, (params, states, opt_state, rng), (xs, ys))
                return params, states, opt_state, rng, losses
            self._scan_epoch = jax.jit(scan_epoch, donate_argnums=(0, 1, 2))
        losses = None
        for _ in range(epochs):
            (self.params, self.states, self._opt_state, self._host_key,
             losses) = self._scan_epoch(self.params, self.states,
                                        self._opt_state, self._host_key,
                                        xs, ys)
            self._step_count += len(batches)
            self.epoch_count += 1
            from ._scan_common import replay_scan_listeners
            replay_scan_listeners(self, losses, len(batches))
        return float(np.asarray(losses)[-1])

    def _fit_epochs(self, run_iter, source_iter, wrapped, epochs, step_fn,
                    anomaly_check):
        last = None
        for e in range(epochs):
            for ds in run_iter:
                from ..data.dataset import MultiDataSet as MDS
                if isinstance(ds, MDS):
                    feats, labs = ds.features, ds.labels
                    fmask = None if ds.features_masks is None else ds.features_masks[0]
                    lmask = None if ds.labels_masks is None else ds.labels_masks[0]
                else:
                    feats, labs = [ds.features], [ds.labels]
                    fmask, lmask = ds.features_mask, ds.labels_mask
                inputs = {n: jnp.asarray(f) for n, f in zip(self.conf.inputs, feats)}
                labels = {n: jnp.asarray(l) for n, l in zip(self.conf.outputs, labs)}
                # examples-throughput telemetry (MetricsListener)
                self._last_batch_size = int(next(iter(inputs.values())).shape[0])
                fm = None if fmask is None else jnp.asarray(fmask)
                lm = None if lmask is None else jnp.asarray(lmask)
                (self.params, self.states, self._opt_state, loss, gstats,
                 self._host_key) = step_fn(
                    self.params, self.states, self._opt_state, inputs, labels,
                    self._host_key, fm, lm)
                self._step_count += 1
                if anomaly_check is not None and gstats is not None:
                    anomaly_check.push(gstats, self._step_count)
                last = loss
                if self.listeners:
                    lv = float(loss)
                    for listener in self.listeners:
                        listener.iteration_done(self, self._step_count, self.epoch_count, lv)
            self.epoch_count += 1
            if e < epochs - 1:
                if hasattr(run_iter, "reset"):
                    run_iter.reset()
            elif wrapped is not None:
                # final epoch: close the wrapper FIRST so reset doesn't
                # spin up a producer whose prefetch is thrown away
                wrapped.close()
                if hasattr(source_iter, "reset"):
                    source_iter.reset()
            elif hasattr(run_iter, "reset"):
                run_iter.reset()
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(self)
        return last

    def score(self, ds):
        from ..data.dataset import MultiDataSet as MDS
        if isinstance(ds, MDS):
            feats, labs = ds.features, ds.labels
        else:
            feats, labs = [ds.features], [ds.labels]
        inputs = {n: jnp.asarray(f) for n, f in zip(self.conf.inputs, feats)}
        labels = {n: jnp.asarray(l) for n, l in zip(self.conf.outputs, labs)}
        loss, _ = self._loss(self.params, self.states, inputs, labels, None, None, None)
        return float(loss)

    def evaluate(self, iterator, top_n: int = 1):
        from ..eval.classification import Evaluation
        ev = Evaluation(top_n=top_n)
        for ds in iterator:
            preds = self.output(jnp.asarray(ds.features))
            if isinstance(preds, list):
                preds = preds[0]
            ev.eval(jnp.asarray(ds.labels), preds)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return ev

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)

    def num_params(self):
        return sum(int(p.size) for p in jax.tree_util.tree_leaves(self.params))

    def params_flat(self):
        """Single flat vector. NOTE: order is jax tree-flatten order
        (sorted node name, then sorted param name within a node), NOT the
        reference's topological node order — self-consistent with
        set_params_flat, but do not zip against a reference-ordered flat
        checkpoint without reindexing."""
        leaves = jax.tree_util.tree_leaves(self.params)
        return jnp.concatenate([l.ravel() for l in leaves]) if leaves \
            else jnp.zeros((0,))

    def set_params_flat(self, flat):
        leaves, treedef = jax.tree_util.tree_flatten(self.params)
        out, off = [], 0
        for l in leaves:
            n = int(l.size)
            out.append(jnp.asarray(flat[off:off + n]).reshape(l.shape)
                       .astype(l.dtype))
            off += n
        self.params = jax.tree_util.tree_unflatten(treedef, out)
        self._invalidate()

    def clone(self):
        """Reference ComputationGraph.clone(): config deep-copied, params/
        states shared-by-value (jax arrays are immutable)."""
        import copy
        net = ComputationGraph(copy.deepcopy(self.conf))
        if self.initialized:
            # REAL copies: fit() donates param buffers, so sharing arrays
            # would let the clone's training invalidate the source's
            net.params = jax.tree_util.tree_map(jnp.copy, self.params)
            net.states = jax.tree_util.tree_map(jnp.copy, self.states)
            net._preprocessors = dict(self._preprocessors)
            net.output_shapes = dict(self.output_shapes)
            net._init_shapes = list(getattr(self, "_init_shapes", []))
            net.initialized = True
        # execution policy / loss weighting are config-level, not
        # init-dependent — copy them even for an uninitialized graph
        # (matches MultiLayerNetwork.clone())
        net.remat_segments = self.remat_segments
        net.output_loss_weights = dict(self.output_loss_weights)
        return net

    def summary(self):
        lines = ["=" * 72, f"{'Node':<26}{'Type':<26}{'Params':<12}", "=" * 72]
        total = 0
        for name in self.conf.topo_order:
            node = self.conf.nodes[name]
            n = sum(int(v.size) for v in jax.tree_util.tree_leaves(self.params.get(name, {})))
            total += n
            lines.append(f"{name:<26}{type(node.op).__name__:<26}{n:<12}")
        lines += ["=" * 72, f"Total params: {total}", "=" * 72]
        return "\n".join(lines)

    def save(self, path, save_updater: bool = False):
        from ..serde.model_serializer import save_model
        save_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path):
        from ..serde.model_serializer import load_model
        return load_model(path)
