from .dashboard import main

main()
