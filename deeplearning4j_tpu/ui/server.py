"""Browser training UI — parity with DL4J's
``org.deeplearning4j.ui.VertxUIServer`` / ``UIServer.getInstance()``
(the live web dashboard fed by ``StatsListener``).

Architecture mirrors the reference: the training process writes stats to
a storage (here the StatsListener JSONL stream — the analogue of
FileStatsStorage), and the UI server *attaches* to that storage and
serves a browser view. The page is fully self-contained (inline
JS/canvas, no external assets — works with zero egress) and polls the
JSON endpoint, rendering the same charts the reference shows: score over
iterations, learning rate, and the per-layer update:param ratio
training-health chart.

Endpoints:
  GET /                 the dashboard page
  GET /train/stats      latest-session records as JSON
  GET /train/stats?sid= any session's records (FileStatsStorage read —
                        reattach to a finished run's history)
  GET /train/sessions   all session ids + static info in the storage
  GET /metrics          Prometheus text exposition of the process-wide
                        telemetry registry (deeplearning4j_tpu.obs) —
                        train-step histograms, inference batch
                        occupancy, scaleout round counters, …
  GET /debug/serving    live serving-plane state (ISSUE 11): one entry
                        per in-process flight recorder — replica, slot
                        map, queue depth, occupancy, last snapshot,
                        SLO report when configured
  GET /debug/requests   recent completed request traces (lifecycle
                        event timelines) from every flight recorder;
                        ?n= caps the count (default 50, newest last),
                        ?replica= filters
  GET /debug/memory     memory plane (ISSUE 12): latest memory census
                        per source/replica (component bytes, allocator
                        view) + live KV residency accounting per
                        scheduler replica
  GET /debug/numerics   numerics & fidelity plane (ISSUE 13): latest
                        tensor-stat exports per source/replica, every
                        live sentinel's trip log, the cross-replica
                        drift-audit summary, and the latest
                        fidelity-probe reports
  GET /debug/trend      perf regression & trend plane (ISSUE 15): the
                        bench ledger replayed into per-row trend
                        verdicts (stable/improved/regressed/unstable/
                        bimodal with cluster medians), verdict counts,
                        pct vs baseline — mirrored as dl4j_trend_*
                        gauges on /metrics
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .dashboard import load_stats
from .stats_storage import FileStatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>deeplearning4j_tpu training UI</title>
<style>
 body { font-family: -apple-system, 'Segoe UI', sans-serif; margin: 2em;
        background: #fafafa; color: #222; }
 h1 { font-size: 1.2em; } h2 { font-size: 1.0em; color: #444; }
 .meta { color: #666; font-size: 0.9em; }
 canvas { background: #fff; border: 1px solid #ddd; border-radius: 4px;
          display: block; margin-bottom: 1.5em; }
 .warn { color: #b00; }
</style></head><body>
<h1>deeplearning4j_tpu — training</h1>
<div class="meta">session: <select id="session"></select>
 <span id="static"></span></div>
<div class="meta" id="meta">waiting for stats…</div>
<h2>score</h2><canvas id="score" width="860" height="220"></canvas>
<h2>learning rate</h2><canvas id="lr" width="860" height="120"></canvas>
<h2>update : param ratios (healthy ≈ 1e-3)</h2>
<canvas id="ratios" width="860" height="220"></canvas>
<div class="meta" id="ratiolegend"></div>
<script>
const COLORS = ['#3366cc','#dc3912','#ff9900','#109618','#990099','#0099c6',
                '#dd4477','#66aa00','#b82e2e','#316395'];
function drawSeries(id, series, logY) {
  const cv = document.getElementById(id), ctx = cv.getContext('2d');
  ctx.clearRect(0, 0, cv.width, cv.height);
  // min/max via reduce, hoisted out of tx/ty: spreading 100k+ points into
  // Math.min(...) overflows the argument limit and O(n^2) kills long runs
  const f = logY ? Math.log10 : (v => v);
  let xlo = Infinity, xhi = -Infinity, lo = Infinity, hi = -Infinity, n = 0;
  series.forEach(s => s.points.forEach(p => {
    if (logY && p[1] <= 0) return;
    xlo = Math.min(xlo, p[0]); xhi = Math.max(xhi, p[0]);
    lo = Math.min(lo, f(p[1])); hi = Math.max(hi, f(p[1])); n++;
  }));
  if (!n) return;
  const tx = v => 40 + (v - xlo) / Math.max(1e-12, xhi - xlo) *
                  (cv.width - 60);
  const ty = v => cv.height - 20 - (f(v) - lo) /
                  Math.max(1e-12, hi - lo) * (cv.height - 40);
  ctx.font = '11px sans-serif'; ctx.fillStyle = '#888';
  ctx.fillText(logY ? ('1e' + hi.toFixed(1)) : hi.toPrecision(4), 2, 14);
  ctx.fillText(logY ? ('1e' + lo.toFixed(1)) : lo.toPrecision(4), 2,
               cv.height - 8);
  series.forEach((s, i) => {
    ctx.strokeStyle = COLORS[i % COLORS.length]; ctx.beginPath();
    s.points.forEach((p, j) => {
      if (logY && p[1] <= 0) return;
      const x = tx(p[0]), y = ty(p[1]);
      j ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
    });
    ctx.stroke();
  });
}
let selectedSid = null;   // null = follow the latest session live
async function refreshSessions() {
  try {
    const r = await fetch('/train/sessions'); const data = await r.json();
    const sel = document.getElementById('session');
    const ids = data.sessions.map(s => s.id);
    if (sel.options.length !== ids.length + 1) {
      const cur = sel.value;
      sel.innerHTML = '<option value="">latest (live)</option>' +
        data.sessions.map(s =>
          `<option value="${s.id}">${s.id} (${s.n} records)</option>`
        ).join('');
      sel.value = cur || '';
    }
    const last = data.sessions[data.sessions.length - 1];
    if (last && last.static && Object.keys(last.static).length)
      document.getElementById('static').textContent =
        Object.entries(last.static).map(([k, v]) => `${k}: ${v}`).join(' · ');
  } catch (e) { /* keep polling */ }
}
document.getElementById('session').addEventListener('change',
  e => { selectedSid = e.target.value || null; refresh(); });
async function refresh() {
  try {
    const url = selectedSid
      ? '/train/stats?sid=' + encodeURIComponent(selectedSid)
      : '/train/stats';
    const r = await fetch(url); const data = await r.json();
    const recs = data.records;
    if (!recs.length) return;
    const last = recs[recs.length - 1];
    document.getElementById('meta').textContent =
      `iter ${last.iter} · epoch ${last.epoch} · score ` +
      `${last.score.toPrecision(5)} · ${recs.length} records`;
    drawSeries('score',
      [{points: recs.filter(r => 'score' in r).map(r => [r.iter, r.score])}],
      false);
    drawSeries('lr',
      [{points: recs.filter(r => 'lr' in r).map(r => [r.iter, r.lr])}],
      false);
    const layers = [...new Set(recs.flatMap(
      r => Object.keys(r.update_ratios || {})))];
    drawSeries('ratios', layers.map(l => ({points:
      recs.filter(r => r.update_ratios && l in r.update_ratios)
          .map(r => [r.iter, r.update_ratios[l]])})), true);
    document.getElementById('ratiolegend').innerHTML = layers.map((l, i) =>
      `<span style="color:${COLORS[i % COLORS.length]}">■ ${l}</span>`
    ).join(' &nbsp; ');
  } catch (e) { /* server restarting; keep polling */ }
}
refreshSessions(); refresh();
setInterval(refresh, 2000); setInterval(refreshSessions, 5000);
</script></body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4j-tpu-ui/1.0"

    def do_GET(self):  # noqa: N802 — http.server API
        if self.path == "/" or self.path == "/train" or self.path == "/index.html":
            body = _PAGE.encode()
            ctype = "text/html; charset=utf-8"
        elif self.path == "/metrics" or self.path.startswith("/metrics?"):
            # Prometheus scrape endpoint: the UI process exposes whatever
            # the in-process registry has accumulated (a training script
            # that starts the UIServer in-process exposes its own fit).
            from ..obs import get_registry
            body = get_registry().to_prometheus().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path.startswith("/debug/serving"):
            # serving black boxes (ISSUE 11): every live FlightRecorder's
            # state — the postmortem data, while the process is alive
            from ..obs import live_flight_recorders
            body = json.dumps({"replicas": [
                fr.debug_state() for fr in live_flight_recorders()
            ]}).encode()
            ctype = "application/json"
        elif self.path.startswith("/debug/memory"):
            # memory plane (ISSUE 12): latest census per source/replica
            # (component bytes + allocator view) and the KV residency
            # accounting of every live scheduler
            from ..obs import memory as obs_memory
            body = json.dumps(obs_memory.debug_state()).encode()
            ctype = "application/json"
        elif self.path.startswith("/debug/numerics"):
            # numerics & fidelity plane (ISSUE 13): stat exports,
            # sentinel trip logs, drift audits, fidelity reports
            from ..obs import numerics as obs_numerics
            body = json.dumps(obs_numerics.debug_state()).encode()
            ctype = "application/json"
        elif self.path.startswith("/debug/trend"):
            # perf trend plane (ISSUE 15): ledger replay — verdicts
            # per (row, backend), cluster medians on bimodal rows
            from ..obs import trend as obs_trend
            body = json.dumps(obs_trend.debug_state()).encode()
            ctype = "application/json"
        elif self.path.startswith("/debug/requests"):
            from ..obs import live_flight_recorders
            q = urllib.parse.parse_qs(
                urllib.parse.urlparse(self.path).query)
            try:
                n = max(1, int(q.get("n", ["50"])[0]))
            except ValueError:
                n = 50
            replica = q.get("replica", [None])[0]
            recs = []
            for fr in live_flight_recorders():
                if replica is not None and fr.replica != replica:
                    continue
                recs.extend(tr.to_record() for tr in fr.requests())
            # newest last ACROSS replicas — a per-recorder concat would
            # let one replica's backlog evict every other's under ?n=
            recs.sort(key=lambda r: r.get("t0_epoch", 0.0)
                      + (r["events"][-1][1] if r.get("events") else 0.0))
            body = json.dumps({"requests": recs[-n:]}).encode()
            ctype = "application/json"
        elif self.path.startswith("/train/sessions"):
            sessions = [{"id": s["id"], "static": s["static"],
                         "n": len(s["updates"])}
                        for s in FileStatsStorage(
                            self.server.ui_log_dir).sessions()]
            body = json.dumps({"sessions": sessions}).encode()
            ctype = "application/json"
        elif self.path.startswith("/train/stats"):
            q = urllib.parse.urlparse(self.path).query
            sid = urllib.parse.parse_qs(q).get("sid", [None])[0]
            if sid:
                match = [s for s in FileStatsStorage(
                    self.server.ui_log_dir).sessions() if s["id"] == sid]
                if not match:
                    self.send_error(404, f"no session {sid}")
                    return
                records = match[0]["updates"]
            else:
                records = load_stats(self.server.ui_log_dir)
            body = json.dumps({"records": records}).encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silent: training logs own the console
        pass


class UIServer:
    """Reference UIServer: ``UIServer.get_instance().attach(log_dir)`` then
    browse http://localhost:<port>/ while training writes stats."""

    _instance: Optional["UIServer"] = None

    def __init__(self, log_dir: str = "runs/dl4j_tpu", port: int = 9000):
        self.log_dir = log_dir
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None  # bound in start()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def get_instance(cls, log_dir: Optional[str] = None,
                     port: Optional[int] = None) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(log_dir or "runs/dl4j_tpu",
                                9000 if port is None else port).start()
        else:
            if port is not None and port != cls._instance.port:
                raise ValueError(
                    f"UI server already running on port "
                    f"{cls._instance.port}; cannot move it to {port} "
                    "(stop() it first)")
            if log_dir is not None and log_dir != cls._instance.log_dir:
                cls._instance.attach(log_dir)
        return cls._instance

    @property
    def port(self) -> int:
        return self._port if self._httpd is None \
            else self._httpd.server_address[1]

    def attach(self, log_dir: str) -> "UIServer":
        """Point the server at a (new) StatsListener log dir — the analogue
        of attaching a StatsStorage instance."""
        self.log_dir = log_dir
        if self._httpd is not None:
            self._httpd.ui_log_dir = log_dir
        return self

    def start(self) -> "UIServer":
        if self._thread is None:
            # bind lazily: construction must neither hold the port nor raise
            self._httpd = ThreadingHTTPServer(("127.0.0.1", self._port),
                                              _Handler)
            self._httpd.ui_log_dir = self.log_dir
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="dl4j-tpu-ui",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            if self._thread is not None:
                # shutdown() waits on a flag only serve_forever() sets —
                # calling it on a never-started server deadlocks forever
                self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if UIServer._instance is self:
            UIServer._instance = None
