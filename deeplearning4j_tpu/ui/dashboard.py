"""Terminal training dashboard — the training-UI analogue (SURVEY §2.9).

Reference counterpart: DL4J's browser training UI (`deeplearning4j-ui`,
``UIServer.getInstance()`` + StatsListener) showing score-vs-iteration,
update:param ratios, layer histograms and system stats. TPU-native stance:
the heavyweight charts belong to TensorBoard (StatsListener writes TB
scalars when torch's SummaryWriter is importable); this module covers the
"glance at the run from a shell" half with a zero-dependency ANSI dashboard
over the StatsListener JSONL fallback stream.

Usage:
    python -m deeplearning4j_tpu.ui runs/dl4j_tpu           # one snapshot
    python -m deeplearning4j_tpu.ui runs/dl4j_tpu --watch   # live refresh
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Dict, List, Optional

_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 60) -> str:
    """Unicode sparkline, downsampled to `width` points."""
    if not values:
        return ""
    if len(values) > width:
        stride = len(values) / width
        values = [values[int(i * stride)] for i in range(width)]
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    return "".join(_BLOCKS[int((v - lo) / span * (len(_BLOCKS) - 1))]
                   for v in values)


def load_stats(log_dir) -> List[Dict]:
    """Parse the StatsListener JSONL stream: skips torn trailing writes and
    returns only the LAST run's records (the listener appends, and writes a
    run_start delimiter each time it opens the file)."""
    path = Path(log_dir) / "stats.jsonl"
    if not path.exists():
        return []
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn write at the tail of a live file
            if "run_start" in rec:
                records = []  # later run supersedes everything before it
            elif "static" in rec:
                continue      # run-level metadata (FileStatsStorage reads it)
            else:
                records.append(rec)
    return records


def render(records: List[Dict], width: int = 72) -> str:
    """One dashboard frame as a string (pure — testable without a tty)."""
    if not records:
        return "no stats yet (is a StatsListener attached and writing JSONL?)"
    scores = [r["score"] for r in records if "score" in r]
    iters = [r["iter"] for r in records if "iter" in r]
    lines = ["┌" + "─" * width + "┐"]

    def row(text=""):
        lines.append("│ " + text[:width - 2].ljust(width - 2) + " │")

    last = records[-1]
    row(f"deeplearning4j_tpu training — iter {last.get('iter', '?')} "
        f"epoch {last.get('epoch', '?')}")
    row("─" * (width - 2))
    if scores:
        row(f"score  last {scores[-1]:.5f}   best {min(scores):.5f}   "
            f"first {scores[0]:.5f}")
        row(sparkline(scores, width - 2))
    ts = [r["ts"] for r in records if "ts" in r]
    if len(ts) >= 2 and len(iters) >= 2 and ts[-1] > ts[0]:
        ips = (iters[-1] - iters[0]) / (ts[-1] - ts[0])
        row(f"throughput  {ips:.2f} it/s   span {ts[-1] - ts[0]:.0f}s   "
            f"{len(records)} records")
    lrs = [r["lr"] for r in records if "lr" in r]
    if lrs:
        row(f"lr  {lrs[-1]:.2e}")
        row(sparkline(lrs, width - 2))
    # per-layer update:param ratio (DL4J's headline training-health chart;
    # healthy range is famously ~1e-3)
    ratios = [r for r in records if "update_ratios" in r]
    if ratios:
        row("update:param ratios (last):")
        for layer, val in ratios[-1]["update_ratios"].items():
            flag = "" if 1e-5 < val < 1e-1 else "  ⚠"
            row(f"  {layer:<24} {val:.2e}{flag}")
    lines.append("└" + "─" * width + "┘")
    return "\n".join(lines)


def watch(log_dir, interval_s: float = 2.0, frames: Optional[int] = None):
    """Live-refresh the dashboard (frames=None → until Ctrl-C)."""
    shown = 0
    try:
        while frames is None or shown < frames:
            frame = render(load_stats(log_dir))
            print("\x1b[2J\x1b[H" + frame, flush=True)
            shown += 1
            if frames is None or shown < frames:
                time.sleep(interval_s)
    except KeyboardInterrupt:
        pass


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description="terminal training dashboard")
    ap.add_argument("log_dir", nargs="?", default="runs/dl4j_tpu")
    ap.add_argument("--watch", action="store_true")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--serve", action="store_true",
                    help="serve the browser dashboard instead")
    ap.add_argument("--port", type=int, default=9000)
    args = ap.parse_args(argv)
    if args.serve:
        from .server import UIServer
        srv = UIServer(args.log_dir, args.port).start()
        print(f"training UI at http://127.0.0.1:{srv.port}/ "
              f"(stats: {args.log_dir}) — Ctrl-C to stop")
        try:
            srv._thread.join()
        except KeyboardInterrupt:
            srv.stop()
    elif args.watch:
        watch(args.log_dir, args.interval)
    else:
        print(render(load_stats(args.log_dir)))


if __name__ == "__main__":
    main()
