"""StatsStorage — persistent, session-scoped training-stats store.

Reference parity: ``org.deeplearning4j.api.storage.StatsStorage`` and its
``FileStatsStorage``/``InMemoryStatsStorage`` implementations (upstream
backs FileStatsStorage with MapDB; the UI attaches to a storage and can
browse EVERY session it holds, including finished runs — VERDICT r4
missing item 4).

TPU-native form: the storage rides the SAME append-only JSONL stream
``StatsListener`` already writes (one `{"run_start": ts}` delimiter per
run, then per-iteration records; optional `{"static": {...}}` records
carry run-level metadata). A session = one run_start-delimited span;
session ids are stable (``run-<index>-<unix ts>``) so a UI can reattach
to any historical run after the process that trained it is long gone.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Dict, List, Optional


class StatsStorage:
    """Session-scoped read API (the subset the UI needs) + append API."""

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def latest_session_id(self) -> Optional[str]:
        ids = self.list_session_ids()
        return ids[-1] if ids else None

    def get_updates(self, session_id: str) -> List[Dict]:
        raise NotImplementedError

    def get_static_info(self, session_id: str) -> Dict:
        raise NotImplementedError

    def put_update(self, record: Dict) -> None:
        raise NotImplementedError

    def put_static_info(self, info: Dict) -> None:
        raise NotImplementedError

    def new_session(self) -> str:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    """Upstream InMemoryStatsStorage: sessions live only in this process."""

    def __init__(self):
        self._sessions: List[Dict] = []

    def new_session(self) -> str:
        sid = f"run-{len(self._sessions)}-{int(time.time())}"
        self._sessions.append({"id": sid, "static": {}, "updates": []})
        return sid

    def _require(self):
        if not self._sessions:
            self.new_session()
        return self._sessions[-1]

    def list_session_ids(self):
        return [s["id"] for s in self._sessions]

    def get_updates(self, session_id):
        for s in self._sessions:
            if s["id"] == session_id:
                return list(s["updates"])
        raise KeyError(session_id)

    def get_static_info(self, session_id):
        for s in self._sessions:
            if s["id"] == session_id:
                return dict(s["static"])
        raise KeyError(session_id)

    def put_update(self, record):
        self._require()["updates"].append(dict(record))

    def put_static_info(self, info):
        self._require()["static"].update(info)


class FileStatsStorage(StatsStorage):
    """Persistent storage over the StatsListener JSONL stream.

    ``path`` is a stats.jsonl file or the log dir containing one. Reads
    re-parse the file on demand (cheap append-only scan with torn-tail
    tolerance), so a storage opened on a finished run's file serves its
    full multi-session history — the upstream "reattach to FileStatsStorage"
    workflow.
    """

    def __init__(self, path):
        p = Path(path)
        # only an actual .jsonl path is treated as the file itself; any
        # other name (incl. dotted dir names like "runs.v2") is the LOG DIR
        # StatsListener writes stats.jsonl into
        self.path = p if p.suffix == ".jsonl" and not p.is_dir() \
            else p / "stats.jsonl"
        self._fh = None

    # ------------------------------------------------------------- read
    def _parse(self) -> List[Dict]:
        sessions: List[Dict] = []
        if not self.path.exists():
            return sessions
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue                    # torn tail of a live file
                if "run_start" in rec:
                    sid = f"run-{len(sessions)}-{int(rec['run_start'])}"
                    sessions.append({"id": sid, "static": {}, "updates": []})
                    continue
                if not sessions:                # pre-delimiter legacy lines
                    sessions.append({"id": "run-0-0", "static": {},
                                     "updates": []})
                if "static" in rec:
                    sessions[-1]["static"].update(rec["static"])
                else:
                    sessions[-1]["updates"].append(rec)
        return sessions

    def sessions(self) -> List[Dict]:
        """One full parse → every session's {id, static, updates} (use this
        when you need more than one session/field — each read method below
        re-parses the file)."""
        return self._parse()

    def list_session_ids(self):
        return [s["id"] for s in self._parse()]

    def get_updates(self, session_id):
        for s in self._parse():
            if s["id"] == session_id:
                return s["updates"]
        raise KeyError(f"no session {session_id!r} in {self.path}")

    def get_static_info(self, session_id):
        for s in self._parse():
            if s["id"] == session_id:
                return s["static"]
        raise KeyError(f"no session {session_id!r} in {self.path}")

    # ------------------------------------------------------------ write
    def _writer(self):
        if self._fh is None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a")
        return self._fh

    def _append(self, obj):
        fh = self._writer()
        fh.write(json.dumps(obj) + "\n")
        fh.flush()

    def new_session(self) -> str:
        ts = time.time()
        sid = f"run-{len(self._parse())}-{int(ts)}"
        self._append({"run_start": ts})
        return sid

    def put_update(self, record):
        self._append(dict(record))

    def put_static_info(self, info):
        self._append({"static": dict(info)})

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
