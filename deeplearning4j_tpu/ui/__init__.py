"""Training UI (SURVEY §2.9): TensorBoard via nn.listeners.StatsListener,
terminal dashboard via this package (`python -m deeplearning4j_tpu.ui`),
and the browser dashboard (`UIServer.get_instance()` — reference
VertxUIServer; or `python -m deeplearning4j_tpu.ui --serve`)."""

from .dashboard import load_stats, render, sparkline, watch
from .server import UIServer
from .stats_storage import (FileStatsStorage, InMemoryStatsStorage,
                            StatsStorage)

__all__ = ["UIServer", "load_stats", "render", "sparkline", "watch",
           "StatsStorage", "FileStatsStorage", "InMemoryStatsStorage"]
