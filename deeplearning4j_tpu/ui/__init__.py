"""Training UI (SURVEY §2.9): TensorBoard via nn.listeners.StatsListener,
terminal dashboard via this package (`python -m deeplearning4j_tpu.ui`)."""

from .dashboard import load_stats, render, sparkline, watch

__all__ = ["load_stats", "render", "sparkline", "watch"]
