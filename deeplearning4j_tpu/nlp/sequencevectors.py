"""SequenceVectors — parity with DL4J's
``org.deeplearning4j.models.sequencevectors.SequenceVectors`` (the generic
"embed any sequence of elements" abstraction that Word2Vec, ParagraphVectors
and DeepWalk all specialise upstream).

Here the specialisation runs the other way round for implementation reuse —
``SequenceVectors`` feeds pre-tokenised element sequences straight into the
shared SGNS trainer (`Word2Vec._fit_tokens`): elements are arbitrary
hashables keyed by ``str(element)``, there is no tokenizer and no
frequent-element subsampling by default, exactly like the upstream base
class configured with a custom ``SequenceIterator``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Hashable, Iterable, List, Sequence

import numpy as np

from .word2vec import Word2Vec


@dataclass
class SequenceVectors(Word2Vec):
    """Skip-gram/NS embeddings over sequences of arbitrary elements."""

    min_word_frequency: int = 1   # upstream minElementFrequency
    subsample: float = 0.0        # elements usually aren't Zipfian words

    def fit(self, sequences: Iterable[Sequence[Hashable]]):
        tok = [[str(e) for e in seq] for seq in sequences]
        return self._fit_tokens(tok)

    # element-named surface (upstream SequenceVectors API)
    def has_element(self, element: Hashable) -> bool:
        return self.has_word(str(element))

    def element_vector(self, element: Hashable) -> np.ndarray:
        return self.get_word_vector(str(element))

    def element_frequency(self, element: Hashable) -> int:
        return self.vocab.word_frequency(str(element))

    def similarity_elements(self, a: Hashable, b: Hashable) -> float:
        return self.similarity(str(a), str(b))

    def elements_nearest(self, element: Hashable, top_n: int = 10) -> List[str]:
        return self.words_nearest(str(element), top_n=top_n)
