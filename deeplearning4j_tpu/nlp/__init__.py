"""deeplearning4j_tpu.nlp — Word2Vec/ParagraphVectors/GloVe/
SequenceVectors + tokenizers (DL4J deeplearning4j-nlp analogue)."""

from .bert_iterator import BertIterator, BertWordPieceTokenizer
from .cnn_sentence import (CnnSentenceDataSetIterator,
                           LabeledSentenceProvider)
from .fasttext import FastText
from .glove import GloVe
from .sequencevectors import SequenceVectors
from .tokenizers import (BasicLineIterator, BPETokenizer, CharTokenizer,
                         CollectionSentenceIterator, CommonPreprocessor,
                         DefaultTokenizerFactory, LowCasePreProcessor,
                         NGramTokenizer, RegexTokenizer, SentenceIterator,
                         StemmingPreprocessor, TokenizerFactory,
                         WhitespaceTokenizer)
from .vocab import VocabCache
from .word2vec import ParagraphVectors, Word2Vec
