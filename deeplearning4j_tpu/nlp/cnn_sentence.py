"""CnnSentenceDataSetIterator — the text-CNN data path.

Reference parity: ``org.deeplearning4j.iterator.CnnSentenceDataSetIterator``
(deeplearning4j-nlp): turns labelled sentences + word vectors into padded
CNN tensors with a per-timestep feature mask, for Kim-2014-style sentence
convolution models.

Layout is TPU-native NHWC: ``format="cnn2d"`` yields features
``(B, maxLen, vecSize, 1)`` (reference CNN2D is NCHW ``[b,1,len,vec]``);
``format="cnn1d"``/``"rnn"`` yields ``(B, maxLen, vecSize)`` [NTC]. Labels
are one-hot over the sorted label set; sentences shorter than the batch max
are zero-padded with ``features_mask`` marking real tokens.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import DataSet
from .tokenizers import DefaultTokenizerFactory, TokenizerFactory


class LabeledSentenceProvider:
    """Reference ``CollectionLabeledSentenceProvider``: shuffled supply of
    (sentence, label) pairs."""

    def __init__(self, sentences: Sequence[str], labels: Sequence[str],
                 seed: Optional[int] = 123):
        if len(sentences) != len(labels):
            raise ValueError(
                f"{len(sentences)} sentences vs {len(labels)} labels")
        self.data = list(zip(sentences, labels))
        self.all_labels = sorted(set(labels))
        self.seed = seed
        self.reset()

    def reset(self):
        order = np.arange(len(self.data))
        if self.seed is not None:
            np.random.default_rng(self.seed).shuffle(order)
        self._order = order
        self._pos = 0

    def has_next(self):
        return self._pos < len(self.data)

    def next(self) -> Tuple[str, str]:
        s, l = self.data[self._order[self._pos]]
        self._pos += 1
        return s, l

    def total_num_sentences(self):
        return len(self.data)


class CnnSentenceDataSetIterator:
    """Builder args mirror the reference: sentenceProvider, wordVectors,
    maxSentenceLength, minibatchSize, unknownWordHandling, format."""

    UNKNOWN_WORD_SENTINEL = "UNKNOWN_WORD_SENTINEL"

    def __init__(self, sentence_provider: LabeledSentenceProvider,
                 word_vectors, batch_size: int = 32,
                 max_sentence_length: int = 256,
                 unknown_word_handling: str = "remove",  # | "use_unknown"
                 format: str = "cnn2d",                  # | "cnn1d" | "rnn"
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        if format not in ("cnn2d", "cnn1d", "rnn"):
            raise ValueError(f"unknown format '{format}'")
        if unknown_word_handling not in ("remove", "use_unknown"):
            raise ValueError(
                f"unknown unknown_word_handling '{unknown_word_handling}'")
        self.provider = sentence_provider
        self.wv = word_vectors
        self.batch_size = batch_size
        self.max_sentence_length = max_sentence_length
        self.unknown_word_handling = unknown_word_handling
        self.format = format
        self.tok = tokenizer_factory or DefaultTokenizerFactory()
        self.labels: List[str] = list(sentence_provider.all_labels)
        self._label_idx = {l: i for i, l in enumerate(self.labels)}
        self._vec_size = int(np.asarray(
            self.wv.syn0).shape[-1]) if getattr(self.wv, "syn0", None) is not None else int(self.wv.layer_size)

    # ---------------------------------------------------------------- vecs
    def _sentence_vectors(self, sentence: str) -> np.ndarray:
        toks = self.tok.create(sentence).get_tokens()
        rows = []
        for t in toks:
            if self.wv.has_word(t):
                rows.append(self.wv.get_word_vector(t))
            elif self.unknown_word_handling == "use_unknown":
                rows.append(self._unknown_vector())
            # "remove": skip (reference UnknownWordHandling.RemoveWord)
            if len(rows) >= self.max_sentence_length:
                break
        if not rows:
            rows = [np.zeros(self._vec_size, np.float32)]
        return np.stack(rows).astype(np.float32)

    def _unknown_vector(self):
        if self.wv.has_word(self.UNKNOWN_WORD_SENTINEL):
            return self.wv.get_word_vector(self.UNKNOWN_WORD_SENTINEL)
        return np.zeros(self._vec_size, np.float32)

    def load_single_sentence(self, sentence: str) -> np.ndarray:
        """Inference helper (reference loadSingleSentence): one padded
        example with batch dim 1."""
        v = self._sentence_vectors(sentence)
        feats = v[None]
        if self.format == "cnn2d":
            feats = feats[..., None]
        return feats

    # ------------------------------------------------------------ iterator
    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def has_next(self) -> bool:
        return self.provider.has_next()

    def reset(self):
        self.provider.reset()

    def batch(self) -> int:
        return self.batch_size

    def total_outcomes(self) -> int:
        return len(self.labels)

    def input_columns(self) -> int:
        return self._vec_size

    def async_supported(self) -> bool:
        return True

    def next(self, num: Optional[int] = None) -> DataSet:
        if not self.has_next():
            raise StopIteration("iterator exhausted — call reset()")
        n = num or self.batch_size
        vecs, ys = [], []
        while self.provider.has_next() and len(vecs) < n:
            s, l = self.provider.next()
            vecs.append(self._sentence_vectors(s))
            ys.append(self._label_idx[l])
        b = len(vecs)
        t = max(v.shape[0] for v in vecs)
        feats = np.zeros((b, t, self._vec_size), np.float32)
        mask = np.zeros((b, t), np.float32)
        for i, v in enumerate(vecs):
            feats[i, :v.shape[0]] = v
            mask[i, :v.shape[0]] = 1.0
        labels = np.eye(len(self.labels), dtype=np.float32)[np.asarray(ys)]
        if self.format == "cnn2d":
            feats = feats[..., None]            # (B, T, vec, 1) NHWC
        return DataSet(feats, labels, features_mask=mask)
