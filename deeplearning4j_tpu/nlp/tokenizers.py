"""Tokenizers + preprocessors — parity with DL4J's
``org.deeplearning4j.text.tokenization.tokenizerfactory.*`` /
``...tokenization.tokenizer.*`` (DefaultTokenizerFactory,
TokenPreProcess, NGramTokenizerFactory) plus a byte-pair-encoding
subset (the reference ships BertWordPieceTokenizer; BPE is the
modern equivalent surface).

Tokenizers here are plain-Python host-side code: tokenization is ETL,
not compute, so it never enters jit. The TPU sees only integer id
batches produced by :class:`~deeplearning4j_tpu.nlp.vocab.VocabCache`.
"""

from __future__ import annotations

import re
import string
from collections import Counter
from typing import Callable, Dict, Iterable, List, Optional, Tuple


# ---------------------------------------------------------------- preprocess
class TokenPreProcess:
    """Reference ``TokenPreProcess`` — a pure str→str hook."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError

    def __call__(self, token: str) -> str:
        return self.pre_process(token)


class CommonPreprocessor(TokenPreProcess):
    """Reference ``CommonPreprocessor``: lowercase + strip punctuation/digits."""

    _strip = re.compile(r"[\d" + re.escape(string.punctuation) + r"]+")

    def pre_process(self, token: str) -> str:
        return self._strip.sub("", token.lower())


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class StemmingPreprocessor(TokenPreProcess):
    """Tiny suffix-stripping stemmer (Porter-lite) — reference uses lucene's."""

    _suffixes = ("ingly", "edly", "ing", "ed", "ly", "ies", "es", "s")

    def pre_process(self, token: str) -> str:
        t = token.lower()
        for suf in self._suffixes:
            if t.endswith(suf) and len(t) - len(suf) >= 3:
                return t[: -len(suf)]
        return t


# ---------------------------------------------------------------- tokenizers
class Tokenizer:
    """Reference ``Tokenizer`` — iteration over tokens of ONE string."""

    def __init__(self, text: str, pre: Optional[TokenPreProcess] = None):
        self._tokens = self._split(text)
        if pre is not None:
            self._tokens = [p for p in (pre(t) for t in self._tokens) if p]

    def _split(self, text: str) -> List[str]:
        raise NotImplementedError

    def count_tokens(self) -> int:
        return len(self._tokens)

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def __iter__(self):
        return iter(self._tokens)


class WhitespaceTokenizer(Tokenizer):
    """Reference ``DefaultTokenizer`` (whitespace/StringTokenizer based)."""

    def _split(self, text):
        return text.split()


class CharTokenizer(Tokenizer):
    """Character tokenizer — the TextGenerationLSTM / char-RNN path."""

    def _split(self, text):
        return list(text)


class RegexTokenizer(Tokenizer):
    """Reference ``PosUimaTokenizer``-class flexibility via a regex."""

    pattern = re.compile(r"\w+|[^\w\s]")

    def _split(self, text):
        return self.pattern.findall(text)


class NGramTokenizer(Tokenizer):
    """Reference ``NGramTokenizerFactory`` — emits n-grams of base tokens."""

    def __init__(self, text, n_min=1, n_max=2, pre=None):
        self.n_min, self.n_max = n_min, n_max
        super().__init__(text, pre)

    def _split(self, text):
        base = text.split()
        out = []
        for n in range(self.n_min, self.n_max + 1):
            out += [" ".join(base[i:i + n]) for i in range(len(base) - n + 1)]
        return out


class TokenizerFactory:
    """Reference ``TokenizerFactory`` — create(text) → Tokenizer."""

    def __init__(self, cls=WhitespaceTokenizer, pre: Optional[TokenPreProcess] = None,
                 **kw):
        self._cls, self._pre, self._kw = cls, pre, kw

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self._pre = pre
        return self

    def create(self, text: str) -> Tokenizer:
        return self._cls(text, pre=self._pre, **self._kw)


class DefaultTokenizerFactory(TokenizerFactory):
    def __init__(self, pre: Optional[TokenPreProcess] = None):
        super().__init__(WhitespaceTokenizer, pre)


# ---------------------------------------------------------------- BPE subset
class BPETokenizer:
    """Byte-pair encoding: ``train`` learns merges from a corpus, ``encode``/
    ``decode`` round-trip text. Greedy rank-based merging (GPT-2 style,
    simplified: no byte fallback — unknown chars become <unk>).
    """

    UNK = "<unk>"
    EOW = "</w>"

    def __init__(self, vocab_size: int = 1000):
        self.vocab_size = vocab_size
        self.merges: Dict[Tuple[str, str], int] = {}
        self.token_to_id: Dict[str, int] = {}
        self.id_to_token: List[str] = []

    # -- training -----------------------------------------------------------
    def train(self, corpus: Iterable[str]):
        word_freq: Counter = Counter()
        for line in corpus:
            word_freq.update(line.split())
        # each word is a tuple of symbols, last symbol carries EOW
        words = {tuple(w[:-1]) + (w[-1] + self.EOW,): c
                 for w, c in word_freq.items() if w}
        alphabet = sorted({s for w in words for s in w})
        vocab = [self.UNK] + alphabet
        while len(vocab) < self.vocab_size:
            pairs: Counter = Counter()
            for w, c in words.items():
                for a, b in zip(w, w[1:]):
                    pairs[(a, b)] += c
            if not pairs:
                break
            best = max(pairs, key=lambda p: (pairs[p], p))
            self.merges[best] = len(self.merges)
            merged = best[0] + best[1]
            vocab.append(merged)
            words = {self._merge_word(w, best, merged): c for w, c in words.items()}
        self.id_to_token = vocab
        self.token_to_id = {t: i for i, t in enumerate(vocab)}
        return self

    @staticmethod
    def _merge_word(word, pair, merged):
        out, i = [], 0
        while i < len(word):
            if i + 1 < len(word) and (word[i], word[i + 1]) == pair:
                out.append(merged)
                i += 2
            else:
                out.append(word[i])
                i += 1
        return tuple(out)

    # -- encode/decode ------------------------------------------------------
    def _bpe(self, word: str) -> List[str]:
        syms = list(word[:-1]) + [word[-1] + self.EOW] if word else []
        while len(syms) > 1:
            ranked = [(self.merges.get((a, b)), i)
                      for i, (a, b) in enumerate(zip(syms, syms[1:]))]
            ranked = [(r, i) for r, i in ranked if r is not None]
            if not ranked:
                break
            _, i = min(ranked)
            syms = syms[:i] + [syms[i] + syms[i + 1]] + syms[i + 2:]
        return syms

    def encode(self, text: str) -> List[int]:
        unk = self.token_to_id[self.UNK]
        ids = []
        for w in text.split():
            ids += [self.token_to_id.get(s, unk) for s in self._bpe(w)]
        return ids

    def decode(self, ids: List[int]) -> str:
        toks = [self.id_to_token[i] for i in ids]
        return "".join(toks).replace(self.EOW, " ").strip()


# ---------------------------------------------------------- sentence sources
class SentenceIterator:
    """Reference ``SentenceIterator`` — restartable stream of sentences."""

    def __iter__(self) -> Iterable[str]:
        raise NotImplementedError


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: List[str]):
        self._sent = list(sentences)

    def __iter__(self):
        return iter(self._sent)


class BasicLineIterator(SentenceIterator):
    """Reference ``BasicLineIterator`` — one sentence per file line."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line
