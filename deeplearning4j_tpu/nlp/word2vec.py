"""Word2Vec + ParagraphVectors — parity with DL4J's
``org.deeplearning4j.models.word2vec.Word2Vec`` (skip-gram AND CBOW
elements learning — upstream ``learning.impl.elements.{SkipGram, CBOW}``;
negative sampling AND hierarchical softmax outputs — upstream
``HierarchicSoftmax``; frequent-word subsampling, linear lr decay,
wordsNearest / similarity surface) and
``org.deeplearning4j.models.paragraphvectors.ParagraphVectors``
(PV-DBOW + PV-DM — upstream ``learning.impl.sequence.{DBOW, DM}`` — with
inferVector for both).

TPU-first redesign: the reference trains with per-pair Hogwild SGD
across threads. Here a whole batch of examples is one jitted step —
negatives are sampled *inside* jit from the unigram^0.75 distribution
(or the Huffman path is gathered for HS), the loss is
``-logσ(u·v⁺) - Σ logσ(-u·v⁻)`` (NS) / the path-sigmoid sum (HS), and
XLA turns the embedding-gather gradients into scatter-adds. One program,
MXU-friendly, no locks — the batch plays the role the reference's
threads did.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .tokenizers import DefaultTokenizerFactory, SentenceIterator, TokenizerFactory
from .vocab import VocabCache


def _log_sigmoid(x):
    return -jax.nn.softplus(-x)


def ns_loss_from_u(u, target, neg, syn1):
    """Negative-sampling loss for predictor vectors u (B, D) against the
    output table syn1: ``-logσ(u·v⁺) - Σ logσ(-u·v⁻)``, SUMMED over the
    batch. The single objective body shared by skip-gram, CBOW and PV-DM."""
    pos = jnp.einsum("bd,bd->b", u, syn1[target])
    negs = jnp.einsum("bd,bkd->bk", u, syn1[neg])
    return -(_log_sigmoid(pos).sum() + _log_sigmoid(-negs).sum())


def sgns_loss(params, center, context, neg):
    """Skip-gram negative-sampling loss, SUMMED over the batch.

    center (B,), context (B,), neg (B, K) int32 → scalar. ``syn0`` is the
    input (word) table, ``syn1`` the output table — names match the
    reference's lookup-table fields. The sum (not mean) makes one jitted
    batch step equivalent to the reference's B sequential per-pair SGD
    updates at the same learning rate (modulo within-batch staleness).
    """
    return ns_loss_from_u(params["syn0"][center], context, neg,
                          params["syn1"])


def hs_path_loss(u, codes, points, mask, syn1h):
    """Hierarchical-softmax loss, SUMMED over the batch — the Huffman-path
    walk of the reference's ``HierarchicSoftmax``: for predictor u (B, D)
    and the target word's padded path (codes/points/mask (B, L)),
    ``-Σ_l logσ((1 - 2·code_l)·(u · syn1h[point_l]))``."""
    v = syn1h[points]                             # (B, L, D)
    s = jnp.einsum("bd,bld->bl", u, v)
    return -(_log_sigmoid((1.0 - 2.0 * codes) * s) * mask).sum()


@dataclass
class Word2Vec:
    """Word embeddings with the reference's Builder knobs.

    ``elements_learning_algorithm``: "skipgram" (default) or "cbow" —
    upstream ``elementsLearningAlgorithm(SkipGram/CBOW)``.
    ``use_hierarchic_softmax``: Huffman-tree output layer instead of
    negative sampling — upstream ``useHierarchicSoftmax(true)``.
    """

    layer_size: int = 100            # reference layerSize
    window_size: int = 5
    negative: int = 5                # negative samples per pair (NS mode)
    min_word_frequency: int = 5
    learning_rate: float = 0.025
    min_learning_rate: float = 1e-4
    subsample: float = 1e-3          # 0 disables frequent-word subsampling
    batch_size: int = 2048
    epochs: int = 1
    seed: int = 42
    elements_learning_algorithm: str = "skipgram"   # "skipgram" | "cbow"
    use_hierarchic_softmax: bool = False
    tokenizer_factory: TokenizerFactory = field(default_factory=DefaultTokenizerFactory)

    vocab: Optional[VocabCache] = None
    syn0: Optional[np.ndarray] = None            # (V, D) trained vectors

    # ------------------------------------------------------------------ fit
    def fit(self, sentences: Iterable[str]):
        tok = [self.tokenizer_factory.create(s).get_tokens()
               for s in sentences]
        return self._fit_tokens(tok)

    def _fit_tokens(self, tok: List[List[str]]):
        """Train from pre-tokenized element sequences — the entry point
        SequenceVectors (the upstream parent abstraction) uses directly."""
        self.vocab = VocabCache(self.min_word_frequency).fit(tok)
        ids = [self.vocab.encode(t) for t in tok]

        cbow = self.elements_learning_algorithm.lower() == "cbow"
        hs = self.use_hierarchic_softmax
        if cbow:
            centers, ctxs, cmask = self._build_cbow_examples(ids)
            batch_arrays = (centers, ctxs, cmask)
        else:
            centers, contexts = self._build_pairs(ids)
            batch_arrays = (centers, contexts)
        if len(centers) == 0:
            raise ValueError("no training pairs — corpus too small for vocab settings")

        V, D = self.vocab.num_words(), self.layer_size
        key = jax.random.PRNGKey(self.seed)
        k0, key = jax.random.split(key)
        params = {
            "syn0": (jax.random.uniform(k0, (V, D), jnp.float32) - 0.5) / D,
        }
        if hs:
            hcodes, hpoints, hmask = (jnp.asarray(a)
                                      for a in self.vocab.huffman_tree())
            params["syn1h"] = jnp.zeros((max(V - 1, 1), D), jnp.float32)
        else:
            params["syn1"] = jnp.zeros((V, D), jnp.float32)
        neg_logits = jnp.log(jnp.asarray(self.vocab.negative_table()) + 1e-30)

        def batch_loss(params, batch, neg):
            if cbow:
                tgt, ctx, cm = batch
                # CBOW predictor: mean of the window's input vectors
                # (upstream CBOW; word2vec.c cbow with mean)
                u = ((params["syn0"][ctx] * cm[..., None]).sum(1)
                     / jnp.maximum(cm.sum(1, keepdims=True), 1.0))
            else:
                ctr, tgt = batch
                u = params["syn0"][ctr]
            if hs:
                return hs_path_loss(u, hcodes[tgt], hpoints[tgt],
                                    hmask[tgt], params["syn1h"])
            return ns_loss_from_u(u, tgt, neg, params["syn1"])

        @jax.jit
        def step(params, key, batch, lr):
            B = batch[0].shape[0]
            nkey, key = jax.random.split(key)
            neg = (None if hs else jax.random.categorical(
                nkey, neg_logits[None, :], shape=(B, self.negative)))
            loss, grads = jax.value_and_grad(batch_loss)(params, batch, neg)
            # Per-row occurrence normalisation: a row hit k times in the batch
            # takes the AVERAGE of its k per-pair gradients at full lr. With a
            # large vocab k≈1 and this is exactly the reference's per-pair
            # SGD; with heavy collisions it stays stable where a raw sum
            # diverges (the reference is safe only because it's sequential).
            if cbow:
                tgt, ctx, cm = batch
                c0 = jnp.zeros(V).at[ctx.ravel()].add(cm.ravel())
            else:
                ctr, tgt = batch
                c0 = jnp.zeros(V).at[ctr].add(1.0)
            new = {"syn0": params["syn0"]
                   - lr * grads["syn0"] / jnp.maximum(c0, 1.0)[:, None]}
            if hs:
                ch = (jnp.zeros(params["syn1h"].shape[0])
                      .at[hpoints[tgt].ravel()].add(hmask[tgt].ravel()))
                new["syn1h"] = (params["syn1h"] - lr * grads["syn1h"]
                                / jnp.maximum(ch, 1.0)[:, None])
            else:
                c1 = jnp.zeros(V).at[tgt].add(1.0).at[neg.ravel()].add(1.0)
                new["syn1"] = (params["syn1"] - lr * grads["syn1"]
                               / jnp.maximum(c1, 1.0)[:, None])
            return new, key, loss / B

        def take(idx):
            return tuple(jnp.asarray(a[idx]) for a in batch_arrays)

        n = len(centers)
        steps_total = max(1, self.epochs * ((n + self.batch_size - 1) // self.batch_size))
        step_i, rng = 0, np.random.default_rng(self.seed)
        last_loss = 0.0
        for _ in range(self.epochs):
            perm = rng.permutation(n)
            for s in range(0, n - self.batch_size + 1, self.batch_size):
                idx = perm[s:s + self.batch_size]
                frac = step_i / steps_total
                lr = max(self.min_learning_rate,
                         self.learning_rate * (1.0 - frac))
                params, key, last_loss = step(params, key, take(idx), lr)
                step_i += 1
            if n < self.batch_size:   # tiny corpora: one padded batch per epoch
                idx = rng.integers(0, n, size=self.batch_size)
                params, key, last_loss = step(
                    params, key, take(idx),
                    max(self.min_learning_rate, self.learning_rate * (1 - step_i / steps_total)))
                step_i += 1
        self.syn0 = np.asarray(params["syn0"])
        self._last_loss = float(last_loss)
        return self

    def _build_pairs(self, ids: List[np.ndarray]):
        rng = np.random.default_rng(self.seed)
        keep = self.vocab.subsample_keep_prob(self.subsample) if self.subsample else None
        cs, xs = [], []
        for sent in ids:
            sent = sent[sent > 0]                        # drop UNK
            if keep is not None and len(sent):
                sent = sent[rng.random(len(sent)) < keep[sent]]
            L = len(sent)
            for i in range(L):
                b = rng.integers(1, self.window_size + 1)  # reference's shrinking window
                lo, hi = max(0, i - b), min(L, i + b + 1)
                for j in range(lo, hi):
                    if j != i:
                        cs.append(sent[i])
                        xs.append(sent[j])
        return (np.asarray(cs, np.int32), np.asarray(xs, np.int32))

    def _build_cbow_examples(self, ids: List[np.ndarray], rng=None,
                             subsample=None):
        """(center (N,), context (N, 2W) 0-padded, mask (N, 2W)) — one CBOW
        example per position with a non-empty (shrinking) window. Pass a
        shared ``rng`` when calling per-document (PV-DM) so window/subsample
        draws stay independent across calls; ``subsample=0`` disables
        frequent-word dropping (inference must see the full query)."""
        if rng is None:
            rng = np.random.default_rng(self.seed)
        if subsample is None:
            subsample = self.subsample
        keep = self.vocab.subsample_keep_prob(subsample) if subsample else None
        C = 2 * self.window_size
        ctr, ctxs, masks = [], [], []
        for sent in ids:
            sent = sent[sent > 0]
            if keep is not None and len(sent):
                sent = sent[rng.random(len(sent)) < keep[sent]]
            L = len(sent)
            for i in range(L):
                b = rng.integers(1, self.window_size + 1)
                win = [int(sent[j]) for j in
                       range(max(0, i - b), min(L, i + b + 1)) if j != i]
                if not win:
                    continue
                pad = C - len(win)
                ctr.append(sent[i])
                ctxs.append(win + [0] * pad)
                masks.append([1.0] * len(win) + [0.0] * pad)
        # empty result keeps rank 2 so per-doc results concatenate (PV-DM)
        return (np.asarray(ctr, np.int32),
                np.asarray(ctxs, np.int32).reshape(-1, C),
                np.asarray(masks, np.float32).reshape(-1, C))

    # -------------------------------------------------------------- queries
    def get_word_vector(self, word: str) -> np.ndarray:
        return self.syn0[self.vocab.index_of(word)]

    def has_word(self, word: str) -> bool:
        return self.vocab.contains_word(word)

    def similarity(self, w1: str, w2: str) -> float:
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        denom = (np.linalg.norm(a) * np.linalg.norm(b)) or 1e-12
        return float(a @ b / denom)

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        v = (self.get_word_vector(word_or_vec)
             if isinstance(word_or_vec, str) else np.asarray(word_or_vec))
        M = self.syn0 / (np.linalg.norm(self.syn0, axis=1, keepdims=True) + 1e-12)
        sims = M @ (v / (np.linalg.norm(v) + 1e-12))
        order = np.argsort(-sims)
        skip = {0}
        if isinstance(word_or_vec, str):
            skip.add(self.vocab.index_of(word_or_vec))
        out = [self.vocab.word_at_index(i) for i in order if i not in skip]
        return out[:top_n]

    # ---------------------------------------------------------------- serde
    def save(self, path: str):
        """WordVectorSerializer analogue: json header + npy matrix."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.save(path + ".npy", self.syn0)
        with open(path + ".json", "w") as f:
            json.dump({"layer_size": self.layer_size,
                       "words": self.vocab.words()}, f)

    @classmethod
    def load(cls, path: str) -> "Word2Vec":
        with open(path + ".json") as f:
            meta = json.load(f)
        m = cls(layer_size=meta["layer_size"])
        m.vocab = VocabCache()
        m.vocab.index_to_word = meta["words"]
        m.vocab.word_to_index = {w: i for i, w in enumerate(meta["words"])}
        m.syn0 = np.load(path + ".npy")
        return m

    def save_word2vec_format(self, path: str, include_header: bool = True,
                             binary: bool = False):
        """The interchange formats every word2vec/fastText/GloVe tool reads
        (reference WordVectorSerializer.writeWord2VecModel): text — optional
        "V D" header line then one `word v1 v2 ... vD` line per word; or the
        word2vec.c binary format — "V D\\n" header then `word` + space +
        D little-endian float32s + newline per word. UNK (index 0) is
        skipped — it is an internal slot, not a word."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if binary:
            with open(path, "wb") as f:
                f.write(f"{len(self.vocab.index_to_word) - 1} "
                        f"{self.layer_size}\n".encode())
                for i, word in enumerate(self.vocab.index_to_word):
                    if i == 0:
                        continue
                    f.write(word.encode("utf-8") + b" ")
                    f.write(np.asarray(self.syn0[i], "<f4").tobytes())
                    f.write(b"\n")
            return
        with open(path, "w", encoding="utf-8") as f:
            if include_header:
                f.write(f"{len(self.vocab.index_to_word) - 1} "
                        f"{self.layer_size}\n")
            for i, word in enumerate(self.vocab.index_to_word):
                if i == 0:
                    continue
                vec = " ".join(f"{v:.6f}" for v in self.syn0[i])
                f.write(f"{word} {vec}\n")

    @classmethod
    def _from_words_rows(cls, words, rows, d) -> "Word2Vec":
        """Assemble a model from loaded (word, vector) pairs, prepending
        the internal UNK slot (index 0, zero vector)."""
        m = cls(layer_size=d)
        m.vocab = VocabCache()
        m.vocab.index_to_word = [VocabCache.UNK] + words
        m.vocab.word_to_index = {w: i for i, w in
                                 enumerate(m.vocab.index_to_word)}
        m.syn0 = np.concatenate([np.zeros((1, d), np.float32),
                                 np.stack(rows)])
        return m

    @classmethod
    def _load_word2vec_binary(cls, path: str) -> "Word2Vec":
        """word2vec.c binary: header "V D\\n", then per word a
        whitespace-terminated utf-8 token followed by D raw float32s and an
        optional trailing newline."""
        with open(path, "rb") as f:
            header = f.readline().split()
            if len(header) != 2:
                raise ValueError(f"{path}: binary word2vec needs a 'V D' "
                                 "header line")
            v, d = int(header[0]), int(header[1])
            words, rows = [], []
            for _ in range(v):
                chars = bytearray()
                while True:
                    c = f.read(1)
                    if not c:
                        raise ValueError(f"{path}: truncated binary "
                                         f"word2vec file after "
                                         f"{len(words)} words")
                    if c in b" ":
                        break
                    if c not in b"\n":      # leading newline from prev row
                        chars.extend(c)
                words.append(chars.decode("utf-8"))
                vec = np.frombuffer(f.read(4 * d), "<f4")
                if vec.size != d:
                    raise ValueError(f"{path}: truncated vector for "
                                     f"'{words[-1]}'")
                rows.append(vec.astype(np.float32))
        return cls._from_words_rows(words, rows, d)

    @classmethod
    def load_word2vec_format(cls, path: str,
                             binary: Optional[bool] = None) -> "Word2Vec":
        """Read the text or binary interchange format (reference
        WordVectorSerializer.readWord2VecModel); header line optional for
        text. binary=None sniffs: a 'V D' header followed by bytes that
        don't decode as clean text means word2vec.c binary."""
        if binary is None:
            with open(path, "rb") as f:
                head = f.readline()
                chunk = f.read(4096)
            parts = head.split()
            looks_header = (len(parts) == 2 and parts[0].isdigit()
                            and parts[1].isdigit())
            # a multibyte utf-8 char may straddle the 4096-byte boundary —
            # trim up to 3 trailing bytes before declaring "not text"
            is_text = False
            for trim in range(4):
                try:
                    chunk[:len(chunk) - trim].decode("utf-8")
                    is_text = True
                    break
                except UnicodeDecodeError:
                    continue
            # raw float32 payload almost always contains control bytes
            # (e.g. the low-order NULs of 0.5 = 00 00 00 3f) which CAN be
            # valid utf-8 — text .vec files never contain them
            has_ctrl = any(b < 9 for b in chunk)
            binary = looks_header and (has_ctrl or not is_text)
        if binary:
            return cls._load_word2vec_binary(path)
        words, rows = [], []
        with open(path, encoding="utf-8") as f:
            for ln_no, ln in enumerate(f):
                # split() (not split(" ")): word2vec.c writes a trailing
                # space after the last value on every line
                parts = ln.split()
                if ln_no == 0 and len(parts) == 2 \
                        and parts[0].isdigit() and parts[1].isdigit():
                    continue  # "V D" header (both tokens must be ints —
                    #              a 1-D vector line is word + ONE float)
                if len(parts) < 2:
                    continue
                words.append(parts[0])
                rows.append(np.asarray(parts[1:], np.float32))
        if not rows:
            raise ValueError(f"no word vectors found in {path}")
        dims = {len(r) for r in rows}
        if len(dims) != 1:
            raise ValueError(f"inconsistent vector sizes in {path}: {dims}")
        return cls._from_words_rows(words, rows, dims.pop())


@dataclass
class ParagraphVectors(Word2Vec):
    """Document embeddings — reference ParagraphVectors with
    ``sequence_learning_algorithm`` "dbow" (PV-DBOW, default: the doc
    vector alone predicts each of its words) or "dm" (PV-DM, upstream
    ``learning.impl.sequence.DM``: the doc vector is averaged with the
    context window to predict the center word). ``infer_vector``
    gradient-descends a fresh doc vector with the word tables frozen,
    using the matching objective.
    """

    sequence_learning_algorithm: str = "dbow"    # "dbow" | "dm"
    doc_vectors: Optional[np.ndarray] = None
    _labels: List[str] = field(default_factory=list)

    def _is_dm(self):
        return self.sequence_learning_algorithm.lower() == "dm"

    def fit(self, documents: Sequence[str], labels: Optional[Sequence[str]] = None):
        docs = list(documents)
        self._labels = list(labels) if labels else [f"DOC_{i}" for i in range(len(docs))]
        super().fit(docs)  # trains word tables + vocab

        tokf = self.tokenizer_factory
        ids = [self.vocab.encode(tokf.create(d).get_tokens()) for d in docs]
        Nd, D = len(docs), self.layer_size
        key = jax.random.PRNGKey(self.seed + 1)
        dvec = (jax.random.uniform(key, (Nd, D)) - 0.5) / D
        syn1 = jnp.asarray(self.syn0)  # predict into trained word space
        neg_logits = jnp.log(jnp.asarray(self.vocab.negative_table()) + 1e-30)

        if self._is_dm():
            ex_rng = np.random.default_rng(self.seed)
            d_list, tgt_list, ctx_list, cm_list = [], [], [], []
            for di, sent in enumerate(ids):
                tgt, ctx, cm = self._build_cbow_examples([sent], rng=ex_rng)
                d_list.append(np.full(len(tgt), di, np.int32))
                tgt_list.append(tgt)
                ctx_list.append(ctx)
                cm_list.append(cm)
            doc_idx = np.concatenate(d_list)
            word_idx = np.concatenate(tgt_list)
            ctx_idx = np.concatenate(ctx_list)
            ctx_mask = np.concatenate(cm_list)
            arrays = (doc_idx, word_idx, ctx_idx, ctx_mask)

            def loss_fn(dvec, batch, neg):
                d, w, ctx, cm = batch
                # PV-DM predictor: mean over [doc vector, window vectors];
                # syn1 doubles as the frozen word-input table (same array)
                u = ((dvec[d] + (syn1[ctx] * cm[..., None]).sum(1))
                     / (1.0 + cm.sum(1, keepdims=True)))
                return ns_loss_from_u(u, w, neg, syn1)
        else:
            doc_idx, word_idx = [], []
            for di, sent in enumerate(ids):
                for w in sent[sent > 0]:
                    doc_idx.append(di)
                    word_idx.append(w)
            doc_idx = np.asarray(doc_idx, np.int32)
            word_idx = np.asarray(word_idx, np.int32)
            arrays = (doc_idx, word_idx)

            def loss_fn(dvec, batch, neg):
                d, w = batch
                return sgns_loss({"syn0": dvec, "syn1": syn1}, d, w, neg)

        @jax.jit
        def step(dvec, key, batch, lr):
            nkey, key = jax.random.split(key)
            neg = jax.random.categorical(nkey, neg_logits[None, :],
                                         shape=(batch[0].shape[0], self.negative))
            loss, g = jax.value_and_grad(loss_fn)(dvec, batch, neg)
            cnt = jnp.zeros(Nd).at[batch[0]].add(1.0)
            return dvec - lr * g / jnp.maximum(cnt, 1.0)[:, None], key, loss

        rng = np.random.default_rng(self.seed)
        n = len(doc_idx)
        if n:
            bs = min(self.batch_size, max(n, 1))
            for e in range(max(self.epochs, 5)):
                idx = rng.integers(0, n, size=bs)
                dvec, key, _ = step(
                    dvec, key, tuple(jnp.asarray(a[idx]) for a in arrays),
                    self.learning_rate)
        self.doc_vectors = np.asarray(dvec)
        return self

    def get_doc_vector(self, label: str) -> np.ndarray:
        return self.doc_vectors[self._labels.index(label)]

    def infer_vector(self, text: str, steps: int = 50, lr: float = 0.05) -> np.ndarray:
        ids = self.vocab.encode(self.tokenizer_factory.create(text).get_tokens())
        ids = ids[ids > 0]
        if len(ids) == 0:
            return np.zeros(self.layer_size, np.float32)
        syn1 = jnp.asarray(self.syn0)
        neg_logits = jnp.log(jnp.asarray(self.vocab.negative_table()) + 1e-30)
        if self._is_dm():
            # no subsampling at inference: upstream inferVector sees the
            # full query, as does our DBOW branch below
            tgt, ctx, cm = self._build_cbow_examples([ids], subsample=0)
            if len(tgt) == 0:   # single-word text: no window -> DBOW objective
                tgt = ids
                ctx = np.zeros((len(ids), 2 * self.window_size), np.int32)
                cm = np.zeros_like(ctx, np.float32)
            w = jnp.asarray(tgt)
            ctx_j, cm_j = jnp.asarray(ctx), jnp.asarray(cm)

            def loss_fn(v, neg):
                u = ((v[None, :] + (syn1[ctx_j] * cm_j[..., None]).sum(1))
                     / (1.0 + cm_j.sum(1, keepdims=True)))
                return ns_loss_from_u(u, w, neg, syn1)
        else:
            w = jnp.asarray(ids)
            d = jnp.zeros((len(ids),), jnp.int32)

            def loss_fn(v, neg):
                return sgns_loss({"syn0": v[None, :], "syn1": syn1}, d, w, neg)

        B = int(w.shape[0])

        @jax.jit
        def run(v, key):
            def body(carry, _):
                v, key = carry
                nkey, key = jax.random.split(key)
                neg = jax.random.categorical(nkey, neg_logits[None, :],
                                             shape=(B, self.negative))
                g = jax.grad(loss_fn)(v, neg)
                return (v - lr * g / B, key), None
            (v, _), _ = jax.lax.scan(body, (v, key), None, length=steps)
            return v

        key = jax.random.PRNGKey(abs(hash(text)) % (2 ** 31))
        v0 = (jax.random.uniform(key, (self.layer_size,)) - 0.5) / self.layer_size
        return np.asarray(run(v0, key))

    def nearest_labels(self, text: str, top_n: int = 5) -> List[str]:
        v = self.infer_vector(text)
        M = self.doc_vectors / (np.linalg.norm(self.doc_vectors, axis=1,
                                               keepdims=True) + 1e-12)
        sims = M @ (v / (np.linalg.norm(v) + 1e-12))
        return [self._labels[i] for i in np.argsort(-sims)[:top_n]]
