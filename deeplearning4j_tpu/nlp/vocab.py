"""Vocabulary cache — parity with DL4J's
``org.deeplearning4j.models.word2vec.wordstore.VocabCache`` /
``AbstractCache`` + the unigram negative-sampling table that the
reference builds inside Word2Vec's lookup table.

Host-side structure; it ships int32 id arrays to the device.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np


class VocabCache:
    """word ↔ index with frequency accounting and a sampling table.

    Index 0 is always the UNK token (reference uses "UNK" literally).
    """

    UNK = "UNK"

    def __init__(self, min_word_frequency: int = 1):
        self.min_word_frequency = min_word_frequency
        self.word_counts: Counter = Counter()
        self.word_to_index: Dict[str, int] = {}
        self.index_to_word: List[str] = []
        self.total_word_count = 0
        self._neg_table: Optional[np.ndarray] = None
        self._keep_prob: Optional[np.ndarray] = None

    # -- building -----------------------------------------------------------
    def fit(self, token_stream: Iterable[List[str]]):
        for tokens in token_stream:
            self.word_counts.update(tokens)
        self.finish()
        return self

    def finish(self):
        kept = [(w, c) for w, c in self.word_counts.most_common()
                if c >= self.min_word_frequency]
        self.index_to_word = [self.UNK] + [w for w, _ in kept]
        self.word_to_index = {w: i for i, w in enumerate(self.index_to_word)}
        self.total_word_count = sum(c for _, c in kept)
        self._neg_table = None
        self._keep_prob = None

    # -- queries (reference VocabCache surface) -----------------------------
    def num_words(self) -> int:
        return len(self.index_to_word)

    def contains_word(self, word: str) -> bool:
        return word in self.word_to_index

    def index_of(self, word: str) -> int:
        return self.word_to_index.get(word, 0)

    def word_at_index(self, idx: int) -> str:
        return self.index_to_word[idx]

    def word_frequency(self, word: str) -> int:
        return self.word_counts.get(word, 0)

    def words(self) -> List[str]:
        return list(self.index_to_word)

    def encode(self, tokens: List[str]) -> np.ndarray:
        return np.asarray([self.index_of(t) for t in tokens], dtype=np.int32)

    # -- sampling machinery -------------------------------------------------
    def negative_table(self, power: float = 0.75) -> np.ndarray:
        """Unigram^0.75 distribution as per-word probabilities (we sample on
        device with jax.random.choice rather than the reference's 100M-slot
        table — same distribution, O(V) memory)."""
        if self._neg_table is None:
            freqs = np.asarray(
                [self.word_counts.get(w, 1) for w in self.index_to_word],
                dtype=np.float64) ** power
            freqs[0] = 0.0  # never sample UNK as a negative
            self._neg_table = (freqs / freqs.sum()).astype(np.float32)
        return self._neg_table

    def huffman_tree(self):
        """Frequency-Huffman coding of the vocab — parity with the tree the
        reference's ``HierarchicSoftmax`` walks (upstream ``Huffman`` /
        word2vec.c CreateBinaryTree). Returns padded device-ready arrays
        ``(codes (V, L) int32 0/1, points (V, L) int32 inner-node ids,
        mask (V, L) float32)`` where L is the longest code. Built with a
        heap, so it does not require count-sorted indices (our index 0 is
        UNK, out of frequency order)."""
        import heapq
        V = len(self.index_to_word)
        if V < 2:
            raise ValueError("hierarchical softmax needs a vocab of >= 2")
        counts = [max(self.word_counts.get(w, 0), 1)
                  for w in self.index_to_word]
        heap = [(c, i) for i, c in enumerate(counts)]
        heapq.heapify(heap)
        parent: Dict[int, int] = {}
        branch: Dict[int, int] = {}
        nxt = V
        while len(heap) > 1:
            c1, i1 = heapq.heappop(heap)
            c2, i2 = heapq.heappop(heap)
            parent[i1], branch[i1] = nxt, 0
            parent[i2], branch[i2] = nxt, 1
            heapq.heappush(heap, (c1 + c2, nxt))
            nxt += 1
        root = heap[0][1]
        codes, points = [], []
        for wi in range(V):
            code, pts = [], []
            node = wi
            while node != root:
                code.append(branch[node])
                pts.append(parent[node] - V)   # inner nodes 0..V-2
                node = parent[node]
            codes.append(code[::-1])           # root-first (canonical;
            points.append(pts[::-1])           #  the HS loss sums the path)
        L = max(len(c) for c in codes)
        cd = np.zeros((V, L), np.int32)
        pt = np.zeros((V, L), np.int32)
        mk = np.zeros((V, L), np.float32)
        for wi in range(V):
            n = len(codes[wi])
            cd[wi, :n] = codes[wi]
            pt[wi, :n] = points[wi]
            mk[wi, :n] = 1.0
        return cd, pt, mk

    def subsample_keep_prob(self, t: float = 1e-3) -> np.ndarray:
        """Mikolov frequent-word subsampling: keep prob per word index."""
        if self._keep_prob is None:
            tot = max(self.total_word_count, 1)
            f = np.asarray(
                [self.word_counts.get(w, 0) / tot for w in self.index_to_word],
                dtype=np.float64)
            with np.errstate(divide="ignore", invalid="ignore"):
                p = np.sqrt(t / np.maximum(f, 1e-12)) + t / np.maximum(f, 1e-12)
            self._keep_prob = np.clip(np.nan_to_num(p, nan=1.0), 0.0, 1.0
                                      ).astype(np.float32)
        return self._keep_prob
