"""BERT data pipeline: WordPiece tokenizer + BertIterator.

Reference parity: ``org.deeplearning4j.text.tokenization.tokenizerfactory
.BertWordPieceTokenizerFactory`` (greedy longest-match-first wordpiece over
a BERT vocab file) and ``org.deeplearning4j.iterator.BertIterator``
(sentences → fixed-length [ids, segment ids] features + attention masks,
Task.SEQ_CLASSIFICATION labels or Task.UNSUPERVISED MLM masking).

TPU-first notes: tokenization is host ETL; sequences are padded to
``max_length``. A dataset not divisible by batch_size yields one ragged
final batch (one extra jit compile) — pass ``drop_last=True`` to keep
every batch identically shaped.
For UNSUPERVISED the 15%/80-10-10 masking runs ON DEVICE per step
(``zoo.transformer.bert_mask_tokens``) — the iterator just supplies ids —
which keeps masking re-randomized every epoch for free, unlike the
reference's host-side masking."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import MultiDataSet


class BertWordPieceTokenizer:
    """Greedy longest-match-first WordPiece (BERT's tokenizer).

    vocab: dict token->id or an iterable of tokens (ids = positions); the
    standard special tokens ([PAD]/[UNK]/[CLS]/[SEP]/[MASK]) must be in
    the vocab (vocab.txt order for real BERT checkpoints).
    """

    def __init__(self, vocab, lower_case: bool = True,
                 max_chars_per_word: int = 100):
        if not isinstance(vocab, dict):
            vocab = {tok: i for i, tok in enumerate(vocab)}
        self.vocab: Dict[str, int] = dict(vocab)
        self.lower_case = lower_case
        self.max_chars = max_chars_per_word
        for special in ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"):
            if special not in self.vocab:
                raise ValueError(f"vocab is missing {special}")

    @classmethod
    def load_vocab(cls, path: str, **kw) -> "BertWordPieceTokenizer":
        """Read a BERT vocab.txt (one token per line, line number = id)."""
        with open(path, encoding="utf-8") as f:
            return cls([ln.rstrip("\r\n") for ln in f], **kw)

    def _basic_split(self, text: str) -> List[str]:
        if self.lower_case:
            text = text.lower()
        out, word = [], []
        for ch in text:
            if ch.isspace():
                if word:
                    out.append("".join(word))
                    word = []
            elif not ch.isalnum():
                # ALL punctuation splits (BERT BasicTokenizer semantics:
                # "don't" -> don ' t — matches pretrained checkpoints)
                if word:
                    out.append("".join(word))
                    word = []
                out.append(ch)
            else:
                word.append(ch)
        if word:
            out.append("".join(word))
        return out

    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return ["[UNK]"]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return ["[UNK]"]
            pieces.append(piece)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out = []
        for word in self._basic_split(text):
            out.extend(self._wordpiece(word))
        return out

    def encode(self, text: str) -> List[int]:
        return [self.vocab[t] for t in self.tokenize(text)]

    def id_of(self, token: str) -> int:
        return self.vocab[token]


class BertIterator:
    """Sentences → padded BERT features (reference BertIterator.Builder).

    Tasks:
      - ``SEQ_CLASSIFICATION``: labeled (sentence, class) pairs →
        MultiDataSet(features=[ids, segment_ids], masks=[attention],
        labels=[one-hot]).
      - ``UNSUPERVISED``: raw sentences; MLM masking happens on device in
        the training step, so labels carry the UNMASKED ids.

    Builder args mirror the reference: tokenizer, max length, batch size,
    padding to fixed shapes.
    """

    SEQ_CLASSIFICATION = "SEQ_CLASSIFICATION"
    UNSUPERVISED = "UNSUPERVISED"

    def __init__(self, tokenizer: BertWordPieceTokenizer, sentences,
                 labels: Optional[Sequence[int]] = None,
                 num_classes: Optional[int] = None,
                 task: str = "SEQ_CLASSIFICATION", max_length: int = 128,
                 batch_size: int = 32, pair_sentences=None,
                 drop_last: bool = False):
        if task not in (self.SEQ_CLASSIFICATION, self.UNSUPERVISED):
            raise ValueError(f"unknown task {task}")
        if task == self.SEQ_CLASSIFICATION and labels is None:
            raise ValueError("SEQ_CLASSIFICATION needs labels")
        self.tok = tokenizer
        self.task = task
        self.drop_last = drop_last
        self.max_length = max_length
        self.batch_size = batch_size
        sentences = list(sentences)
        pairs = list(pair_sentences) if pair_sentences is not None else None

        cls_id = tokenizer.id_of("[CLS]")
        sep_id = tokenizer.id_of("[SEP]")
        pad_id = tokenizer.id_of("[PAD]")
        self.pad_id, self.mask_id = pad_id, tokenizer.id_of("[MASK]")
        # positions never selected as MLM targets (feed to
        # make_bert_mlm_train_step(special_ids=it.special_ids))
        self.special_ids = (pad_id, cls_id, sep_id)
        n = len(sentences)
        ids = np.full((n, max_length), pad_id, np.int32)
        seg = np.zeros((n, max_length), np.int32)
        attn = np.zeros((n, max_length), np.float32)
        for i, sent in enumerate(sentences):
            toks = [cls_id] + tokenizer.encode(sent) + [sep_id]
            segs = [0] * len(toks)
            if pairs is not None:
                second = tokenizer.encode(pairs[i]) + [sep_id]
                toks += second
                segs += [1] * len(second)
            toks, segs = toks[:max_length], segs[:max_length]
            ids[i, :len(toks)] = toks
            seg[i, :len(segs)] = segs
            attn[i, :len(toks)] = 1.0
        self._ids, self._seg, self._attn = ids, seg, attn
        if task == self.SEQ_CLASSIFICATION:
            labels = np.asarray(labels, np.int64)
            k = num_classes or int(labels.max()) + 1
            self._labels = np.eye(k, dtype=np.float32)[labels]
        else:
            self._labels = ids.copy()       # MLM targets = unmasked ids
        self._pos = 0

    # ------------------------------------------------------------ iteration
    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> MultiDataSet:
        remaining = len(self._ids) - self._pos
        if remaining <= 0 or (self.drop_last and remaining < self.batch_size):
            # drop_last keeps every batch the same shape so a jitted train
            # step never recompiles for a ragged tail
            raise StopIteration
        lo, hi = self._pos, min(self._pos + self.batch_size, len(self._ids))
        self._pos = hi
        feats = [self._ids[lo:hi], self._seg[lo:hi]]
        fmasks = [self._attn[lo:hi], None]
        return MultiDataSet(feats, [self._labels[lo:hi]],
                            features_masks=fmasks)

    def next(self):
        return self.__next__()

    def has_next(self) -> bool:
        remaining = len(self._ids) - self._pos
        if self.drop_last:
            return remaining >= self.batch_size
        return remaining > 0

    def reset(self):
        self._pos = 0
