"""GloVe — parity with DL4J's
``org.deeplearning4j.models.glove.Glove`` (co-occurrence counting +
AdaGrad on the weighted least-squares objective, ``xMax``/``alpha``
weighting, symmetric windows).

TPU-first redesign: the reference shards co-occurrence counting across
threads and runs per-pair Hogwild AdaGrad. Here the co-occurrence pass is
a host-side dict accumulation (it is IO/string bound, like the
reference's CoOccurrenceReader), and training is mini-batched AdaGrad on
device: each jitted step takes a batch of (i, j, log X_ij, f(X_ij))
records, autodiff turns the embedding gathers into scatter-adds, and the
AdaGrad accumulator update rides the same program. Final vectors are
``W + W̃`` (both tables summed, the standard GloVe export).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .vocab import VocabCache
from .word2vec import Word2Vec


@dataclass
class GloVe(Word2Vec):
    """GloVe embeddings with the reference Builder's knobs."""

    x_max: float = 100.0         # reference xMax
    alpha: float = 0.75          # reference alpha
    learning_rate: float = 0.05  # AdaGrad base lr (reference learningRate)
    epochs: int = 25
    symmetric: bool = True       # reference symmetric(true)
    batch_size: int = 8192

    def __post_init__(self):
        # inherited SGNS-only knobs have no meaning for the GloVe objective —
        # reject them loudly rather than silently no-op a hyperparam sweep
        if self.negative != 5 or self.subsample != 1e-3 \
                or self.min_learning_rate != 1e-4:
            raise ValueError(
                "GloVe has no negative sampling, subsampling, or lr decay: "
                "'negative'/'subsample'/'min_learning_rate' are Word2Vec-only "
                "knobs (use x_max/alpha/learning_rate)")

    # ------------------------------------------------------------------ fit
    def fit(self, sentences: Iterable[str]):
        tok = [self.tokenizer_factory.create(s).get_tokens()
               for s in sentences]
        return self._fit_tokens(tok)

    def _fit_tokens(self, tok: List[List[str]]):
        self.vocab = VocabCache(self.min_word_frequency).fit(tok)
        ids = [self.vocab.encode(t) for t in tok]
        rows, cols, vals = self._cooccurrences(ids)
        if len(rows) == 0:
            raise ValueError("no co-occurrences — corpus too small")

        V, D = self.vocab.num_words(), self.layer_size
        key = jax.random.PRNGKey(self.seed)
        ks = jax.random.split(key, 4)
        scale = 0.5 / D
        params = {
            "W": jax.random.uniform(ks[0], (V, D), jnp.float32, -scale, scale),
            "Wc": jax.random.uniform(ks[1], (V, D), jnp.float32, -scale, scale),
            "b": jnp.zeros((V,), jnp.float32),
            "bc": jnp.zeros((V,), jnp.float32),
        }
        # AdaGrad history, initialised at 1.0 like the reference's
        # (and the original C implementation's) gradsq tables
        hist = jax.tree_util.tree_map(jnp.ones_like, params)
        lr = self.learning_rate

        def loss_fn(p, i, j, logx, f):
            pred = (jnp.einsum("bd,bd->b", p["W"][i], p["Wc"][j])
                    + p["b"][i] + p["bc"][j])
            return jnp.sum(f * jnp.square(pred - logx))

        @jax.jit
        def step(params, hist, i, j, logx, f):
            loss, g = jax.value_and_grad(loss_fn)(params, i, j, logx, f)
            hist = jax.tree_util.tree_map(lambda h, gr: h + gr * gr, hist, g)
            params = jax.tree_util.tree_map(
                lambda p, gr, h: p - lr * gr / jnp.sqrt(h), params, g, hist)
            return params, hist, loss

        logx = np.log(vals).astype(np.float32)
        f = np.minimum(1.0, (vals / self.x_max) ** self.alpha).astype(np.float32)
        n = len(rows)
        bs = min(self.batch_size, n)
        rng = np.random.default_rng(self.seed)
        last = 0.0
        for _ in range(self.epochs):
            perm = rng.permutation(n)
            for s in range(0, n, bs):
                idx = perm[s:s + bs]
                if len(idx) < bs:     # pad final batch with zero-weight rows
                    pad = rng.integers(0, n, bs - len(idx))
                    fb = np.concatenate([f[idx], np.zeros(len(pad), np.float32)])
                    idx = np.concatenate([idx, pad])
                else:
                    fb = f[idx]
                params, hist, last = step(
                    params, hist, jnp.asarray(rows[idx]),
                    jnp.asarray(cols[idx]), jnp.asarray(logx[idx]),
                    jnp.asarray(fb))
        self.syn0 = np.asarray(params["W"] + params["Wc"])
        self._last_loss = float(last)
        return self

    # ------------------------------------------------- co-occurrence pass
    def _cooccurrences(self, ids: List[np.ndarray]
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Window-weighted counts X_ij += 1/distance (reference
        CoOccurrenceReader semantics; symmetric adds both directions)."""
        counts: Dict[Tuple[int, int], float] = {}
        for sent in ids:
            sent = sent[sent > 0]                       # drop UNK
            L = len(sent)
            for i in range(L):
                wi = int(sent[i])
                for d in range(1, self.window_size + 1):
                    j = i - d
                    if j < 0:
                        break
                    wj = int(sent[j])
                    w = 1.0 / d
                    counts[(wi, wj)] = counts.get((wi, wj), 0.0) + w
                    if self.symmetric:
                        counts[(wj, wi)] = counts.get((wj, wi), 0.0) + w
        if not counts:
            return (np.empty(0, np.int32),) * 2 + (np.empty(0, np.float32),)
        keys = np.asarray(list(counts.keys()), np.int32)
        vals = np.asarray(list(counts.values()), np.float32)
        return keys[:, 0], keys[:, 1], vals
