"""FastText — subword n-gram embeddings.

Reference parity: ``org.deeplearning4j.models.fasttext.FastText`` (a JNI
wrapper over facebookresearch/fastText). Semantics follow the fastText
skipgram model: a word's input representation is the MEAN of its subword
vectors — the word itself plus the character n-grams of ``<word>`` for
n in [minn, maxn], hashed into ``bucket`` slots with FNV-1a — trained
against negative sampling; OOV words get vectors from their n-grams alone.

TPU-first redesign: upstream fastText is a sequential C++ SGD loop over one
(center, context) pair at a time. Here the subword id matrix (V, S) is
precomputed once, a batch's hidden vectors are one gather + masked mean on
device, and the whole step (loss, grads, occurrence-normalized update) is a
single jitted program — the same batched-SGD regime as our Word2Vec.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .vocab import VocabCache
from .word2vec import Word2Vec, ns_loss_from_u


def fnv1a_32(data: bytes) -> int:
    """FNV-1a 32-bit — the hash fastText uses for n-gram bucketing."""
    h = 2166136261
    for b in data:
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h


def char_ngrams(word: str, minn: int, maxn: int):
    """Character n-grams of ``<word>`` (with boundary markers), excluding
    the full token itself — fastText computeSubwords."""
    w = f"<{word}>"
    out = []
    for n in range(minn, maxn + 1):
        if n >= len(w):
            continue
        for i in range(len(w) - n + 1):
            out.append(w[i:i + n])
    return out


@dataclass
class FastText(Word2Vec):
    """fastText skipgram with subword enrichment. Builder knobs mirror the
    reference: ``minn``/``maxn`` n-gram range, ``bucket`` hash buckets."""

    minn: int = 3
    maxn: int = 6
    bucket: int = 50_000   # upstream default is 2M; sized for typical corpora

    def _subword_ids(self, word: str, index: int = None):
        """Input-row ids for a word: its own slot (in-vocab only) plus
        hashed n-gram slots offset by V."""
        V = self.vocab.num_words()
        ids = [] if index is None else [index]
        for g in char_ngrams(word, self.minn, self.maxn):
            ids.append(V + fnv1a_32(g.encode("utf-8")) % self.bucket)
        return ids

    def _build_subword_table(self):
        """(V, S) padded id matrix + (V, S) mask over the vocab."""
        V = self.vocab.num_words()
        rows = [[0] for _ in range(1)]  # UNK slot: just itself
        for i in range(1, V):
            rows.append(self._subword_ids(self.vocab.word_at_index(i), i))
        S = max(len(r) for r in rows)
        ids = np.zeros((V, S), np.int32)
        mask = np.zeros((V, S), np.float32)
        for i, r in enumerate(rows):
            ids[i, :len(r)] = r
            mask[i, :len(r)] = 1.0
        return jnp.asarray(ids), jnp.asarray(mask)

    def _fit_tokens(self, tok):
        if self.elements_learning_algorithm.lower() != "skipgram" \
                or self.use_hierarchic_softmax:
            raise ValueError(
                "FastText here trains skipgram + negative sampling only; "
                "cbow/hierarchic-softmax subword variants are not "
                "implemented — use Word2Vec for those modes")
        self.vocab = VocabCache(self.min_word_frequency).fit(tok)
        ids = [self.vocab.encode(t) for t in tok]
        centers, contexts = self._build_pairs(ids)
        if len(centers) == 0:
            raise ValueError(
                "no training pairs — corpus too small for vocab settings")

        V, D = self.vocab.num_words(), self.layer_size
        sub_ids, sub_mask = self._build_subword_table()
        rows_total = V + self.bucket
        key = jax.random.PRNGKey(self.seed)
        k0, key = jax.random.split(key)
        params = {
            "syn0": (jax.random.uniform(k0, (rows_total, D), jnp.float32)
                     - 0.5) / D,
            "syn1": jnp.zeros((V, D), jnp.float32),
        }
        neg_logits = jnp.log(jnp.asarray(self.vocab.negative_table()) + 1e-30)

        def batch_loss(params, ctr, tgt, neg):
            sids, sm = sub_ids[ctr], sub_mask[ctr]          # (B,S), (B,S)
            u = ((params["syn0"][sids] * sm[..., None]).sum(1)
                 / jnp.maximum(sm.sum(1, keepdims=True), 1.0))
            return ns_loss_from_u(u, tgt, neg, params["syn1"])

        @jax.jit
        def step(params, key, ctr, tgt, lr):
            B = ctr.shape[0]
            nkey, key = jax.random.split(key)
            neg = jax.random.categorical(nkey, neg_logits[None, :],
                                         shape=(B, self.negative))
            loss, grads = jax.value_and_grad(batch_loss)(params, ctr, tgt,
                                                         neg)
            # occurrence normalization over INPUT ROWS (word + ngram slots):
            # same stability argument as Word2Vec's batched SGD
            sids, sm = sub_ids[ctr], sub_mask[ctr]
            c0 = jnp.zeros(rows_total).at[sids.ravel()].add(sm.ravel())
            c1 = jnp.zeros(V).at[tgt].add(1.0).at[neg.ravel()].add(1.0)
            new = {
                "syn0": params["syn0"]
                - lr * grads["syn0"] / jnp.maximum(c0, 1.0)[:, None],
                "syn1": params["syn1"]
                - lr * grads["syn1"] / jnp.maximum(c1, 1.0)[:, None],
            }
            return new, key, loss / B

        n = len(centers)
        steps_total = max(1, self.epochs
                          * ((n + self.batch_size - 1) // self.batch_size))
        step_i, rng = 0, np.random.default_rng(self.seed)
        centers = jnp.asarray(centers)
        contexts = jnp.asarray(contexts)
        last_loss = 0.0
        for _ in range(self.epochs):
            perm = rng.permutation(n)
            for s in range(0, n - self.batch_size + 1, self.batch_size):
                idx = perm[s:s + self.batch_size]
                lr = max(self.min_learning_rate,
                         self.learning_rate * (1.0 - step_i / steps_total))
                params, key, last_loss = step(params, key, centers[idx],
                                              contexts[idx], lr)
                step_i += 1
            if n < self.batch_size:
                idx = rng.integers(0, n, size=self.batch_size)
                lr = max(self.min_learning_rate,
                         self.learning_rate * (1.0 - step_i / steps_total))
                params, key, last_loss = step(params, key, centers[idx],
                                              contexts[idx], lr)
                step_i += 1
        self.syn0_full = np.asarray(params["syn0"])   # (V+bucket, D)
        # composed per-word vectors so the inherited query/serde API
        # (similarity, words_nearest, save_word2vec_format) works unchanged
        sm = np.asarray(sub_mask)
        comp = (self.syn0_full[np.asarray(sub_ids)] * sm[..., None]).sum(1)
        self.syn0 = comp / np.maximum(sm.sum(1, keepdims=True), 1.0)
        self._last_loss = float(last_loss)
        return self

    # -------------------------------------------------------------- queries
    def get_word_vector(self, word: str) -> np.ndarray:
        """In-vocab: composed subword mean. OOV: mean of n-gram buckets —
        the fastText signature capability."""
        if self.vocab.contains_word(word):
            return self.syn0[self.vocab.index_of(word)]
        if getattr(self, "syn0_full", None) is None:
            raise ValueError("model not trained")
        ids = self._subword_ids(word)
        if not ids:
            raise ValueError(
                f"'{word}' is OOV and too short for [{self.minn},{self.maxn}]"
                " n-grams")
        return self.syn0_full[np.asarray(ids)].mean(axis=0)

    def out_of_vocab_supported(self) -> bool:
        return getattr(self, "syn0_full", None) is not None
