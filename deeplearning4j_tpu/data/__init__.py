"""deeplearning4j_tpu.data — datasets, iterators, normalizers."""

from .dataset import DataSet, MultiDataSet
from .iterators import (ArrayDataSetIterator, BaseDatasetIterator,
                        Cifar10DataSetIterator, EmnistDataSetIterator,
                        IrisDataSetIterator, KFoldIterator,
                        ListDataSetIterator, MnistDataSetIterator,
                        MultipleEpochsIterator, RandomDataSetIterator,
                        make_synthetic_mnist)
