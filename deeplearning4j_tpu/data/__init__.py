"""deeplearning4j_tpu.data — datasets, iterators, normalizers."""

from .dataset import DataSet, MultiDataSet
from .datavec import (CSVRecordReader, CollectionRecordReader,
                      LineRecordReader, RecordReader,
                      RecordReaderDataSetIterator, Schema, TransformProcess,
                      make_image_augmenter, resize_images)
from .iterators import (ArrayDataSetIterator, BaseDatasetIterator,
                        Cifar10DataSetIterator, EmnistDataSetIterator,
                        IrisDataSetIterator, KFoldIterator,
                        ListDataSetIterator, MnistDataSetIterator,
                        MultipleEpochsIterator, RandomDataSetIterator,
                        make_synthetic_mnist)
