"""deeplearning4j_tpu.data — datasets, iterators, normalizers."""

from .dataset import DataSet, MultiDataSet
from .datavec import (CSVRecordReader, CollectionRecordReader,
                      JDBCRecordReader,
                      LineRecordReader, RecordReader,
                      RecordReaderDataSetIterator, SVMLightRecordReader,
                      Schema, TransformProcess,
                      make_image_augmenter, resize_images)
from .iterators import (ArrayDataSetIterator, BaseDatasetIterator,
                        Cifar10DataSetIterator, EmnistDataSetIterator,
                        IrisDataSetIterator, IteratorDataSetIterator,
                        KFoldIterator, ListDataSetIterator,
                        MnistDataSetIterator, MultipleEpochsIterator,
                        RandomDataSetIterator, make_synthetic_mnist)
from .audio import (AudioDataSetIterator, WavFileRecordReader,
                    make_spectrogram_fn, read_wav, write_wav)
from .extra_datasets import (SvhnDataSetIterator,
                             TinyImageNetDataSetIterator,
                             UciSequenceDataSetIterator)
from .image import (ImageDataSetIterator, ImageRecordReader,
                    NativeImageLoader, ParentPathLabelGenerator)
from .transforms import (Condition, ConvertToSequence, DataAnalysis,
                         DataQualityAnalysis, Join, Reducer, analyze,
                         analyze_quality, column_condition,
                         invalid_value_condition, sequence_difference,
                         sequence_moving_window_reduce, sequence_offset,
                         sequence_trim, split_sequences_by_length)
from .normalizers import (CompositeDataSetPreProcessor,
                          ImagePreProcessingScaler,
                          MultiNormalizerMinMaxScaler,
                          MultiNormalizerStandardize, NormalizerMinMaxScaler,
                          NormalizerStandardize, VGG16ImagePreProcessor)
from .sequence_readers import (ALIGN_END, ALIGN_START, EQUAL_LENGTH,
                               CollectionSequenceRecordReader,
                               CSVLineSequenceRecordReader,
                               CSVSequenceRecordReader,
                               RegexSequenceRecordReader,
                               SequenceRecordReader,
                               SequenceRecordReaderDataSetIterator)
