"""DataSet / MultiDataSet — feature+label containers.

Reference parity: ``org.nd4j.linalg.dataset.DataSet`` (features, labels,
featuresMask, labelsMask, save/load, split, shuffle, batchBy) and
``MultiDataSet`` (multi-input/multi-output).
Host arrays stay numpy (cheap slicing for the input pipeline) and move to
device inside the jitted step; arrays that are ALREADY on device (jax
Arrays — on-device augmentation/synthesis pipelines) are kept as-is, like
the reference's device-backed INDArray DataSet: forcing them through
numpy would bounce every batch device→host→device.
"""

from __future__ import annotations

import io
import zipfile
from typing import List, Optional, Sequence

import numpy as np


def _as_host_or_device(a):
    """numpy for host data; pass jax Arrays through untouched."""
    if a is None or isinstance(a, np.ndarray):
        return a
    try:
        import jax
        if isinstance(a, jax.Array):
            return a
    except ImportError:      # pragma: no cover — jax is a hard dep anyway
        pass
    return np.asarray(a)


class DataSet:
    def __init__(self, features, labels, features_mask=None, labels_mask=None):
        self.features = _as_host_or_device(features)
        self.labels = _as_host_or_device(labels)
        self.features_mask = _as_host_or_device(features_mask)
        self.labels_mask = _as_host_or_device(labels_mask)

    # reference getters
    def get_features(self):
        return self.features

    def get_labels(self):
        return self.labels

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def __len__(self):
        return self.num_examples()

    def shuffle(self, seed: Optional[int] = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        return self._take(idx)

    def _take(self, idx) -> "DataSet":
        return DataSet(
            self.features[idx], self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx])

    def split_test_and_train(self, n_train: int):
        """Reference splitTestAndTrain → (train, test)."""
        return self._take(np.arange(0, n_train)), \
            self._take(np.arange(n_train, self.num_examples()))

    def sample(self, n: int, seed: Optional[int] = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        return self._take(rng.choice(self.num_examples(), size=n, replace=False))

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        for i in range(0, self.num_examples(), batch_size):
            out.append(self._take(np.arange(i, min(i + batch_size, self.num_examples()))))
        return out

    def merge(others: Sequence["DataSet"]) -> "DataSet":  # noqa: N805 — static-style
        ds = list(others)
        return DataSet(
            np.concatenate([d.features for d in ds]),
            np.concatenate([d.labels for d in ds]),
            None if ds[0].features_mask is None else np.concatenate([d.features_mask for d in ds]),
            None if ds[0].labels_mask is None else np.concatenate([d.labels_mask for d in ds]))

    def save(self, path):
        parts = {"features": self.features, "labels": self.labels}
        if self.features_mask is not None:
            parts["features_mask"] = self.features_mask
        if self.labels_mask is not None:
            parts["labels_mask"] = self.labels_mask
        np.savez_compressed(path, **parts)

    @staticmethod
    def load(path) -> "DataSet":
        with np.load(path) as z:
            return DataSet(z["features"], z["labels"],
                           z["features_mask"] if "features_mask" in z else None,
                           z["labels_mask"] if "labels_mask" in z else None)

    def __repr__(self):
        return (f"DataSet(features{self.features.shape}, labels{self.labels.shape}, "
                f"fmask={None if self.features_mask is None else self.features_mask.shape}, "
                f"lmask={None if self.labels_mask is None else self.labels_mask.shape})")


class MultiDataSet:
    """N features arrays, M labels arrays (reference MultiDataSet)."""

    def __init__(self, features, labels, features_masks=None, labels_masks=None):
        self.features = [_as_host_or_device(f) for f in _as_list(features)]
        self.labels = [_as_host_or_device(l) for l in _as_list(labels)]
        self.features_masks = (None if features_masks is None
                               else [_as_host_or_device(m)
                                     for m in _as_list(features_masks)])
        self.labels_masks = (None if labels_masks is None
                             else [_as_host_or_device(m)
                                   for m in _as_list(labels_masks)])

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])

    def __len__(self):
        return self.num_examples()


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
