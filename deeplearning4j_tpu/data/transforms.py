"""DataVec transform catalog: conditions, reducers, joins, sequences, analysis.

Reference parity: ``org.datavec.api.transform`` —
`condition.column.*` + `condition.BooleanCondition` (Condition),
`reduce.Reducer` (group-by + per-column aggregations),
`join.Join` (Inner/LeftOuter/RightOuter/FullOuter on key columns),
`sequence.ConvertToSequence` + sequence transforms
(SequenceDifferenceTransform, SequenceMovingWindowReduceTransform,
SequenceOffsetTransform), and `AnalyzeLocal` / `DataQualityAnalysis`.

Host-side by design — ETL shapes the records that feed the device; the
numeric heavy lifting happens later on the TPU. Everything operates on the
same (records: list[list], Schema) pair as `datavec.TransformProcess`.
"""

from __future__ import annotations

import math
import re
import statistics
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .datavec import Column, Schema


# ------------------------------------------------------------------ conditions
class Condition:
    """Boolean predicate over a row dict, with &, |, ~ combinators.

    Reference: org.datavec.api.transform.condition.Condition +
    BooleanCondition.AND/OR/NOT.
    """

    def __init__(self, fn: Callable[[Dict[str, Any]], bool], desc: str = ""):
        self._fn = fn
        self.desc = desc

    def __call__(self, row: Dict[str, Any]) -> bool:
        return bool(self._fn(row))

    def __and__(self, other: "Condition") -> "Condition":
        return Condition(lambda r: self(r) and other(r),
                         f"({self.desc} AND {other.desc})")

    def __or__(self, other: "Condition") -> "Condition":
        return Condition(lambda r: self(r) or other(r),
                         f"({self.desc} OR {other.desc})")

    def __invert__(self) -> "Condition":
        return Condition(lambda r: not self(r), f"(NOT {self.desc})")


_COND_OPS = {
    "eq": lambda v, t: v == t,
    "neq": lambda v, t: v != t,
    "lt": lambda v, t: v < t,
    "lte": lambda v, t: v <= t,
    "gt": lambda v, t: v > t,
    "gte": lambda v, t: v >= t,
    "in": lambda v, t: v in t,
    "not_in": lambda v, t: v not in t,
}


def column_condition(name: str, op: str, value: Any = None) -> Condition:
    """DoubleColumnCondition / CategoricalColumnCondition / ... in one factory.

    op: eq|neq|lt|lte|gt|gte|in|not_in|is_null|regex
    """
    if op == "is_null":
        return Condition(lambda r: r[name] is None or r[name] == "",
                         f"{name} is null")
    if op == "regex":
        pat = re.compile(value)
        return Condition(lambda r: pat.search(str(r[name])) is not None,
                         f"{name} ~ /{value}/")
    if op not in _COND_OPS:
        raise ValueError(f"unknown condition op '{op}' "
                         f"(choose from {sorted(_COND_OPS)} | is_null | regex)")
    fn = _COND_OPS[op]
    return Condition(lambda r: fn(r[name], value), f"{name} {op} {value!r}")


def invalid_value_condition(name: str) -> Condition:
    """True when the column value is not parseable as a number
    (FilterInvalidValues analogue for numeric columns)."""

    def bad(r):
        v = r[name]
        try:
            return math.isnan(float(v))
        except (TypeError, ValueError):
            return True

    return Condition(bad, f"{name} invalid")


# -------------------------------------------------------------------- reducer
_AGG_FNS = {
    "sum": lambda vs: float(np.sum(vs)),
    "mean": lambda vs: float(np.mean(vs)),
    "min": lambda vs: float(np.min(vs)),
    "max": lambda vs: float(np.max(vs)),
    "stdev": lambda vs: float(statistics.stdev(vs)) if len(vs) > 1 else 0.0,
    "count": lambda vs: len(vs),
    "count_unique": lambda vs: len(set(vs)),
    "range": lambda vs: float(np.max(vs) - np.min(vs)),
    "first": lambda vs: vs[0],
    "last": lambda vs: vs[-1],
}
_NUMERIC_AGGS = {"sum", "mean", "min", "max", "stdev", "range"}


class Reducer:
    """Group rows by key column(s), aggregate the rest.

    Reference: org.datavec.api.transform.reduce.Reducer (Builder pattern:
    keyColumns + per-column ReduceOp).
    """

    def __init__(self, keys: Sequence[str], ops: Dict[str, str],
                 default_op: Optional[str] = None):
        self.keys = list(keys)
        self.ops = dict(ops)
        self.default_op = default_op

    class Builder:
        def __init__(self, *keys: str):
            self._keys = list(keys)
            self._ops: Dict[str, str] = {}
            self._default: Optional[str] = None

        def _add(self, op, names):
            if op not in _AGG_FNS:
                raise ValueError(f"unknown reduce op '{op}'")
            for n in names:
                self._ops[n] = op
            return self

        def sum_columns(self, *names):
            return self._add("sum", names)

        def mean_columns(self, *names):
            return self._add("mean", names)

        def min_columns(self, *names):
            return self._add("min", names)

        def max_columns(self, *names):
            return self._add("max", names)

        def stdev_columns(self, *names):
            return self._add("stdev", names)

        def count_columns(self, *names):
            return self._add("count", names)

        def count_unique_columns(self, *names):
            return self._add("count_unique", names)

        def range_columns(self, *names):
            return self._add("range", names)

        def first_columns(self, *names):
            return self._add("first", names)

        def last_columns(self, *names):
            return self._add("last", names)

        def default_op(self, op: str):
            if op not in _AGG_FNS:
                raise ValueError(f"unknown reduce op '{op}'")
            self._default = op
            return self

        def build(self) -> "Reducer":
            return Reducer(self._keys, self._ops, self._default)

    @staticmethod
    def builder(*keys: str) -> "Reducer.Builder":
        return Reducer.Builder(*keys)

    def reduce(self, records: Iterable[Sequence[Any]],
               schema: Schema) -> Tuple[List[List[Any]], Schema]:
        names = schema.names()
        key_idx = [schema.index_of(k) for k in self.keys]
        groups: Dict[tuple, List[List[Any]]] = {}
        order: List[tuple] = []
        for r in records:
            k = tuple(r[i] for i in key_idx)
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append(list(r))

        out_cols: List[Column] = [schema.column(k) for k in self.keys]
        agg_plan: List[Tuple[int, str, str]] = []   # (col idx, op, out name)
        for i, n in enumerate(names):
            if n in self.keys:
                continue
            op = self.ops.get(n, self.default_op)
            if op is None:
                continue
            out_name = f"{op}({n})"
            kind = schema.columns[i].kind
            if op in ("count", "count_unique"):
                kind = "integer"
            elif op in _NUMERIC_AGGS:
                kind = "numeric"
            agg_plan.append((i, op, out_name))
            out_cols.append(Column(out_name, kind))

        out_records = []
        for k in order:
            rows = groups[k]
            rec = list(k)
            for i, op, _ in agg_plan:
                vals = [row[i] for row in rows]
                rec.append(_AGG_FNS[op](vals))
            out_records.append(rec)
        return out_records, Schema(out_cols)


# ----------------------------------------------------------------------- join
class Join:
    """Relational join of two record sets on key columns.

    Reference: org.datavec.api.transform.join.Join (Inner, LeftOuter,
    RightOuter, FullOuter). Missing values fill with None.
    """

    TYPES = ("Inner", "LeftOuter", "RightOuter", "FullOuter")

    def __init__(self, join_type: str, keys: Sequence[str],
                 left_schema: Schema, right_schema: Schema):
        if join_type not in self.TYPES:
            raise ValueError(f"join_type must be one of {self.TYPES}")
        self.join_type = join_type
        self.keys = list(keys)
        self.left_schema = left_schema
        self.right_schema = right_schema

    def out_schema(self) -> Schema:
        cols = [Column(c.name, c.kind, c.categories)
                for c in self.left_schema.columns]
        for c in self.right_schema.columns:
            if c.name not in self.keys:
                cols.append(Column(c.name, c.kind, c.categories))
        return Schema(cols)

    def execute(self, left: Iterable[Sequence[Any]],
                right: Iterable[Sequence[Any]]) -> List[List[Any]]:
        lkeys = [self.left_schema.index_of(k) for k in self.keys]
        rkeys = [self.right_schema.index_of(k) for k in self.keys]
        r_nonkey = [i for i in range(len(self.right_schema.columns))
                    if i not in rkeys]
        l_width = len(self.left_schema.columns)

        rindex: Dict[tuple, List[List[Any]]] = {}
        right_rows = [list(r) for r in right]
        for r in right_rows:
            rindex.setdefault(tuple(r[i] for i in rkeys), []).append(r)

        out: List[List[Any]] = []
        matched_right = set()
        for l in left:
            l = list(l)
            k = tuple(l[i] for i in lkeys)
            matches = rindex.get(k)
            if matches:
                matched_right.add(k)
                for r in matches:
                    out.append(l + [r[i] for i in r_nonkey])
            elif self.join_type in ("LeftOuter", "FullOuter"):
                out.append(l + [None] * len(r_nonkey))
        if self.join_type in ("RightOuter", "FullOuter"):
            # right-only rows: key values land in the key columns' positions
            for k, rows in rindex.items():
                if k in matched_right:
                    continue
                for r in rows:
                    rec = [None] * l_width
                    for kn, kv in zip(self.keys, k):
                        rec[self.left_schema.index_of(kn)] = kv
                    out.append(rec + [r[i] for i in r_nonkey])
        return out


# ------------------------------------------------------------------ sequences
class ConvertToSequence:
    """Group flat records into sequences by key, sorted within each group.

    Reference: TransformProcess.convertToSequence(keyColumn, comparator).
    Returns (list_of_sequences, per-sequence key values).
    """

    def __init__(self, schema: Schema, key: str, sort_by: Optional[str] = None):
        self.schema = schema
        self.key = key
        self.sort_by = sort_by

    def execute(self, records: Iterable[Sequence[Any]]):
        ki = self.schema.index_of(self.key)
        si = None if self.sort_by is None else self.schema.index_of(self.sort_by)
        groups: Dict[Any, List[List[Any]]] = {}
        order = []
        for r in records:
            r = list(r)
            if r[ki] not in groups:
                groups[r[ki]] = []
                order.append(r[ki])
            groups[r[ki]].append(r)
        seqs = []
        for k in order:
            rows = groups[k]
            if si is not None:
                rows = sorted(rows, key=lambda r: r[si])
            seqs.append(rows)
        return seqs, order


def sequence_difference(seqs: List[List[List[Any]]], schema: Schema,
                        name: str, lookback: int = 1):
    """x[t] -= x[t-lookback]; first `lookback` steps become 0
    (SequenceDifferenceTransform)."""
    i = schema.index_of(name)
    out = []
    for seq in seqs:
        new = [list(r) for r in seq]
        for t in range(len(new) - 1, -1, -1):
            new[t][i] = (new[t][i] - new[t - lookback][i]
                         if t >= lookback else 0)
        out.append(new)
    return out


def sequence_offset(seqs: List[List[List[Any]]], schema: Schema, name: str,
                    offset: int, *, edge: str = "trim"):
    """Shift one column by `offset` steps within each sequence
    (SequenceOffsetTransform). edge='trim' drops rows without a shifted
    value; edge='pad' keeps length and fills with None."""
    i = schema.index_of(name)
    out = []
    for seq in seqs:
        n = len(seq)
        new = []
        for t in range(n):
            src = t - offset
            row = list(seq[t])
            if 0 <= src < n:
                row[i] = seq[src][i]
                new.append(row)
            elif edge == "pad":
                row[i] = None
                new.append(row)
        out.append(new)
    return out


def sequence_moving_window_reduce(seqs: List[List[List[Any]]], schema: Schema,
                                  name: str, window: int, op: str = "mean"):
    """Append `<op>(<name>,w)` column: aggregate over the trailing window
    (SequenceMovingWindowReduceTransform). Returns (seqs, new_schema)."""
    if op not in _AGG_FNS:
        raise ValueError(f"unknown reduce op '{op}'")
    i = schema.index_of(name)
    fn = _AGG_FNS[op]
    out = []
    for seq in seqs:
        new = []
        for t, r in enumerate(seq):
            vals = [seq[s][i] for s in range(max(0, t - window + 1), t + 1)]
            new.append(list(r) + [fn(vals)])
        out.append(new)
    new_schema = Schema([Column(c.name, c.kind, c.categories)
                         for c in schema.columns]
                        + [Column(f"{op}({name},{window})", "numeric")])
    return out, new_schema


def sequence_trim(seqs, n: int, from_front: bool = True):
    """Drop n steps from the front (or back) of every sequence
    (SequenceTrimTransform)."""
    return [s[n:] if from_front else s[:len(s) - n] for s in seqs]


def split_sequences_by_length(seqs, max_length: int):
    """Split long sequences into chunks of at most max_length
    (SequenceSplit / SplitMaxLengthSequence)."""
    out = []
    for s in seqs:
        for i in range(0, len(s), max_length):
            out.append(s[i:i + max_length])
    return out


# ------------------------------------------------------------------- analysis
class ColumnAnalysis:
    def __init__(self, name: str, kind: str, stats: Dict[str, Any]):
        self.name, self.kind, self.stats = name, kind, stats

    def __repr__(self):
        inner = ", ".join(f"{k}={v}" for k, v in self.stats.items())
        return f"ColumnAnalysis({self.name}: {inner})"


class DataAnalysis:
    """Per-column statistics over a record set (AnalyzeLocal.analyze)."""

    def __init__(self, schema: Schema,
                 columns: Dict[str, ColumnAnalysis], n_rows: int):
        self.schema = schema
        self.columns = columns
        self.n_rows = n_rows

    def column_analysis(self, name: str) -> ColumnAnalysis:
        return self.columns[name]

    def stats(self) -> str:
        lines = [f"rows: {self.n_rows}"]
        for c in self.schema.names():
            lines.append(repr(self.columns[c]))
        return "\n".join(lines)


def analyze(schema: Schema, records: Iterable[Sequence[Any]]) -> DataAnalysis:
    rows = [list(r) for r in records]
    cols: Dict[str, ColumnAnalysis] = {}
    for i, c in enumerate(schema.columns):
        vals = [r[i] for r in rows]
        if c.kind in ("numeric", "integer"):
            nums = [v for v in vals if isinstance(v, (int, float))
                    and not (isinstance(v, float) and math.isnan(v))]
            if nums:
                arr = np.asarray(nums, np.float64)
                st = {"count": len(nums), "min": float(arr.min()),
                      "max": float(arr.max()), "mean": float(arr.mean()),
                      "stdev": float(arr.std(ddof=1)) if len(nums) > 1 else 0.0,
                      "n_missing": len(vals) - len(nums)}
                if c.kind == "integer":
                    st["n_unique"] = len(set(nums))
            else:
                st = {"count": 0, "n_missing": len(vals)}
        elif c.kind == "categorical":
            counts: Dict[Any, int] = {}
            for v in vals:
                counts[v] = counts.get(v, 0) + 1
            st = {"count": len(vals), "counts": counts,
                  "n_unique": len(counts)}
        else:   # string
            lens = [len(str(v)) for v in vals]
            st = {"count": len(vals),
                  "min_length": min(lens) if lens else 0,
                  "max_length": max(lens) if lens else 0,
                  "mean_length": (sum(lens) / len(lens)) if lens else 0.0}
        cols[c.name] = ColumnAnalysis(c.name, c.kind, st)
    return DataAnalysis(schema, cols, len(rows))


class DataQualityAnalysis:
    """Missing/invalid counts per column (DataQualityAnalysis)."""

    def __init__(self, schema: Schema, quality: Dict[str, Dict[str, int]]):
        self.schema = schema
        self.quality = quality

    def column_quality(self, name: str) -> Dict[str, int]:
        return self.quality[name]


def analyze_quality(schema: Schema,
                    records: Iterable[Sequence[Any]]) -> DataQualityAnalysis:
    rows = [list(r) for r in records]
    q: Dict[str, Dict[str, int]] = {}
    for i, c in enumerate(schema.columns):
        missing = invalid = 0
        for r in rows:
            v = r[i]
            if v is None or v == "":
                missing += 1
                continue
            if c.kind in ("numeric", "integer"):
                try:
                    f = float(v)
                    if math.isnan(f):
                        missing += 1
                    elif c.kind == "integer" and int(f) != f:
                        invalid += 1
                except (TypeError, ValueError):
                    invalid += 1
            elif c.kind == "categorical":
                if c.categories is not None and v not in c.categories:
                    invalid += 1
        q[c.name] = {"missing": missing, "invalid": invalid,
                     "total": len(rows)}
    return DataQualityAnalysis(schema, q)
