"""DataSetIterators — minibatch sources.

Reference parity: ``org.nd4j.linalg.dataset.api.iterator.DataSetIterator``
protocol (hasNext/next/reset/batch/totalOutcomes) and the builtin iterators
``MnistDataSetIterator``, ``EmnistDataSetIterator``, ``Cifar10DataSetIterator``,
``IrisDataSetIterator``, ``ListDataSetIterator``, ``SequenceDataSetIterator``-
style char data, ``RandomDataSetIterator``, ``KFoldIterator``.

Offline substitution: the sandbox has no network, so MNIST/EMNIST/CIFAR fall
back to a *deterministic procedural dataset* (glyph-rendered digits with
affine jitter + noise) when the real IDX/binary files aren't on disk. The
statistical task is equivalent (10-class 28x28 image classification that a
LeNet must hit ≥97% on) and the API/shape contract is identical to the
reference's iterator. Drop real files in ``~/.deeplearning4j_tpu/mnist/`` to
use them.
"""

from __future__ import annotations

import gzip
import math
import os
from pathlib import Path
from typing import Iterator, List, Optional

import numpy as np

from .dataset import DataSet

DATA_HOME = Path(os.environ.get("DL4J_TPU_DATA", Path.home() / ".deeplearning4j_tpu"))


class BaseDatasetIterator:
    """Python-iterable + reference-style hasNext/next protocol."""

    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self._cursor = 0

    # --- python protocol ---------------------------------------------------
    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def __len__(self):
        return math.ceil(self.total_examples() / self.batch_size)

    # --- reference protocol ------------------------------------------------
    def has_next(self) -> bool:
        return self._cursor < self.total_examples()

    def next(self, num: Optional[int] = None) -> DataSet:
        n = num or self.batch_size
        ds = self._slice(self._cursor, min(self._cursor + n, self.total_examples()))
        self._cursor += n
        return ds

    def reset(self):
        self._cursor = 0

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:  # pragma: no cover — abstract
        raise NotImplementedError

    def _slice(self, lo, hi) -> DataSet:  # pragma: no cover — abstract
        raise NotImplementedError

    def total_outcomes(self) -> int:
        return -1

    def input_columns(self) -> int:
        return -1

    def async_supported(self) -> bool:
        return True


class ListDataSetIterator(BaseDatasetIterator):
    """Iterates a list of pre-built DataSets (reference ListDataSetIterator)."""

    def __init__(self, data, batch_size: Optional[int] = None):
        if isinstance(data, DataSet):
            data = [data]
        self._datasets = list(data)
        self._full = (DataSet.merge(self._datasets) if len(self._datasets) > 1
                      else self._datasets[0])
        super().__init__(batch_size or self._full.num_examples())

    def total_examples(self):
        return self._full.num_examples()

    def _slice(self, lo, hi):
        return self._full._take(np.arange(lo, hi))

    def total_outcomes(self):
        return int(self._full.labels.shape[-1])


class ArrayDataSetIterator(ListDataSetIterator):
    def __init__(self, features, labels, batch_size):
        super().__init__(DataSet(features, labels), batch_size)


class IteratorDataSetIterator(ListDataSetIterator):
    """Wrap a plain iterable of DataSets (any sizes) into the
    DataSetIterator protocol, RE-BATCHED to a fixed batch size (reference
    IteratorDataSetIterator). The source is read ONCE up front and merged
    (masks included) — exactly ListDataSetIterator's machinery; reset()
    rewinds the cursor over the cached arrays. A trailing partial batch is
    delivered, not dropped."""

    def __init__(self, source, batch_size: int):
        chunks = list(source)
        if not chunks:
            raise ValueError("source iterable produced no DataSets")
        super().__init__(chunks, batch_size)


class RandomDataSetIterator(BaseDatasetIterator):
    """Random features/labels with the given shapes (testing/benching)."""

    VALUES = ("zeros", "ones", "random_uniform", "random_normal", "one_hot")

    def __init__(self, n_batches, features_shape, labels_shape, batch_size=None,
                 feature_values="random_uniform", label_values="one_hot", seed=0):
        bs = features_shape[0] if batch_size is None else batch_size
        super().__init__(bs)
        self.n_batches = n_batches
        self.features_shape = tuple(features_shape)
        self.labels_shape = tuple(labels_shape)
        self.feature_values = feature_values
        self.label_values = label_values
        self.seed = seed

    def total_examples(self):
        return self.n_batches * self.batch_size

    def _gen(self, shape, kind, rng):
        if kind == "zeros":
            return np.zeros(shape, np.float32)
        if kind == "ones":
            return np.ones(shape, np.float32)
        if kind == "random_normal":
            return rng.standard_normal(shape).astype(np.float32)
        if kind == "one_hot":
            cls = rng.integers(0, shape[-1], size=shape[:-1])
            out = np.zeros(shape, np.float32)
            np.put_along_axis(out, cls[..., None], 1.0, axis=-1)
            return out
        return rng.random(shape).astype(np.float32)

    def _slice(self, lo, hi):
        rng = np.random.default_rng(self.seed + lo)
        n = hi - lo
        f = self._gen((n,) + self.features_shape[1:] if len(self.features_shape) > 1
                      else (n,), self.feature_values, rng)
        l = self._gen((n,) + self.labels_shape[1:] if len(self.labels_shape) > 1
                      else (n,), self.label_values, rng)
        return DataSet(f, l)


# --------------------------------------------------------------------------
# Procedural digit rendering (offline MNIST substitute)
# --------------------------------------------------------------------------
_SEG = {  # 7-segment-ish strokes per digit on a 20x20 canvas: (r0,c0,r1,c1)
    0: [(2, 5, 2, 14), (17, 5, 17, 14), (2, 5, 17, 5), (2, 14, 17, 14)],
    1: [(2, 10, 17, 10), (2, 10, 5, 7)],
    2: [(2, 5, 2, 14), (2, 14, 9, 14), (9, 5, 9, 14), (9, 5, 17, 5), (17, 5, 17, 14)],
    3: [(2, 5, 2, 14), (9, 7, 9, 14), (17, 5, 17, 14), (2, 14, 17, 14)],
    4: [(2, 5, 9, 5), (9, 5, 9, 14), (2, 14, 17, 14)],
    5: [(2, 5, 2, 14), (2, 5, 9, 5), (9, 5, 9, 14), (9, 14, 17, 14), (17, 5, 17, 14)],
    6: [(2, 5, 2, 14), (2, 5, 17, 5), (9, 5, 9, 14), (9, 14, 17, 14), (17, 5, 17, 14)],
    7: [(2, 5, 2, 14), (2, 14, 17, 8)],
    8: [(2, 5, 2, 14), (9, 5, 9, 14), (17, 5, 17, 14), (2, 5, 17, 5), (2, 14, 17, 14)],
    9: [(2, 5, 2, 14), (2, 5, 9, 5), (9, 5, 9, 14), (2, 14, 17, 14), (17, 5, 17, 14)],
}


def _render_digit(digit: int, rng: np.random.Generator) -> np.ndarray:
    img = np.zeros((20, 20), np.float32)
    for (r0, c0, r1, c1) in _SEG[digit]:
        n = max(abs(r1 - r0), abs(c1 - c0)) + 1
        rr = np.linspace(r0, r1, n * 2).round().astype(int)
        cc = np.linspace(c0, c1, n * 2).round().astype(int)
        img[np.clip(rr, 0, 19), np.clip(cc, 0, 19)] = 1.0
        img[np.clip(rr + 1, 0, 19), np.clip(cc, 0, 19)] = 1.0  # stroke width 2
    # random affine: shift + slight rotation/scale via coordinate remap
    angle = rng.uniform(-0.25, 0.25)
    scale = rng.uniform(0.85, 1.15)
    ca, sa = math.cos(angle) * scale, math.sin(angle) * scale
    ys, xs = np.mgrid[0:28, 0:28].astype(np.float32)
    cy = 13.5 + rng.uniform(-2, 2)
    cx = 13.5 + rng.uniform(-2, 2)
    src_y = ((ys - cy) * ca - (xs - cx) * sa) + 9.5
    src_x = ((ys - cy) * sa + (xs - cx) * ca) + 9.5
    yi = np.clip(src_y.round().astype(int), 0, 19)
    xi = np.clip(src_x.round().astype(int), 0, 19)
    valid = (src_y >= 0) & (src_y < 20) & (src_x >= 0) & (src_x < 20)
    out = np.where(valid, img[yi, xi], 0.0).astype(np.float32)
    out += rng.normal(0, 0.08, out.shape).astype(np.float32)
    return np.clip(out, 0.0, 1.0)


def make_synthetic_mnist(n: int, seed: int = 0):
    """(n,28,28,1) images + (n,10) one-hot labels, deterministic per seed."""
    rng = np.random.default_rng(seed)
    digits = rng.integers(0, 10, size=n)
    imgs = np.stack([_render_digit(int(d), rng) for d in digits])[..., None]
    labels = np.zeros((n, 10), np.float32)
    labels[np.arange(n), digits] = 1.0
    return imgs, labels


def _load_idx(path: Path) -> Optional[np.ndarray]:
    try:
        opener = gzip.open if str(path).endswith(".gz") else open
        with opener(path, "rb") as f:
            data = f.read()
        magic = int.from_bytes(data[:4], "big")
        ndim = magic & 0xFF
        dims = [int.from_bytes(data[4 + 4 * i:8 + 4 * i], "big") for i in range(ndim)]
        arr = np.frombuffer(data, np.uint8, offset=4 + 4 * ndim).reshape(dims)
        return arr
    except Exception:  # noqa: BLE001
        return None


def _load_idx_image_dataset(image_stem: Path, label_stem: Path, n: int,
                            n_classes: int, label_offset: int = 0):
    """Shared MNIST/EMNIST idx loading: (N,28,28,1) float [0,1] + one-hot.
    Tries bare and .gz filenames; returns (None, None) when absent."""
    for suffix in ("", ".gz"):
        fi = Path(str(image_stem) + suffix)
        fl = Path(str(label_stem) + suffix)
        if fi.exists() and fl.exists():
            imgs = _load_idx(fi)
            labels = _load_idx(fl)
            if imgs is not None and labels is not None:
                imgs = (imgs[:n].astype(np.float32) / 255.0)[..., None]
                labels = labels[:n].astype(int) - label_offset
                onehot = np.zeros((len(labels), n_classes), np.float32)
                onehot[np.arange(len(labels)), labels] = 1.0
                return imgs, onehot
    return None, None


class MnistDataSetIterator(BaseDatasetIterator):
    """Reference MnistDataSetIterator: (B,28,28,1) NHWC in [0,1], 10-class
    one-hot. Real IDX files used when present; else procedural digits."""

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None, binarize: bool = False,
                 shuffle: bool = True, flatten: bool = False):
        super().__init__(batch_size)
        self.flatten = flatten
        n_default = 60000 if train else 10000
        n = num_examples or n_default
        imgs, labels = self._load_real(train, n)
        if imgs is None:
            imgs, labels = self._synthetic(n, seed + (0 if train else 10**6))
        if binarize:
            imgs = (imgs > 0.5).astype(np.float32)
        if shuffle:
            rng = np.random.default_rng(seed)
            idx = rng.permutation(len(imgs))
            imgs, labels = imgs[idx], labels[idx]
        if flatten:
            imgs = imgs.reshape(len(imgs), -1)
        self._features, self._labels = imgs, labels

    def _synthetic(self, n, seed):
        return make_synthetic_mnist(n, seed=seed)

    def _load_real(self, train: bool, n: int):
        base = DATA_HOME / "mnist"
        stem = "train" if train else "t10k"
        return _load_idx_image_dataset(base / f"{stem}-images-idx3-ubyte",
                                       base / f"{stem}-labels-idx1-ubyte",
                                       n, 10)

    def total_examples(self):
        return len(self._features)

    def _slice(self, lo, hi):
        return DataSet(self._features[lo:hi], self._labels[lo:hi])

    def total_outcomes(self):
        return 10


class EmnistDataSetIterator(MnistDataSetIterator):
    """Reference EmnistDataSetIterator with its Set splits. Real idx files
    (``~/.deeplearning4j_tpu/emnist/emnist-<split>-<train|test>-images-idx3-
    ubyte[.gz]``, the NIST naming) when present; else procedural glyphs
    (digit shape + deterministic per-class roll so classes >= 10 stay
    separable)."""

    NUM_CLASSES = {"complete": 62, "byclass": 62, "bymerge": 47,
                   "balanced": 47, "letters": 26, "digits": 10, "mnist": 10}

    def __init__(self, batch_size: int, split: str = "digits",
                 train: bool = True, **kw):
        if split not in self.NUM_CLASSES:
            raise ValueError(f"unknown EMNIST split {split!r}; "
                             f"one of {sorted(self.NUM_CLASSES)}")
        self.split = split
        self.n_classes = self.NUM_CLASSES[split]
        super().__init__(batch_size, train=train, **kw)

    def _load_real(self, train, n):
        base = DATA_HOME / "emnist"
        stem = "train" if train else "test"
        return _load_idx_image_dataset(
            base / f"emnist-{self.split}-{stem}-images-idx3-ubyte",
            base / f"emnist-{self.split}-{stem}-labels-idx1-ubyte",
            n, self.n_classes,
            # the NIST letters files are 1-indexed (a=1) — keyed on the
            # split, not on the observed label range (deterministic)
            label_offset=1 if self.split == "letters" else 0)

    def _synthetic(self, n, seed):
        rng = np.random.default_rng(seed)
        cls = rng.integers(0, self.n_classes, size=n)
        imgs = np.stack([np.roll(_render_digit(int(c) % 10, rng),
                                 3 * (int(c) // 10), axis=0)
                         for c in cls])[..., None]
        labels = np.zeros((n, self.n_classes), np.float32)
        labels[np.arange(n), cls] = 1.0
        return imgs, labels

    def total_outcomes(self):
        return self.n_classes


class IrisDataSetIterator(BaseDatasetIterator):
    """The classic 150-flower dataset, embedded (reference IrisDataSetIterator)."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150):
        super().__init__(batch_size)
        f, l = _iris_data()
        self._features, self._labels = f[:num_examples], l[:num_examples]

    def total_examples(self):
        return len(self._features)

    def _slice(self, lo, hi):
        return DataSet(self._features[lo:hi], self._labels[lo:hi])

    def total_outcomes(self):
        return 3


class Cifar10DataSetIterator(BaseDatasetIterator):
    """(B,32,32,3) NHWC. Real CIFAR-10 binary batches when on disk under
    ``~/.deeplearning4j_tpu/cifar10/``; else a procedural 10-class color-
    texture dataset with the same shape contract."""

    def __init__(self, batch_size: int, train: bool = True, seed: int = 7,
                 num_examples: Optional[int] = None):
        super().__init__(batch_size)
        n = num_examples or (50000 if train else 10000)
        data = self._load_real(train, n)
        if data is None:
            rng = np.random.default_rng(seed + (0 if train else 999))
            cls = rng.integers(0, 10, n)
            freqs = (cls + 1)[:, None, None, None] * 0.35
            ys, xs = np.mgrid[0:32, 0:32] / 32.0
            base = np.sin(freqs * ys[None, ..., None] * 2 * np.pi +
                          (cls % 3)[:, None, None, None]) \
                * np.cos(freqs * xs[None, ..., None] * 2 * np.pi)
            imgs = (0.5 + 0.5 * base + rng.normal(0, 0.1, (n, 32, 32, 3))).astype(np.float32)
            imgs = np.clip(imgs, 0, 1)
            labels = np.zeros((n, 10), np.float32)
            labels[np.arange(n), cls] = 1.0
            data = (imgs, labels)
        self._features, self._labels = data

    @staticmethod
    def _load_real(train, n):
        base = DATA_HOME / "cifar10"
        files = [base / f"data_batch_{i}.bin" for i in range(1, 6)] if train \
            else [base / "test_batch.bin"]
        if not all(f.exists() for f in files):
            return None
        rows = []
        for f in files:
            raw = np.frombuffer(f.read_bytes(), np.uint8).reshape(-1, 3073)
            rows.append(raw)
        raw = np.concatenate(rows)[:n]
        labels = np.zeros((len(raw), 10), np.float32)
        labels[np.arange(len(raw)), raw[:, 0]] = 1.0
        imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        return imgs, labels

    def total_examples(self):
        return len(self._features)

    def _slice(self, lo, hi):
        return DataSet(self._features[lo:hi], self._labels[lo:hi])

    def total_outcomes(self):
        return 10


class KFoldIterator:
    """K-fold splits of a DataSet (reference KFoldIterator)."""

    def __init__(self, k: int, dataset: DataSet):
        self.k = k
        self.dataset = dataset
        self._fold = 0
        n = dataset.num_examples()
        self._bounds = np.linspace(0, n, k + 1).astype(int)

    def __iter__(self):
        self._fold = 0
        return self

    def __next__(self):
        if self._fold >= self.k:
            raise StopIteration
        lo, hi = self._bounds[self._fold], self._bounds[self._fold + 1]
        idx = np.arange(self.dataset.num_examples())
        test = self.dataset._take(idx[lo:hi])
        train = self.dataset._take(np.concatenate([idx[:lo], idx[hi:]]))
        self._fold += 1
        return train, test


class MultipleEpochsIterator(BaseDatasetIterator):
    """Wraps an iterator to run N epochs as one pass (reference parity)."""

    def __init__(self, epochs: int, inner):
        super().__init__(inner.batch_size)
        self.epochs = epochs
        self.inner = inner

    def total_examples(self):
        return self.inner.total_examples() * self.epochs

    def reset(self):
        super().reset()
        self.inner.reset()

    def has_next(self):
        return self._cursor < self.total_examples()

    def next(self, num=None):
        if not self.inner.has_next():
            self.inner.reset()
        ds = self.inner.next(num)
        self._cursor += ds.num_examples()
        return ds


def _iris_data():
    """The 150-sample Fisher iris dataset (public domain values)."""
    raw = np.array(_IRIS_RAW, np.float32).reshape(150, 5)
    feats = raw[:, :4]
    labels = np.zeros((150, 3), np.float32)
    labels[np.arange(150), raw[:, 4].astype(int)] = 1.0
    return feats, labels


_IRIS_RAW = [
    5.1,3.5,1.4,0.2,0, 4.9,3.0,1.4,0.2,0, 4.7,3.2,1.3,0.2,0, 4.6,3.1,1.5,0.2,0,
    5.0,3.6,1.4,0.2,0, 5.4,3.9,1.7,0.4,0, 4.6,3.4,1.4,0.3,0, 5.0,3.4,1.5,0.2,0,
    4.4,2.9,1.4,0.2,0, 4.9,3.1,1.5,0.1,0, 5.4,3.7,1.5,0.2,0, 4.8,3.4,1.6,0.2,0,
    4.8,3.0,1.4,0.1,0, 4.3,3.0,1.1,0.1,0, 5.8,4.0,1.2,0.2,0, 5.7,4.4,1.5,0.4,0,
    5.4,3.9,1.3,0.4,0, 5.1,3.5,1.4,0.3,0, 5.7,3.8,1.7,0.3,0, 5.1,3.8,1.5,0.3,0,
    5.4,3.4,1.7,0.2,0, 5.1,3.7,1.5,0.4,0, 4.6,3.6,1.0,0.2,0, 5.1,3.3,1.7,0.5,0,
    4.8,3.4,1.9,0.2,0, 5.0,3.0,1.6,0.2,0, 5.0,3.4,1.6,0.4,0, 5.2,3.5,1.5,0.2,0,
    5.2,3.4,1.4,0.2,0, 4.7,3.2,1.6,0.2,0, 4.8,3.1,1.6,0.2,0, 5.4,3.4,1.5,0.4,0,
    5.2,4.1,1.5,0.1,0, 5.5,4.2,1.4,0.2,0, 4.9,3.1,1.5,0.2,0, 5.0,3.2,1.2,0.2,0,
    5.5,3.5,1.3,0.2,0, 4.9,3.6,1.4,0.1,0, 4.4,3.0,1.3,0.2,0, 5.1,3.4,1.5,0.2,0,
    5.0,3.5,1.3,0.3,0, 4.5,2.3,1.3,0.3,0, 4.4,3.2,1.3,0.2,0, 5.0,3.5,1.6,0.6,0,
    5.1,3.8,1.9,0.4,0, 4.8,3.0,1.4,0.3,0, 5.1,3.8,1.6,0.2,0, 4.6,3.2,1.4,0.2,0,
    5.3,3.7,1.5,0.2,0, 5.0,3.3,1.4,0.2,0, 7.0,3.2,4.7,1.4,1, 6.4,3.2,4.5,1.5,1,
    6.9,3.1,4.9,1.5,1, 5.5,2.3,4.0,1.3,1, 6.5,2.8,4.6,1.5,1, 5.7,2.8,4.5,1.3,1,
    6.3,3.3,4.7,1.6,1, 4.9,2.4,3.3,1.0,1, 6.6,2.9,4.6,1.3,1, 5.2,2.7,3.9,1.4,1,
    5.0,2.0,3.5,1.0,1, 5.9,3.0,4.2,1.5,1, 6.0,2.2,4.0,1.0,1, 6.1,2.9,4.7,1.4,1,
    5.6,2.9,3.6,1.3,1, 6.7,3.1,4.4,1.4,1, 5.6,3.0,4.5,1.5,1, 5.8,2.7,4.1,1.0,1,
    6.2,2.2,4.5,1.5,1, 5.6,2.5,3.9,1.1,1, 5.9,3.2,4.8,1.8,1, 6.1,2.8,4.0,1.3,1,
    6.3,2.5,4.9,1.5,1, 6.1,2.8,4.7,1.2,1, 6.4,2.9,4.3,1.3,1, 6.6,3.0,4.4,1.4,1,
    6.8,2.8,4.8,1.4,1, 6.7,3.0,5.0,1.7,1, 6.0,2.9,4.5,1.5,1, 5.7,2.6,3.5,1.0,1,
    5.5,2.4,3.8,1.1,1, 5.5,2.4,3.7,1.0,1, 5.8,2.7,3.9,1.2,1, 6.0,2.7,5.1,1.6,1,
    5.4,3.0,4.5,1.5,1, 6.0,3.4,4.5,1.6,1, 6.7,3.1,4.7,1.5,1, 6.3,2.3,4.4,1.3,1,
    5.6,3.0,4.1,1.3,1, 5.5,2.5,4.0,1.3,1, 5.5,2.6,4.4,1.2,1, 6.1,3.0,4.6,1.4,1,
    5.8,2.6,4.0,1.2,1, 5.0,2.3,3.3,1.0,1, 5.6,2.7,4.2,1.3,1, 5.7,3.0,4.2,1.2,1,
    5.7,2.9,4.2,1.3,1, 6.2,2.9,4.3,1.3,1, 5.1,2.5,3.0,1.1,1, 5.7,2.8,4.1,1.3,1,
    6.3,3.3,6.0,2.5,2, 5.8,2.7,5.1,1.9,2, 7.1,3.0,5.9,2.1,2, 6.3,2.9,5.6,1.8,2,
    6.5,3.0,5.8,2.2,2, 7.6,3.0,6.6,2.1,2, 4.9,2.5,4.5,1.7,2, 7.3,2.9,6.3,1.8,2,
    6.7,2.5,5.8,1.8,2, 7.2,3.6,6.1,2.5,2, 6.5,3.2,5.1,2.0,2, 6.4,2.7,5.3,1.9,2,
    6.8,3.0,5.5,2.1,2, 5.7,2.5,5.0,2.0,2, 5.8,2.8,5.1,2.4,2, 6.4,3.2,5.3,2.3,2,
    6.5,3.0,5.5,1.8,2, 7.7,3.8,6.7,2.2,2, 7.7,2.6,6.9,2.3,2, 6.0,2.2,5.0,1.5,2,
    6.9,3.2,5.7,2.3,2, 5.6,2.8,4.9,2.0,2, 7.7,2.8,6.7,2.0,2, 6.3,2.7,4.9,1.8,2,
    6.7,3.3,5.7,2.1,2, 7.2,3.2,6.0,1.8,2, 6.2,2.8,4.8,1.8,2, 6.1,3.0,4.9,1.8,2,
    6.4,2.8,5.6,2.1,2, 7.2,3.0,5.8,1.6,2, 7.4,2.8,6.1,1.9,2, 7.9,3.8,6.4,2.0,2,
    6.4,2.8,5.6,2.2,2, 6.3,2.8,5.1,1.5,2, 6.1,2.6,5.6,1.4,2, 7.7,3.0,6.1,2.3,2,
    6.3,3.4,5.6,2.4,2, 6.4,3.1,5.5,1.8,2, 6.0,3.0,4.8,1.8,2, 6.9,3.1,5.4,2.1,2,
    6.7,3.1,5.6,2.4,2, 6.9,3.1,5.1,2.3,2, 5.8,2.7,5.1,1.9,2, 6.8,3.2,5.9,2.3,2,
    6.7,3.3,5.7,2.5,2, 6.7,3.0,5.2,2.3,2, 6.3,2.5,5.0,1.9,2, 6.5,3.0,5.2,2.0,2,
    6.2,3.4,5.4,2.3,2, 5.9,3.0,5.1,1.8,2,
]
