"""Dataset-iterator long tail: UCI synthetic control, SVHN, TinyImageNet.

Reference parity: ``org.deeplearning4j.datasets.iterator.impl
.UciSequenceDataSetIterator`` (UCI synthetic-control time series),
``SvhnDataSetIterator`` (cropped-digits .mat files),
``TinyImageNetDataSetIterator`` (200-class 64x64 image folders).

Offline-sandbox policy (same as MNIST/CIFAR): real files are used when
present under ``~/.deeplearning4j_tpu/<name>/``; otherwise a deterministic
procedural dataset with the same shape/label contract. For UCI the
"fallback" IS the real generative process — the UCI synthetic-control
corpus was itself generated from the Alcock & Manolopoulos equations
(normal / cyclic / increasing / decreasing / upward-shift /
downward-shift), which we reproduce exactly.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

import numpy as np

from .dataset import DataSet
from .iterators import ArrayDataSetIterator

_DATA_ROOT = Path(os.environ.get("DL4J_TPU_DATA",
                                 Path.home() / ".deeplearning4j_tpu"))

UCI_CLASSES = ["normal", "cyclic", "increasing", "decreasing",
               "upward_shift", "downward_shift"]


def _uci_series(cls: int, rng, t: int = 60) -> np.ndarray:
    """One synthetic-control series by the original generative equations."""
    m, s = 30.0, 2.0
    e = rng.uniform(-3, 3, t)
    base = m + s * e
    steps = np.arange(t, dtype=np.float64)
    if cls == 0:            # normal
        return base
    if cls == 1:            # cyclic
        a, T = rng.uniform(10, 15), rng.uniform(10, 15)
        return base + a * np.sin(2 * np.pi * steps / T)
    if cls == 2:            # increasing trend
        g = rng.uniform(0.2, 0.5)
        return base + g * steps
    if cls == 3:            # decreasing trend
        g = rng.uniform(0.2, 0.5)
        return base - g * steps
    x = rng.uniform(7.5, 20)            # shift magnitude
    t3 = rng.integers(t // 3, 2 * t // 3)
    k = (steps >= t3).astype(np.float64)
    return base + (x if cls == 4 else -x) * k


class UciSequenceDataSetIterator(ArrayDataSetIterator):
    """(B, T=60, 1) series with one-hot 6-class labels.

    Reference UciSequenceDataSetIterator reads the UCI download; here the
    series are regenerated from the dataset's own published equations
    (train/test use disjoint deterministic seeds), normalized to zero
    mean/unit variance like the reference's NormalizerStandardize usage.
    """

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: int = 600, seed: int = 17):
        rng = np.random.default_rng(seed + (0 if train else 1000))
        per = num_examples // len(UCI_CLASSES)
        xs, ys = [], []
        for c in range(len(UCI_CLASSES)):
            for _ in range(per):
                xs.append(_uci_series(c, rng))
                ys.append(c)
        x = np.asarray(xs, np.float32)
        x = (x - x.mean()) / x.std()
        order = rng.permutation(len(xs))
        feats = x[order][:, :, None]
        labels = np.eye(len(UCI_CLASSES), dtype=np.float32)[
            np.asarray(ys)[order]]
        super().__init__(feats, labels, batch_size)


class SvhnDataSetIterator(ArrayDataSetIterator):
    """(B, 32, 32, 3) cropped street-view digits, 10 classes.

    Real ``train_32x32.mat`` / ``test_32x32.mat`` under
    ``~/.deeplearning4j_tpu/svhn/`` when present (scipy.io loader);
    else a procedural digit-on-noise dataset with the same contract.
    """

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 23):
        data = self._load_real(train, num_examples)
        if data is None:
            n = num_examples or (4096 if train else 1024)
            data = self._synthetic(n, seed + (0 if train else 999))
        feats, labels = data
        super().__init__(feats, labels, batch_size)

    @staticmethod
    def _load_real(train, num_examples):
        path = _DATA_ROOT / "svhn" / \
            ("train_32x32.mat" if train else "test_32x32.mat")
        if not path.exists():
            return None
        from scipy.io import loadmat
        m = loadmat(str(path))
        x = m["X"].transpose(3, 0, 1, 2).astype(np.float32) / 255.0  # NHWC
        y = m["y"].ravel().astype(int) % 10          # SVHN labels 1..10
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        return x, np.eye(10, dtype=np.float32)[y]

    @staticmethod
    def _synthetic(n, seed):
        from .iterators import make_synthetic_mnist
        imgs, labels = make_synthetic_mnist(n, seed=seed)   # (n,28,28,1)
        rng = np.random.default_rng(seed)
        canvas = rng.uniform(0.2, 0.6, (n, 32, 32, 3)).astype(np.float32)
        digit = imgs.reshape(n, 28, 28, 1)
        canvas[:, 2:30, 2:30, :] = 0.3 * canvas[:, 2:30, 2:30, :] \
            + 0.7 * digit
        return canvas, labels


class TinyImageNetDataSetIterator(ArrayDataSetIterator):
    """(B, 64, 64, 3), 200 classes. Real class-subdir tree under
    ``~/.deeplearning4j_tpu/tiny-imagenet-200/<train|val>/`` via
    ImageRecordReader when present; else procedural color/texture classes
    (settable num_classes to keep the synthetic case tractable)."""

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, num_classes: int = 200,
                 seed: int = 31):
        root = _DATA_ROOT / "tiny-imagenet-200" / ("train" if train else "val")
        if root.exists():
            from .image import ImageRecordReader
            rr = ImageRecordReader(64, 64, 3).initialize(str(root))
            imgs, ys = rr.load_arrays()
            if num_classes < rr.num_labels():
                # honor the requested label width on the real path too:
                # keep only the first num_classes (alphabetical) classes
                keep = ys < num_classes
                imgs, ys = imgs[keep], ys[keep]
            width = min(num_classes, rr.num_labels())
            if num_examples:
                imgs, ys = imgs[:num_examples], ys[:num_examples]
            feats = imgs / 255.0
            labels = np.eye(width, dtype=np.float32)[ys]
        else:
            n = num_examples or 2048
            rng = np.random.default_rng(seed + (0 if train else 999))
            cls = rng.integers(0, num_classes, n)
            yy, xx = np.mgrid[0:64, 0:64] / 64.0
            freq = 1 + (cls % 8)
            phase = (cls // 8) * 0.35
            base = np.sin(freq[:, None, None] * np.pi * yy[None]
                          + phase[:, None, None]) \
                * np.cos(freq[:, None, None] * np.pi * xx[None])
            feats = np.stack([
                0.5 + 0.5 * base * np.cos(phase)[:, None, None],
                0.5 + 0.5 * base * np.sin(phase)[:, None, None],
                0.5 - 0.25 * base], -1).astype(np.float32)
            feats += rng.normal(0, 0.05, feats.shape).astype(np.float32)
            labels = np.eye(num_classes, dtype=np.float32)[cls]
        super().__init__(feats, labels, batch_size)
