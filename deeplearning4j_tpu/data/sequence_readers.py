"""Sequence record readers — parity with DataVec's
``org.datavec.api.records.reader.impl.csv.CSVSequenceRecordReader`` /
``CSVLineSequenceRecordReader`` / ``regex.RegexSequenceRecordReader`` and
the bridge ``org.deeplearning4j.datasets.datavec.
SequenceRecordReaderDataSetIterator`` (alignment modes, masking).

A sequence record is ``List[List[value]]`` — time steps of column values.
The bridge pads ragged sequences to the batch max and emits (B, T, C)
features + masks, which is exactly what the recurrent layers consume; on
TPU padded-dense + mask beats ragged host-side batching (static shapes →
one compiled program per bucket).
"""

from __future__ import annotations

import glob as _glob
import re
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from .dataset import DataSet
from .iterators import BaseDatasetIterator


class SequenceRecordReader:
    """Iterable of sequences; each sequence is a list of time-step rows."""

    def __iter__(self) -> Iterable[List[List[float]]]:  # pragma: no cover
        raise NotImplementedError

    def reset(self):
        return self


class CollectionSequenceRecordReader(SequenceRecordReader):
    """In-memory sequences (reference CollectionSequenceRecordReader)."""

    def __init__(self, sequences: Sequence[Sequence[Sequence[float]]]):
        self._seqs = [[list(step) for step in seq] for seq in sequences]

    def __iter__(self):
        return iter(self._seqs)


def _resolve_paths(source: Union[str, Sequence[str]]) -> List[Path]:
    """str = glob pattern or directory (sorted for determinism); list = as-is."""
    if isinstance(source, (list, tuple)):
        return [Path(p) for p in source]
    p = Path(source)
    if p.is_dir():
        return sorted(q for q in p.iterdir() if q.is_file())
    return [Path(q) for q in sorted(_glob.glob(str(source)))]


def _parse_value(v: str) -> float:
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"non-numeric value {v!r} in sequence file "
                         "(apply a TransformProcess for categorical data)")


class CSVSequenceRecordReader(SequenceRecordReader):
    """One CSV file per sequence, rows = time steps (reference
    CSVSequenceRecordReader(skipLines, delimiter))."""

    def __init__(self, source: Union[str, Sequence[str]], skip_lines: int = 0,
                 delimiter: str = ","):
        self.paths = _resolve_paths(source)
        if not self.paths:
            raise ValueError(f"no sequence files match {source!r}")
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for path in self.paths:
            lines = path.read_text().splitlines()[self.skip_lines:]
            seq = [[_parse_value(v) for v in ln.split(self.delimiter)]
                   for ln in lines if ln.strip()]
            if not seq:
                # dropping it would silently MISPAIR parallel feature/label
                # file sets in two-reader mode
                raise ValueError(f"empty sequence file: {path}")
            yield seq


class CSVLineSequenceRecordReader(SequenceRecordReader):
    """Each LINE of one CSV file is a whole univariate sequence: the line's
    values become T single-column time steps (reference
    CSVLineSequenceRecordReader)."""

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ","):
        self.path = Path(path)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        lines = self.path.read_text().splitlines()[self.skip_lines:]
        for ln in lines:
            if ln.strip():
                yield [[_parse_value(v)] for v in ln.split(self.delimiter)]


class RegexSequenceRecordReader(SequenceRecordReader):
    """One file per sequence; each line parsed by a regex whose capture
    groups become the step's columns (reference RegexSequenceRecordReader).
    Lines that don't match raise — silent row drops hide data bugs."""

    def __init__(self, source: Union[str, Sequence[str]], regex: str):
        self.paths = _resolve_paths(source)
        if not self.paths:
            raise ValueError(f"no sequence files match {source!r}")
        self.pattern = re.compile(regex)

    def __iter__(self):
        for path in self.paths:
            seq = []
            for i, ln in enumerate(path.read_text().splitlines()):
                if not ln.strip():
                    continue
                m = self.pattern.match(ln)
                if m is None:
                    raise ValueError(
                        f"{path}:{i + 1}: line does not match regex "
                        f"{self.pattern.pattern!r}: {ln!r}")
                seq.append([_parse_value(g) for g in m.groups()])
            if not seq:
                raise ValueError(f"empty sequence file: {path}")
            yield seq


# ------------------------------------------------ bridge → padded DataSets
ALIGN_START = "ALIGN_START"
ALIGN_END = "ALIGN_END"
EQUAL_LENGTH = "EQUAL_LENGTH"


class SequenceRecordReaderDataSetIterator(BaseDatasetIterator):
    """Reference SequenceRecordReaderDataSetIterator.

    Single-reader mode: ``label_index`` splits each step's row into
    features and a per-step label (one-hot unless ``regression``).
    Two-reader mode: separate feature/label readers, aligned per
    ``alignment_mode`` (EQUAL_LENGTH asserts equal; ALIGN_START/END pad
    the shorter stream's mask at the end/start — reference AlignmentMode).
    Ragged sequences are padded to the longest in the SOURCE (static
    shapes for jit) with 0/1 masks.
    """

    def __init__(self, reader: SequenceRecordReader, batch_size: int,
                 num_classes: Optional[int] = None, label_index: int = -1,
                 regression: bool = False,
                 labels_reader: Optional[SequenceRecordReader] = None,
                 alignment_mode: str = ALIGN_END):
        super().__init__(batch_size)
        feats, labels = [], []
        if labels_reader is None:
            for seq in reader:
                rows = np.asarray(seq, np.float32)
                li = label_index if label_index >= 0 \
                    else rows.shape[1] + label_index
                labels.append(rows[:, li])
                feats.append(np.delete(rows, li, axis=1))
        else:
            fseqs = [np.asarray(s, np.float32) for s in reader]
            lseqs = [np.asarray(s, np.float32) for s in labels_reader]
            if len(fseqs) != len(lseqs):
                raise ValueError(f"feature reader yielded {len(fseqs)} "
                                 f"sequences, label reader {len(lseqs)}")
            if alignment_mode == EQUAL_LENGTH:
                for i, (f, l) in enumerate(zip(fseqs, lseqs)):
                    if len(f) != len(l):
                        raise ValueError(
                            f"sequence {i}: feature length {len(f)} != label "
                            f"length {len(l)} under EQUAL_LENGTH")
            elif alignment_mode not in (ALIGN_START, ALIGN_END):
                raise ValueError(f"unknown alignment mode {alignment_mode!r}")
            feats, labels = fseqs, [l[:, 0] if l.ndim > 1 and l.shape[1] == 1
                                    else l for l in lseqs]

        n = len(feats)
        if n == 0:
            raise ValueError("sequence reader produced no sequences")
        T = max(max(len(f) for f in feats), max(len(l) for l in labels))
        C = feats[0].shape[1]
        self._features = np.zeros((n, T, C), np.float32)
        self._fmask = np.zeros((n, T), np.float32)
        self._lmask = np.zeros((n, T), np.float32)

        if regression:
            lab_dim = (np.asarray(labels[0]).shape[1]
                       if np.asarray(labels[0]).ndim > 1 else 1)
        else:
            if num_classes is None:
                num_classes = int(max(np.max(l) for l in labels)) + 1
            lab_dim = num_classes
        self._labels = np.zeros((n, T, lab_dim), np.float32)

        align_end = (labels_reader is not None and alignment_mode == ALIGN_END)
        for i, (f, l) in enumerate(zip(feats, labels)):
            # ALIGN_END aligns the LAST step of both streams to t = T-1
            # (reference AlignmentMode.ALIGN_END) — whichever stream is
            # shorter shifts right; ALIGN_START/single-reader start at 0
            fo = T - len(f) if align_end else 0
            self._features[i, fo:fo + len(f)] = f
            self._fmask[i, fo:fo + len(f)] = 1.0
            l = np.asarray(l)
            lo = T - len(l) if align_end else 0
            sl = slice(lo, lo + len(l))
            if regression:
                self._labels[i, sl] = l.reshape(len(l), lab_dim)
            else:
                li = l.astype(int)
                if (li != l).any() or li.min() < 0 or li.max() >= lab_dim:
                    raise ValueError(
                        f"class labels must be integers in [0, {lab_dim}); "
                        f"sequence {i} has range [{l.min()}, {l.max()}]")
                self._labels[i, sl] = np.eye(lab_dim, dtype=np.float32)[li]
            self._lmask[i, sl] = 1.0

    def total_examples(self):
        return len(self._features)

    def total_outcomes(self):
        return self._labels.shape[-1]

    def _slice(self, lo, hi):
        return DataSet(self._features[lo:hi], self._labels[lo:hi],
                       self._fmask[lo:hi], self._lmask[lo:hi])
