"""Image loading — ImageRecordReader + NativeImageLoader analogues.

Reference parity: ``org.datavec.image.recordreader.ImageRecordReader``
(directory-of-class-subdirs datasets via ParentPathLabelGenerator) and
``org.datavec.image.loader.NativeImageLoader`` (file → matrix).

TPU-first split: decode on host (PIL, gated import — torch ships pillow in
this image), then resize/augment/normalize as batched XLA programs on device
(`datavec.make_image_augmenter` / `resize_images`) instead of the
reference's per-image OpenCV transform chain. Output layout is NHWC (the
TPU-native layout), not the reference's NCHW.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from .dataset import DataSet
from .iterators import ArrayDataSetIterator

_IMG_EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".gif", ".ppm", ".pgm", ".tif",
             ".tiff", ".webp"}


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError as e:   # pragma: no cover - PIL is in this image
        raise ImportError(
            "ImageRecordReader needs pillow for decoding; install PIL or "
            "feed arrays via CollectionRecordReader") from e


class NativeImageLoader:
    """File → float32 array, resized to (height, width, channels), NHWC.

    Reference: NativeImageLoader(height, width, channels).asMatrix(file) —
    ours returns HWC (batch added by callers) and uses PIL + jax resize.
    """

    def __init__(self, height: int, width: int, channels: int = 3):
        self.height, self.width, self.channels = height, width, channels

    _MODES = {1: "L", 3: "RGB", 4: "RGBA"}

    def as_matrix(self, path: str) -> np.ndarray:
        Image = _pil()
        mode = self._MODES.get(self.channels)
        if mode is None:
            raise ValueError(
                f"channels must be one of {sorted(self._MODES)}, "
                f"got {self.channels}")
        with Image.open(path) as im:
            im = im.convert(mode)
            if im.size != (self.width, self.height):
                im = im.resize((self.width, self.height),
                               Image.Resampling.BILINEAR)
            arr = np.asarray(im, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr


class ParentPathLabelGenerator:
    """Label = name of the file's parent directory (reference class)."""

    def label_for(self, path: str) -> str:
        return os.path.basename(os.path.dirname(path))


class ImageRecordReader:
    """Walk a directory tree of images; each record is [flattened image...,
    label index]. Labels come from the label generator over parent dirs,
    sorted alphabetically like the reference.

    Reference: ImageRecordReader(height, width, channels, labelGenerator) +
    FileSplit(rootDir).
    """

    def __init__(self, height: int, width: int, channels: int = 3,
                 label_generator: Optional[ParentPathLabelGenerator] = None):
        self.loader = NativeImageLoader(height, width, channels)
        self.label_gen = label_generator or ParentPathLabelGenerator()
        self.labels: List[str] = []
        self._files: List[str] = []

    def initialize(self, root_dir: str) -> "ImageRecordReader":
        files = []
        for dirpath, _, names in os.walk(root_dir):
            for n in sorted(names):
                if os.path.splitext(n)[1].lower() in _IMG_EXTS:
                    files.append(os.path.join(dirpath, n))
        if not files:
            raise ValueError(f"no image files under {root_dir}")
        self._files = sorted(files)
        self.labels = sorted({self.label_gen.label_for(f)
                              for f in self._files})
        return self

    def num_labels(self) -> int:
        return len(self.labels)

    def __iter__(self):
        lut = {l: i for i, l in enumerate(self.labels)}
        for f in self._files:
            img = self.loader.as_matrix(f)
            yield list(img.ravel()) + [lut[self.label_gen.label_for(f)]]

    def load_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Bulk path: (images (N,H,W,C) float32, label indices (N,))."""
        lut = {l: i for i, l in enumerate(self.labels)}
        imgs = np.stack([self.loader.as_matrix(f) for f in self._files])
        ys = np.asarray([lut[self.label_gen.label_for(f)]
                         for f in self._files], np.int32)
        return imgs, ys


class ImageDataSetIterator(ArrayDataSetIterator):
    """ImageRecordReader → DataSet batches with one-hot labels (the
    RecordReaderDataSetIterator configuration the reference zoo examples
    use for image folders). Keeps NHWC; scale=1/255 matches
    ImagePreProcessingScaler defaults."""

    def __init__(self, reader: ImageRecordReader, batch_size: int,
                 scale: Optional[float] = 1.0 / 255.0):
        imgs, ys = reader.load_arrays()
        if scale is not None:
            imgs = imgs * scale
        labels = np.eye(reader.num_labels(), dtype=np.float32)[ys]
        super().__init__(imgs.astype(np.float32), labels, batch_size)
