"""AsyncDataSetIterator — background-thread prefetch over any iterator.

Reference parity: ``org.deeplearning4j.datasets.iterator.AsyncDataSetIterator``
(worker thread + bounded queue so host ETL overlaps device compute).
Backing store is the native SPSC ring (`native/dl4j_tpu_native.cpp`) when the
lib is available — batches are serialized into fixed byte slots, so the
producer thread never holds the GIL during the copy — with a pure-Python
queue fallback. Either way the consumer API is a normal DataSetIterator.

reset() swaps in a FRESH ring/queue generation before restarting the
producer: an old producer blocked on a full buffer keeps writing (and
sentinel-ing) only its own abandoned generation, so a stale sentinel can
never truncate the next epoch.
"""

from __future__ import annotations

import io
import queue
import threading
from typing import Optional

import numpy as np

from .dataset import DataSet, MultiDataSet

_SENTINEL = b"__END__"


def _pack(ds) -> bytes:
    buf = io.BytesIO()
    if isinstance(ds, MultiDataSet):
        parts = {}
        for i, f in enumerate(ds.features):
            parts[f"mf{i}"] = f
        for i, l in enumerate(ds.labels):
            parts[f"ml{i}"] = l
        for i, m in enumerate(ds.features_masks or []):
            if m is not None:
                parts[f"mfm{i}"] = m
        for i, m in enumerate(ds.labels_masks or []):
            if m is not None:
                parts[f"mlm{i}"] = m
    else:
        parts = {"features": ds.features, "labels": ds.labels}
        if ds.features_mask is not None:
            parts["features_mask"] = ds.features_mask
        if ds.labels_mask is not None:
            parts["labels_mask"] = ds.labels_mask
    np.savez(buf, **parts)
    return buf.getvalue()


def _unpack(raw: bytes):
    with np.load(io.BytesIO(raw)) as z:
        if "features" in z:
            return DataSet(z["features"], z["labels"],
                           z["features_mask"] if "features_mask" in z else None,
                           z["labels_mask"] if "labels_mask" in z else None)
        def series(prefix):
            out = []
            for i in range(len(z.files)):
                if f"{prefix}{i}" not in z:
                    break
                out.append(z[f"{prefix}{i}"])
            return out
        feats, labs = series("mf"), series("ml")
        fmasks = [z[f"mfm{i}"] if f"mfm{i}" in z else None
                  for i in range(len(feats))]
        lmasks = [z[f"mlm{i}"] if f"mlm{i}" in z else None
                  for i in range(len(labs))]
        return MultiDataSet(
            feats, labs,
            fmasks if any(m is not None for m in fmasks) else None,
            lmasks if any(m is not None for m in lmasks) else None)


def maybe_wrap_async(iterator, queue_size: int = 2):
    """(possibly-wrapped iterator, wrapper-or-None): wrap when the source
    opts in via async_supported() and isn't already async — the shared
    policy for MultiLayerNetwork.fit and ComputationGraph.fit."""
    if getattr(iterator, "async_supported", lambda: False)() \
            and not isinstance(iterator, AsyncDataSetIterator):
        wrapped = AsyncDataSetIterator(iterator, queue_size=queue_size)
        return wrapped, wrapped
    return iterator, None


class AsyncDataSetIterator:
    def __init__(self, inner, queue_size: int = 4, use_native: bool = True,
                 slot_size: int = 64 << 20):
        self.inner = inner
        self.queue_size = queue_size
        self.use_native = use_native
        self.slot_size = slot_size
        self.batch_size = getattr(inner, "batch_size", None)
        self._ring = None
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._start()

    def _make_buffers(self):
        self._ring = None
        if self.use_native:
            try:
                from ..utils.native import NativeRing
                self._ring = NativeRing(self.slot_size, self.queue_size)
            except Exception:  # noqa: BLE001 — fall back to queue
                self._ring = None
        self._q = queue.Queue(maxsize=self.queue_size)

    # ------------------------------------------------------------- producer
    def _start(self):
        self._make_buffers()
        self._stop = threading.Event()
        self._error = []   # generation-local; producer appends, consumer raises
        self._thread = threading.Thread(
            target=self._produce,
            args=(self._ring, self._q, self._stop, self._error),
            daemon=True)
        self._thread.start()

    def _produce(self, ring, q, stop, error):
        """Writes ONLY to the generation's own (ring, q, stop, error) — after
        reset() these are abandoned objects and nothing here touches the
        live ones. A source exception is captured into `error` and re-raised
        on the CONSUMER side at the sentinel — silently truncating an epoch
        because the data pipeline died would be a training-integrity bug."""
        try:
            for ds in self.inner:
                payload = _pack(ds) if ring is not None else ds
                while not stop.is_set():
                    if ring is not None:
                        if ring.push(payload):
                            break
                        stop.wait(0.001)
                    else:
                        try:
                            q.put(payload, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — handed to the consumer
            error.append(e)
        finally:
            while not stop.is_set():
                if ring is not None:
                    if ring.push(_SENTINEL):
                        break
                    stop.wait(0.001)
                else:
                    try:
                        q.put(_SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

    # ------------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __next__(self) -> DataSet:
        ring, q = self._ring, self._q
        while True:
            if ring is not None:
                raw = ring.pop()
                if raw is None:
                    self._stop.wait(0.001)
                    continue
                if raw == _SENTINEL:
                    self._raise_producer_error()
                    raise StopIteration
                return _unpack(raw)
            item = q.get()
            if isinstance(item, bytes) and item == _SENTINEL:
                self._raise_producer_error()
                raise StopIteration
            return item

    def _raise_producer_error(self):
        if self._error:
            raise RuntimeError(
                "async data producer failed mid-epoch (source iterator "
                "raised) — training would silently truncate"
            ) from self._error[0]

    def __len__(self):
        return len(self.inner)

    def reset(self):
        self._stop.set()
        old_thread, old_ring = self._thread, self._ring
        if old_thread is not None:
            old_thread.join(timeout=5)
        if hasattr(self.inner, "reset"):
            self.inner.reset()
        self._start()  # fresh generation: new ring/queue/stop event
        # free the old ring ONLY if its producer actually exited (a live
        # producer pushing into freed memory would be use-after-free)
        if old_ring is not None and (old_thread is None or not old_thread.is_alive()):
            old_ring.close()

    def total_outcomes(self):
        return getattr(self.inner, "total_outcomes", lambda: -1)()

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._ring is not None:
            self._ring.close()
            self._ring = None
