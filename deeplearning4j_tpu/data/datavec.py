"""DataVec-lite ETL — parity with the DataVec modules the reference trains
from: record readers (``org.datavec.api.records.reader.impl.csv
.CSVRecordReader``, ``LineRecordReader``, ``CollectionRecordReader``),
``Schema`` + ``TransformProcess`` (categorical→onehot/integer, filters,
derived/removed columns, normalization) and the
``RecordReaderDataSetIterator`` bridge into DataSet batches.

Host-side by design (ETL feeds the device); the image-augmentation ops at
the bottom are the exception — they are jax/jit batched functions so
augmentation runs on-device, replacing DataVec's per-image OpenCV
ImageTransform chain with one vectorised XLA program.
"""

from __future__ import annotations

import csv as _csv
import io
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from .dataset import DataSet
from .iterators import ArrayDataSetIterator


# ------------------------------------------------------------ record readers
class RecordReader:
    """Reference RecordReader: iterate records (lists of values)."""

    def __iter__(self) -> Iterable[List[Any]]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionRecordReader(RecordReader):
    def __init__(self, records: Sequence[Sequence[Any]]):
        self._records = [list(r) for r in records]

    def __iter__(self):
        return iter(self._records)


class LineRecordReader(RecordReader):
    """One record per line, single string value."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                yield [line.rstrip("\r\n")]


class CSVRecordReader(RecordReader):
    """Reference CSVRecordReader(skipNumLines, delimiter). Values parsed to
    float when possible, else kept as strings."""

    def __init__(self, path: Optional[str] = None, skip_lines: int = 0,
                 delimiter: str = ",", text: Optional[str] = None):
        self.path, self.text = path, text
        self.skip_lines, self.delimiter = skip_lines, delimiter

    @staticmethod
    def _parse(v: str):
        v = v.strip()
        try:
            return int(v)          # exact — no float round-trip for big ints
        except ValueError:
            pass
        try:
            return float(v)
        except ValueError:
            return v

    def __iter__(self):
        if self.text is not None:
            src = io.StringIO(self.text)
        else:
            src = open(self.path, "r", encoding="utf-8", newline="")
        try:
            for i, row in enumerate(_csv.reader(src, delimiter=self.delimiter)):
                if i < self.skip_lines or not row:
                    continue
                yield [self._parse(v) for v in row]
        finally:
            src.close()


class SVMLightRecordReader(RecordReader):
    """SVMLight / LibSVM sparse-format reader — parity with datavec-api
    ``SVMLightRecordReader``. Lines look like::

        <label>[,<label2>...] [qid:<n>] <idx>:<val> <idx>:<val> ... # comment

    Records come out dense: ``num_features`` feature floats followed by the
    label value(s) (feed through ``RecordReaderDataSetIterator`` with
    ``num_classes`` to one-hot, exactly like the CSV path). Indices are
    1-based per the SVMLight convention unless ``zero_based_indexing``;
    ``qid:`` tokens and ``#`` comments are skipped like upstream.
    """

    def __init__(self, path: Optional[str] = None, num_features: int = 0,
                 text: Optional[str] = None,
                 zero_based_indexing: bool = False):
        if num_features <= 0:
            raise ValueError("num_features must be set (upstream "
                             "SVMLightRecordReader.NUM_FEATURES is required)")
        self.path, self.text = path, text
        self.num_features = num_features
        self.zero_based_indexing = zero_based_indexing

    @staticmethod
    def _label(tok: str):
        f = float(tok)
        i = int(f)
        return i if i == f else f

    def __iter__(self):
        src = io.StringIO(self.text) if self.text is not None \
            else open(self.path, "r", encoding="utf-8")
        off = 0 if self.zero_based_indexing else 1
        try:
            for lineno, line in enumerate(src, 1):
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split()
                feats = [0.0] * self.num_features
                for tok in parts[1:]:
                    if tok.startswith("qid:"):
                        continue
                    idx_s, val_s = tok.split(":", 1)
                    i = int(idx_s) - off
                    if not 0 <= i < self.num_features:
                        raise ValueError(
                            f"line {lineno}: feature index {idx_s} outside "
                            f"num_features={self.num_features} "
                            f"(zero_based_indexing={self.zero_based_indexing})")
                    feats[i] = float(val_s)
                yield feats + [self._label(t) for t in parts[0].split(",")]
        finally:
            src.close()


def read_csv_matrix(path: Optional[str] = None, n_cols: int = 0,
                    text: Optional[bytes] = None) -> "np.ndarray":
    """All-numeric CSV → (rows, n_cols) float32 via the native parser
    (native/dl4j_tpu_native.cpp parse_csv_matrix; pure-numpy fallback).
    The bulk-load fast path behind CSVRecordReader for numeric datasets —
    reference counterpart: CSVRecordReader + RecordConverter.toMatrix.
    Header/blank/ragged lines are skipped."""
    from ..utils.native import parse_csv_matrix
    if text is None:
        with open(path, "rb") as f:
            text = f.read()
    elif isinstance(text, str):
        text = text.encode()
    return parse_csv_matrix(text, n_cols)


# -------------------------------------------------------------------- schema
@dataclass
class Column:
    name: str
    kind: str                       # 'numeric' | 'integer' | 'categorical' | 'string'
    categories: Optional[List[str]] = None


class Schema:
    """Reference ``org.datavec.api.transform.schema.Schema`` (builder)."""

    def __init__(self, columns: Optional[List[Column]] = None):
        self.columns = columns or []

    class Builder:
        def __init__(self):
            self._cols: List[Column] = []

        def add_column_double(self, name):
            self._cols.append(Column(name, "numeric"))
            return self

        add_column_float = add_column_double

        def add_column_integer(self, name):
            self._cols.append(Column(name, "integer"))
            return self

        def add_column_categorical(self, name, categories):
            self._cols.append(Column(name, "categorical", list(categories)))
            return self

        def add_column_string(self, name):
            self._cols.append(Column(name, "string"))
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)

    def column(self, name: str) -> Column:
        return self.columns[self.index_of(name)]


# ----------------------------------------------------------- transform steps
class TransformProcess:
    """Reference ``TransformProcess`` — an ordered pipeline over records.

    Built via ``TransformProcess.builder(schema)``; ``execute(records)``
    runs every step; the post-transform schema is ``final_schema()``.
    """

    def __init__(self, initial_schema: Schema, steps: List[Callable]):
        self.initial_schema = initial_schema
        self._steps = steps

    class Builder:
        def __init__(self, schema: Schema):
            self._schema = Schema(list(schema.columns))
            self._steps: List[Callable] = []

        # each builder method appends (fn(records, schema) -> (records, schema))
        def categorical_to_integer(self, name):
            def step(records, schema):
                i = schema.index_of(name)
                cats = schema.columns[i].categories
                lut = {c: j for j, c in enumerate(cats)}
                for r in records:
                    r[i] = lut[r[i]]
                schema.columns[i] = Column(name, "integer")
                return records, schema
            self._steps.append(step)
            return self

        def categorical_to_one_hot(self, name):
            def step(records, schema):
                i = schema.index_of(name)
                cats = schema.columns[i].categories
                lut = {c: j for j, c in enumerate(cats)}
                for r in records:
                    onehot = [0.0] * len(cats)
                    onehot[lut[r[i]]] = 1.0
                    r[i:i + 1] = onehot
                schema.columns[i:i + 1] = [Column(f"{name}[{c}]", "numeric")
                                           for c in cats]
                return records, schema
            self._steps.append(step)
            return self

        def remove_columns(self, *names):
            def step(records, schema):
                idx = sorted((schema.index_of(n) for n in names), reverse=True)
                for r in records:
                    for i in idx:
                        del r[i]
                for i in idx:
                    del schema.columns[i]
                return records, schema
            self._steps.append(step)
            return self

        def filter_rows(self, predicate: Callable[[Dict[str, Any]], bool]):
            """Keep rows where predicate(dict row) is True (reference
            FilterInvalidValues / ConditionFilter analogue)."""
            def step(records, schema):
                names = schema.names()
                records = [r for r in records
                           if predicate(dict(zip(names, r)))]
                return records, schema
            self._steps.append(step)
            return self

        def add_derived_column(self, name: str, fn: Callable[[Dict[str, Any]], Any],
                               kind: str = "numeric"):
            def step(records, schema):
                names = schema.names()
                for r in records:
                    r.append(fn(dict(zip(names, r))))
                schema.columns.append(Column(name, kind))
                return records, schema
            self._steps.append(step)
            return self

        def normalize_min_max(self, name, new_min=0.0, new_max=1.0):
            # Stats are fit on the FIRST non-empty execute() and reused for
            # later calls (so train-fitted stats apply to the test split,
            # like DataVec's DataAnalysis-derived normalizers).
            stats = {}

            def step(records, schema):
                i = schema.index_of(name)
                if "lo" not in stats:
                    if not records:
                        return records, schema
                    vals = np.asarray([r[i] for r in records], np.float64)
                    stats["lo"], stats["hi"] = vals.min(), vals.max()
                lo, hi = stats["lo"], stats["hi"]
                span = (hi - lo) or 1.0
                for r in records:
                    r[i] = float((r[i] - lo) / span * (new_max - new_min) + new_min)
                return records, schema
            self._steps.append(step)
            return self

        def normalize_standardize(self, name):
            stats = {}

            def step(records, schema):
                i = schema.index_of(name)
                if "mu" not in stats:
                    if not records:
                        return records, schema
                    vals = np.asarray([r[i] for r in records], np.float64)
                    stats["mu"], stats["sd"] = vals.mean(), vals.std() or 1.0
                for r in records:
                    r[i] = float((r[i] - stats["mu"]) / stats["sd"])
                return records, schema
            self._steps.append(step)
            return self

        # ---- column math (DoubleMathOpTransform / IntegerMathOpTransform /
        # MathOpTransform between columns) --------------------------------
        _MATH_OPS = {
            "add": lambda a, b: a + b, "subtract": lambda a, b: a - b,
            "multiply": lambda a, b: a * b, "divide": lambda a, b: a / b,
            "modulus": lambda a, b: a % b, "pow": lambda a, b: a ** b,
            "min": min, "max": max,
        }

        def math_op(self, name, op, scalar):
            """column <- column <op> scalar (DoubleMathOpTransform)."""
            fn = self._MATH_OPS[op]

            def step(records, schema):
                i = schema.index_of(name)
                for r in records:
                    r[i] = fn(r[i], scalar)
                return records, schema
            self._steps.append(step)
            return self

        def math_op_between_columns(self, new_name, op, col_a, col_b):
            """new column <- colA <op> colB (MathOpTransform)."""
            fn = self._MATH_OPS[op]

            def step(records, schema):
                ia, ib = schema.index_of(col_a), schema.index_of(col_b)
                for r in records:
                    r.append(fn(r[ia], r[ib]))
                schema.columns.append(Column(new_name, "numeric"))
                return records, schema
            self._steps.append(step)
            return self

        # ---- column surgery (RenameColumns / DuplicateColumns /
        # ReorderColumns / RemoveAllColumnsExceptFor) ---------------------
        def rename_column(self, old, new):
            def step(records, schema):
                i = schema.index_of(old)
                c = schema.columns[i]
                schema.columns[i] = Column(new, c.kind, c.categories)
                return records, schema
            self._steps.append(step)
            return self

        def duplicate_column(self, name, new_name):
            def step(records, schema):
                i = schema.index_of(name)
                for r in records:
                    r.append(r[i])
                c = schema.columns[i]
                schema.columns.append(Column(new_name, c.kind, c.categories))
                return records, schema
            self._steps.append(step)
            return self

        def reorder_columns(self, *names):
            def step(records, schema):
                idx = [schema.index_of(n) for n in names]
                rest = [i for i in range(len(schema.columns)) if i not in idx]
                perm = idx + rest
                for k, r in enumerate(records):
                    records[k] = [r[i] for i in perm]
                schema.columns = [schema.columns[i] for i in perm]
                return records, schema
            self._steps.append(step)
            return self

        def remove_all_columns_except_for(self, *names):
            def step(records, schema):
                keep = [schema.index_of(n) for n in names]
                for k, r in enumerate(records):
                    records[k] = [r[i] for i in keep]
                schema.columns = [schema.columns[i] for i in keep]
                return records, schema
            self._steps.append(step)
            return self

        # ---- string transforms (Append/ChangeCase/Replace/Map) ----------
        def _map_column(self, name, fn):
            def step(records, schema):
                i = schema.index_of(name)
                for r in records:
                    r[i] = fn(r[i])
                return records, schema
            self._steps.append(step)
            return self

        def append_string(self, name, suffix):
            return self._map_column(name, lambda v: str(v) + suffix)

        def prepend_string(self, name, prefix):
            return self._map_column(name, lambda v: prefix + str(v))

        def to_lower_case(self, name):
            return self._map_column(name, lambda v: str(v).lower())

        def to_upper_case(self, name):
            return self._map_column(name, lambda v: str(v).upper())

        def replace_string(self, name, old, new):
            return self._map_column(name, lambda v: str(v).replace(old, new))

        def regex_replace(self, name, pattern, replacement):
            import re as _re
            pat = _re.compile(pattern)
            return self._map_column(
                name, lambda v: pat.sub(replacement, str(v)))

        def string_to_categorical(self, name, categories):
            def step(records, schema):
                i = schema.index_of(name)
                schema.columns[i] = Column(name, "categorical",
                                           list(categories))
                return records, schema
            self._steps.append(step)
            return self

        # ---- conditional / invalid-value replacement --------------------
        def conditional_replace_value(self, name, condition, new_value):
            """Replace value where condition(row dict) holds
            (ConditionalReplaceValueTransform). `condition` is a
            transforms.Condition or any row-dict predicate."""
            def step(records, schema):
                i = schema.index_of(name)
                names = schema.names()
                for r in records:
                    if condition(dict(zip(names, r))):
                        r[i] = new_value
                return records, schema
            self._steps.append(step)
            return self

        def replace_invalid_with(self, name, value):
            """Replace non-numeric entries of a numeric column
            (ReplaceInvalidWithIntegerTransform analogue)."""
            def step(records, schema):
                i = schema.index_of(name)
                for r in records:
                    try:
                        float(r[i])
                    except (TypeError, ValueError):
                        r[i] = value
                return records, schema
            self._steps.append(step)
            return self

        # ---- time (StringToTimeTransform / DeriveColumnsFromTime) -------
        def string_to_time(self, name, fmt="%Y-%m-%d %H:%M:%S"):
            """Parse a string column to integer epoch seconds."""
            import datetime as _dt

            def step(records, schema):
                i = schema.index_of(name)
                for r in records:
                    t = _dt.datetime.strptime(str(r[i]), fmt)
                    r[i] = int(t.replace(tzinfo=_dt.timezone.utc).timestamp())
                schema.columns[i] = Column(name, "integer")
                return records, schema
            self._steps.append(step)
            return self

        def derive_columns_from_time(self, name, fields=("hour", "dayofweek")):
            """Append derived integer columns from an epoch-seconds column:
            hour, minute, dayofweek (Mon=0), dayofmonth, month, year."""
            import datetime as _dt
            getters = {
                "hour": lambda t: t.hour, "minute": lambda t: t.minute,
                "dayofweek": lambda t: t.weekday(),
                "dayofmonth": lambda t: t.day, "month": lambda t: t.month,
                "year": lambda t: t.year,
            }
            for f in fields:
                if f not in getters:
                    raise ValueError(f"unknown time field '{f}'")

            def step(records, schema):
                i = schema.index_of(name)
                for r in records:
                    t = _dt.datetime.fromtimestamp(int(r[i]),
                                                   _dt.timezone.utc)
                    for f in fields:
                        r.append(getters[f](t))
                for f in fields:
                    schema.columns.append(Column(f"{name}.{f}", "integer"))
                return records, schema
            self._steps.append(step)
            return self

        # ---- integration with the catalog (transforms.py) ---------------
        def filter_by_condition(self, condition):
            """Remove rows matching condition (ConditionFilter removes
            matching examples — note the inversion vs filter_rows)."""
            return self.filter_rows(lambda row: not condition(row))

        def reduce(self, reducer):
            """Group-by + aggregate via transforms.Reducer."""
            def step(records, schema):
                recs, new_schema = reducer.reduce(records, schema)
                schema.columns = new_schema.columns
                return recs, schema
            self._steps.append(step)
            return self

        def transform(self, fn):
            """Escape hatch: fn(records, schema) -> (records, schema)."""
            self._steps.append(fn)
            return self

        def build(self) -> "TransformProcess":
            return TransformProcess(self._schema, self._steps)

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)

    def execute(self, records: Iterable[Sequence[Any]]):
        recs = [list(r) for r in records]
        schema = Schema([Column(c.name, c.kind, c.categories)
                         for c in self.initial_schema.columns])
        for step in self._steps:
            recs, schema = step(recs, schema)
        self._final_schema = schema
        return recs

    def final_schema(self) -> Schema:
        if not hasattr(self, "_final_schema"):
            self.execute([])
        return self._final_schema


# ------------------------------------------------- reader → DataSet iterator
class RecordReaderDataSetIterator(ArrayDataSetIterator):
    """Reference ``RecordReaderDataSetIterator(reader, batch, labelIdx,
    numClasses)`` — materialises the reader, splits label column, one-hots
    classification labels; ``regression=True`` keeps labels continuous."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False,
                 transform: Optional[TransformProcess] = None):
        records = list(reader)
        if transform is not None:
            records = transform.execute(records)
        if not records:
            raise ValueError("record reader produced no records "
                             "(empty source or filter removed every row)")
        rows = np.asarray(records, np.float32)
        if label_index < 0:
            label_index = rows.shape[1] + label_index
        y = rows[:, label_index]
        X = np.delete(rows, label_index, axis=1)
        if regression:
            labels = y[:, None].astype(np.float32)
        else:
            if num_classes is None:
                num_classes = int(y.max()) + 1
            yi = y.astype(int)
            if (yi != y).any() or yi.min() < 0 or yi.max() >= num_classes:
                raise ValueError(
                    f"class labels must be integers in [0, {num_classes}); "
                    f"got range [{y.min()}, {y.max()}]")
            labels = np.eye(num_classes, dtype=np.float32)[yi]
        super().__init__(X, labels, batch_size)


# ---------------------------------------------------- on-device image pipeline
def make_image_augmenter(*, crop_padding: int = 0, flip_horizontal: bool = False,
                         mean: Optional[Sequence[float]] = None,
                         std: Optional[Sequence[float]] = None):
    """Build a jitted ``augment(key, images (B,H,W,C)) -> images`` pipeline.

    The TPU-native replacement for DataVec's per-image ImageTransform chain
    (CropImageTransform/FlipImageTransform/NormalizeImageTransform): the
    whole batch is augmented in one XLA program on device.
    """
    import jax
    import jax.numpy as jnp

    mean_a = None if mean is None else jnp.asarray(mean, jnp.float32)
    std_a = None if std is None else jnp.asarray(std, jnp.float32)

    def augment(key, images):
        B, H, W, C = images.shape
        if crop_padding:
            p = crop_padding
            padded = jnp.pad(images, ((0, 0), (p, p), (p, p), (0, 0)), mode="reflect")
            key, k = jax.random.split(key)
            offs = jax.random.randint(k, (B, 2), 0, 2 * p + 1)

            def crop_one(img, off):
                return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (H, W, C))
            images = jax.vmap(crop_one)(padded, offs)
        if flip_horizontal:
            key, k = jax.random.split(key)
            do = jax.random.bernoulli(k, 0.5, (B,))
            images = jnp.where(do[:, None, None, None], images[:, :, ::-1, :], images)
        if mean_a is not None:
            images = images - mean_a
        if std_a is not None:
            images = images / std_a
        return images

    return jax.jit(augment)


def resize_images(images, height: int, width: int, method: str = "bilinear"):
    """Batched on-device resize (DataVec ResizeImageTransform analogue)."""
    import jax
    import jax.numpy as jnp
    images = jnp.asarray(images)
    B, _, _, C = images.shape
    return jax.image.resize(images, (B, height, width, C), method=method)


class JDBCRecordReader(RecordReader):
    """SQL-backed records (reference: ``datavec-jdbc``'s JDBCRecordReader).

    The JVM reference takes a JDBC DataSource + query; the Python-native
    analogue takes a DB-API connection (or a sqlite file path — stdlib,
    no drivers needed) + query. Each record is one row; column names come
    from the cursor description (``column_names()``).
    """

    def __init__(self, conn_or_path, query: str, params: Sequence = ()):
        import os as _os
        self._own = isinstance(conn_or_path, (str, bytes, _os.PathLike))
        if self._own:
            import sqlite3
            self._conn = sqlite3.connect(conn_or_path)
        else:
            self._conn = conn_or_path
        self.query = query
        self.params = tuple(params)
        self._cols: Optional[List[str]] = None

    def _execute(self):
        # DB-API 2.0: only cursors execute (conn.execute is a sqlite3 extra)
        cur = self._conn.cursor()
        cur.execute(self.query, self.params)
        return cur

    def column_names(self) -> List[str]:
        if self._cols is None:
            cur = self._conn.cursor()
            try:
                # zero-row probe: avoids materializing the full result set
                # on eager DB-API drivers just to read the description
                cur.execute(f"SELECT * FROM ({self.query}) AS _probe "
                            "LIMIT 0", self.params)
            except Exception:   # noqa: BLE001 — driver without subquery
                cur.close()     # support: fall back to the real query
                cur = self._execute()
            self._cols = [d[0] for d in cur.description]
            cur.close()
        return self._cols

    def __iter__(self):
        cur = self._execute()
        if self._cols is None:   # keep one consistent naming view: the
            # LIMIT-0 probe may disambiguate duplicate column names
            # ('id', 'id:1') differently from the raw query
            self._cols = [d[0] for d in cur.description]
        try:
            for row in cur:
                yield list(row)
        finally:
            cur.close()

    def close(self):
        if self._own:
            self._conn.close()
