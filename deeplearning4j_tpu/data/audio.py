"""Audio ETL — WAV reading + on-device spectrogram features.

Reference parity: ``datavec-audio`` (WavFileRecordReader,
spectrogram/MFCC-style featurization via its DSP helpers).

TPU-first split: WAV decode is host ETL (stdlib ``wave`` — no external
deps); the featurization (STFT → power spectrogram → mel filterbank →
log) is a single jitted XLA program over the whole batch
(`jnp.fft.rfft` on framed windows), replacing the reference's per-clip
host DSP loop.
"""

from __future__ import annotations

import os
import wave
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .iterators import ArrayDataSetIterator


# ------------------------------------------------------------------ wav io
def read_wav(path: str) -> Tuple[np.ndarray, int]:
    """(samples float32 in [-1, 1] (mono-mixed), sample_rate)."""
    with wave.open(path, "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        width = w.getsampwidth()
        raw = w.readframes(n)
    if width == 2:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 1:     # 8-bit wav is unsigned
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    elif width == 4:
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported sample width {width} in {path}")
    if ch > 1:
        x = x.reshape(-1, ch).mean(-1)
    return x, sr


def write_wav(path: str, samples, sample_rate: int = 16000):
    """float [-1, 1] mono → 16-bit PCM wav (test-fixture helper)."""
    x = np.clip(np.asarray(samples, np.float32), -1.0, 1.0)
    with wave.open(path, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sample_rate)
        w.writeframes((x * 32767.0).astype("<i2").tobytes())


class WavFileRecordReader:
    """Walk a directory tree of .wav files; record = [samples..., label]
    with labels from parent dirs (reference WavFileRecordReader +
    ParentPathLabelGenerator). Clips are padded/trimmed to
    ``max_samples`` so records are fixed-length."""

    def __init__(self, max_samples: int = 16000):
        self.max_samples = int(max_samples)
        self.labels: List[str] = []
        self._files: List[str] = []
        self.sample_rate: Optional[int] = None

    def initialize(self, root_dir: str) -> "WavFileRecordReader":
        files = []
        for dirpath, _, names in os.walk(root_dir):
            for nm in sorted(names):
                if nm.lower().endswith(".wav"):
                    files.append(os.path.join(dirpath, nm))
        if not files:
            raise ValueError(f"no .wav files under {root_dir}")
        self._files = sorted(files)
        self.labels = sorted({os.path.basename(os.path.dirname(f))
                              for f in self._files})
        return self

    def _clip(self, path):
        x, sr = read_wav(path)
        if self.sample_rate is None:
            self.sample_rate = sr
        elif sr != self.sample_rate:
            raise ValueError(
                f"mixed sample rates: {path} is {sr} Hz but the corpus "
                f"started at {self.sample_rate} Hz — resample first (the "
                "mel filterbank is built for ONE rate)")
        if len(x) < self.max_samples:
            x = np.pad(x, (0, self.max_samples - len(x)))
        return x[:self.max_samples]

    def load_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        lut = {l: i for i, l in enumerate(self.labels)}
        xs = np.stack([self._clip(f) for f in self._files])
        ys = np.asarray([lut[os.path.basename(os.path.dirname(f))]
                         for f in self._files], np.int32)
        return xs.astype(np.float32), ys

    def __iter__(self):
        lut = {l: i for i, l in enumerate(self.labels)}
        for f in self._files:
            yield list(self._clip(f)) + [
                lut[os.path.basename(os.path.dirname(f))]]


# --------------------------------------------------------- on-device DSP
def _mel_filterbank(n_mels: int, n_fft: int, sample_rate: int,
                    fmin: float = 0.0, fmax: Optional[float] = None):
    """Triangular mel filterbank (n_mels, n_fft//2 + 1), HTK mel scale."""
    fmax = fmax or sample_rate / 2.0
    mel = lambda f: 2595.0 * np.log10(1.0 + f / 700.0)   # noqa: E731
    imel = lambda m: 700.0 * (10.0 ** (m / 2595.0) - 1.0)  # noqa: E731
    pts = imel(np.linspace(mel(fmin), mel(fmax), n_mels + 2))
    bins = np.floor((n_fft + 1) * pts / sample_rate).astype(int)
    fb = np.zeros((n_mels, n_fft // 2 + 1), np.float32)
    for i in range(n_mels):
        a, b, c = bins[i], bins[i + 1], bins[i + 2]
        if b > a:
            fb[i, a:b] = (np.arange(a, b) - a) / (b - a)
        if c > b:
            fb[i, b:c] = (c - np.arange(b, c)) / (c - b)
    return fb


def make_spectrogram_fn(*, n_fft: int = 512, hop: int = 256,
                        n_mels: Optional[int] = None,
                        sample_rate: int = 16000, log: bool = True,
                        eps: float = 1e-6):
    """Build a jitted ``(B, samples) -> (B, frames, bins)`` featurizer.

    STFT (Hann window, rfft) → power → optional mel projection → optional
    log. One XLA program for the whole batch — the TPU-native replacement
    for datavec-audio's per-clip host DSP.
    """
    import jax
    import jax.numpy as jnp

    window = jnp.asarray(np.hanning(n_fft).astype(np.float32))
    mel_fb = (None if n_mels is None
              else jnp.asarray(_mel_filterbank(n_mels, n_fft, sample_rate)))

    def features(batch):
        batch = jnp.asarray(batch, jnp.float32)
        n = batch.shape[-1]
        if n < n_fft:
            raise ValueError(f"clips have {n} samples < n_fft={n_fft} — "
                             "pad the clips or shrink n_fft")
        n_frames = 1 + (n - n_fft) // hop
        idx = (jnp.arange(n_frames)[:, None] * hop
               + jnp.arange(n_fft)[None, :])          # (frames, n_fft)
        frames = batch[:, idx] * window               # (B, frames, n_fft)
        spec = jnp.abs(jnp.fft.rfft(frames, axis=-1)) ** 2
        if mel_fb is not None:
            spec = jnp.einsum("bfk,mk->bfm", spec, mel_fb)
        if log:
            spec = jnp.log(spec + eps)
        return spec

    return jax.jit(features)


class AudioDataSetIterator(ArrayDataSetIterator):
    """WavFileRecordReader → batched spectrogram DataSets (features
    (B, frames, bins), one-hot labels). The featurizer runs once on
    device over the whole corpus."""

    def __init__(self, reader: WavFileRecordReader, batch_size: int,
                 n_fft: int = 512, hop: int = 256,
                 n_mels: Optional[int] = 64, log: bool = True):
        xs, ys = reader.load_arrays()
        fn = make_spectrogram_fn(n_fft=n_fft, hop=hop, n_mels=n_mels,
                                 sample_rate=reader.sample_rate or 16000,
                                 log=log)
        feats = np.asarray(fn(xs))
        labels = np.eye(len(reader.labels), dtype=np.float32)[ys]
        super().__init__(feats, labels, batch_size)
