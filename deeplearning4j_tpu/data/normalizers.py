"""Data normalizers — parity with ``org.nd4j.linalg.dataset.api.preprocessor``.

NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler,
VGG16ImagePreProcessor, MultiNormalizerStandardize/MinMaxScaler,
CompositeDataSetPreProcessor. fit(iterator) accumulates streaming stats;
transform/revert operate on DataSets or raw arrays; picklable for
ModelSerializer.addNormalizerToModel parity.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .dataset import DataSet, MultiDataSet


class _Stats:
    """Streaming mean/std/min/max accumulator over the batch axis."""

    def __init__(self):
        self.n = 0
        self.sum = None
        self.sum_sq = None
        self.min = None
        self.max = None

    def update(self, x: np.ndarray):
        x = np.asarray(x, np.float64)
        flat = x.reshape(-1, x.shape[-1]) if x.ndim > 1 else x.reshape(-1, 1)
        s = flat.sum(0)
        ss = (flat * flat).sum(0)
        mn = flat.min(0)
        mx = flat.max(0)
        if self.sum is None:
            self.sum, self.sum_sq, self.min, self.max = s, ss, mn, mx
        else:
            self.sum += s
            self.sum_sq += ss
            self.min = np.minimum(self.min, mn)
            self.max = np.maximum(self.max, mx)
        self.n += flat.shape[0]

    @property
    def mean(self):
        return self.sum / self.n

    @property
    def std(self):
        var = self.sum_sq / self.n - self.mean ** 2
        return np.sqrt(np.maximum(var, 1e-12))


class AbstractNormalizer:
    fit_labels = False

    def fit_label(self, flag: bool):
        self.fit_labels = flag
        return self

    def fit(self, data):
        """fit(DataSetIterator | DataSet)."""
        it = [data] if isinstance(data, DataSet) else data
        for ds in it:
            self._update(ds)
        if hasattr(data, "reset"):
            data.reset()
        return self

    def transform(self, ds: DataSet) -> DataSet:
        out = DataSet(self._tf(np.asarray(ds.features, np.float32)),
                      ds.labels if not self.fit_labels
                      else self._tf_labels(np.asarray(ds.labels, np.float32)),
                      ds.features_mask, ds.labels_mask)
        return out

    def pre_process(self, ds: DataSet) -> DataSet:  # reference naming
        return self.transform(ds)

    def __call__(self, ds: DataSet) -> DataSet:
        return self.transform(ds)

    def revert(self, ds: DataSet) -> DataSet:
        return DataSet(self._inv(np.asarray(ds.features, np.float32)),
                       ds.labels if not self.fit_labels
                       else self._inv_labels(np.asarray(ds.labels, np.float32)),
                       ds.features_mask, ds.labels_mask)

    def revert_features(self, f):
        return self._inv(np.asarray(f, np.float32))

    def revert_labels(self, l):
        return self._inv_labels(np.asarray(l, np.float32)) if self.fit_labels else l


class NormalizerStandardize(AbstractNormalizer):
    """Zero-mean unit-variance per feature column."""

    def __init__(self):
        self._f = _Stats()
        self._l = _Stats()

    def _update(self, ds):
        self._f.update(ds.features)
        if self.fit_labels:
            self._l.update(ds.labels)

    def _tf(self, x):
        return ((x - self._f.mean) / self._f.std).astype(np.float32)

    def _inv(self, x):
        return (x * self._f.std + self._f.mean).astype(np.float32)

    def _tf_labels(self, y):
        return ((y - self._l.mean) / self._l.std).astype(np.float32)

    def _inv_labels(self, y):
        return (y * self._l.std + self._l.mean).astype(np.float32)

    @property
    def mean(self):
        return self._f.mean

    @property
    def std(self):
        return self._f.std


class NormalizerMinMaxScaler(AbstractNormalizer):
    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self._f = _Stats()
        self._l = _Stats()

    def _update(self, ds):
        self._f.update(ds.features)
        if self.fit_labels:
            self._l.update(ds.labels)

    def _scale(self, x, st):
        return _minmax_scale(x, st, self.min_range, self.max_range)

    def _unscale(self, x, st):
        rng = np.maximum(st.max - st.min, 1e-12)
        unit = (x - self.min_range) / (self.max_range - self.min_range)
        return (unit * rng + st.min).astype(np.float32)

    def _tf(self, x):
        return self._scale(x, self._f)

    def _inv(self, x):
        return self._unscale(x, self._f)

    def _tf_labels(self, y):
        return self._scale(y, self._l)

    def _inv_labels(self, y):
        return self._unscale(y, self._l)


class ImagePreProcessingScaler(AbstractNormalizer):
    """Scale pixel range [0,maxPixel] → [a,b] (no fit needed)."""

    def __init__(self, a: float = 0.0, b: float = 1.0, max_pixel_value: float = 255.0):
        self.a, self.b, self.max_pixel = a, b, max_pixel_value

    def fit(self, data):
        return self

    def _update(self, ds):
        pass

    def _tf(self, x):
        return (x / self.max_pixel * (self.b - self.a) + self.a).astype(np.float32)

    def _inv(self, x):
        return ((x - self.a) / (self.b - self.a) * self.max_pixel).astype(np.float32)


class VGG16ImagePreProcessor(AbstractNormalizer):
    """Subtract ImageNet channel means (RGB), NHWC."""

    MEANS = np.array([123.68, 116.779, 103.939], np.float32)

    def fit(self, data):
        return self

    def _update(self, ds):
        pass

    def _tf(self, x):
        return (x - self.MEANS).astype(np.float32)

    def _inv(self, x):
        return (x + self.MEANS).astype(np.float32)


class _MultiNormalizerBase:
    """Shared streaming fit over MultiDataSet inputs/outputs; subclasses
    define the per-array transform via _apply(x, stats)."""

    def __init__(self):
        self._f: list = []
        self._l: list = []
        self.fit_labels = False

    def fit_label(self, flag: bool):
        self.fit_labels = flag
        return self

    def fit(self, data):
        it = [data] if isinstance(data, MultiDataSet) else data
        for mds in it:
            if not self._f:
                self._f = [_Stats() for _ in mds.features]
                self._l = [_Stats() for _ in mds.labels]
            for st, f in zip(self._f, mds.features):
                st.update(f)
            if self.fit_labels:
                for st, l in zip(self._l, mds.labels):
                    st.update(l)
        if hasattr(data, "reset"):
            data.reset()
        return self

    def _apply(self, x, st):  # pragma: no cover — abstract
        raise NotImplementedError

    def transform(self, mds: MultiDataSet) -> MultiDataSet:
        feats = [self._apply(f, st) for st, f in zip(self._f, mds.features)]
        labs = mds.labels if not self.fit_labels else [
            self._apply(l, st) for st, l in zip(self._l, mds.labels)]
        return MultiDataSet(feats, labs, mds.features_masks, mds.labels_masks)


class MultiNormalizerStandardize(_MultiNormalizerBase):
    """Per-input/per-output standardization for MultiDataSet."""

    def _apply(self, x, st):
        return ((np.asarray(x, np.float32) - st.mean) / st.std
                ).astype(np.float32)


def _minmax_scale(x, st, lo, hi):
    rng = np.maximum(st.max - st.min, 1e-12)
    unit = (np.asarray(x, np.float32) - st.min) / rng
    return (unit * (hi - lo) + lo).astype(np.float32)


class MultiNormalizerMinMaxScaler(_MultiNormalizerBase):
    """Per-input/per-output min-max scaling for MultiDataSet (reference
    MultiNormalizerMinMaxScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        super().__init__()
        self.min_range = min_range
        self.max_range = max_range

    def _apply(self, x, st):
        return _minmax_scale(x, st, self.min_range, self.max_range)


class CompositeDataSetPreProcessor:
    def __init__(self, *preprocessors):
        self.preprocessors = preprocessors

    def transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def pre_process(self, ds):
        return self.transform(ds)
