"""NASNet-A (mobile) — learned normal/reduction cells.

Reference parity: ``org.deeplearning4j.zoo.model.NASNet`` (NASNet-A mobile:
stem conv, 3 stacks of N normal cells separated by reduction cells,
penultimate 1056 filters). Cell wiring follows the published NASNet-A
search-result architecture; branch separable convs are single sep-conv+BN
(the reference stacks two — one here keeps the graph lean with the same
connectivity and receptive field per branch).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax.numpy as jnp

from ..nn.computation_graph import ComputationGraph
from ..nn.conf import NeuralNetConfiguration
from ..nn.layers.base import InputType
from ..nn.layers.conv import (ConvolutionLayer, GlobalPoolingLayer,
                              SeparableConvolution2D, SubsamplingLayer)
from ..nn.layers.core import ActivationLayer, OutputLayer
from ..nn.layers.norm import BatchNormalization
from ..nn.vertices import ElementWiseVertex, MergeVertex
from ..train.updaters import Adam
from .base import ZooModel


@dataclass
class NASNet(ZooModel):
    num_classes: int = 1000
    input_shape: Tuple = (224, 224, 3)
    stem_filters: int = 32
    penultimate_filters: int = 1056
    cells_per_stack: int = 4

    def conf(self):
        b = NeuralNetConfiguration.builder().seed(self.seed)
        b.updater(self.updater or Adam(1e-3))
        if self.compute_dtype is not None:
            b.data_type(jnp.float32, self.compute_dtype)
        g = b.graph_builder().add_inputs("in")
        uid = [0]

        def nm(p):
            uid[0] += 1
            return f"{p}{uid[0]}"

        def conv_bn(inp, n, k=1, stride=1, act=None):
            name = nm("cb")
            g.add_layer(f"{name}_c", ConvolutionLayer(
                n_out=n, kernel_size=(k, k), stride=(stride, stride),
                convolution_mode="same", activation="identity",
                has_bias=False), inp)
            g.add_layer(f"{name}_b", BatchNormalization(), f"{name}_c")
            if act is None:
                return f"{name}_b"
            g.add_layer(name, ActivationLayer(activation=act), f"{name}_b")
            return name

        def sep(inp, n, k, stride=1):
            """relu → separable kxk → BN (NASNet branch unit)."""
            name = nm("sep")
            g.add_layer(f"{name}_a", ActivationLayer(activation="relu"), inp)
            g.add_layer(f"{name}_s", SeparableConvolution2D(
                n_out=n, kernel_size=(k, k), stride=(stride, stride),
                convolution_mode="same", activation="identity",
                has_bias=False), f"{name}_a")
            g.add_layer(name, BatchNormalization(), f"{name}_s")
            return name

        def pool(inp, kind, stride):
            name = nm("pool")
            g.add_layer(name, SubsamplingLayer(
                kernel_size=(3, 3), stride=(stride, stride),
                pooling_type=kind, convolution_mode="same"), inp)
            return name

        def add(a, b_):
            name = nm("add")
            g.add_vertex(name, ElementWiseVertex(op="add"), a, b_)
            return name

        def cat(*ins):
            name = nm("cat")
            g.add_vertex(name, MergeVertex(), *ins)
            return name

        def adjust(p, p_level, h_level, f):
            """Bring the skip input to the working resolution (reference:
            factorized reduction in the NASNet adjust block)."""
            for _ in range(h_level - p_level):
                p = conv_bn(p, f, 1, stride=2, act="relu")
            return p

        def normal_cell(p, h, f):
            p = conv_bn(p, f, 1)
            h = conv_bn(h, f, 1)
            x1 = add(sep(h, f, 5), sep(p, f, 3))
            x2 = add(sep(p, f, 5), sep(p, f, 3))
            x3 = add(pool(h, "avg", 1), p)
            x4 = add(pool(p, "avg", 1), pool(p, "avg", 1))
            x5 = add(sep(h, f, 3), h)
            return cat(p, x1, x2, x3, x4, x5)

        def reduction_cell(p, h, f):
            p = conv_bn(p, f, 1)
            h = conv_bn(h, f, 1)
            x1 = add(sep(h, f, 5, 2), sep(p, f, 7, 2))
            x2 = add(pool(h, "max", 2), sep(p, f, 7, 2))
            x3 = add(pool(h, "avg", 2), sep(p, f, 5, 2))
            x4 = add(pool(x1, "avg", 1), x2)
            x5 = add(sep(x1, f, 3), pool(h, "max", 2))
            return cat(x2, x3, x4, x5)

        # filters per stack: penultimate/24 (normal-cell concat = 6 branches
        # over 3 stacks with x2 per reduction): mobile → 44, 88, 176
        f = self.penultimate_filters // 24
        x = conv_bn("in", self.stem_filters, 3, stride=2)
        p, p_lv, x_lv = x, 1, 1
        # stem reductions to 1/8 resolution (reference stem has 2 reduction cells)
        for sf in (max(f // 2, 1), f):
            pa = adjust(p, p_lv, x_lv, sf)
            x_new = reduction_cell(pa, x, sf)
            p, p_lv, x, x_lv = x, x_lv, x_new, x_lv + 1
        for stack in range(3):
            if stack > 0:
                pa = adjust(p, p_lv, x_lv, f)
                x_new = reduction_cell(pa, x, f)
                p, p_lv, x, x_lv = x, x_lv, x_new, x_lv + 1
            for _ in range(self.cells_per_stack):
                pa = adjust(p, p_lv, x_lv, f)
                x_new = normal_cell(pa, x, f)
                p, p_lv, x, x_lv = x, x_lv, x_new, x_lv
            f *= 2
        g.add_layer("final_act", ActivationLayer(activation="relu"), x)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), "final_act")
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"), "gap")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(*self.input_shape))
        return g.build()

    def init(self):
        return ComputationGraph(self.conf()).init()
