"""Sequential CNN zoo models — LeNet, SimpleCNN, AlexNet, VGG16, VGG19,
Darknet19, SqueezeNet, TextGenerationLSTM.

Reference parity: ``org.deeplearning4j.zoo.model.{LeNet, SimpleCNN, AlexNet,
VGG16, VGG19, Darknet19, SqueezeNet, TextGenerationLSTM}``. Architectures
match the reference's topologies; layout is NHWC and compute can be bf16
(TPU MXU) via ``compute_dtype``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Tuple

import jax.numpy as jnp

from ..nn.conf import NeuralNetConfiguration
from ..nn.graph import GraphBuilder
from ..nn.computation_graph import ComputationGraph
from ..nn.layers.base import InputType
from ..nn.layers.conv import (ConvolutionLayer, GlobalPoolingLayer,
                              SubsamplingLayer)
from ..nn.layers.core import DenseLayer, DropoutLayer, OutputLayer
from ..nn.layers.norm import BatchNormalization, LocalResponseNormalization
from ..nn.layers.recurrent import LSTM
from ..nn.layers.core import RnnOutputLayer
from ..nn.multi_layer_network import MultiLayerNetwork
from ..nn.vertices import MergeVertex
from ..train.updaters import Adam, Nesterovs
from .base import ZooModel


def _builder(seed, updater, compute_dtype):
    b = NeuralNetConfiguration.builder().seed(seed)
    b.updater(updater or Adam(1e-3))
    if compute_dtype is not None:
        b.data_type(jnp.float32, compute_dtype)
    return b


@dataclass
class LeNet(ZooModel):
    """LeNet-5: 2x(conv5x5 + maxpool) + fc500 + softmax (reference LeNet)."""

    num_classes: int = 10
    input_shape: Tuple = (28, 28, 1)

    def conf(self):
        return (_builder(self.seed, self.updater, self.compute_dtype)
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                        convolution_mode="same", activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), stride=(1, 1),
                                        convolution_mode="same", activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=500, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(*self.input_shape))
                .build())

    def init(self):
        return MultiLayerNetwork(self.conf()).init()


@dataclass
class SimpleCNN(ZooModel):
    """4-block CNN (reference SimpleCNN)."""

    num_classes: int = 10
    input_shape: Tuple = (48, 48, 3)

    def conf(self):
        b = (_builder(self.seed, self.updater, self.compute_dtype).list())
        for n_out in (16, 32, 64, 128):
            b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(3, 3),
                                     convolution_mode="same", activation="identity"))
            b.layer(BatchNormalization())
            b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(3, 3),
                                     convolution_mode="same", activation="relu"))
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        b.layer(DenseLayer(n_out=256, activation="relu"))
        b.layer(DropoutLayer(rate=0.5))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax", loss="mcxent"))
        b.set_input_type(InputType.convolutional(*self.input_shape))
        return b.build()

    def init(self):
        return MultiLayerNetwork(self.conf()).init()


@dataclass
class AlexNet(ZooModel):
    """AlexNet with LRN (reference AlexNet)."""

    num_classes: int = 1000
    input_shape: Tuple = (224, 224, 3)

    def conf(self):
        return (_builder(self.seed, self.updater or Nesterovs(1e-2, 0.9),
                         self.compute_dtype)
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11), stride=(4, 4),
                                        convolution_mode="truncate", padding=2,
                                        activation="relu"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5),
                                        convolution_mode="same", activation="relu"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu"))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu"))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, activation="relu"))
                .layer(DropoutLayer(rate=0.5))
                .layer(DenseLayer(n_out=4096, activation="relu"))
                .layer(DropoutLayer(rate=0.5))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(*self.input_shape))
                .build())

    def init(self):
        return MultiLayerNetwork(self.conf()).init()


def _vgg_blocks(b, cfg):
    for item in cfg:
        if item == "M":
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        else:
            b.layer(ConvolutionLayer(n_out=item, kernel_size=(3, 3),
                                     convolution_mode="same", activation="relu"))
    return b


@dataclass
class VGG16(ZooModel):
    num_classes: int = 1000
    input_shape: Tuple = (224, 224, 3)

    _CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
            512, 512, 512, "M", 512, 512, 512, "M")

    def conf(self):
        b = _builder(self.seed, self.updater or Nesterovs(1e-2, 0.9),
                     self.compute_dtype).list()
        _vgg_blocks(b, self._CFG)
        b.layer(DenseLayer(n_out=4096, activation="relu"))
        b.layer(DropoutLayer(rate=0.5))
        b.layer(DenseLayer(n_out=4096, activation="relu"))
        b.layer(DropoutLayer(rate=0.5))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax", loss="mcxent"))
        b.set_input_type(InputType.convolutional(*self.input_shape))
        return b.build()

    def init(self):
        return MultiLayerNetwork(self.conf()).init()


@dataclass
class VGG19(VGG16):
    _CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
            512, 512, 512, 512, "M", 512, 512, 512, 512, "M")


@dataclass
class Darknet19(ZooModel):
    """Darknet-19 classifier backbone (reference Darknet19)."""

    num_classes: int = 1000
    input_shape: Tuple = (224, 224, 3)

    def conf(self):
        b = _builder(self.seed, self.updater, self.compute_dtype).list()

        def conv_bn(n, k):
            b.layer(ConvolutionLayer(n_out=n, kernel_size=(k, k),
                                     convolution_mode="same", activation="identity",
                                     has_bias=False))
            b.layer(BatchNormalization())
            from ..nn.layers.core import ActivationLayer
            b.layer(ActivationLayer(activation="leakyrelu"))

        conv_bn(32, 3)
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conv_bn(64, 3)
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for trio in ((128, 64, 128), (256, 128, 256)):
            conv_bn(trio[0], 3)
            conv_bn(trio[1], 1)
            conv_bn(trio[2], 3)
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conv_bn(512, 3)
        conv_bn(256, 1)
        conv_bn(512, 3)
        conv_bn(256, 1)
        conv_bn(512, 3)
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conv_bn(1024, 3)
        conv_bn(512, 1)
        conv_bn(1024, 3)
        conv_bn(512, 1)
        conv_bn(1024, 3)
        b.layer(ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1),
                                 convolution_mode="same", activation="identity"))
        b.layer(GlobalPoolingLayer(pooling_type="avg"))
        b.layer(OutputLayer(n_in=self.num_classes, n_out=self.num_classes,
                            activation="softmax", loss="mcxent"))
        b.set_input_type(InputType.convolutional(*self.input_shape))
        return b.build()

    def init(self):
        return MultiLayerNetwork(self.conf()).init()


@dataclass
class SqueezeNet(ZooModel):
    """SqueezeNet v1.1 (fire modules) — built as a ComputationGraph since
    fire modules merge squeeze/expand branches."""

    num_classes: int = 1000
    input_shape: Tuple = (227, 227, 3)

    def conf(self):
        g = (_builder(self.seed, self.updater, self.compute_dtype)
             .graph_builder()
             .add_inputs("in"))
        g.add_layer("conv1", ConvolutionLayer(n_out=64, kernel_size=(3, 3), stride=(2, 2),
                                              convolution_mode="same", activation="relu"), "in")
        g.add_layer("pool1", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), "conv1")
        prev = "pool1"

        def fire(name, squeeze, expand, inp):
            g.add_layer(f"{name}_s", ConvolutionLayer(n_out=squeeze, kernel_size=(1, 1),
                                                      convolution_mode="same",
                                                      activation="relu"), inp)
            g.add_layer(f"{name}_e1", ConvolutionLayer(n_out=expand, kernel_size=(1, 1),
                                                       convolution_mode="same",
                                                       activation="relu"), f"{name}_s")
            g.add_layer(f"{name}_e3", ConvolutionLayer(n_out=expand, kernel_size=(3, 3),
                                                       convolution_mode="same",
                                                       activation="relu"), f"{name}_s")
            g.add_vertex(name, MergeVertex(), f"{name}_e1", f"{name}_e3")
            return name

        prev = fire("fire2", 16, 64, prev)
        prev = fire("fire3", 16, 64, prev)
        g.add_layer("pool3", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), prev)
        prev = fire("fire4", 32, 128, "pool3")
        prev = fire("fire5", 32, 128, prev)
        g.add_layer("pool5", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), prev)
        prev = fire("fire6", 48, 192, "pool5")
        prev = fire("fire7", 48, 192, prev)
        prev = fire("fire8", 64, 256, prev)
        prev = fire("fire9", 64, 256, prev)
        g.add_layer("drop", DropoutLayer(rate=0.5), prev)
        g.add_layer("conv10", ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1),
                                               convolution_mode="same", activation="relu"),
                    "drop")
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), "conv10")
        g.add_layer("out", OutputLayer(n_in=self.num_classes, n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"), "gap")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(*self.input_shape))
        return g.build()

    def init(self):
        return ComputationGraph(self.conf()).init()


@dataclass
class TextGenerationLSTM(ZooModel):
    """Char-RNN: 2xGravesLSTM + RnnOutput (reference TextGenerationLSTM)."""

    num_classes: int = 77      # vocab
    input_shape: Tuple = (60, 77)  # (T, vocab) NTC
    units: int = 256

    def conf(self):
        from ..nn.layers.recurrent import GravesLSTM
        return (_builder(self.seed, self.updater, self.compute_dtype)
                .list()
                .layer(GravesLSTM(n_in=self.input_shape[1], n_out=self.units))
                .layer(GravesLSTM(n_in=self.units, n_out=self.units))
                .layer(RnnOutputLayer(n_in=self.units, n_out=self.num_classes,
                                      activation="softmax", loss="mcxent"))
                .build())

    def init(self):
        return MultiLayerNetwork(self.conf()).init(self.input_shape)

    def generate(self, net, seed, n_steps, temperature: float = 1.0,
                 key=None):
        """Sample `n_steps` tokens after priming on `seed` (B, T, vocab)
        one-hot — the reference example's sampleCharactersFromNetwork, built
        on rnn_time_step so each sampled char is ONE streamed step (state
        stays on device), not a re-run of the whole prefix.

        Returns int32 token ids (B, n_steps)."""
        import jax
        import jax.numpy as jnp
        if key is None:
            key = jax.random.PRNGKey(0)
        vocab = self.num_classes
        net.rnn_clear_previous_state()
        probs = net.rnn_time_step(jnp.asarray(seed))[:, -1]  # prime on seed
        tokens = []
        for _ in range(n_steps):
            key, sub = jax.random.split(key)
            logits = jnp.log(jnp.clip(probs, 1e-9)) / temperature
            tok = jax.random.categorical(sub, logits, axis=-1)   # (B,)
            tokens.append(tok)
            probs = net.rnn_time_step(jax.nn.one_hot(tok, vocab))
        return jnp.stack(tokens, axis=1).astype(jnp.int32)
