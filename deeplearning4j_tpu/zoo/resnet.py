"""ResNet-50 — the headline benchmark model (BASELINE.json config 2).

Reference parity: ``org.deeplearning4j.zoo.model.ResNet50`` (ImageNet
ComputationGraph; cuDNN conv path). TPU-first build: NHWC bf16 convs on
the MXU (f32 internal accumulation), fused BN+ReLU (XLA fuses the elementwise chain
into the conv epilogue), identity/projection bottleneck blocks as graph
vertices. The same topology is also exposed as a pure-functional
``resnet50_fn`` for bench/parallel use (single jaxpr, scan-free).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

import jax.numpy as jnp

from ..nn.computation_graph import ComputationGraph
from ..nn.conf import NeuralNetConfiguration
from ..nn.layers.base import InputType
from ..nn.layers.conv import (ConvolutionLayer, GlobalPoolingLayer,
                              SpaceToDepthLayer, SubsamplingLayer,
                              ZeroPaddingLayer)
from ..nn.layers.core import ActivationLayer, OutputLayer
from ..nn.layers.norm import BatchNormalization
from ..nn.vertices import ElementWiseVertex
from ..train.updaters import Adam
from .base import ZooModel


@dataclass
class ResNet50(ZooModel):
    num_classes: int = 1000
    input_shape: Tuple = (224, 224, 3)
    # TPU stem optimization (MLPerf-style): rearrange the input
    # (H, W, 3) -> (H/2, W/2, 12) with space-to-depth(2) and replace the
    # 7x7/s2 stem conv by the EXACTLY equivalent 4x4/s1 conv on 12
    # channels (weights folded by `fold_stem_weights_s2d`). C=3 pads
    # terribly onto the MXU's 128 lanes; C=12 tiles 4x denser and the
    # conv becomes stride-1. Same function, same init distribution
    # (init draws a 7x7x3 kernel and folds it).
    stem_space_to_depth: bool = False
    # int n -> run the train-time forward as n jax.checkpoint segments
    # (cuts at minimal-live-set block boundaries; see
    # ComputationGraph._forward_remat). Trades recompute for HBM
    # activation traffic on the bandwidth-bound b128 step.
    remat_segments: "int | None" = None

    # (n_blocks, filters) per stage; first block of stages 2-4 downsamples
    STAGES = ((3, (64, 64, 256)), (4, (128, 128, 512)),
              (6, (256, 256, 1024)), (3, (512, 512, 2048)))

    def conf(self):
        b = NeuralNetConfiguration.builder().seed(self.seed)
        b.updater(self.updater or Adam(1e-3))
        if self.compute_dtype is not None:
            b.data_type(jnp.float32, self.compute_dtype)
        g = b.graph_builder().add_inputs("in")

        def conv_bn(name, inp, n_out, k, stride=1, act="relu"):
            g.add_layer(f"{name}_conv",
                        ConvolutionLayer(n_out=n_out, kernel_size=(k, k),
                                         stride=(stride, stride),
                                         convolution_mode="same",
                                         activation="identity", has_bias=False), inp)
            # the conv→bn→act chain folds the activation INTO the BN node so
            # the fused pallas BN-act kernels (inference and training) can
            # engage; `act=None` BNs (pre-residual-add) stay identity
            g.add_layer(f"{name}_bn",
                        BatchNormalization(activation=act or "identity"),
                        f"{name}_conv")
            return f"{name}_bn"

        def bottleneck(name, inp, f1, f2, f3, stride, project):
            x = conv_bn(f"{name}_a", inp, f1, 1, stride)
            x = conv_bn(f"{name}_b", x, f2, 3, 1)
            x = conv_bn(f"{name}_c", x, f3, 1, 1, act=None)
            if project:
                sc = conv_bn(f"{name}_sc", inp, f3, 1, stride, act=None)
            else:
                sc = inp
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, sc)
            g.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
            return f"{name}_out"

        if self.stem_space_to_depth:
            g.add_layer("stem_s2d", SpaceToDepthLayer(block_size=2), "in")
            x = conv_bn("stem", "stem_s2d", 64, 4, 1)
        else:
            x = conv_bn("stem", "in", 64, 7, 2)
        g.add_layer("stem_pool", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2),
                                                  convolution_mode="same"), x)
        x = "stem_pool"
        for si, (n_blocks, (f1, f2, f3)) in enumerate(self.STAGES):
            for bi in range(n_blocks):
                stride = 2 if (bi == 0 and si > 0) else 1
                x = bottleneck(f"s{si}b{bi}", x, f1, f2, f3, stride, project=(bi == 0))
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("out", OutputLayer(n_in=self.STAGES[-1][1][2],
                                       n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"), "gap")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(*self.input_shape))
        return g.build()

    def init(self):
        net = ComputationGraph(self.conf()).init()
        net.remat_segments = self.remat_segments
        if self.stem_space_to_depth:
            # keep the baseline stem's function family + init distribution:
            # draw a 7x7x3 kernel with the stem conv's own initializer and
            # fold it into the equivalent 4x4x12 layout
            w4 = net.params["stem_conv"]["W"]
            proto = ConvolutionLayer(n_out=w4.shape[-1], kernel_size=(7, 7))
            import jax
            c_in = self.input_shape[-1]
            w7 = proto._make_weight(jax.random.PRNGKey(self.seed),
                                    (7, 7, c_in, w4.shape[-1]))
            net.params["stem_conv"]["W"] = fold_stem_weights_s2d(
                w7).astype(w4.dtype)
        return net


def fold_stem_weights_s2d(w7):
    """Fold a (7, 7, 3, F) stem kernel into the (4, 4, 12, F) kernel that
    computes the IDENTICAL conv(7x7, stride 2, SAME) on the
    space-to-depth(2) input.

    Derivation: SAME 7x7/s2 on 224 pads (2, 3), so
    y[o] = sum_k x[2o + k - 2] W[k]. Writing k - 2 = 2*b + d with
    d in {0,1} gives block taps b in {-1..2} -> a 4-tap stride-1 conv in
    block space whose SAME padding for k=4 is exactly (1, 2). The s2d
    channel layout is (dh, dw, c) (SpaceToDepthLayer's transpose order);
    the (b=2, d=1) position corresponds to k=7 and is zero."""
    kh, kw, c, f = w7.shape
    assert (kh, kw) == (7, 7), w7.shape
    w8 = jnp.zeros((8, 8, c, f), w7.dtype).at[:7, :7].set(w7)
    # (8,8,c,f) -> (bh, dh, bw, dw, c, f) -> (bh, bw, dh, dw, c, f)
    w = w8.reshape(4, 2, 4, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
    return w.reshape(4, 4, 4 * c, f)


# --------------------------------------------------------------------------
# Pure-functional ResNet-50 (bench / parallel path) — identical topology,
# but params as a flat dict and a single apply fn; lets bench.py and the
# data-parallel trainer jit/donate without the class machinery.
# --------------------------------------------------------------------------

def resnet50_init(key, num_classes=1000, dtype=jnp.float32):
    model = ResNet50(num_classes=num_classes)
    net = ComputationGraph(model.conf())
    net._g.seed = int(jnp.asarray(0))  # deterministic; key unused by init()
    net.init()
    return net


def resnet50_apply(net, params, states, x, train=False, rng=None):
    acts, _, new_states = net._forward(params, states, {"in": x},
                                       train=train, rng=rng)
    return acts["out"], new_states
