"""ResNet-50 — the headline benchmark model (BASELINE.json config 2).

Reference parity: ``org.deeplearning4j.zoo.model.ResNet50`` (ImageNet
ComputationGraph; cuDNN conv path). TPU-first build: NHWC bf16 convs on
the MXU (f32 internal accumulation), fused BN+ReLU (XLA fuses the elementwise chain
into the conv epilogue), identity/projection bottleneck blocks as graph
vertices. The same topology is also exposed as a pure-functional
``resnet50_fn`` for bench/parallel use (single jaxpr, scan-free).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

import jax.numpy as jnp

from ..nn.computation_graph import ComputationGraph
from ..nn.conf import NeuralNetConfiguration
from ..nn.layers.base import InputType
from ..nn.layers.conv import (ConvolutionLayer, GlobalPoolingLayer,
                              SubsamplingLayer, ZeroPaddingLayer)
from ..nn.layers.core import ActivationLayer, OutputLayer
from ..nn.layers.norm import BatchNormalization
from ..nn.vertices import ElementWiseVertex
from ..train.updaters import Adam
from .base import ZooModel


@dataclass
class ResNet50(ZooModel):
    num_classes: int = 1000
    input_shape: Tuple = (224, 224, 3)

    # (n_blocks, filters) per stage; first block of stages 2-4 downsamples
    STAGES = ((3, (64, 64, 256)), (4, (128, 128, 512)),
              (6, (256, 256, 1024)), (3, (512, 512, 2048)))

    def conf(self):
        b = NeuralNetConfiguration.builder().seed(self.seed)
        b.updater(self.updater or Adam(1e-3))
        if self.compute_dtype is not None:
            b.data_type(jnp.float32, self.compute_dtype)
        g = b.graph_builder().add_inputs("in")

        def conv_bn(name, inp, n_out, k, stride=1, act="relu"):
            g.add_layer(f"{name}_conv",
                        ConvolutionLayer(n_out=n_out, kernel_size=(k, k),
                                         stride=(stride, stride),
                                         convolution_mode="same",
                                         activation="identity", has_bias=False), inp)
            # the conv→bn→act chain folds the activation INTO the BN node so
            # the fused pallas BN-act kernels (inference and training) can
            # engage; `act=None` BNs (pre-residual-add) stay identity
            g.add_layer(f"{name}_bn",
                        BatchNormalization(activation=act or "identity"),
                        f"{name}_conv")
            return f"{name}_bn"

        def bottleneck(name, inp, f1, f2, f3, stride, project):
            x = conv_bn(f"{name}_a", inp, f1, 1, stride)
            x = conv_bn(f"{name}_b", x, f2, 3, 1)
            x = conv_bn(f"{name}_c", x, f3, 1, 1, act=None)
            if project:
                sc = conv_bn(f"{name}_sc", inp, f3, 1, stride, act=None)
            else:
                sc = inp
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, sc)
            g.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
            return f"{name}_out"

        x = conv_bn("stem", "in", 64, 7, 2)
        g.add_layer("stem_pool", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2),
                                                  convolution_mode="same"), x)
        x = "stem_pool"
        for si, (n_blocks, (f1, f2, f3)) in enumerate(self.STAGES):
            for bi in range(n_blocks):
                stride = 2 if (bi == 0 and si > 0) else 1
                x = bottleneck(f"s{si}b{bi}", x, f1, f2, f3, stride, project=(bi == 0))
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("out", OutputLayer(n_in=self.STAGES[-1][1][2],
                                       n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"), "gap")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(*self.input_shape))
        return g.build()

    def init(self):
        return ComputationGraph(self.conf()).init()


# --------------------------------------------------------------------------
# Pure-functional ResNet-50 (bench / parallel path) — identical topology,
# but params as a flat dict and a single apply fn; lets bench.py and the
# data-parallel trainer jit/donate without the class machinery.
# --------------------------------------------------------------------------

def resnet50_init(key, num_classes=1000, dtype=jnp.float32):
    model = ResNet50(num_classes=num_classes)
    net = ComputationGraph(model.conf())
    net._g.seed = int(jnp.asarray(0))  # deterministic; key unused by init()
    net.init()
    return net


def resnet50_apply(net, params, states, x, train=False, rng=None):
    acts, _, new_states = net._forward(params, states, {"in": x},
                                       train=train, rng=rng)
    return acts["out"], new_states
