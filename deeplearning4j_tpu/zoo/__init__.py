"""deeplearning4j_tpu.zoo — model zoo (org.deeplearning4j.zoo parity)."""

from .base import ZooModel
from .cnn_simple import (AlexNet, Darknet19, LeNet, SimpleCNN, SqueezeNet,
                         TextGenerationLSTM, VGG16, VGG19)
from .detection import TINY_YOLO_ANCHORS, YOLO2, YOLO2_ANCHORS, TinyYOLO
from .inception import FaceNetNN4Small2, InceptionResNetV1, Xception
from .nasnet import NASNet
from .resnet import ResNet50
from .unet import UNet
from .transformer import (BertConfig, TransformerConfig, bert_forward,
                          bert_init, draft_config, draft_params,
                          forward as transformer_forward,
                          generate as transformer_generate,
                          init_params as transformer_init)
