"""Inception-family zoo models — Xception, InceptionResNetV1, FaceNetNN4Small2.

Reference parity: ``org.deeplearning4j.zoo.model.{Xception,
InceptionResNetV1, FaceNetNN4Small2}``. Topologies follow the reference
ComputationGraph structures; NHWC layout, optional bf16 compute on the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax.numpy as jnp

from ..nn.computation_graph import ComputationGraph
from ..nn.conf import NeuralNetConfiguration
from ..nn.layers.base import InputType
from ..nn.layers.conv import (ConvolutionLayer, GlobalPoolingLayer,
                              SeparableConvolution2D, SubsamplingLayer)
from ..nn.layers.core import (ActivationLayer, CenterLossOutputLayer,
                              DenseLayer, DropoutLayer, OutputLayer)
from ..nn.layers.norm import BatchNormalization
from ..nn.vertices import ElementWiseVertex, L2NormalizeVertex, MergeVertex, ScaleVertex
from ..train.updaters import Adam
from .base import ZooModel


def _graph(seed, updater, compute_dtype, default_lr=1e-3):
    b = NeuralNetConfiguration.builder().seed(seed)
    b.updater(updater or Adam(default_lr))
    if compute_dtype is not None:
        b.data_type(jnp.float32, compute_dtype)
    return b.graph_builder().add_inputs("in")


class _G:
    """Small helper for building conv-heavy graphs with unique names."""

    def __init__(self, g):
        self.g = g
        self.i = 0

    def conv_bn(self, inp, n, k, stride=1, act="relu", name=None):
        name = name or f"cv{self.i}"
        self.i += 1
        self.g.add_layer(f"{name}_c", ConvolutionLayer(
            n_out=n, kernel_size=(k, k) if isinstance(k, int) else k,
            stride=(stride, stride), convolution_mode="same",
            activation="identity", has_bias=False), inp)
        self.g.add_layer(f"{name}_b", BatchNormalization(), f"{name}_c")
        if act is None:
            return f"{name}_b"
        self.g.add_layer(name, ActivationLayer(activation=act), f"{name}_b")
        return name

    def sep_bn(self, inp, n, act="relu", pre_act=False, name=None):
        name = name or f"sp{self.i}"
        self.i += 1
        src = inp
        if pre_act:
            self.g.add_layer(f"{name}_pre", ActivationLayer(activation="relu"), src)
            src = f"{name}_pre"
        self.g.add_layer(f"{name}_s", SeparableConvolution2D(
            n_out=n, kernel_size=(3, 3), convolution_mode="same",
            activation="identity", has_bias=False), src)
        self.g.add_layer(f"{name}_b" if act is None else f"{name}_bn",
                         BatchNormalization(), f"{name}_s")
        if act is None:
            return f"{name}_b"
        self.g.add_layer(name, ActivationLayer(activation=act), f"{name}_bn")
        return name

    def pool(self, inp, k=3, stride=2, kind="max", name=None):
        name = name or f"pl{self.i}"
        self.i += 1
        self.g.add_layer(name, SubsamplingLayer(
            kernel_size=(k, k), stride=(stride, stride), pooling_type=kind,
            convolution_mode="same"), inp)
        return name

    def add(self, a, b, name=None):
        name = name or f"ad{self.i}"
        self.i += 1
        self.g.add_vertex(name, ElementWiseVertex(op="add"), a, b)
        return name

    def cat(self, name, *ins):
        self.g.add_vertex(name, MergeVertex(), *ins)
        return name


@dataclass
class Xception(ZooModel):
    """Xception: depthwise-separable Inception redesign (entry/middle/exit
    flows with residuals). Reference Xception; 299x299x3."""

    num_classes: int = 1000
    input_shape: Tuple = (299, 299, 3)

    def conf(self):
        g = _graph(self.seed, self.updater, self.compute_dtype)
        G = _G(g)
        # entry flow
        x = G.conv_bn("in", 32, 3, stride=2)
        x = G.conv_bn(x, 64, 3)
        for n in (128, 256, 728):
            res = G.conv_bn(x, n, 1, stride=2, act=None)
            y = G.sep_bn(x, n, act=None, pre_act=(n != 128))
            if n == 128:
                g.add_layer(f"eact{n}", ActivationLayer(activation="relu"), y)
                y = f"eact{n}"
                y = G.sep_bn(y, n, act=None)
            else:
                y = G.sep_bn(y, n, act=None, pre_act=True)
            y = G.pool(y)
            x = G.add(y, res)
        # middle flow: 8 residual blocks of 3 separable convs
        for i in range(8):
            y = x
            for j in range(3):
                y = G.sep_bn(y, 728, act=None, pre_act=True)
            x = G.add(y, x)
        # exit flow
        res = G.conv_bn(x, 1024, 1, stride=2, act=None)
        y = G.sep_bn(x, 728, act=None, pre_act=True)
        y = G.sep_bn(y, 1024, act=None, pre_act=True)
        y = G.pool(y)
        x = G.add(y, res)
        x = G.sep_bn(x, 1536)
        x = G.sep_bn(x, 2048)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("out", OutputLayer(n_in=2048, n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"), "gap")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(*self.input_shape))
        return g.build()

    def init(self):
        return ComputationGraph(self.conf()).init()


@dataclass
class InceptionResNetV1(ZooModel):
    """Inception-ResNet-v1 (FaceNet backbone): stem + 5xA + reduction-A +
    10xB + reduction-B + 5xC + 128-d bottleneck. Reference
    InceptionResNetV1 (embedding + softmax training head)."""

    num_classes: int = 1000
    input_shape: Tuple = (160, 160, 3)
    embedding_size: int = 128
    blocks_a: int = 5
    blocks_b: int = 10
    blocks_c: int = 5

    def conf(self):
        g = _graph(self.seed, self.updater, self.compute_dtype, 1e-1)
        G = _G(g)
        # stem
        x = G.conv_bn("in", 32, 3, stride=2)
        x = G.conv_bn(x, 32, 3)
        x = G.conv_bn(x, 64, 3)
        x = G.pool(x)
        x = G.conv_bn(x, 80, 1)
        x = G.conv_bn(x, 192, 3)
        x = G.conv_bn(x, 256, 3, stride=2)

        def block_a(x, i):
            b0 = G.conv_bn(x, 32, 1)
            b1 = G.conv_bn(G.conv_bn(x, 32, 1), 32, 3)
            b2 = G.conv_bn(G.conv_bn(G.conv_bn(x, 32, 1), 32, 3), 32, 3)
            cat = G.cat(f"a{i}_cat", b0, b1, b2)
            up = G.conv_bn(cat, 256, 1, act=None)
            g.add_vertex(f"a{i}_scale", ScaleVertex(scale=0.17), up)
            s = G.add(x, f"a{i}_scale")
            g.add_layer(f"a{i}", ActivationLayer(activation="relu"), s)
            return f"a{i}"

        def block_b(x, i):
            b0 = G.conv_bn(x, 128, 1)
            b1 = G.conv_bn(G.conv_bn(G.conv_bn(x, 128, 1), 128, (1, 7)), 128, (7, 1))
            cat = G.cat(f"b{i}_cat", b0, b1)
            up = G.conv_bn(cat, 896, 1, act=None)
            g.add_vertex(f"b{i}_scale", ScaleVertex(scale=0.10), up)
            s = G.add(x, f"b{i}_scale")
            g.add_layer(f"b{i}", ActivationLayer(activation="relu"), s)
            return f"b{i}"

        def block_c(x, i):
            b0 = G.conv_bn(x, 192, 1)
            b1 = G.conv_bn(G.conv_bn(G.conv_bn(x, 192, 1), 192, (1, 3)), 192, (3, 1))
            cat = G.cat(f"c{i}_cat", b0, b1)
            up = G.conv_bn(cat, 1792, 1, act=None)
            g.add_vertex(f"c{i}_scale", ScaleVertex(scale=0.20), up)
            s = G.add(x, f"c{i}_scale")
            g.add_layer(f"c{i}", ActivationLayer(activation="relu"), s)
            return f"c{i}"

        for i in range(self.blocks_a):
            x = block_a(x, i)
        # reduction-A → 896ch
        ra0 = G.pool(x)
        ra1 = G.conv_bn(x, 384, 3, stride=2)
        ra2 = G.conv_bn(G.conv_bn(G.conv_bn(x, 192, 1), 192, 3), 256, 3, stride=2)
        x = G.cat("redA", ra0, ra1, ra2)
        for i in range(self.blocks_b):
            x = block_b(x, i)
        # reduction-B → 1792ch
        rb0 = G.pool(x)
        rb1 = G.conv_bn(G.conv_bn(x, 256, 1), 384, 3, stride=2)
        rb2 = G.conv_bn(G.conv_bn(x, 256, 1), 256, 3, stride=2)
        rb3 = G.conv_bn(G.conv_bn(G.conv_bn(x, 256, 1), 256, 3), 256, 3, stride=2)
        x = G.cat("redB", rb0, rb1, rb2, rb3)
        for i in range(self.blocks_c):
            x = block_c(x, i)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("drop", DropoutLayer(rate=0.2), "gap")
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"), "drop")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("out", OutputLayer(n_in=self.embedding_size,
                                       n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"),
                    "embeddings")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(*self.input_shape))
        return g.build()

    def init(self):
        return ComputationGraph(self.conf()).init()


@dataclass
class FaceNetNN4Small2(ZooModel):
    """FaceNet NN4-small2: GoogLeNet-style inception modules + 128-d
    L2-normalised embedding + center-loss softmax head (reference
    FaceNetNN4Small2, FaceNetHelper inception blocks)."""

    num_classes: int = 1000
    input_shape: Tuple = (96, 96, 3)
    embedding_size: int = 128

    def conf(self):
        g = _graph(self.seed, self.updater, self.compute_dtype, 1e-1)
        G = _G(g)

        def inception(name, inp, c1, c3r, c3, c5r, c5, pp):
            """1x1 + (1x1→3x3) + (1x1→5x5) + (pool→1x1proj) merge."""
            branches = []
            if c1:
                branches.append(G.conv_bn(inp, c1, 1, name=f"{name}_1x1"))
            b3 = G.conv_bn(inp, c3r, 1, name=f"{name}_3r")
            branches.append(G.conv_bn(b3, c3, 3, name=f"{name}_3x3"))
            if c5r:
                b5 = G.conv_bn(inp, c5r, 1, name=f"{name}_5r")
                branches.append(G.conv_bn(b5, c5, 5, name=f"{name}_5x5"))
            p = G.pool(inp, k=3, stride=1, name=f"{name}_pool")
            if pp:
                branches.append(G.conv_bn(p, pp, 1, name=f"{name}_pp"))
            else:
                branches.append(p)
            return G.cat(name, *branches)

        x = G.conv_bn("in", 64, 7, stride=2)
        x = G.pool(x)
        x = G.conv_bn(x, 64, 1)
        x = G.conv_bn(x, 192, 3)
        x = G.pool(x)
        x = inception("3a", x, 64, 96, 128, 16, 32, 32)
        x = inception("3b", x, 64, 96, 128, 32, 64, 64)
        x = G.pool(x)
        x = inception("4a", x, 256, 96, 192, 32, 64, 128)
        x = inception("4e", x, 0, 160, 256, 64, 128, 0)
        x = G.pool(x)
        x = inception("5a", x, 256, 96, 384, 0, 0, 96)
        x = inception("5b", x, 256, 96, 384, 0, 0, 96)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"), "gap")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("out", CenterLossOutputLayer(
            n_in=self.embedding_size, n_out=self.num_classes,
            activation="softmax", loss="mcxent", alpha=0.9, lambda_=2e-4),
            "embeddings")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(*self.input_shape))
        return g.build()

    def init(self):
        return ComputationGraph(self.conf()).init()
