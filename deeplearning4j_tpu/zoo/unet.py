"""UNet — encoder/decoder segmentation network with skip connections.

Reference parity: ``org.deeplearning4j.zoo.model.UNet`` (512x512x3 input,
double-conv blocks 64..1024, up-conv decoder with merge skips, 1x1 sigmoid
conv + per-pixel binary cross-entropy via CnnLossLayer).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax.numpy as jnp

from ..nn.computation_graph import ComputationGraph
from ..nn.conf import NeuralNetConfiguration
from ..nn.layers.base import InputType
from ..nn.layers.conv import (ConvolutionLayer, SubsamplingLayer, Upsampling2D)
from ..nn.layers.core import CnnLossLayer
from ..nn.multi_layer_network import MultiLayerNetwork
from ..nn.vertices import MergeVertex
from ..train.updaters import Adam
from .base import ZooModel


@dataclass
class UNet(ZooModel):
    num_classes: int = 1                 # binary mask (reference UNet)
    input_shape: Tuple = (512, 512, 3)

    def conf(self):
        b = NeuralNetConfiguration.builder().seed(self.seed)
        b.updater(self.updater or Adam(1e-4))
        if self.compute_dtype is not None:
            b.data_type(jnp.float32, self.compute_dtype)
        g = b.graph_builder().add_inputs("in")

        def double_conv(name, inp, n):
            g.add_layer(f"{name}_1", ConvolutionLayer(
                n_out=n, kernel_size=(3, 3), convolution_mode="same",
                activation="relu"), inp)
            g.add_layer(f"{name}_2", ConvolutionLayer(
                n_out=n, kernel_size=(3, 3), convolution_mode="same",
                activation="relu"), f"{name}_1")
            return f"{name}_2"

        # encoder
        skips = []
        x = "in"
        for i, n in enumerate((64, 128, 256, 512)):
            x = double_conv(f"enc{i}", x, n)
            skips.append(x)
            g.add_layer(f"pool{i}", SubsamplingLayer(kernel_size=(2, 2),
                                                     stride=(2, 2)), x)
            x = f"pool{i}"
        x = double_conv("bottom", x, 1024)

        # decoder: upsample + 2x2 conv ("up-conv"), concat skip, double conv
        for i, n in zip(range(3, -1, -1), (512, 256, 128, 64)):
            g.add_layer(f"up{i}_us", Upsampling2D(size=2), x)
            g.add_layer(f"up{i}_conv", ConvolutionLayer(
                n_out=n, kernel_size=(2, 2), convolution_mode="same",
                activation="relu"), f"up{i}_us")
            g.add_vertex(f"cat{i}", MergeVertex(), skips[i], f"up{i}_conv")
            x = double_conv(f"dec{i}", f"cat{i}", n)

        g.add_layer("head", ConvolutionLayer(n_out=2, kernel_size=(3, 3),
                                             convolution_mode="same",
                                             activation="relu"), x)
        g.add_layer("mask", ConvolutionLayer(n_out=self.num_classes,
                                             kernel_size=(1, 1),
                                             convolution_mode="same",
                                             activation="identity"), "head")
        g.add_layer("out", CnnLossLayer(activation="sigmoid", loss="binary_xent"),
                    "mask")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(*self.input_shape))
        return g.build()

    def init(self):
        return ComputationGraph(self.conf()).init()
