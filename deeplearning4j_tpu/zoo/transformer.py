"""Transformer-LM — flagship model for distributed training (tp/sp/ep/dp).

Reference counterpart: DL4J's transformer story is BERT via SameDiff TF
import (attention assembled from SameDiff ops, run per-op on cuDNN). The
TPU-native redesign is a pure-functional GPT-style LM engineered for SPMD:

- params for all L blocks are STACKED (leading L axis) and the blocks run
  under ``lax.scan`` — one compile of one block instead of L inlined copies
  (compile time O(1) in depth; XLA still pipelines the unrolled loop).
- Megatron-style tensor parallel: qkv/mlp-in weights column-sharded over
  'tp', out-proj/mlp-out row-sharded; XLA inserts the two psums per block.
- Sequence parallel: activations sharded over 'sp' on the time axis; the
  attention inner either all-gathers k/v (XLA default) or runs the ring
  kernel (`parallel/ring_attention.py`) when `use_ring_attention`.
- Expert parallel: optional MoE MLP (top-k router, capacity factor,
  einsum dispatch) with experts sharded over 'ep'.
- bf16 activations/f32 params & optimizer; `jax.checkpoint` on each block
  (remat) so long sequences fit HBM.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 8
    d_ff: int = 2048
    max_seq: int = 1024
    n_experts: int = 0          # 0 → dense MLP
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16   # activation/compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    # Fused (chunked) LM cross-entropy: never materializes the full
    # (B, T, V) f32 logits — per time-chunk the head matmul, logsumexp and
    # target gather collapse into one rematerialized scan step. Cuts the
    # dominant HBM traffic of a 32k-vocab loss (logits f32 write+read is
    # ~4 GB/step at B16/T1024) for ~one extra head matmul in backward.
    # True | False | "auto" (fuse when B*T*V is large enough to matter).
    fused_loss: Any = "auto"
    loss_chunk: int = 1024      # rows (B*T) per chunk in the fused loss
    # Rematerialization policy for the per-block checkpoint (remat=True):
    # "full"  — save only block inputs, recompute everything (min HBM)
    # "dots"  — save matmul outputs, recompute elementwise (XLA
    #           checkpoint_policies.dots_saveable: trades HBM for the
    #           cheap recompute only)
    # "dots_no_batch" — dots_with_no_batch_dims_saveable (saves the
    #           small contraction results, not the big batched ones)
    # "save_attn" — save only the attention outputs (checkpoint_name
    #           "attn_out"), recompute the rest: remat-full's HBM saving
    #           without re-running the T² attention op in backward
    remat_policy: str = "full"
    use_ring_attention: bool = False
    # True = always pallas flash kernel (TPU single-chip); False = XLA fused
    # attention; "auto" = flash from `flash_min_seq` up. Measured on v5e
    # (2026-08-01, d_model 512/h8, grad-tuned flash5 blocks — the earlier
    # "XLA wins at short T" result was an artifact of fwd-only autotuning
    # picking 128×128 blocks): full-model train step, flash vs best XLA
    # path, tokens/s — t1024 b16: 221k vs 187k; t4096 b4: 160k vs 87k;
    # t8192 b2: 107k vs 44k (scripts/diag_attn_r5_out.json). Below 1024
    # the XLA bf16-scores path is unmeasured-against and stays default.
    use_flash_attention: Any = "auto"
    flash_min_seq: int = 1024
    # Default-on (r4): materialize attention scores in bf16 instead of f32
    # on the XLA path (matmuls still accumulate f32 in-register; softmax
    # still reduces in f32). Halves the dominant (B,H,T,T) HBM traffic at
    # T<=flash_min_seq for a ~1e-2-relative perturbation of the
    # probabilities — measured +18% MFU at T=1024 on v5e composed with
    # remat-full (scripts/sweep_transformer_out.json). Set False for
    # exact-f32 scores. Ignored when the flash kernel engages (which
    # keeps scores in VMEM and is exact).
    attn_scores_bf16: bool = True
    tie_embeddings: bool = False

    @property
    def head_dim(self):
        return self.d_model // self.n_heads


# ---------------------------------------------------------------- params

def init_params(key, cfg: TransformerConfig):
    """Stacked-block params. Names are stable for checkpoints/sharding."""
    k = jax.random.split(key, 12)
    d, f, h, L = cfg.d_model, cfg.d_ff, cfg.n_heads * cfg.head_dim, cfg.n_layers
    pd = cfg.param_dtype

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, pd) / math.sqrt(fan_in))

    params = {
        "embed": norm(k[0], (cfg.vocab_size, d), d),  # scaled-init embedding
        "pos_embed": 0.02 * jax.random.normal(k[1], (cfg.max_seq, d), pd),
        "blocks": {
            "ln1": jnp.ones((L, d), pd),
            "wqkv": norm(k[2], (L, d, 3 * h), d),
            "wo": norm(k[3], (L, h, d), h),
            "ln2": jnp.ones((L, d), pd),
        },
        "ln_f": jnp.ones((d,), pd),
    }
    if cfg.n_experts:
        E = cfg.n_experts
        params["blocks"]["router"] = norm(k[4], (L, d, E), d)
        params["blocks"]["we_in"] = norm(k[5], (L, E, d, f), d)
        params["blocks"]["we_out"] = norm(k[6], (L, E, f, d), f)
    else:
        params["blocks"]["w_in"] = norm(k[7], (L, d, f), d)
        params["blocks"]["w_out"] = norm(k[8], (L, f, d), f)
    if not cfg.tie_embeddings:
        params["head"] = norm(k[9], (d, cfg.vocab_size), d)
    return params


def draft_config(cfg: TransformerConfig,
                 n_layers: int = 2) -> TransformerConfig:
    """Config for a layer-truncated draft model (ISSUE 19 speculative
    decoding): the target's shape with only the first ``n_layers``
    blocks — everything else (vocab, widths, max_seq, dtypes) must
    match so the draft can share embeddings/head and propose in the
    target's token space."""
    n = int(n_layers)
    if not (1 <= n <= cfg.n_layers):
        raise ValueError(f"draft n_layers={n} outside 1..{cfg.n_layers}")
    return dataclasses.replace(cfg, n_layers=n)


def draft_params(params, cfg: TransformerConfig, n_layers: int = 2):
    """Params for :func:`draft_config`'s truncated draft: the FIRST
    ``n_layers`` slices of the target's stacked block tensors, with
    embed/pos_embed/ln_f/head SHARED (same arrays, no copy) — a free
    draft, no training run needed. Returns ``(draft_cfg,
    draft_params)``. Acceptance depends entirely on how much of the
    target's next-token behaviour the early layers carry; the spec
    promotion race measures it rather than assuming it."""
    dcfg = draft_config(cfg, n_layers)
    blocks = {name: w[:dcfg.n_layers]
              for name, w in params["blocks"].items()}
    out = dict(params, blocks=blocks)
    return dcfg, out


def param_pspecs(cfg: TransformerConfig):
    """PartitionSpecs per param (tp/ep sharding; fsdp composes on top)."""
    specs = {
        "embed": P("tp", None),          # vocab-sharded embedding
        "pos_embed": P(),
        "blocks": {
            "ln1": P(),
            "wqkv": P(None, None, "tp"),   # column parallel
            "wo": P(None, "tp", None),     # row parallel
            "ln2": P(),
        },
        "ln_f": P(),
    }
    if cfg.n_experts:
        specs["blocks"]["router"] = P()
        specs["blocks"]["we_in"] = P(None, "ep", None, "tp")
        specs["blocks"]["we_out"] = P(None, "ep", "tp", None)
    else:
        specs["blocks"]["w_in"] = P(None, None, "tp")
        specs["blocks"]["w_out"] = P(None, "tp", None)
    if not cfg.tie_embeddings:
        specs["head"] = P(None, "tp")
    return specs


def shardings_for(mesh: Mesh, cfg: TransformerConfig, params_like=None):
    specs = param_pspecs(cfg)

    def to_sh(spec):
        spec = P(*(a if (a is None or a in mesh.axis_names) else None
                   for a in spec))
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map(to_sh, specs,
                                  is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------- forward

def _constrain(x, *spec):
    """with_sharding_constraint that silently no-ops outside jit/mesh."""
    try:
        return lax.with_sharding_constraint(x, P(*spec))
    except (ValueError, RuntimeError):
        return x


def flash_engages(cfg, t) -> bool:
    """True when :func:`_attention` will run the pallas flash kernel for a
    length-``t`` sequence under ``cfg`` — THE single gate, shared with the
    bench's analytic flash-flops accounting (the kernel's matmuls are
    invisible to jaxpr flop tracing). Explicit ``True`` engages the kernel
    even off-TPU (interpret mode — slow but correct, and the only way CI
    covers the branch); "auto" stays TPU-only. Single-chip only either
    way: pallas_call has no SPMD partitioning rule, so a tp/sp-sharded
    mesh keeps the XLA fused path (which shards). Ring attention wins
    over flash when both are requested."""
    if cfg.use_ring_attention or jax.device_count() != 1:
        return False
    if cfg.use_flash_attention is True:
        return True
    return (cfg.use_flash_attention == "auto" and t >= cfg.flash_min_seq
            and jax.default_backend() == "tpu")


def _attention(cfg, q, k, v, mask_bias=None):
    b, t = q.shape[0], q.shape[1]
    q = q.reshape(b, t, cfg.n_heads, cfg.head_dim)
    k = k.reshape(b, t, cfg.n_heads, cfg.head_dim)
    v = v.reshape(b, t, cfg.n_heads, cfg.head_dim)
    if cfg.use_ring_attention:
        from ..parallel.ring_attention import ring_attention_inner
        out = ring_attention_inner(q, k, v, causal=True)
    elif flash_engages(cfg, t):
        from ..kernels.flash_attention import flash_attention_ntc
        out = flash_attention_ntc(q, k, v, causal=True)
    elif cfg.attn_scores_bf16 and q.dtype == jnp.bfloat16:
        out = _xla_attention_bf16_scores(q, k, v)
    else:
        out = jax.nn.dot_product_attention(q, k, v, is_causal=True)
    out = checkpoint_name(out, "attn_out")  # remat_policy="save_attn" hook
    return out.reshape(b, t, cfg.n_heads * cfg.head_dim)


def _xla_attention_bf16_scores(q, k, v, causal=True, bias=None):
    """Attention with the (B,H,T,S) score matrix MATERIALIZED bf16:
    the QK^T matmul accumulates f32 in-register (BF16_BF16_F32) but stores
    bf16, and the f32 upcast for the softmax fuses into its reduce — so
    the two T^2 HBM tensors (scores, probs) are half the bytes of the
    stock XLA path's f32 logits. q/k/v are (B, T, H, D). ``bias`` is an
    additive mask broadcastable to (B, H, T, S) (e.g. padding mask −1e9,
    well inside bf16 range)."""
    t = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)  # pre-scale q (exact
    # for power-of-two head dims), so no extra pass over the T^2 logits
    dot_kw = {"preferred_element_type": jnp.bfloat16}
    if jax.default_backend() == "tpu":
        # explicit MXU algorithm: bf16 inputs, f32 in-register accumulate,
        # bf16 store. XLA:CPU rejects this preset outright (tier-1 runs
        # the same path at toy shapes), so off-TPU the einsum falls back
        # to the default algorithm for the dtype — same math, CPU-legal.
        dot_kw["precision"] = lax.DotAlgorithmPreset.BF16_BF16_F32
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, **dot_kw)
    if bias is not None:
        logits = logits + bias.astype(jnp.bfloat16)
    if causal:
        neg = jnp.asarray(jnp.finfo(jnp.bfloat16).min / 2, jnp.bfloat16)
        mask = jnp.tril(jnp.ones((t, t), jnp.bool_))
        logits = jnp.where(mask[None, None, :, :], logits, neg)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1
                           ).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _remat_wrap(fn, policy: str):
    """jax.checkpoint around a block fn under one of the supported
    rematerialization policies (shared by the LM and BERT encoders)."""
    policies = {
        "full": None,
        "dots": jax.checkpoint_policies.dots_saveable,
        "dots_no_batch":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        # "save_attn": save ONLY the attention outputs (B·T·D bf16 — tiny,
        # ~16 MB/layer at T=4096 b4) and recompute everything else. This
        # spares the block's DOWNSTREAM recompute (mlp/norms feeding the
        # loss side) from re-running attention; the gradient THROUGH
        # attention still re-executes the kernel forward to rebuild its
        # unsaved vjp residuals, so the win over remat-full is the
        # downstream share only (measured ~2-3% tokens/s at T=1024-8192,
        # scripts/diag_attn_r5_out.json — consistent, not dramatic).
        "save_attn":
            jax.checkpoint_policies.save_only_these_names("attn_out"),
    }
    if policy not in policies:
        raise ValueError(f"Unknown remat_policy {policy!r}; "
                         f"expected one of {sorted(policies)}")
    pol = policies[policy]
    return jax.checkpoint(fn) if pol is None else jax.checkpoint(fn, policy=pol)


def _rmsnorm(x, scale, eps=1e-6):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * lax.rsqrt(ms + eps) * scale.astype(jnp.float32)).astype(x.dtype)


def _dense_mlp(cfg, x, w_in, w_out):
    h = jnp.einsum("btd,df->btf", x, w_in.astype(x.dtype))
    h = _constrain(h, "dp", "sp", "tp")
    h = jax.nn.gelu(h)
    o = jnp.einsum("btf,fd->btd", h, w_out.astype(x.dtype))
    return o


def _moe_mlp(cfg, x, router, we_in, we_out):
    """Top-k routed MoE with capacity; einsum dispatch (expert axis 'ep').

    Dispatch/combine are one-hot einsums — dense matmuls the MXU likes —
    with all_to_all inserted by XLA from the sharding constraints.
    """
    b, t, d = x.shape
    E = cfg.n_experts
    tokens = x.reshape(b * t, d)
    logits = jnp.einsum("nd,de->ne", tokens.astype(jnp.float32),
                        router.astype(jnp.float32))
    gates = jax.nn.softmax(logits, axis=-1)
    topv, topi = lax.top_k(gates, cfg.expert_top_k)             # (N, K)
    topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
    cap = max(1, int(cfg.capacity_factor * (b * t) * cfg.expert_top_k / E))
    # position of each token within its expert's buffer
    onehot = jax.nn.one_hot(topi, E, dtype=jnp.float32)          # (N, K, E)
    pos = jnp.cumsum(onehot.reshape(-1, E), axis=0).reshape(b * t, -1, E) - 1.0
    keep = (pos < cap) & (onehot > 0)
    disp = (onehot * keep).astype(x.dtype)                       # (N, K, E)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=x.dtype) * disp[..., None]
    # dispatch: (N,K,E,C) x (N,d) → (E,C,d)
    expert_in = jnp.einsum("nkec,nd->ecd", pos_oh, tokens)
    expert_in = _constrain(expert_in, "ep", None, None)
    h = jnp.einsum("ecd,edf->ecf", expert_in, we_in.astype(x.dtype))
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ecf,efd->ecd", h, we_out.astype(x.dtype))
    expert_out = _constrain(expert_out, "ep", None, None)
    combine = (pos_oh * topv[:, :, None, None].astype(x.dtype))
    out = jnp.einsum("nkec,ecd->nd", combine, expert_out)
    # aux load-balancing loss (Switch-style)
    density = onehot.reshape(-1, E).mean(0)
    density_proxy = gates.mean(0)
    aux = E * jnp.sum(density * density_proxy)
    return out.reshape(b, t, d), aux.astype(jnp.float32)


def embed(params, cfg: TransformerConfig, ids, pos_offset=0):
    """ids (B,T) → embedded activations (B,T,d) in compute dtype.

    ``pos_offset`` (static or traced int) shifts the learned position
    table — required when the SEQUENCE is explicitly sharded (shard_map
    ring step): shard i holds global positions [i·T_local, (i+1)·T_local)
    but sees a local (B, T_local) slice."""
    t = ids.shape[1]
    x = jnp.take(params["embed"], ids, axis=0).astype(cfg.dtype)
    x = x * math.sqrt(cfg.d_model)
    pos = lax.dynamic_slice_in_dim(params["pos_embed"],
                                   pos_offset, t, axis=0)
    x = x + pos.astype(cfg.dtype)
    return _constrain(x, "dp", "sp", None)


def _resolve_head(params, cfg: TransformerConfig):
    """(d, V) head matrix — shared by the naive and fused loss paths so
    tie_embeddings/untied resolution can't drift between them."""
    return params.get("head",
                      params["embed"].T if cfg.tie_embeddings else None)


def head_logits(params, cfg: TransformerConfig, x):
    """Final norm + LM head → f32 logits."""
    x = _rmsnorm(x, params["ln_f"])
    head = _resolve_head(params, cfg)
    logits = jnp.einsum("btd,dv->btv", x, head.astype(x.dtype))
    return _constrain(logits, "dp", "sp", "tp").astype(jnp.float32)


def head_logits_rows(params, cfg: TransformerConfig, x):
    """head_logits for (N, d) hidden ROWS (no time axis) → (N, V) f32.
    The serving engine's shape: one row per decode slot / per prefill's
    last position — never the (B, T, V) tensor a generation step doesn't
    need."""
    x = _rmsnorm(x, params["ln_f"])
    head = _resolve_head(params, cfg)
    return jnp.einsum("nd,dv->nv", x, head.astype(x.dtype)
                      ).astype(jnp.float32)


def hidden_rows(params, cfg: TransformerConfig, x):
    """The final-norm hidden rows themselves — (N, d) f32, no head
    matmul. The EMBED workload's representation (ISSUE 20): the same
    post-``ln_f`` activations ``head_logits_rows`` projects, surfaced
    for pooling instead of next-token prediction."""
    return _rmsnorm(x, params["ln_f"]).astype(jnp.float32)


def generate(params, cfg: TransformerConfig, prompt_ids, max_new_tokens=32,
             *, key=None, temperature=0.0, top_k=0, eos_id=None,
             max_len=None):
    """Autoregressive generation from the LM — the zoo-level serving entry
    point. Prefills the prompt into a preallocated KV cache, then decodes
    one token per jitted donated-cache step; ``temperature=0`` is greedy,
    ``top_k`` restricts sampling to the k most likely tokens, and all
    randomness flows from the explicit PRNG ``key``. Returns the generated
    ids (without the prompt) as a numpy array — ``(B, n)`` for a batched
    prompt, ``(n,)`` for a single sequence. For sustained mixed-length
    traffic use ``serving.ContinuousBatchingScheduler`` on top of a shared
    ``serving.GenerationEngine`` instead of this one-shot helper."""
    from ..serving.engine import GenerationEngine
    eng = GenerationEngine(cfg, params, max_len=max_len)
    return eng.generate(prompt_ids, max_new_tokens, key=key,
                        temperature=temperature, top_k=top_k, eos_id=eos_id)


def apply_blocks(blocks, cfg: TransformerConfig, x, *, return_kv=False):
    """Scan the stacked transformer blocks over x. Returns (x, aux_sum).

    ``return_kv=True`` is the serving-plane prefill hook: the SAME block
    math additionally yields each layer's per-head key/value activations,
    stacked ``(L, B, T, H, Dh)`` in compute dtype, and the return becomes
    ``(x, aux_sum, (k, v))``. Remat is skipped on that path — prefill is
    forward-only, there are no residuals to trade for recompute — which
    keeps the captured k/v out of any checkpoint policy's hands."""

    def block(x, blk):
        h = _rmsnorm(x, blk["ln1"])
        qkv = jnp.einsum("btd,dz->btz", h, blk["wqkv"].astype(h.dtype))
        qkv = _constrain(qkv, "dp", "sp", "tp")
        q, k, v = jnp.split(qkv, 3, axis=-1)
        a = _attention(cfg, q, k, v)
        a = jnp.einsum("bth,hd->btd", a, blk["wo"].astype(h.dtype))
        x = x + _constrain(a, "dp", "sp", None)
        h2 = _rmsnorm(x, blk["ln2"])
        if cfg.n_experts:
            m, aux = _moe_mlp(cfg, h2, blk["router"], blk["we_in"], blk["we_out"])
        else:
            m, aux = _dense_mlp(cfg, h2, blk["w_in"], blk["w_out"]), 0.0
        x = x + _constrain(m, "dp", "sp", None)
        kv = None
        if return_kv:
            b, t = x.shape[0], x.shape[1]
            kv = (k.reshape(b, t, cfg.n_heads, cfg.head_dim),
                  v.reshape(b, t, cfg.n_heads, cfg.head_dim))
        return x, (aux, kv)

    blk_fn = block if (return_kv or not cfg.remat) \
        else _remat_wrap(block, cfg.remat_policy)

    def scan_body(carry, blk):
        x = carry
        x, ys = blk_fn(x, blk)
        return x, ys

    x, (auxes, kvs) = lax.scan(scan_body, x, blocks)
    if return_kv:
        return x, jnp.sum(auxes), kvs
    return x, jnp.sum(auxes)


def forward(params, cfg: TransformerConfig, ids, *, train=False, rng=None,
            pos_offset=0):
    """ids (B, T) int32 → logits (B, T, vocab). Returns (logits, aux_loss)."""
    x = embed(params, cfg, ids, pos_offset)
    x, aux = apply_blocks(params["blocks"], cfg, x)
    return head_logits(params, cfg, x), aux


def _use_fused_loss(cfg: TransformerConfig, n_rows: int) -> bool:
    if cfg.fused_loss is True:
        return True
    if cfg.fused_loss is False:
        return False
    # "auto": fuse once the f32 logits tensor would exceed ~64 MB — below
    # that XLA's ordinary fusion handles it and chunking only adds scan
    # overhead
    return n_rows * cfg.vocab_size * 4 > 64 * 2 ** 20


def _chunked_ce(x, head, targets, chunk, weights=None, bias=None):
    """WEIGHTED-SUM NLL of (N, d) hidden rows against (N,) targets WITHOUT
    materializing the (N, V) f32 logits: scan over row chunks; each step
    is rematerialized so backward recomputes the chunk's logits from the
    (small) saved hidden rows instead of saving V-wide activations.
    Returns sum(w·nll) — the caller divides by its own denominator.
    ``weights`` default to 1 per row; ``bias`` (V,) supports BERT's MLM
    output bias."""
    n, d = x.shape
    chunk = min(chunk, n)
    pad = (-n) % chunk
    w = (jnp.ones((n,), jnp.float32) if weights is None
         else weights.astype(jnp.float32))
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, d), x.dtype)])
        targets = jnp.concatenate(
            [targets, jnp.zeros((pad,), targets.dtype)])
        w = jnp.concatenate([w, jnp.zeros((pad,), jnp.float32)])
    xk = x.reshape(-1, chunk, d)
    tk = targets.reshape(-1, chunk)
    wk = w.reshape(-1, chunk)

    @jax.checkpoint
    def chunk_nll(xc, tc, wc):
        logits = jnp.einsum("cd,dv->cv", xc, head).astype(jnp.float32)
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(
            logits, tc[:, None].astype(jnp.int32), -1)[:, 0]
        return ((lse - tl) * wc).sum()      # pad rows weighted out

    def body(carry, sl):
        xc, tc, wc = sl
        return carry + chunk_nll(xc, tc, wc), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xk, tk, wk))
    return total


def lm_loss(params, cfg: TransformerConfig, ids, targets, *, aux_weight=1e-2,
            pos_offset=0):
    b, t = ids.shape
    if _use_fused_loss(cfg, b * t):
        x = embed(params, cfg, ids, pos_offset)
        x, aux = apply_blocks(params["blocks"], cfg, x)
        x = _rmsnorm(x, params["ln_f"])
        head = _resolve_head(params, cfg)
        nll = _chunked_ce(x.reshape(b * t, -1), head.astype(x.dtype),
                          targets.reshape(b * t), cfg.loss_chunk) / (b * t)
        return nll + aux_weight * aux
    logits, aux = forward(params, cfg, ids, train=True, pos_offset=pos_offset)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32), -1)[..., 0]
    return nll.mean() + aux_weight * aux


def make_train_step(cfg: TransformerConfig, optimizer):
    """One jitted step: grads → optax update → new params. Shard via the
    caller's jit(in_shardings=...) or run as-is on one device."""

    def step(params, opt_state, ids, targets):
        loss, grads = jax.value_and_grad(lm_loss)(params, cfg, ids, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax as _optax
        params = _optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def make_ring_train_step(cfg: TransformerConfig, optimizer, mesh: Mesh):
    """Training step with EXPLICIT ring sequence parallelism: the whole
    loss+grad runs under ``shard_map`` over the mesh's ('dp', 'sp') axes.
    Data (B, T) is sharded batch-over-dp and SEQUENCE-over-sp; params and
    optimizer state are replicated. Inside the mapped region
    `cfg.use_ring_attention` routes attention onto the ppermute ring
    (parallel/ring_attention.py — the (T,T) score matrix never exists on
    any one device), the position table is indexed at each shard's global
    offset, and loss/grads are pmean'd over both axes so the update is
    identical to a monolithic step up to float reassociation.

    Dense blocks only (MoE expert dispatch needs the 'ep' axis plumbing
    of the GSPMD path); requires cfg.use_ring_attention=True so the
    single-device fallback of `_attention` can never silently run full
    attention per shard."""
    if not cfg.use_ring_attention:
        raise ValueError("make_ring_train_step requires "
                         "cfg.use_ring_attention=True")
    if getattr(cfg, "n_experts", 0):
        raise NotImplementedError(
            "ring step is dense-only; MoE routes through the GSPMD path "
            "(make_train_step under jit with shardings_for)")
    from .._jax_compat import shard_map
    import optax as _optax

    def local_step(params, opt_state, ids, targets):
        t_local = ids.shape[1]
        pos_offset = lax.axis_index("sp") * t_local

        def loss_fn(p):
            return lm_loss(p, cfg, ids, targets, pos_offset=pos_offset)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        loss = lax.pmean(loss, ("dp", "sp"))
        grads = lax.pmean(grads, ("dp", "sp"))
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = _optax.apply_updates(params, updates)
        return params, opt_state, loss

    def step(params, opt_state, ids, targets):
        # dynamic_slice would silently CLAMP an out-of-table position
        # offset (shards would reuse the last rows instead of failing the
        # way the monolithic path does) — reject at trace time instead
        if ids.shape[1] > cfg.max_seq:
            raise ValueError(
                f"global sequence length {ids.shape[1]} exceeds "
                f"cfg.max_seq={cfg.max_seq}: position offsets past the "
                "table would clamp, not error")
        rep = jax.tree_util.tree_map(lambda _: P(), params)
        rep_opt = jax.tree_util.tree_map(lambda _: P(), opt_state)
        return shard_map(
            local_step, mesh=mesh,
            in_specs=(rep, rep_opt, P("dp", "sp"), P("dp", "sp")),
            out_specs=(rep, rep_opt, P()),
            check_vma=False,  # optax update replication is data-dependent
        )(params, opt_state, ids, targets)

    return jax.jit(step, donate_argnums=(0, 1))


# ------------------------------------------------------------- BERT family

@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_seq: int = 512
    type_vocab: int = 2
    num_labels: int = 2
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # r5: the transformer-LM sweep's two HBM cuts, applied to the encoder
    # (VERDICT r4 item 5). Defaults off = r4 behavior; bench flips both.
    remat: bool = False
    # "full" | "dots" | "dots_no_batch" | "save_attn" (pin the attention
    # outputs via checkpoint_name — see _remat_wrap)
    remat_policy: str = "full"
    attn_scores_bf16: bool = False


def bert_init(key, cfg: BertConfig):
    """BERT-base encoder (reference: SameDiff TF-import BERT path —
    BASELINE.json config 4). Bidirectional attention, learned positions,
    pooler + classification head for fine-tune."""
    k = jax.random.split(key, 8)
    d, f, h, L = cfg.d_model, cfg.d_ff, cfg.d_model, cfg.n_layers

    def norm(key, shape, fan_in):
        return jax.random.normal(key, shape, cfg.param_dtype) / math.sqrt(fan_in)

    return {
        "embed": norm(k[0], (cfg.vocab_size, d), d),
        "pos_embed": 0.02 * jax.random.normal(k[1], (cfg.max_seq, d), cfg.param_dtype),
        "type_embed": 0.02 * jax.random.normal(k[2], (cfg.type_vocab, d), cfg.param_dtype),
        "blocks": {
            "ln1": jnp.ones((L, d), cfg.param_dtype),
            "wqkv": norm(k[3], (L, d, 3 * h), d),
            "wo": norm(k[4], (L, h, d), h),
            "ln2": jnp.ones((L, d), cfg.param_dtype),
            "w_in": norm(k[5], (L, d, f), d),
            "w_out": norm(k[6], (L, f, d), f),
        },
        "pooler": norm(k[7], (d, d), d),
        "cls": jnp.zeros((d, cfg.num_labels), cfg.param_dtype),
        # MLM head: transform dense + norm scale + decoder bias; the decoder
        # weight is TIED to the token embedding (upstream BERT convention —
        # reference: BertIterator MLM pretraining task, SURVEY §2.7).
        "mlm_dense": norm(jax.random.fold_in(k[7], 1), (d, d), d),
        "mlm_ln": jnp.ones((d,), cfg.param_dtype),
        "mlm_bias": jnp.zeros((cfg.vocab_size,), cfg.param_dtype),
    }


def bert_forward(params, cfg: BertConfig, ids, type_ids=None, attn_mask=None):
    b, t = ids.shape
    x = jnp.take(params["embed"], ids, axis=0).astype(cfg.dtype)
    x = x + params["pos_embed"][:t].astype(cfg.dtype)
    if type_ids is not None:
        x = x + jnp.take(params["type_embed"], type_ids, axis=0).astype(cfg.dtype)
    nh = cfg.n_heads
    hd = cfg.d_model // nh
    bias = None
    if attn_mask is not None:
        bias = jnp.where(attn_mask[:, None, None, :] > 0, 0.0, -1e9).astype(jnp.float32)

    def block(x, blk):
        h = _rmsnorm(x, blk["ln1"])
        qkv = jnp.einsum("btd,dz->btz", h, blk["wqkv"].astype(h.dtype))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, nh, hd)
        k = k.reshape(b, t, nh, hd)
        v = v.reshape(b, t, nh, hd)
        if cfg.attn_scores_bf16 and q.dtype == jnp.bfloat16:
            a = _xla_attention_bf16_scores(q, k, v, causal=False, bias=bias
                                           ).reshape(b, t, nh * hd)
        else:
            kw = {}
            if bias is not None:
                kw["bias"] = jnp.broadcast_to(bias, (b, nh, t, t))
            a = jax.nn.dot_product_attention(q, k, v, **kw
                                             ).reshape(b, t, nh * hd)
        a = checkpoint_name(a, "attn_out")  # remat_policy="save_attn" hook
        x = x + jnp.einsum("bth,hd->btd", a, blk["wo"].astype(h.dtype))
        h2 = _rmsnorm(x, blk["ln2"])
        m = jnp.einsum("btf,fd->btd",
                       jax.nn.gelu(jnp.einsum("btd,df->btf", h2,
                                              blk["w_in"].astype(h2.dtype))),
                       blk["w_out"].astype(h2.dtype))
        return x + m, 0.0

    if cfg.remat:
        block = _remat_wrap(block, cfg.remat_policy)
    x, _ = lax.scan(block, x, params["blocks"])
    pooled = jnp.tanh(x[:, 0] @ params["pooler"].astype(x.dtype))
    logits = pooled @ params["cls"].astype(x.dtype)
    return logits.astype(jnp.float32), x


def bert_classifier_loss(params, cfg: BertConfig, ids, labels, type_ids=None,
                         attn_mask=None):
    """labels: integer class ids (B,) or one-hot (B, num_labels) — the
    latter is what BertIterator emits (reference MultiDataSet contract)."""
    logits, _ = bert_forward(params, cfg, ids, type_ids, attn_mask)
    logp = jax.nn.log_softmax(logits, -1)
    if labels.ndim == 2:
        return -(logp * labels.astype(logp.dtype)).sum(-1).mean()
    return -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), -1).mean()


# ---------------------------------------------------------------- BERT MLM
def bert_mlm_logits(params, cfg: BertConfig, hidden):
    """MLM decoder over final hidden states: dense+gelu+norm, then project
    onto the TIED token embedding + bias. (B, T, vocab) float32 logits."""
    h = jax.nn.gelu(hidden @ params["mlm_dense"].astype(hidden.dtype))
    h = _rmsnorm(h, params["mlm_ln"])
    logits = jnp.einsum("btd,vd->btv", h, params["embed"].astype(h.dtype))
    return (logits + params["mlm_bias"].astype(logits.dtype)).astype(jnp.float32)


def bert_mask_tokens(key, ids, cfg: BertConfig, mask_token_id,
                     mask_prob: float = 0.15, special_mask=None):
    """Standard BERT masking (80% [MASK] / 10% random / 10% keep).

    Returns (masked_ids, labels, weights): `labels` are the original ids,
    `weights` 1.0 at selected positions. jit-friendly (static shapes, no
    data-dependent control flow). `special_mask` (B, T) bool marks positions
    never selected (CLS/SEP/PAD).
    """
    k_sel, k_op, k_rand = jax.random.split(key, 3)
    sel = jax.random.uniform(k_sel, ids.shape) < mask_prob
    if special_mask is not None:
        sel = jnp.logical_and(sel, jnp.logical_not(special_mask))
    op = jax.random.uniform(k_op, ids.shape)
    rand_ids = jax.random.randint(k_rand, ids.shape, 0, cfg.vocab_size)
    masked = jnp.where(op < 0.8, mask_token_id,
                       jnp.where(op < 0.9, rand_ids, ids))
    masked_ids = jnp.where(sel, masked, ids)
    return masked_ids, ids, sel.astype(jnp.float32)


def bert_mlm_loss(params, cfg: BertConfig, masked_ids, labels, weights,
                  type_ids=None, attn_mask=None, fused: bool = True):
    """Weighted cross-entropy over masked positions only. ``fused`` routes
    through the chunked CE (no (B, T, V) f32 logits materialized — the MLM
    decoder's dense+norm runs full-size, only the vocab projection is
    chunked)."""
    _, hidden = bert_forward(params, cfg, masked_ids, type_ids, attn_mask)
    denom = jnp.maximum(weights.sum(), 1.0)
    if fused:
        h = jax.nn.gelu(hidden @ params["mlm_dense"].astype(hidden.dtype))
        h = _rmsnorm(h, params["mlm_ln"])
        b, t, d = h.shape
        total = _chunked_ce(
            h.reshape(b * t, d), params["embed"].T.astype(h.dtype),
            labels.reshape(b * t), 1024,
            weights=weights.reshape(b * t), bias=params["mlm_bias"])
        return total / denom
    logp = jax.nn.log_softmax(bert_mlm_logits(params, cfg, hidden), -1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                               -1)[..., 0]
    return (nll * weights).sum() / denom


def make_bert_mlm_train_step(cfg: BertConfig, optimizer, mask_token_id,
                             mask_prob: float = 0.15, special_ids=None):
    """Jittable MLM pretrain step: (params, opt_state, rng, ids) ->
    (params, opt_state, rng, loss). Masking happens on-device inside jit.
    `special_ids` (e.g. PAD/CLS/SEP ids) are never selected as MLM targets;
    pass `attn_mask` so attention ignores padding (BertIterator provides
    both)."""
    import optax

    specials = (None if special_ids is None
                else jnp.asarray(list(special_ids), jnp.int32))

    def step(params, opt_state, rng, ids, type_ids=None, attn_mask=None):
        rng, sub = jax.random.split(rng)
        special_mask = (None if specials is None
                        else jnp.isin(ids, specials))
        masked_ids, labels, weights = bert_mask_tokens(
            sub, ids, cfg, mask_token_id, mask_prob,
            special_mask=special_mask)
        loss, grads = jax.value_and_grad(bert_mlm_loss)(
            params, cfg, masked_ids, labels, weights, type_ids, attn_mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, rng, loss

    return step
