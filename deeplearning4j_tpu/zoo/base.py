"""ZooModel API — parity with ``org.deeplearning4j.zoo.ZooModel`` /
``org.deeplearning4j.zoo.model.*``.

Each model class exposes ``conf()`` (the network configuration),
``init() -> network`` and ``init_pretrained(path)`` (local weights — the
sandbox has no egress, so pretrained loading reads a local checkpoint rather
than downloading like the reference's initPretrained()).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple


@dataclass
class ZooModel:
    num_classes: int = 1000
    seed: int = 123
    input_shape: Tuple = ()          # (H, W, C) NHWC or model-specific
    updater: Any = None
    compute_dtype: Any = None        # e.g. jnp.bfloat16

    def conf(self):
        raise NotImplementedError

    def init(self):
        raise NotImplementedError

    def init_pretrained(self, path):
        """Load weights from a local ModelSerializer zip (offline analogue
        of the reference's pretrained-download path)."""
        from ..serde.model_serializer import load_model
        return load_model(path)

    def meta_data(self) -> dict:
        net = self.init()
        return {"name": type(self).__name__, "num_params": net.num_params(),
                "input_shape": self.input_shape, "num_classes": self.num_classes}
