"""ZooModel API — parity with ``org.deeplearning4j.zoo.ZooModel`` /
``org.deeplearning4j.zoo.model.*``.

Each model class exposes ``conf()`` (the network configuration),
``init() -> network`` and ``init_pretrained(path)`` (local weights — the
sandbox has no egress, so pretrained loading reads a local checkpoint rather
than downloading like the reference's initPretrained()).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple


@dataclass
class ZooModel:
    num_classes: int = 1000
    seed: int = 123
    input_shape: Tuple = ()          # (H, W, C) NHWC or model-specific
    updater: Any = None
    compute_dtype: Any = None        # e.g. jnp.bfloat16

    def conf(self):
        raise NotImplementedError

    def init(self):
        raise NotImplementedError

    def init_pretrained(self, path):
        """Load a local pretrained checkpoint (offline analogue of the
        reference's pretrained-download path): a ModelSerializer zip, or a
        keras .h5/.hdf5 file routed through the keras importer."""
        if str(path).endswith((".h5", ".hdf5")):
            import json

            import h5py

            from ..import_.keras import (import_keras_model,
                                         import_keras_sequential)
            with h5py.File(path, "r") as f:   # route EXPLICITLY by class
                raw = f.attrs["model_config"]
                cls = json.loads(
                    raw.decode() if isinstance(raw, bytes) else raw
                )["class_name"]
            if cls == "Sequential":
                return import_keras_sequential(path)
            return import_keras_model(path)
        from ..serde.model_serializer import load_model
        return load_model(path)

    def meta_data(self) -> dict:
        net = self.init()
        return {"name": type(self).__name__, "num_params": net.num_params(),
                "input_shape": self.input_shape, "num_classes": self.num_classes}
