"""Object-detection zoo models — TinyYOLO and YOLO2.

Reference parity: ``org.deeplearning4j.zoo.model.{TinyYOLO, YOLO2}``.
Topologies match the reference (Darknet backbones + Yolo2OutputLayer);
layout is NHWC, passthrough reorg uses SpaceToDepth, compute can run bf16
on the MXU via ``compute_dtype``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence, Tuple

from ..nn.computation_graph import ComputationGraph
from ..nn.conf import NeuralNetConfiguration
from ..nn.layers.base import InputType
from ..nn.layers.conv import (ConvolutionLayer, SpaceToDepthLayer,
                              SubsamplingLayer)
from ..nn.layers.core import ActivationLayer
from ..nn.layers.norm import BatchNormalization
from ..nn.layers.objdetect import Yolo2OutputLayer
from ..nn.multi_layer_network import MultiLayerNetwork
from ..nn.vertices import MergeVertex
from ..train.updaters import Adam
from .base import ZooModel

# reference anchor priors (grid units), TinyYOLO/YOLO2 defaults
TINY_YOLO_ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38),
                     (9.42, 5.11), (16.62, 10.52))
YOLO2_ANCHORS = ((0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
                 (7.88282, 3.52778), (9.77052, 9.16828))


def _builder(seed, updater, compute_dtype):
    import jax.numpy as jnp
    b = NeuralNetConfiguration.builder().seed(seed)
    b.updater(updater or Adam(1e-3))
    if compute_dtype is not None:
        b.data_type(jnp.float32, compute_dtype)
    return b


@dataclass
class TinyYOLO(ZooModel):
    """TinyYOLO (YOLOv2-tiny on Darknet-tiny): 8 conv-BN-leaky blocks with
    maxpool downsampling + 1x1 detection conv + Yolo2OutputLayer."""

    num_classes: int = 20                  # VOC
    input_shape: Tuple = (416, 416, 3)
    anchors: Sequence[Tuple[float, float]] = TINY_YOLO_ANCHORS

    def conf(self):
        b = _builder(self.seed, self.updater, self.compute_dtype).list()

        def conv_bn(n):
            b.layer(ConvolutionLayer(n_out=n, kernel_size=(3, 3),
                                     convolution_mode="same",
                                     activation="identity", has_bias=False))
            b.layer(BatchNormalization())
            b.layer(ActivationLayer(activation="leakyrelu"))

        for i, n in enumerate((16, 32, 64, 128, 256, 512)):
            conv_bn(n)
            stride = 1 if i == 5 else 2
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(stride, stride),
                                     convolution_mode="same"))
        conv_bn(1024)
        conv_bn(1024)
        n_a = len(self.anchors)
        b.layer(ConvolutionLayer(n_out=n_a * (5 + self.num_classes),
                                 kernel_size=(1, 1), convolution_mode="same",
                                 activation="identity"))
        b.layer(Yolo2OutputLayer(anchors=list(self.anchors)))
        b.set_input_type(InputType.convolutional(*self.input_shape))
        return b.build()

    def init(self):
        return MultiLayerNetwork(self.conf()).init()


@dataclass
class YOLO2(ZooModel):
    """YOLOv2 on Darknet-19 with the passthrough (reorg) connection: the
    1/16-resolution 512-channel map is squeezed to 64 channels by a 1x1
    conv, SpaceToDepth'd to 1/32 resolution x 256 channels, and merged with
    the 1024-channel head before detection. (This is the original Darknet
    yolov2.cfg passthrough; the reference's YOLO2 reorgs the 512-channel
    map directly without the 1x1 squeeze — same connectivity, wider merge.)"""

    num_classes: int = 80                  # COCO
    input_shape: Tuple = (608, 608, 3)
    anchors: Sequence[Tuple[float, float]] = YOLO2_ANCHORS

    def conf(self):
        g = (_builder(self.seed, self.updater, self.compute_dtype)
             .graph_builder().add_inputs("in"))
        idx = [0]

        def conv_bn(inp, n, k):
            name = f"c{idx[0]}"
            idx[0] += 1
            g.add_layer(f"{name}_conv",
                        ConvolutionLayer(n_out=n, kernel_size=(k, k),
                                         convolution_mode="same",
                                         activation="identity", has_bias=False), inp)
            g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
            g.add_layer(name, ActivationLayer(activation="leakyrelu"), f"{name}_bn")
            return name

        def pool(inp):
            name = f"p{idx[0]}"
            idx[0] += 1
            g.add_layer(name, SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), inp)
            return name

        # Darknet-19 feature extractor
        x = conv_bn("in", 32, 3)
        x = pool(x)
        x = conv_bn(x, 64, 3)
        x = pool(x)
        for trio in ((128, 64, 128), (256, 128, 256)):
            x = conv_bn(x, trio[0], 3)
            x = conv_bn(x, trio[1], 1)
            x = conv_bn(x, trio[2], 3)
            x = pool(x)
        x = conv_bn(x, 512, 3)
        x = conv_bn(x, 256, 1)
        x = conv_bn(x, 512, 3)
        x = conv_bn(x, 256, 1)
        passthrough = conv_bn(x, 512, 3)   # 1/16 res, 512ch
        x = pool(passthrough)
        x = conv_bn(x, 1024, 3)
        x = conv_bn(x, 512, 1)
        x = conv_bn(x, 1024, 3)
        x = conv_bn(x, 512, 1)
        x = conv_bn(x, 1024, 3)
        # detection head
        x = conv_bn(x, 1024, 3)
        x = conv_bn(x, 1024, 3)
        # passthrough: 1x1 squeeze + reorg to the head's resolution
        pt = conv_bn(passthrough, 64, 1)
        g.add_layer("reorg", SpaceToDepthLayer(block_size=2), pt)
        g.add_vertex("merge", MergeVertex(), "reorg", x)
        x = conv_bn("merge", 1024, 3)
        n_a = len(self.anchors)
        g.add_layer("det_conv",
                    ConvolutionLayer(n_out=n_a * (5 + self.num_classes),
                                     kernel_size=(1, 1), convolution_mode="same",
                                     activation="identity"), x)
        g.add_layer("out", Yolo2OutputLayer(anchors=list(self.anchors)), "det_conv")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(*self.input_shape))
        return g.build()

    def init(self):
        return ComputationGraph(self.conf()).init()
