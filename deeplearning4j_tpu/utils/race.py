"""Race-detection analogue — donation/aliasing + async-pipeline auditing.

Reference counterpart: DL4J's workspace validation
(``MemoryWorkspace`` leak/scope checks, ``DebugMode``) and the async
iterator's queue invariants — the JVM relies on the workspace manager to
catch a buffer used outside its lifecycle. On TPU the analogous hazards are:

1. **Buffer donation**: ``jit(..., donate_argnums=...)`` lets XLA reuse input
   HBM for outputs. Passing the SAME array in a donated and a non-donated
   slot (or twice in donated slots), or touching a donated array after the
   call, is the TPU's use-after-free.
2. **Async prefetch**: the native SPSC ring hands byte slots between a
   producer thread and the consumer; a slot overwritten while still being
   read is a torn batch (silent data corruption, not a crash).

This module makes both failure modes loud and testable.
"""

from __future__ import annotations

import hashlib
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np


# --------------------------------------------------------------------------
# Donation / aliasing checks.
# --------------------------------------------------------------------------

@dataclass
class AliasViolation:
    kind: str          # "dup-donated" | "donated-aliases-kept" | "use-after-donate"
    detail: str

    def __str__(self):
        return f"[{self.kind}] {self.detail}"


def _buffer_key(leaf):
    """Identity key for a device BUFFER (not the Python wrapper): two
    distinct jax.Array objects can alias one buffer (no-copy device_put,
    tree re-wraps), so id(leaf) would miss exactly the aliases that
    matter. Keyed by (device, address) — per-chip address spaces can reuse
    numeric addresses. Falls back to ("py-id", id) where the pointer is
    unavailable (multi-device arrays, tracers); the tag keeps the two key
    spaces from colliding."""
    if isinstance(leaf, jax.Array):
        try:
            return (leaf.device, leaf.unsafe_buffer_pointer())
        except Exception:  # noqa: BLE001
            return ("py-id", id(leaf))
    return None


def check_donation_aliasing(args: Sequence[Any],
                            donate_argnums: Sequence[int]) -> List[AliasViolation]:
    """Static check BEFORE a donated call: no buffer may appear both in a
    donated argument and anywhere else. XLA would either refuse the alias or
    silently copy; either way the program is wrong about its memory model."""
    donate = set(donate_argnums)
    donated_ids, kept_ids = {}, {}
    out: List[AliasViolation] = []
    for i, arg in enumerate(args):
        for path, leaf in jax.tree_util.tree_flatten_with_path(arg)[0]:
            key = _buffer_key(leaf)
            if key is None:
                continue
            label = f"arg{i}{jax.tree_util.keystr(path)}"
            if i in donate:
                if key in donated_ids:
                    out.append(AliasViolation(
                        "dup-donated",
                        f"{label} and {donated_ids[key]} are the same buffer, "
                        f"both donated"))
                else:
                    donated_ids[key] = label
            else:
                kept_ids.setdefault(key, label)
    for key, label in donated_ids.items():
        if key in kept_ids:
            out.append(AliasViolation(
                "donated-aliases-kept",
                f"{label} (donated) is the same buffer as {kept_ids[key]} (kept)"))
    return out


def assert_live(tree, name: str = "tree") -> None:
    """Raise if any leaf was donated (deleted) by a previous jit call —
    the explicit use-after-donate probe."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if isinstance(leaf, jax.Array) and leaf.is_deleted():
            raise RuntimeError(
                f"use-after-donate: {name}{jax.tree_util.keystr(path)} was "
                f"donated to a previous jitted call and its buffer is gone")


class DonationGuard:
    """Wrap a jitted-with-donation step function; every call first runs the
    aliasing check and a liveness check on donated inputs, then records what
    was donated so later misuse raises with a helpful message.

        step = jax.jit(train_step, donate_argnums=(0, 1))
        guarded = DonationGuard(step, donate_argnums=(0, 1))
        params, opt_state = guarded(params, opt_state, batch)
    """

    def __init__(self, fn: Callable, donate_argnums: Sequence[int],
                 strict: bool = True):
        self.fn = fn
        self.donate_argnums = tuple(donate_argnums)
        self.strict = strict
        self.violations: List[AliasViolation] = []

    def __call__(self, *args, **kwargs):
        for i in self.donate_argnums:
            if i < len(args):
                try:
                    assert_live(args[i], name=f"arg{i}")
                except RuntimeError as e:
                    self.violations.append(AliasViolation("use-after-donate", str(e)))
                    if self.strict:
                        raise
        found = check_donation_aliasing(args, self.donate_argnums)
        self.violations.extend(found)
        if found and self.strict:
            raise RuntimeError("donation aliasing violation(s):\n  " +
                               "\n  ".join(map(str, found)))
        return self.fn(*args, **kwargs)


# --------------------------------------------------------------------------
# Async-pipeline (ring buffer) auditing.
# --------------------------------------------------------------------------

class RaceCheckedRing:
    """Wrap any SPSC ring exposing push(bytes)->bool / pop()->bytes|None with
    shadow sequence + checksum tracking. Detects, at pop time:

    - **reorder**: payloads coming out in a different order than pushed
    - **corruption/torn read**: checksum mismatch (slot overwritten while
      being read, or partial copy)
    - **phantom**: a pop that was never pushed

    Shadow state lives host-side under a lock; the wrapped ring keeps its
    lock-free fast path (the audit is for tests/debug runs, like the
    reference's workspace DebugMode, not production).
    """

    def __init__(self, inner):
        self.inner = inner
        self._lock = threading.Lock()
        self._expected: deque[Tuple[int, bytes]] = deque()
        self._seq = 0
        self.errors: List[str] = []

    @staticmethod
    def _digest(payload: bytes) -> bytes:
        return hashlib.blake2b(payload, digest_size=16).digest()

    def push(self, payload: bytes) -> bool:
        ok = self.inner.push(payload)
        if ok:
            with self._lock:
                self._expected.append((self._seq, self._digest(payload)))
                self._seq += 1
        return ok

    def pop(self):
        raw = self.inner.pop()
        if raw is None:
            return None
        with self._lock:
            if not self._expected:
                self.errors.append("phantom pop: ring returned data never pushed")
                return raw
            seq, digest = self._expected.popleft()
            if self._digest(raw) != digest:
                self.errors.append(
                    f"payload {seq}: checksum mismatch — slot overwritten or "
                    f"torn read (got {len(raw)} bytes)")
        return raw

    def close(self):
        return self.inner.close()

    def assert_clean(self):
        if self.errors:
            raise RuntimeError("ring race audit failed:\n  " + "\n  ".join(self.errors))


def audit_async_iterator(make_inner: Callable[[], Any], *, queue_size: int = 4,
                         use_native: bool = True, epochs: int = 2) -> None:
    """End-to-end race audit of AsyncDataSetIterator: run `epochs` epochs
    async and verify every epoch yields exactly the serial iterator's batches
    (count + content). Raises on loss, duplication, reordering or corruption.

    The serial oracle run is what the reference's tests do with
    AsyncDataSetIterator vs its wrapped iterator.
    """
    from ..data.async_iter import AsyncDataSetIterator

    oracle = [(np.asarray(ds.features).copy(), np.asarray(ds.labels).copy())
              for ds in make_inner()]

    it = AsyncDataSetIterator(make_inner(), queue_size=queue_size,
                              use_native=use_native)
    try:
        for epoch in range(epochs):
            got = [(np.asarray(ds.features), np.asarray(ds.labels)) for ds in it]
            if len(got) != len(oracle):
                raise RuntimeError(
                    f"epoch {epoch}: async yielded {len(got)} batches, "
                    f"serial oracle has {len(oracle)} (lost/duplicated batch)")
            for i, ((gf, gl), (of, ol)) in enumerate(zip(got, oracle)):
                if gf.shape != of.shape or not np.array_equal(gf, of):
                    raise RuntimeError(f"epoch {epoch} batch {i}: features "
                                       f"corrupted or reordered")
                if not np.array_equal(gl, ol):
                    raise RuntimeError(f"epoch {epoch} batch {i}: labels "
                                       f"corrupted or reordered")
            it.reset()
    finally:
        it.close()
