"""ctypes bindings for the native runtime (native/dl4j_tpu_native.cpp).

Builds the .so on first use if g++ is available; every caller has a pure-
Python fallback, so the framework works without the native lib (slower
pipeline, same results).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libdl4j_tpu_native.so"
_lib = None
_tried = False


def load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _LIB_PATH.exists():
        try:
            subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                           capture_output=True, timeout=120)
        except Exception:  # noqa: BLE001 — fall back to pure python
            return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None
    lib.ring_create.restype = ctypes.c_void_p
    lib.ring_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.ring_destroy.argtypes = [ctypes.c_void_p]
    lib.ring_push.restype = ctypes.c_int
    lib.ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.ring_pop.restype = ctypes.c_int64
    lib.ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
    lib.ring_size.restype = ctypes.c_uint64
    lib.ring_size.argtypes = [ctypes.c_void_p]
    lib.threshold_encode.restype = ctypes.c_int64
    lib.threshold_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_float,
        ctypes.c_void_p, ctypes.c_int64]
    lib.threshold_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_float,
        ctypes.c_void_p, ctypes.c_int64]
    lib.parse_csv_floats.restype = ctypes.c_int64
    lib.parse_csv_floats.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
    lib.f32_to_bf16.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
    lib.arena_create.restype = ctypes.c_void_p
    lib.arena_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.arena_destroy.argtypes = [ctypes.c_void_p]
    lib.arena_alloc.restype = ctypes.c_void_p
    lib.arena_alloc.argtypes = [ctypes.c_void_p]
    lib.arena_free.restype = ctypes.c_int
    lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    for fn in ("arena_block_size", "arena_in_use", "arena_peak"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.npy_parse_header.restype = ctypes.c_int
    lib.npy_parse_header.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.parse_csv_matrix.restype = ctypes.c_int64
    lib.parse_csv_matrix.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int64]
    _lib = lib
    return _lib


def has_native() -> bool:
    return load() is not None


class NativeRing:
    """SPSC ring of byte slots (AsyncDataSetIterator backing store)."""

    def __init__(self, slot_size: int, n_slots: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native lib unavailable")
        self._lib = lib
        self._ptr = lib.ring_create(slot_size, n_slots)
        if not self._ptr:
            raise MemoryError("ring_create failed")
        self.slot_size = slot_size

    def push(self, payload: bytes) -> bool:
        rc = self._lib.ring_push(self._ptr, payload, len(payload))
        if rc == -1:
            raise ValueError(f"payload {len(payload)} > slot {self.slot_size}")
        return rc == 1

    def pop(self) -> Optional[bytes]:
        buf = ctypes.create_string_buffer(self.slot_size)
        n = self._lib.ring_pop(self._ptr, buf, self.slot_size)
        if n <= 0:
            return None
        return buf.raw[:n]

    def __len__(self):
        return int(self._lib.ring_size(self._ptr))

    def close(self):
        if self._ptr:
            self._lib.ring_destroy(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def threshold_encode(grad: np.ndarray, residual: np.ndarray, threshold: float,
                     max_out: Optional[int] = None):
    """Returns int64 token array; residual updated IN PLACE (error feedback)."""
    g = np.ascontiguousarray(grad, np.float32).ravel()
    assert residual.dtype == np.float32 and residual.size == g.size
    cap = max_out or g.size
    lib = load()
    if lib is not None:
        out = np.empty(cap, np.int64)
        n = lib.threshold_encode(
            g.ctypes.data, residual.ctypes.data, g.size,
            ctypes.c_float(threshold), out.ctypes.data, cap)
        return out[:n]
    # pure python fallback
    acc = g + residual
    pos = acc >= threshold
    neg = acc <= -threshold
    idx = np.nonzero(pos | neg)[0][:cap]
    sel_pos = pos[idx]
    residual[:] = acc
    residual[idx[sel_pos]] -= threshold
    residual[idx[~sel_pos]] += threshold
    return ((idx.astype(np.int64) << 1) | (~sel_pos).astype(np.int64))


def threshold_decode(tokens: np.ndarray, threshold: float, n: int) -> np.ndarray:
    out = np.zeros(n, np.float32)
    lib = load()
    if lib is not None and tokens.size:
        t = np.ascontiguousarray(tokens, np.int64)
        lib.threshold_decode(t.ctypes.data, t.size,
                             ctypes.c_float(threshold), out.ctypes.data, n)
        return out
    if tokens.size:
        idx = tokens >> 1
        sign = np.where((tokens & 1) == 1, -1.0, 1.0).astype(np.float32)
        np.add.at(out, idx, sign * threshold)
    return out


def parse_csv_floats(text: bytes, max_out: int) -> np.ndarray:
    lib = load()
    if lib is not None:
        out = np.empty(max_out, np.float32)
        n = lib.parse_csv_floats(text, len(text), out.ctypes.data, max_out)
        return out[:n]
    import re
    vals = re.split(rb"[,\s;]+", text.strip())
    return np.asarray([float(v) for v in vals if v], np.float32)[:max_out]


class _ArenaBlock(np.ndarray):
    """ndarray view over an arena block; holds a reference to its arena so
    the slab can never be freed (GC or close) while a view is reachable."""
    _arena = None


class StagingArena:
    """Pinned-host-style staging allocator (reference: libnd4j workspaces +
    cudaHostAlloc staging). Page-aligned fixed-size blocks, LIFO freelist,
    first-touch NUMA placement at creation; zero malloc churn in the
    steady-state input pipeline. `borrow()` yields a numpy view over a
    block; `release()` returns it (double-release and foreign blocks are
    rejected). Falls back to plain numpy allocation when the native lib is
    absent (same API, no reuse guarantee)."""

    def __init__(self, block_size: int, n_blocks: int):
        self._lib = load()
        self._ptr = None
        self._fallback: list = []
        self._fallback_peak = 0
        self.n_blocks = n_blocks
        if self._lib is not None:
            self._ptr = self._lib.arena_create(block_size, n_blocks)
            if not self._ptr:
                raise MemoryError("arena_create failed")
            self.block_size = int(self._lib.arena_block_size(self._ptr))
        else:
            self.block_size = block_size

    def borrow(self) -> Optional[np.ndarray]:
        """A uint8 view over one block, or None if the arena is exhausted.
        Pass the SAME array (not a slice) back to release()."""
        if self._ptr:
            p = self._lib.arena_alloc(self._ptr)
            if not p:
                return None
            raw = np.ctypeslib.as_array(
                ctypes.cast(p, ctypes.POINTER(ctypes.c_uint8)),
                shape=(self.block_size,))
            block = raw.view(_ArenaBlock)
            block._arena = self  # slab outlives every reachable view
            return block
        if len(self._fallback) >= self.n_blocks:
            return None
        buf = np.zeros(self.block_size, np.uint8)
        self._fallback.append(buf)
        self._fallback_peak = max(self._fallback_peak, len(self._fallback))
        return buf

    def release(self, block: np.ndarray) -> None:
        if self._ptr:
            if not self._lib.arena_free(self._ptr, block.ctypes.data):
                raise ValueError(
                    "block does not belong to this arena (or was already "
                    "released, or is a slice rather than the borrowed array)")
            # _arena stays set: even a released view keeps the slab alive so
            # a stray late write can never hit freed memory
        else:
            kept = [b for b in self._fallback if b is not block]
            if len(kept) == len(self._fallback):
                raise ValueError(
                    "block does not belong to this arena (or was already "
                    "released)")
            self._fallback = kept

    @property
    def in_use(self) -> int:
        return int(self._lib.arena_in_use(self._ptr)) if self._ptr else len(self._fallback)

    @property
    def peak(self) -> int:
        return int(self._lib.arena_peak(self._ptr)) if self._ptr else self._fallback_peak

    def close(self, force: bool = False):
        """Free the slab. Refuses while blocks are outstanding unless
        `force=True` (outstanding views would become dangling pointers)."""
        if self._ptr:
            if not force and int(self._lib.arena_in_use(self._ptr)):
                raise RuntimeError(
                    f"{self.in_use} block(s) still borrowed; release them "
                    f"first or close(force=True)")
            self._lib.arena_destroy(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            # no outstanding views can exist here: each holds a reference to
            # this arena, so reachable views keep __del__ from running
            self.close(force=True)
        except Exception:  # noqa: BLE001
            pass


def npy_header(buf: bytes):
    """Parse a .npy v1/v2 header natively: (shape, dtype, data_offset,
    fortran). Pure-numpy fallback uses numpy's own parser."""
    lib = load()
    if lib is not None:
        shape = np.zeros(8, np.int64)
        ndim = ctypes.c_int32()
        dch = ctypes.c_char()
        isz = ctypes.c_int32()
        off = ctypes.c_int64()
        fortran = ctypes.c_int32()
        rc = lib.npy_parse_header(
            buf, len(buf), shape.ctypes.data, ctypes.byref(ndim),
            ctypes.byref(dch), ctypes.byref(isz), ctypes.byref(off),
            ctypes.byref(fortran))
        if rc == 0:
            dtype = np.dtype(f"{dch.value.decode()}{isz.value}")
            return (tuple(int(s) for s in shape[:ndim.value]), dtype,
                    int(off.value), bool(fortran.value))
        # fall through to numpy on unsupported (e.g. big-endian) headers
    import io
    from numpy.lib import format as npf
    f = io.BytesIO(buf)
    version = npf.read_magic(f)
    shape, fortran, dtype = npf._read_array_header(f, version)
    return shape, dtype, f.tell(), fortran


def load_npy(buf: bytes) -> np.ndarray:
    """bytes of a .npy file → ndarray (zero-copy view onto `buf`)."""
    shape, dtype, off, fortran = npy_header(buf)
    n = int(np.prod(shape)) if shape else 1
    arr = np.frombuffer(buf, dtype=dtype, count=n, offset=off)
    return arr.reshape(shape, order="F" if fortran else "C")


def parse_csv_matrix(text: bytes, n_cols: int,
                     max_rows: Optional[int] = None) -> np.ndarray:
    """CSV text → (rows, n_cols) f32; rows with a different column count
    (headers, blanks) are skipped. Native fast path, numpy fallback."""
    cap = max_rows if max_rows is not None else text.count(b"\n") + 1
    lib = load()
    if lib is not None:
        out = np.empty((cap, n_cols), np.float32)
        n = lib.parse_csv_matrix(text, len(text), n_cols,
                                 out.ctypes.data, cap)
        return out[:n].copy()
    import re
    rows = []
    for line in text.splitlines():
        # same delimiter set as the native parser: , ; tab space
        parts = [p for p in re.split(rb"[,;\t ]+", line.strip()) if p]
        if len(parts) != n_cols:
            continue
        try:
            rows.append([float(p) for p in parts])
        except ValueError:
            continue
        if len(rows) >= cap:
            break
    return np.asarray(rows, np.float32).reshape(-1, n_cols)


def f32_to_bf16(arr: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(arr, np.float32)
    lib = load()
    out = np.empty(a.size, np.uint16)
    if lib is not None:
        lib.f32_to_bf16(a.ctypes.data, out.ctypes.data, a.size)
    else:
        bits = a.view(np.uint32).ravel()
        lsb = (bits >> 16) & 1
        out = ((bits + 0x7FFF + lsb) >> 16).astype(np.uint16)
    import jax.numpy as jnp
    return out.reshape(arr.shape).view(jnp.bfloat16)
