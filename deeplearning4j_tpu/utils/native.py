"""ctypes bindings for the native runtime (native/dl4j_tpu_native.cpp).

Builds the .so on first use if g++ is available; every caller has a pure-
Python fallback, so the framework works without the native lib (slower
pipeline, same results).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libdl4j_tpu_native.so"
_lib = None
_tried = False


def load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _LIB_PATH.exists():
        try:
            subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                           capture_output=True, timeout=120)
        except Exception:  # noqa: BLE001 — fall back to pure python
            return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None
    lib.ring_create.restype = ctypes.c_void_p
    lib.ring_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.ring_destroy.argtypes = [ctypes.c_void_p]
    lib.ring_push.restype = ctypes.c_int
    lib.ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.ring_pop.restype = ctypes.c_int64
    lib.ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
    lib.ring_size.restype = ctypes.c_uint64
    lib.ring_size.argtypes = [ctypes.c_void_p]
    lib.threshold_encode.restype = ctypes.c_int64
    lib.threshold_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_float,
        ctypes.c_void_p, ctypes.c_int64]
    lib.threshold_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_float,
        ctypes.c_void_p, ctypes.c_int64]
    lib.parse_csv_floats.restype = ctypes.c_int64
    lib.parse_csv_floats.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
    lib.f32_to_bf16.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
    _lib = lib
    return _lib


def has_native() -> bool:
    return load() is not None


class NativeRing:
    """SPSC ring of byte slots (AsyncDataSetIterator backing store)."""

    def __init__(self, slot_size: int, n_slots: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native lib unavailable")
        self._lib = lib
        self._ptr = lib.ring_create(slot_size, n_slots)
        if not self._ptr:
            raise MemoryError("ring_create failed")
        self.slot_size = slot_size

    def push(self, payload: bytes) -> bool:
        rc = self._lib.ring_push(self._ptr, payload, len(payload))
        if rc == -1:
            raise ValueError(f"payload {len(payload)} > slot {self.slot_size}")
        return rc == 1

    def pop(self) -> Optional[bytes]:
        buf = ctypes.create_string_buffer(self.slot_size)
        n = self._lib.ring_pop(self._ptr, buf, self.slot_size)
        if n <= 0:
            return None
        return buf.raw[:n]

    def __len__(self):
        return int(self._lib.ring_size(self._ptr))

    def close(self):
        if self._ptr:
            self._lib.ring_destroy(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def threshold_encode(grad: np.ndarray, residual: np.ndarray, threshold: float,
                     max_out: Optional[int] = None):
    """Returns int64 token array; residual updated IN PLACE (error feedback)."""
    g = np.ascontiguousarray(grad, np.float32).ravel()
    assert residual.dtype == np.float32 and residual.size == g.size
    cap = max_out or g.size
    lib = load()
    if lib is not None:
        out = np.empty(cap, np.int64)
        n = lib.threshold_encode(
            g.ctypes.data, residual.ctypes.data, g.size,
            ctypes.c_float(threshold), out.ctypes.data, cap)
        return out[:n]
    # pure python fallback
    acc = g + residual
    pos = acc >= threshold
    neg = acc <= -threshold
    idx = np.nonzero(pos | neg)[0][:cap]
    sel_pos = pos[idx]
    residual[:] = acc
    residual[idx[sel_pos]] -= threshold
    residual[idx[~sel_pos]] += threshold
    return ((idx.astype(np.int64) << 1) | (~sel_pos).astype(np.int64))


def threshold_decode(tokens: np.ndarray, threshold: float, n: int) -> np.ndarray:
    out = np.zeros(n, np.float32)
    lib = load()
    if lib is not None and tokens.size:
        t = np.ascontiguousarray(tokens, np.int64)
        lib.threshold_decode(t.ctypes.data, t.size,
                             ctypes.c_float(threshold), out.ctypes.data, n)
        return out
    if tokens.size:
        idx = tokens >> 1
        sign = np.where((tokens & 1) == 1, -1.0, 1.0).astype(np.float32)
        np.add.at(out, idx, sign * threshold)
    return out


def parse_csv_floats(text: bytes, max_out: int) -> np.ndarray:
    lib = load()
    if lib is not None:
        out = np.empty(max_out, np.float32)
        n = lib.parse_csv_floats(text, len(text), out.ctypes.data, max_out)
        return out[:n]
    import re
    vals = re.split(rb"[,\s;]+", text.strip())
    return np.asarray([float(v) for v in vals if v], np.float32)[:max_out]


def f32_to_bf16(arr: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(arr, np.float32)
    lib = load()
    out = np.empty(a.size, np.uint16)
    if lib is not None:
        lib.f32_to_bf16(a.ctypes.data, out.ctypes.data, a.size)
    else:
        bits = a.view(np.uint32).ravel()
        lsb = (bits >> 16) & 1
        out = ((bits + 0x7FFF + lsb) >> 16).astype(np.uint16)
    import jax.numpy as jnp
    return out.reshape(arr.shape).view(jnp.bfloat16)
