"""Aux subsystems: tracing/profiling, race detection, native bindings."""

from . import race, tracing

__all__ = ["race", "tracing"]
