"""CPU-forced subprocess scaffolding.

The sandbox pins JAX to the real single-chip TPU tunnel (env JAX_PLATFORMS
plus a sitecustomize `jax.config.update` at interpreter start), and a process
that has already initialized that backend cannot be retargeted. Anything that
needs an n-device virtual CPU platform (multichip dry-runs, dp-scaling bench)
must therefore re-exec in a child whose env forces CPU BEFORE jax initializes.
This module is the single copy of that recipe (used by __graft_entry__ and
bench.py — the round-1 libtpu-mismatch lesson, encoded once).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

COUNT_FLAG = "xla_force_host_platform_device_count"


def cpu_forced_env(n_devices: int,
                   base_env: Optional[Dict[str, str]] = None
                   ) -> Tuple[Dict[str, str], str]:
    """(env, preamble) for a child python that must see `n_devices` CPU
    devices. `preamble` is python source to exec FIRST in the child: it
    overrides the sitecustomize's config.update and puts the repo root on
    sys.path (`-c` children don't get the '' entry under PYTHONSAFEPATH)."""
    env = dict(os.environ if base_env is None else base_env)
    env["JAX_PLATFORMS"] = "cpu"
    kept = [f for f in env.get("XLA_FLAGS", "").split() if COUNT_FLAG not in f]
    env["XLA_FLAGS"] = " ".join(kept + [f"--{COUNT_FLAG}={n_devices}"])
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    preamble = ("import jax; jax.config.update('jax_platforms', 'cpu');\n"
                f"import sys; sys.path.insert(0, {repo!r});\n")
    return env, preamble


def env_forces_cpu(n_devices: int) -> bool:
    """True if THIS process's env already forces >= n_devices CPU devices
    (i.e. running inline is plausible, pending a live-backend check)."""
    import re
    m = re.search(rf"{COUNT_FLAG}=(\d+)", os.environ.get("XLA_FLAGS", ""))
    return (os.environ.get("JAX_PLATFORMS") == "cpu" and m is not None
            and int(m.group(1)) >= n_devices)
