"""Tracing & profiling — parity with the reference's op profiler / debug path.

Reference counterparts (upstream Eclipse DL4J, per SURVEY.md provenance):
- nd4j ``OpProfiler`` / ``ProfilerConfig`` (op invocation counts, timings,
  bad-value checks) — `nd4j-api/.../profiler/OpProfiler`.
- ``Nd4j.getExecutioner().printEnvironmentInformation()`` and exec debug.
- Performance listener + training UI timing charts.

TPU-native rethink: under ``jit`` everything fuses, so "per-op timing" at
runtime is an XLA concern, not a Python one. The tracer therefore works at
THREE levels, matching how TPU work is actually analysed:

1. **Trace-time op inventory** (`trace_ops`): walk the jaxpr — exact list of
   primitives, shapes, and analytic FLOP counts. Zero execution cost.
2. **Interpreted per-op profile** (`profile_ops`): eval the jaxpr op-by-op
   with host timing — the debug/dev analogue of OpProfiler (not for prod).
3. **XLA-level** (`profile_trace`, `dump_hlo`, `cost_analysis`): the real
   TPU story — jax.profiler traces for tensorboard, compiled-HLO text dump,
   and XLA's own cost model per executable.
"""

from __future__ import annotations

import contextlib
import math
import time
from collections import defaultdict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np
from jax.extend import core as jcore


# --------------------------------------------------------------------------
# FLOP estimation for the primitives that dominate TPU time (MXU ops).
# --------------------------------------------------------------------------

def _dot_general_flops(eqn) -> int:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    batch = math.prod(lhs.shape[d] for d in lb) if lb else 1
    contract = math.prod(lhs.shape[d] for d in lc) if lc else 1
    m = math.prod(lhs.shape[d] for d in range(len(lhs.shape))
                  if d not in lc and d not in lb)
    n = math.prod(rhs.shape[d] for d in range(len(rhs.shape))
                  if d not in rc and d not in rb)
    return 2 * batch * m * n * contract


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval  # kernel
    # 2 * output_elements * kernel_spatial * in_features
    dn = eqn.params["dimension_numbers"]
    k_spatial = math.prod(rhs.shape[d] for d in dn.rhs_spec[2:])
    cin = rhs.shape[dn.rhs_spec[1]]
    groups = eqn.params.get("feature_group_count", 1)
    return 2 * math.prod(out.shape) * k_spatial * (cin // max(groups, 1)) * 1


_FLOP_FNS = {
    "dot_general": _dot_general_flops,
    "conv_general_dilated": _conv_flops,
}


@dataclass
class OpRecord:
    """One primitive occurrence (or aggregate) from a traced computation."""
    prim: str
    count: int = 0
    flops: int = 0
    bytes_out: int = 0
    time_s: float = 0.0
    shapes: List[str] = field(default_factory=list)

    def row(self) -> str:
        t = f"{self.time_s * 1e3:10.3f}ms" if self.time_s else " " * 12
        fl = f"{self.flops / 1e9:9.3f}G" if self.flops else " " * 10
        return f"{self.prim:<28}{self.count:>6}  {fl}  {t}  {self.shapes[0] if self.shapes else ''}"


def _walk_jaxpr(jaxpr, agg: Dict[str, OpRecord], depth=0, mult=1):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        # Recurse into higher-order primitives so scan/cond/jit bodies count.
        # A scan body executes `length` times — multiply its contribution, or
        # every scanned model (LSTM over T, per-layer transformer scan)
        # under-counts by the trip count. while_loop trip counts are unknown
        # at trace time: counted once (documented best-effort floor).
        sub_mult = mult
        if name == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1))
        for pname in ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr",
                      "branches", "fun_jaxpr"):
            sub = eqn.params.get(pname)
            if sub is None:
                continue
            subs = sub if isinstance(sub, (tuple, list)) else [sub]
            for s in subs:
                inner = s.jaxpr if hasattr(s, "jaxpr") else s
                if hasattr(inner, "eqns"):
                    _walk_jaxpr(inner, agg, depth + 1, sub_mult)
        rec = agg.setdefault(name, OpRecord(prim=name))
        rec.count += mult
        fn = _FLOP_FNS.get(name)
        if fn is not None:
            try:
                rec.flops += mult * fn(eqn)
            except Exception:  # noqa: BLE001 — estimation is best-effort
                pass
        for ov in eqn.outvars:
            aval = getattr(ov, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                rec.bytes_out += mult * math.prod(aval.shape or (1,)) * getattr(
                    aval.dtype, "itemsize", 4)
        if len(rec.shapes) < 3 and eqn.outvars:
            aval = getattr(eqn.outvars[0], "aval", None)
            if aval is not None:
                rec.shapes.append(str(aval))


def trace_ops(fn: Callable, *args, **kwargs) -> List[OpRecord]:
    """Trace `fn` and return aggregated per-primitive records (no execution).

    The TPU analogue of OpProfiler's invocation census: exact op inventory
    with analytic FLOPs for MXU ops (dot_general / conv).
    """
    closed = jax.make_jaxpr(fn, **({"static_argnums": kwargs.pop("static_argnums")}
                                   if "static_argnums" in kwargs else {}))(*args, **kwargs)
    agg: Dict[str, OpRecord] = {}
    _walk_jaxpr(closed.jaxpr, agg)
    return sorted(agg.values(), key=lambda r: (-r.flops, -r.count))


def total_flops(fn: Callable, *args, **kwargs) -> int:
    return sum(r.flops for r in trace_ops(fn, *args, **kwargs))


def format_op_report(records: List[OpRecord], title="op trace") -> str:
    lines = [f"== {title} ==",
             f"{'primitive':<28}{'count':>6}  {'flops':>10}  {'time':>12}  sample shape"]
    lines += [r.row() for r in records]
    lines.append(f"total primitives: {sum(r.count for r in records)}; "
                 f"total flops: {sum(r.flops for r in records) / 1e9:.3f} GFLOP")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Interpreted per-op profiling (debug mode — runs op-by-op on host).
# --------------------------------------------------------------------------

def profile_ops(fn: Callable, *args) -> List[OpRecord]:
    """Execute `fn` one primitive at a time, timing each (debug analogue of
    OpProfiler's ALL_OPS timing mode). Orders of magnitude slower than jit —
    use for small shapes / debugging only; real profiling is `profile_trace`.
    """
    closed = jax.make_jaxpr(fn)(*args)
    flat_args = jax.tree_util.tree_leaves(args)
    agg: Dict[str, OpRecord] = {}

    def eval_jaxpr(jaxpr, consts, *inputs):
        env: Dict[Any, Any] = {}

        def read(v):
            return v.val if isinstance(v, jcore.Literal) else env[v]

        def write(v, val):
            env[v] = val

        for v, c in zip(jaxpr.constvars, consts):
            write(v, c)
        for v, a in zip(jaxpr.invars, inputs):
            write(v, a)
        for eqn in jaxpr.eqns:
            invals = [read(v) for v in eqn.invars]
            t0 = time.perf_counter()
            subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
            outs = eqn.primitive.bind(*subfuns, *invals, **bind_params)
            outs_flat = outs if eqn.primitive.multiple_results else [outs]
            for o in outs_flat:
                if hasattr(o, "block_until_ready"):
                    o.block_until_ready()
            dt = time.perf_counter() - t0
            rec = agg.setdefault(eqn.primitive.name, OpRecord(prim=eqn.primitive.name))
            rec.count += 1
            rec.time_s += dt
            fl = _FLOP_FNS.get(eqn.primitive.name)
            if fl is not None:
                try:
                    rec.flops += fl(eqn)
                except Exception:  # noqa: BLE001
                    pass
            for v, o in zip(eqn.outvars, outs_flat):
                write(v, o)
        return [read(v) for v in jaxpr.outvars]

    eval_jaxpr(closed.jaxpr, closed.consts, *flat_args)
    return sorted(agg.values(), key=lambda r: -r.time_s)


# --------------------------------------------------------------------------
# jax.profiler hooks — the production path (tensorboard / xprof traces).
# --------------------------------------------------------------------------

@contextlib.contextmanager
def profile_trace(log_dir: str = "runs/profile", host_tracer_level: int = 2):
    """Capture a device+host trace viewable in TensorBoard's profile plugin.
    Wraps jax.profiler.trace; on TPU this records XLA executable timelines."""
    Path(log_dir).mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(log_dir)):
        yield log_dir


def annotate(name: str):
    """Named region that shows up on the trace timeline (host + device)."""
    return jax.profiler.TraceAnnotation(name)


def start_profiler_server(port: int = 9999):
    """On-demand profiling: connect tensorboard's capture-profile to this."""
    return jax.profiler.start_server(port)


class StepTimer:
    """Lightweight wall-clock step timer with percentile summary — what the
    PerformanceListener uses under the hood; usable standalone around any
    step function (blocks on the result to include device time)."""

    def __init__(self):
        self.times: List[float] = []

    @contextlib.contextmanager
    def step(self):
        t0 = time.perf_counter()
        yield
        self.times.append(time.perf_counter() - t0)

    def summary(self, skip_first: int = 1) -> Dict[str, float]:
        ts = self.times[skip_first:] or self.times
        if not ts:
            return {}
        arr = np.array(ts)
        return {"mean_s": float(arr.mean()), "p50_s": float(np.percentile(arr, 50)),
                "p90_s": float(np.percentile(arr, 90)), "min_s": float(arr.min()),
                "steps": len(ts)}


# --------------------------------------------------------------------------
# XLA HLO dump + cost analysis.
# --------------------------------------------------------------------------

def dump_hlo(fn: Callable, *args, directory: Optional[str] = None,
             name: str = "computation", optimized: bool = True) -> Dict[str, str]:
    """Lower + compile `fn` and return {stage: text} for StableHLO and
    (optionally) the post-optimization HLO the TPU actually runs.
    If `directory` is given, also writes `<name>.<stage>.txt` files."""
    lowered = jax.jit(fn).lower(*args)
    out = {"stablehlo": lowered.as_text()}
    if optimized:
        compiled = lowered.compile()
        try:
            out["optimized_hlo"] = compiled.as_text()
        except Exception:  # noqa: BLE001 — some backends withhold it
            pass
    if directory:
        d = Path(directory)
        d.mkdir(parents=True, exist_ok=True)
        for stage, text in out.items():
            (d / f"{name}.{stage}.txt").write_text(text)
    return out


def cost_analysis(fn: Callable, *args) -> Dict[str, float]:
    """XLA's own cost model for the compiled executable: flops, bytes
    accessed, transcendentals — the ground truth the analytic estimate in
    `trace_ops` approximates."""
    compiled = jax.jit(fn).lower(*args).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def memory_analysis(fn: Callable, *args) -> Dict[str, int]:
    """Compiled-executable memory footprint (bytes): args, outputs, temps,
    generated code. Key for fitting models in HBM before touching a chip."""
    compiled = jax.jit(fn).lower(*args).compile()
    ma = compiled.memory_analysis()
    if ma is None:
        return {}
    keys = ["argument_size_in_bytes", "output_size_in_bytes",
            "temp_size_in_bytes", "generated_code_size_in_bytes",
            "alias_size_in_bytes"]
    return {k: getattr(ma, k) for k in keys if hasattr(ma, k)}
