"""Process-wide metrics registry — the unified telemetry plane's state.

Three instrument kinds, Prometheus-shaped (counter / gauge / histogram
with exponential buckets and streaming quantiles), one process-wide
registry, and text exposition for the UI server's ``/metrics`` endpoint.

Design constraints, in order:

1. **Hot-path cheap.** Every observation is a dict update + a bisect
   under one lock — a few microseconds, paid on HOST between jitted
   steps (never inside a traced computation). The documented budget is
   <2% of a tier-1 CPU train step (tests/test_obs.py pins it).
2. **Namespace discipline.** Every metric name must live under the
   registry namespace (``dl4j_`` by default) and counters must end in
   ``_total`` — ``scripts/check_metric_names.py`` lints the
   instrumentation sites against the same rules, so a stray name fails
   in CI, not in a Grafana query.
3. **Get-or-create registration.** Instrument constructors are
   idempotent per (name, kind, labelnames); re-registering the same
   name as a different kind or label set raises — the duplicate-
   registration failure mode the lint also catches statically.

No jax import here: the registry is usable from data loaders, the UI
process, and bench subprocesses alike.
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")

# exponential (powers-of-2) upper bounds, 0.1 ms .. ~105 s — covers a
# sub-ms LeNet step and a multi-second scaleout round in one layout
DEFAULT_BUCKETS = tuple(1e-4 * (2.0 ** i) for i in range(21))


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt(v: float) -> str:
    """Prometheus float rendering: integers without the trailing .0."""
    if v != v:  # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Instrument:
    """Shared label plumbing: values keyed by the label-value tuple (the
    empty tuple for an unlabeled instrument)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._sorted_names = tuple(sorted(self.labelnames))
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        # fast path: unlabeled instrument, no labels passed — the shape
        # every per-iteration listener metric takes (hot-path budget)
        if not labels and not self.labelnames:
            return ()
        # second fast path: labels passed in declared order (every
        # scheduler hot-path write) — a tuple identity check instead of
        # two sorts per write keeps labeled gauges inside the <2%
        # serving bookkeeping budget
        if tuple(labels) != self.labelnames \
                and tuple(sorted(labels)) != self._sorted_names:
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} do not match "
                f"declared labelnames {sorted(self.labelnames)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def _label_str(self, key: Tuple[str, ...], extra: str = "") -> str:
        pairs = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(self.labelnames, key)]
        if extra:
            pairs.append(extra)
        return "{" + ",".join(pairs) + "}" if pairs else ""


class Counter(_Instrument):
    """Monotonic counter. ``inc(v, **labels)``; names end in ``_total``."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, v: float = 1.0, **labels):
        if v < 0:
            raise ValueError(f"{self.name}: counters only go up (got {v})")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + v

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = [f"{self.name}{self._label_str(k)} {_fmt(v)}"
               for k, v in items]
        if not out and not self.labelnames:
            out = [f"{self.name} 0"]
        return out


class Gauge(_Instrument):
    """Point-in-time value: ``set`` / ``inc`` / ``dec``."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, v: float, **labels):
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(v)

    def inc(self, v: float = 1.0, **labels):
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + v

    def dec(self, v: float = 1.0, **labels):
        self.inc(-v, **labels)

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labelnames:
            return [f"{self.name} 0"]
        return [f"{self.name}{self._label_str(k)} {_fmt(v)}"
                for k, v in items]


class _HistState:
    __slots__ = ("counts", "total", "sum", "min", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1: the +Inf overflow bucket
        self.total = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf


class Histogram(_Instrument):
    """Cumulative-bucket histogram with streaming quantile estimates.

    Buckets are UPPER bounds (exponential by default); ``quantile(q)``
    interpolates linearly inside the bucket the q-th observation landed
    in, clamped to the observed min/max so the estimate never exceeds
    reality on a sparse tail.
    """

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Optional[Iterable[float]] = None):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(buckets)) if buckets is not None else DEFAULT_BUCKETS
        if not bs or any(b <= 0 for b in bs):
            raise ValueError(f"{self.name}: buckets must be positive bounds")
        self.buckets = bs
        self._states: Dict[Tuple[str, ...], _HistState] = {}

    def _state(self, key) -> _HistState:
        st = self._states.get(key)
        if st is None:
            st = self._states.setdefault(key, _HistState(len(self.buckets)))
        return st

    def observe(self, v: float, **labels):
        key = self._key(labels)
        i = bisect_left(self.buckets, v)
        with self._lock:
            st = self._state(key)
            st.counts[i] += 1
            st.total += 1
            st.sum += v
            st.min = min(st.min, v)
            st.max = max(st.max, v)

    def observe_many(self, values: Sequence[float], **labels):
        """Batch ``observe``: one key resolution + lock round for the
        whole sequence. The serving close-out path records every
        request's per-token ITL samples at once — per-sample locking
        measurably ate into the <2% bookkeeping budget."""
        if not values:
            return
        key = self._key(labels)
        buckets = self.buckets
        with self._lock:
            st = self._state(key)
            counts = st.counts
            for v in values:
                counts[bisect_left(buckets, v)] += 1
                st.sum += v
                if v < st.min:
                    st.min = v
                if v > st.max:
                    st.max = v
            st.total += len(values)

    def count(self, **labels) -> int:
        st = self._states.get(self._key(labels))
        return 0 if st is None else st.total

    def sum(self, **labels) -> float:
        st = self._states.get(self._key(labels))
        return 0.0 if st is None else st.sum

    def quantile(self, q: float, **labels) -> Optional[float]:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        st = self._states.get(self._key(labels))
        if st is None or st.total == 0:
            return None
        target = q * st.total
        cum = 0.0
        for i, c in enumerate(st.counts):
            if c == 0:
                continue
            lo = self.buckets[i - 1] if i > 0 else 0.0
            hi = self.buckets[i] if i < len(self.buckets) else st.max
            if cum + c >= target:
                frac = (target - cum) / c
                est = lo + frac * (hi - lo)
                return min(max(est, st.min), st.max)
            cum += c
        return st.max

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted((k, (list(st.counts), st.total, st.sum))
                           for k, st in self._states.items())
        out: List[str] = []
        for key, (counts, total, s) in items:
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                le = 'le="%s"' % _fmt(bound)
                out.append(f"{self.name}_bucket"
                           f"{self._label_str(key, le)} {cum}")
            inf = 'le="+Inf"'
            out.append(f"{self.name}_bucket"
                       f"{self._label_str(key, inf)} {total}")
            out.append(f"{self.name}_sum{self._label_str(key)} {_fmt(s)}")
            out.append(f"{self.name}_count{self._label_str(key)} {total}")
        return out


class MetricsRegistry:
    """Name -> instrument map with namespace enforcement and idempotent
    get-or-create registration. One process-wide instance lives in
    ``deeplearning4j_tpu.obs`` (``get_registry()``); tests construct
    their own."""

    def __init__(self, namespace: str = "dl4j"):
        self.namespace = namespace
        self._metrics: Dict[str, _Instrument] = {}
        self._lock = threading.Lock()

    # -------------------------------------------------- registration
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        if not name.startswith(self.namespace + "_"):
            raise ValueError(
                f"metric {name!r} outside the registered "
                f"{self.namespace}_ namespace")
        if cls is Counter and not name.endswith("_total"):
            raise ValueError(f"counter {name!r} must end in '_total'")
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or \
                        m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"duplicate registration of {name!r}: existing "
                        f"{m.kind}{m.labelnames} vs requested "
                        f"{cls.kind}{tuple(labelnames)}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    # -------------------------------------------------- introspection
    def get(self, name: str) -> Optional[_Instrument]:
        return self._metrics.get(name)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def reset(self):
        """Drop every registered instrument (tests). Instrument objects
        created before the reset keep working but stop being exposed —
        long-lived holders (listeners, wrappers) should be constructed
        after the reset, and call-site instrumentation re-fetches via
        ``get_registry()`` each time precisely so a reset can't orphan
        it."""
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> Dict[str, dict]:
        """Name -> plain-data summary (counters/gauges: label->value;
        histograms: count/sum/p50/p95/p99 per label set). Takes each
        instrument's lock: a daemon thread (scaleout hub, UI handler)
        may be minting a new label set mid-snapshot."""
        out: Dict[str, dict] = {}
        for name in self.names():
            m = self._metrics[name]
            if isinstance(m, Histogram):
                with m._lock:
                    keys = list(m._states)
                out[name] = {
                    ",".join(k) or "": {
                        "count": m._states[k].total,
                        "sum": m._states[k].sum,
                        "p50": self.quantile_of(m, 0.50, k),
                        "p95": self.quantile_of(m, 0.95, k),
                        "p99": self.quantile_of(m, 0.99, k)}
                    for k in keys}
            else:
                with m._lock:
                    items = list(m._values.items())
                out[name] = {",".join(k) or "": v for k, v in items}
        return out

    @staticmethod
    def quantile_of(h: Histogram, q: float, key: Tuple[str, ...]):
        return h.quantile(q, **dict(zip(h.labelnames, key)))

    # -------------------------------------------------- exposition
    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 — what
        ``GET /metrics`` on the UI server returns."""
        lines: List[str] = []
        for name in self.names():
            m = self._metrics[name]
            if m.help:
                esc = m.help.replace("\\", r"\\").replace("\n", r"\n")
                lines.append(f"# HELP {name} {esc}")
            lines.append(f"# TYPE {name} {m.kind}")
            lines.extend(m.expose())
        return "\n".join(lines) + "\n" if lines else ""
