"""Numerics plane — tensor-health sentinels + cross-replica drift audit
(ISSUE 13).

ROADMAP items 3 (int8/fp8 KV, int8 weights, speculative decoding) and 4
(ZeRO update sharding) are numerics plays, and nothing measured tensor
health before this module: a NaN'd batch surfaced only as a poisoned
run, a loss spike only as a worse convergence plot, and "the dp
replicas hold the same params" was an article of faith. Three pieces
make those first-class observables:

- :func:`summarize` — a jitted streaming tensor-stat engine: per-leaf
  mean / rms / absmax / zero-fraction / nonfinite-count for a whole
  pytree in ONE fused reduction pass over each leaf (XLA fuses the five
  reductions into a single read of the tensor), returning a DEVICE stat
  tree — no host round-trip until :func:`export_summary` fetches the
  tiny stat vectors in one ``device_get``. :func:`emit_stats` publishes
  a summary as ``dl4j_num_*{layer, kind}`` gauges (kind ∈ params /
  grads / loss) and remembers the latest per (source, replica) for
  ``GET /debug/numerics``.
- :class:`NumericsSentinel` — a configurable policy (``warn`` /
  ``raise`` / ``skip_step``) on non-finite loss or grads, plus a
  rolling z-score loss-spike detector. It plugs into the SAME
  ``_anomaly_detector`` slot the train steps already wire
  (``net.enable_gradient_anomaly_detection(sentinel)``): grad stats are
  computed inside the jitted step, and for ``raise`` / ``skip_step``
  the in-jit :func:`~..train.anomaly.gate_on_finite` makes the poisoned
  step a bit-identical no-op BEFORE the host ever sees it. Every trip
  auto-dumps the offending step's full stat tree through the PR 11
  flight-recorder machinery (``kind: "numerics"`` records in the same
  JSONL black box), so a NaN postmortem starts from data, not a rerun.
- :class:`DriftAuditor` — param checksums per replica per round.
  ``ParallelWrapper.fit`` audits its device replicas at the end of
  every fit call (:func:`audit_params` — per-device crc + f64 sum over
  each REPLICATED leaf's addressable shards); the scaleout round
  barrier records the mean each end of the wire saw (hub at round
  close, every worker after applying it). Replicas that report the
  same (source, round) are compared: ``dl4j_replica_checksum{replica}``
  / ``dl4j_replica_drift_max`` gauges, divergence warned and counted
  (``dl4j_replica_drift_detected_total``). Zero drift here is the
  lockstep proof the ZeRO update-sharding equivalence case will cite.

Label discipline (``scripts/check_metric_names.py`` enforces): the
``dl4j_num_*`` plane labels by ``layer`` / ``kind`` / ``replica`` ONLY,
``dl4j_replica_*`` by ``replica`` only — never per-request identity.

No jax import at module load (the memory.py discipline): the sentinel
report and drift tables must be readable from the UI process without
paying the jax import chain; everything device-touching imports jax
inside the function.
"""

from __future__ import annotations

import math
import threading
import time
import warnings
import weakref
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .reqtrace import FlightRecorder

# the per-leaf stat vector summarize() produces, in order
STAT_FIELDS = ("mean", "rms", "absmax", "zero_frac", "nonfinite")

# stat trees the listener/sentinel publish under these kinds only — a
# stable label vocabulary, like memory.KNOWN_COMPONENTS
KNOWN_KINDS = ("params", "grads", "loss", "optimizer", "states",
               "activations")


# ------------------------------------------------------------ summarize

_SUMMARIZE_JIT = None


def _leaf_stats(x):
    """One fused pass over one leaf → (5,) f32 stat vector.

    mean/rms treat non-finite elements as 0 (so the summary itself
    stays finite and readable while the nonfinite count tells the
    story); zero_frac counts exact zeros among FINITE elements."""
    import jax.numpy as jnp
    xf = jnp.asarray(x).astype(jnp.float32).reshape(-1)
    n = max(int(xf.size), 1)
    finite = jnp.isfinite(xf)
    xz = jnp.where(finite, xf, 0.0)
    mean = jnp.sum(xz) / n
    rms = jnp.sqrt(jnp.sum(xz * xz) / n)
    absmax = jnp.max(jnp.abs(xz)) if xf.size else jnp.float32(0.0)
    zero = jnp.sum(finite & (xf == 0.0)) / n
    nonf = jnp.sum(~finite)
    return jnp.stack([mean, rms, absmax, zero,
                      nonf.astype(jnp.float32)])


def summarize(tree):
    """Device-side stat tree: every array leaf of ``tree`` mapped to its
    (5,) stat vector (see :data:`STAT_FIELDS`) in one jitted dispatch —
    no host round-trip happens here. ``None`` leaves are dropped.
    Scalars (a loss) work: ``summarize(loss)`` is a single stat leaf."""
    global _SUMMARIZE_JIT
    import jax
    if _SUMMARIZE_JIT is None:
        _SUMMARIZE_JIT = jax.jit(
            lambda t: jax.tree_util.tree_map(_leaf_stats, t))
    return _SUMMARIZE_JIT(tree)


def _path_str(path) -> str:
    parts = []
    for k in path:
        for attr in ("key", "name", "idx"):
            v = getattr(k, attr, None)
            if v is not None:
                parts.append(str(v))
                break
        else:
            parts.append(str(k))
    return "/".join(parts) or "value"


def export_summary(stat_tree) -> Dict[str, Dict[str, float]]:
    """ONE host fetch of a :func:`summarize` result →
    ``{leaf_path: {mean, rms, absmax, zero_frac, nonfinite}}``."""
    import jax
    host = jax.device_get(stat_tree)
    flat, _ = jax.tree_util.tree_flatten_with_path(host)
    out: Dict[str, Dict[str, float]] = {}
    for path, vec in flat:
        out[_path_str(path)] = {
            f: float(vec[i]) for i, f in enumerate(STAT_FIELDS)}
    return out


# latest exported summaries per (source, replica) — /debug/numerics
_LATEST: Dict[Tuple[str, str], Dict[str, Any]] = {}
_LOCK = threading.Lock()


# per-registry gauge cache (the NumericsSentinel._m discipline): five
# registry get-or-creates (regex + lock) per record_stats call would
# be the listener's single biggest per-sample cost
_GAUGE_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _gauges(registry):
    if registry is None:
        from . import get_registry
        registry = get_registry()
    try:
        cached = _GAUGE_CACHE.get(registry)
    except TypeError:           # unhashable/unweakrefable test double
        cached = None
    if cached is not None:
        return cached
    lab = ("layer", "kind")
    g = {
        "mean": registry.gauge(
            "dl4j_num_mean", "Per-leaf mean (non-finite as 0) of a "
            "sampled tensor tree", labelnames=lab),
        "rms": registry.gauge(
            "dl4j_num_rms", "Per-leaf root-mean-square of a sampled "
            "tensor tree", labelnames=lab),
        "absmax": registry.gauge(
            "dl4j_num_absmax", "Per-leaf max |x| of a sampled tensor "
            "tree", labelnames=lab),
        "zero_frac": registry.gauge(
            "dl4j_num_zero_fraction", "Per-leaf fraction of exact "
            "zeros (dead-unit / sparsity watch)", labelnames=lab),
        "nonfinite": registry.gauge(
            "dl4j_num_nonfinite_count", "Per-leaf count of NaN/Inf "
            "elements (anything >0 is a sentinel trip)",
            labelnames=lab),
    }
    try:
        _GAUGE_CACHE[registry] = g
    except TypeError:
        pass
    return g


def emit_stats(tree, kind: str, *, source: str = "train",
               replica: str = "0", registry=None
               ) -> Dict[str, Dict[str, float]]:
    """Summarize ``tree`` and publish every leaf's stats as
    ``dl4j_num_*{layer, kind}`` gauges; the export is also recorded per
    (source, replica) for ``GET /debug/numerics``. Returns the exported
    ``{leaf_path: stats}`` dict."""
    if kind not in KNOWN_KINDS:
        raise ValueError(f"unknown stat kind {kind!r}: pick from "
                         f"{KNOWN_KINDS} (a stable label vocabulary)")
    stats = export_summary(summarize(tree))
    record_stats(stats, kind, source=source, replica=replica,
                 registry=registry)
    return stats


def record_stats(stats: Dict[str, Dict[str, float]], kind: str, *,
                 source: str = "train", replica: str = "0",
                 registry=None):
    """Publish an ALREADY-exported stat dict (gauges + /debug/numerics
    record) — the path for stats that were computed elsewhere (the
    in-jit grad stats the sentinel receives)."""
    g = _gauges(registry)
    for layer, vec in stats.items():
        if not isinstance(vec, dict):
            continue            # e.g. an {"error": ...} forensics entry
        for field, gauge in g.items():
            if field in vec:
                gauge.set(float(vec[field]), layer=layer, kind=kind)
    with _LOCK:
        # replace wholesale, never mutate in place: latest_stats hands
        # out the record object itself, and the UI thread json.dumps it
        # concurrently — a dict growing mid-iteration would 500 the
        # debug endpoint (the memory.py fresh-dict-per-census pattern)
        key = (str(source), str(replica))
        old = _LATEST.get(key)
        kinds = dict(old["kinds"]) if old else {}
        kinds[kind] = stats
        _LATEST[key] = {"source": str(source), "replica": str(replica),
                        "kinds": kinds, "ts": time.time()}


def latest_stats() -> List[Dict[str, Any]]:
    """Every (source, replica)'s most recent stat export, stable order."""
    with _LOCK:
        return [_LATEST[k] for k in sorted(_LATEST)]


def reset_stats():
    """Drop recorded stat exports (tests)."""
    with _LOCK:
        _LATEST.clear()


# ------------------------------------------------------------- sentinel

_SENTINELS: "weakref.WeakSet[NumericsSentinel]" = weakref.WeakSet()

POLICIES = ("warn", "raise", "skip_step")


class NumericsSentinel:
    """Tensor-health tripwire with a configurable policy.

    Wire it twice (or once via ``NumericsListener(...).attach(net)``):

    - ``net.enable_gradient_anomaly_detection(sentinel)`` — the jitted
      train step computes per-layer grad stats and, when
      :attr:`gate_updates` (policies ``raise`` / ``skip_step``), gates
      params/opt-state/layer-state on grad finiteness INSIDE jit — a
      poisoned batch leaves them bit-identical (the
      ``train.anomaly.gate_on_finite`` contract). Host-side,
      :meth:`check` receives the (one-step-delayed) stats and trips on
      any non-finite element.
    - ``NumericsListener`` — calls :meth:`observe_loss` every step:
      trips on non-finite loss, and keeps a rolling window for the
      z-score loss-spike detector (|score − mean| / std over the last
      ``window`` scores; std is floored at ``rel_floor·|mean|`` so a
      flat loss doesn't alarm on noise).

    Every trip increments ``dl4j_num_sentinel_trips_total{kind}`` and
    auto-dumps the offending step's full stat tree — params summarized
    via :func:`summarize`, the step's grad stats, the recent loss
    window — as a ``kind: "numerics"`` record through the PR 11 flight
    recorder (JSONL at ``dump_path``). Policy then decides: ``warn``
    warns and lets the run proceed (no in-jit gate — observe only),
    ``raise`` raises :class:`FloatingPointError` (the gated step never
    applied, so the run is salvageable), ``skip_step`` warns and
    continues with the update skipped. The loss-spike detector never
    escalates past warn+dump — a spike is a lead, not a verdict.

    Policy is captured when the train step compiles (the gate is traced
    in); change it by constructing a new sentinel and re-enabling.
    """

    def __init__(self, policy: str = "warn", *, z_threshold: float = 6.0,
                 window: int = 64, min_window: int = 16,
                 rel_floor: float = 1e-3, replica: str = "0",
                 dump_path: Optional[str] = "runs/numerics_blackbox.jsonl",
                 registry=None):
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r}: pick from "
                             f"{POLICIES}")
        self.policy = policy
        self.z_threshold = float(z_threshold)
        self.min_window = max(2, int(min_window))
        self.rel_floor = float(rel_floor)
        self.replica = str(replica)
        self._registry = registry
        self._scores: "deque[float]" = deque(maxlen=max(int(window),
                                                        self.min_window))
        # O(1) rolling moments: recomputing mean/var over the window
        # every step would be the plane's single biggest per-step cost
        self._sum = 0.0
        self._sumsq = 0.0
        self._m_cache = None
        self.trips: List[Dict[str, Any]] = []
        # incident gating: a persistent-NaN run (policy "warn" applies
        # the poisoned update, so every later loss is NaN too) must not
        # pay a full stat pass + ring re-dump PER STEP — only the FIRST
        # trip of each kind per incident dumps; repeats count + record
        # lightweight. An incident ends when the signal goes clean
        # (finite loss / finite grads), re-arming the dump.
        self._active_trips: set = set()
        self._last_raw_grads = None   # as pushed by the step (host)
        self._model = None       # weakref, bound by observe_loss
        self._overhead = 0.0
        self.recorder = FlightRecorder(
            capacity_requests=4, capacity_snapshots=64,
            replica=self.replica, crash_dump_path=dump_path)
        _SENTINELS.add(self)

    # ---------------------------------------------------------- wiring
    @property
    def gate_updates(self) -> bool:
        """True → the train step gates params/opt-state on grad
        finiteness inside jit (``raise`` / ``skip_step``); ``warn``
        observes without touching the update."""
        return self.policy in ("raise", "skip_step")

    @property
    def overhead_seconds(self) -> float:
        """Cumulative host bookkeeping cost (the MetricsListener
        self-timing discipline; trips excluded — a dump is forensics,
        not steady-state overhead)."""
        return self._overhead

    def _m(self):
        # cached like MetricsListener's instruments: a registry
        # get-or-create per observation (regex + lock) would be the
        # sentinel's single biggest per-step cost
        m = self._m_cache
        if m is not None:
            return m
        reg = self._registry
        if reg is None:
            from . import get_registry
            reg = get_registry()
        m = (
            reg.counter(
                "dl4j_num_sentinel_trips_total",
                "Numerics-sentinel trips, by trip kind (nonfinite_grads "
                "/ nonfinite_loss / loss_spike)", labelnames=("kind",)),
            reg.gauge(
                "dl4j_num_loss_zscore",
                "Rolling z-score of the last observed loss against the "
                "sentinel window"),
        )
        self._m_cache = m
        return m

    # ------------------------------------------------------- grad path
    def check(self, stats, iteration: int):
        """GradientAnomalyDetector-compatible entry point: host-fetched
        per-layer grad stats from the jitted step (one step late via
        ``DelayedAnomalyCheck`` — the gate already ran in-jit). Hot
        path: two float reads per layer; the rms/absmax export shape is
        derived lazily by :attr:`last_grad_stats` (frequency-gated
        sampling and trips only)."""
        t0 = time.perf_counter()
        self._last_raw_grads = stats
        nonfinite = 0.0
        bad_l2 = False
        for s in stats.values():
            nonfinite += float(s.get("nonfinite", 0.0))
            if not math.isfinite(float(s.get("l2", 0.0))):
                bad_l2 = True
        self._overhead += time.perf_counter() - t0
        if nonfinite or bad_l2:
            self._trip("nonfinite_grads", iteration,
                       f"{int(nonfinite)} non-finite gradient "
                       "element(s)"
                       + (" (l2 overflowed)" if bad_l2 else "")
                       + ("" if self.gate_updates else
                          " (policy 'warn': update was APPLIED)"))
        else:
            self._active_trips.discard("nonfinite_grads")  # incident over
        return []   # detector API: anomalies list (sentinel keeps own)

    @property
    def last_grad_stats(self) -> Optional[Dict[str, Dict[str, float]]]:
        """The most recent step's per-layer grad stats in the numerics
        export shape ({layer: {l2, rms, absmax, nonfinite}}), converted
        on demand — None before the first step."""
        raw = self._last_raw_grads
        if raw is None:
            return None
        out: Dict[str, Dict[str, float]] = {}
        for layer, s in raw.items():
            d = {k: float(v) for k, v in s.items()}
            size = d.pop("size", 0.0)
            if size > 0:
                d["rms"] = d.get("l2", 0.0) / math.sqrt(size)
            d["absmax"] = d.pop("max_abs", d.get("absmax", 0.0))
            d["nonfinite"] = d.get("nonfinite", 0.0)
            out[str(layer)] = d
        return out

    # ------------------------------------------------------- loss path
    def observe_loss(self, model, iteration: int, score: float):
        """Called by ``NumericsListener`` every iteration: non-finite
        loss trips immediately; otherwise the score feeds the rolling
        z-score spike detector."""
        t0 = time.perf_counter()
        if model is not None and (self._model is None
                                  or self._model() is not model):
            self._model = weakref.ref(model)
        score = float(score)
        if not math.isfinite(score):
            self._overhead += time.perf_counter() - t0
            self._trip("nonfinite_loss", iteration, f"loss = {score}")
            return
        self._active_trips.discard("nonfinite_loss")       # incident over
        z = None
        n = len(self._scores)
        if n >= self.min_window:
            mean = self._sum / n
            var = max(self._sumsq / n - mean * mean, 0.0)
            floor = self.rel_floor * max(abs(mean), 1e-12)
            std = max(math.sqrt(var), floor)
            z = abs(score - mean) / std
            _, g_z = self._m()
            g_z.set(z)
        if n == self._scores.maxlen:      # evict before append
            old = self._scores[0]
            self._sum -= old
            self._sumsq -= old * old
        self._scores.append(score)
        self._sum += score
        self._sumsq += score * score
        self._overhead += time.perf_counter() - t0
        if z is not None and z > self.z_threshold:
            self._trip("loss_spike", iteration,
                       f"loss {score:.6g} is {z:.1f} sigma off the "
                       f"rolling window (threshold {self.z_threshold})")

    # ------------------------------------------------------------ trip
    def _stat_tree(self) -> Dict[str, Any]:
        """The offending step's full stat tree: params summarized live
        (one fused pass + one fetch), the step's grad stats, the recent
        loss window."""
        stats: Dict[str, Any] = {}
        model = self._model() if self._model is not None else None
        if model is not None and getattr(model, "params", None):
            try:
                stats["params"] = export_summary(summarize(model.params))
            except Exception as e:  # noqa: BLE001 — forensics must not
                stats["params"] = {"error": repr(e)}   # mask the trip
        if self.last_grad_stats is not None:
            stats["grads"] = self.last_grad_stats
        stats["loss_window"] = [round(s, 8) for s in self._scores]
        return stats

    def _trip(self, kind: str, iteration: int, detail: str):
        c_trips, _ = self._m()
        c_trips.inc(kind=kind)
        trip = {"reason": kind, "iteration": int(iteration),
                "detail": detail, "policy": self.policy,
                "ts": time.time()}
        self.trips.append(trip)
        del self.trips[:-64]
        if kind in self._active_trips:
            # repeat within one incident: counted and recorded above,
            # but no stat pass / re-dump / warning storm — the first
            # trip already left the forensics (and under policy "warn"
            # a poisoned run would otherwise pay a full device stat
            # pass + a whole ring dump EVERY step, uncounted by the
            # overhead budget)
            if self.policy == "raise":
                raise FloatingPointError(
                    f"numerics sentinel [{kind}] at iteration "
                    f"{iteration}: {detail} (repeat within incident)")
            return
        if kind != "loss_spike":
            # spikes are one-shot by construction (the spike value
            # enters the rolling window and damps immediate repeats);
            # gating them would swallow a genuinely new spike later
            self._active_trips.add(kind)
        stats = self._stat_tree()
        # publish the grads/params snapshot under the numerics gauges
        # too (layer-labeled) so /metrics shows WHICH layer poisoned
        for k in ("params", "grads"):
            if isinstance(stats.get(k), dict):
                try:
                    record_stats(stats[k], k, source="sentinel",
                                 replica=self.replica,
                                 registry=self._registry)
                except Exception:  # noqa: BLE001 — gauges are decoration
                    pass
        dump_path = None
        try:
            self.recorder.record_snapshot(kind="numerics", **trip,
                                          stats=stats)
            # append ONLY this trip's record (not recorder.dump(): that
            # re-appends the whole ring, duplicating earlier trips on
            # every new incident). dump_path=None at construction keeps
            # the record in the in-memory ring only (tests, embedded).
            if self.recorder.crash_dump_path:
                import json
                from pathlib import Path
                p = Path(self.recorder.crash_dump_path)
                p.parent.mkdir(parents=True, exist_ok=True)
                with open(p, "a") as f:
                    f.write(json.dumps({"kind": "numerics",
                                        "replica": self.replica,
                                        **trip, "stats": stats}) + "\n")
                dump_path = str(p)
        except Exception:  # noqa: BLE001 — a failed dump (full disk)
            pass           # must not mask the trip itself
        msg = (f"numerics sentinel [{kind}] at iteration {iteration}: "
               f"{detail}"
               + (f" — stat tree dumped to {dump_path}" if dump_path
                  else ""))
        if kind != "loss_spike" and self.policy == "raise":
            raise FloatingPointError(msg)
        warnings.warn(msg, RuntimeWarning, stacklevel=4)

    def report(self) -> Dict[str, Any]:
        """Plain-data state for /debug/numerics."""
        return {"policy": self.policy, "replica": self.replica,
                "trips": list(self.trips),
                "window": len(self._scores),
                "z_threshold": self.z_threshold,
                "overhead_seconds": round(self._overhead, 6)}


def live_sentinels() -> List[NumericsSentinel]:
    return sorted(_SENTINELS, key=lambda s: (s.replica, id(s)))


# ---------------------------------------------------------- drift audit

def checksum_ndarray(a) -> Dict[str, Any]:
    """Order-stable checksum of one host array: f64 sum (a drift
    MAGNITUDE when replicas diverge) + crc32 of the raw bytes (the
    bit-identity verdict)."""
    import numpy as np
    a = np.ascontiguousarray(a)
    return {"checksum": float(np.sum(a, dtype=np.float64)),
            "crc": zlib.crc32(a.tobytes()), "nbytes": int(a.nbytes)}


def tree_replica_checksums(tree) -> Dict[str, Dict[str, Any]]:
    """Per-device checksums over every REPLICATED leaf of ``tree``.

    A leaf whose addressable shards are full copies (dp replication)
    contributes each device's copy to that device's checksum — the
    copies MUST be bit-identical, and this measures whether they are.
    Sharded leaves (fsdp/tp: each device holds a different slice) are
    skipped — there is no cross-replica copy to compare. Host arrays
    and single-device leaves are one shared copy, not per-replica
    state: they fold IDENTICALLY into every replica's checksum (so
    crc equality across replicas is unaffected by them — a mixed tree
    must not raise a false drift alarm). With no replicated leaf at
    all, everything lands under replica "0"."""
    import jax
    import numpy as np
    acc: Dict[str, Tuple[float, int, int]] = {}

    def add(dev: str, data):
        data = np.ascontiguousarray(np.asarray(data))
        s, crc, nb = acc.get(dev, (0.0, 0, 0))
        acc[dev] = (s + float(np.sum(data, dtype=np.float64)),
                    zlib.crc32(data.tobytes(), crc), nb + data.nbytes)

    # pass 1: classify leaves; the replica set comes from the
    # replicated leaves (checksums are order-chained crc32, so the
    # device set must be known before the first leaf is folded)
    leaves = jax.tree_util.tree_leaves(tree)
    kinds: List[Optional[str]] = []
    devices: set = set()
    for leaf in leaves:
        shards = getattr(leaf, "addressable_shards", None)
        shape = getattr(leaf, "shape", None)
        if shards and len(shards) > 1:
            if any(tuple(sh.data.shape) != tuple(shape)
                   for sh in shards):
                kinds.append(None)  # genuinely sharded: nothing to compare
                continue
            kinds.append("replicated")
            devices.update(str(getattr(sh.device, "id", 0))
                           for sh in shards)
        else:
            kinds.append("shared")
    if not devices:
        devices = {"0"}
    for leaf, kind in zip(leaves, kinds):
        if kind is None:
            continue
        if kind == "replicated":
            for sh in leaf.addressable_shards:
                add(str(getattr(sh.device, "id", 0)), sh.data)
        else:
            data = np.ascontiguousarray(np.asarray(leaf))
            for dev in devices:
                add(dev, data)
    return {dev: {"checksum": s, "crc": crc, "nbytes": nb}
            for dev, (s, crc, nb) in acc.items()}


class DriftAuditor:
    """Collects (source, round, replica) checksums and compares the
    replicas of each round as they arrive: max |Δchecksum| and crc
    agreement across every replica that reported the round. In-process
    emitters (ParallelWrapper devices, threaded scaleout workers + hub)
    meet in the process-wide instance; multi-process deployments each
    export their own ``dl4j_replica_checksum`` gauge and an external
    scraper does the comparing — same metric either way."""

    def __init__(self, registry=None, keep_rounds: int = 64):
        self._registry = registry
        self.keep_rounds = int(keep_rounds)
        self._rounds: Dict[str, Dict[int, Dict[str, Dict]]] = {}
        self._summary: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._m_cache = None

    def _m(self):
        # cached (the sentinel-_m discipline): record() runs once per
        # replica per round — 4 registry get-or-creates each would add
        # up on a long scaleout job. The cache revalidates against the
        # registry: the process-wide auditor outlives a test's
        # registry.reset(), and stale handles would keep publishing
        # into gauges the registry no longer serves.
        reg = self._registry
        if reg is None:
            from . import get_registry
            reg = get_registry()
        if self._m_cache is not None:
            try:
                # identity, not name: after a registry.reset() someone
                # else may have re-registered the same NAME — publishing
                # into our orphaned pre-reset handle would still vanish
                # from the exporter
                if reg.get("dl4j_replica_checksum") \
                        is self._m_cache["checksum"]:
                    return self._m_cache
            except Exception:  # noqa: BLE001 — rebuild on any doubt
                pass
        self._m_cache = {
            "checksum": reg.gauge(
                "dl4j_replica_checksum",
                "Per-replica f64 param checksum at the last audited "
                "round", labelnames=("replica",)),
            "drift": reg.gauge(
                "dl4j_replica_drift_max",
                "Max |checksum delta| across replicas at the last "
                "audited round (0.0 = lockstep)"),
            "rounds": reg.counter(
                "dl4j_replica_drift_rounds_total",
                "Rounds with >=2 replica checksums compared"),
            "detected": reg.counter(
                "dl4j_replica_drift_detected_total",
                "Audited rounds where replica params were NOT "
                "bit-identical"),
        }
        return self._m_cache

    def record(self, source: str, replica: str, round_idx: int, *,
               checksum: float, crc: int, nbytes: int = 0):
        m = self._m()
        m["checksum"].set(checksum, replica=str(replica))
        with self._lock:
            rounds = self._rounds.setdefault(str(source), {})
            entry = rounds.setdefault(int(round_idx), {})
            entry[str(replica)] = {"checksum": checksum, "crc": crc,
                                   "nbytes": nbytes}
            summ = self._summary.setdefault(str(source), {
                "rounds_audited": 0, "max_drift": 0.0,
                "detected": 0, "last": None})
            reps = {k: v for k, v in entry.items()
                    if not k.startswith("_")}
            compared = len(reps) >= 2
            drift, identical, newly_detected = 0.0, True, False
            if compared:
                sums = [e["checksum"] for e in reps.values()]
                crcs = {e["crc"] for e in reps.values()}
                drift = max(sums) - min(sums)
                identical = len(crcs) == 1
                first_cmp = not entry.get("_compared")
                entry["_compared"] = True
                newly_detected = (not identical
                                  and not entry.get("_detected"))
                if newly_detected:
                    entry["_detected"] = True
                summ["last"] = {"round": int(round_idx),
                                "replicas": sorted(reps),
                                "max_drift": drift,
                                "bit_identical": identical}
                if first_cmp:
                    summ["rounds_audited"] += 1
                    m["rounds"].inc()
                summ["max_drift"] = max(summ["max_drift"], drift)
                if newly_detected:
                    summ["detected"] += 1
            # prune old rounds so a long job stays bounded
            while len(rounds) > self.keep_rounds:
                del rounds[min(rounds)]
        if compared:
            m["drift"].set(drift)
        if newly_detected:
            m["detected"].inc()
            warnings.warn(
                f"replica drift detected: source {source!r} round "
                f"{round_idx} — checksums span {drift:.3e} across "
                f"replicas {sorted(reps)} (params are NOT "
                "bit-identical; the lockstep contract is broken)",
                RuntimeWarning, stacklevel=3)

    def round_detail(self, source: str, round_idx: int) -> Dict:
        with self._lock:
            entry = self._rounds.get(str(source), {}).get(int(round_idx),
                                                          {})
            return {k: dict(v) for k, v in entry.items()
                    if not k.startswith("_")}

    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {src: dict(summ)
                    for src, summ in sorted(self._summary.items())}

    def reset_source(self, source: str):
        """Drop one source's rounds and summary — a FRESH job reusing
        an address (round counter back at 0) must not be compared
        against the previous job's stale checksums."""
        with self._lock:
            self._rounds.pop(str(source), None)
            self._summary.pop(str(source), None)

    def reset(self):
        with self._lock:
            self._rounds.clear()
            self._summary.clear()


_AUDITOR = DriftAuditor()


def get_auditor() -> DriftAuditor:
    """The process-wide drift auditor every built-in emitter records
    into (ParallelWrapper, the scaleout hub + workers)."""
    return _AUDITOR


def drift_report() -> Dict[str, Any]:
    return _AUDITOR.report()


# per-source auto round counter for audit_params
_AUDIT_ROUNDS: Dict[str, int] = {}


def audit_params(tree, *, source: str = "parallel_fit",
                 round_idx: Optional[int] = None,
                 auditor: Optional[DriftAuditor] = None) -> Dict[str, Any]:
    """Audit one replicated pytree NOW: per-device checksums over every
    replicated leaf, recorded into the auditor under ``source`` (round
    auto-increments per source when not given). Returns the round's
    verdict: ``{replicas, max_drift, bit_identical, round}``."""
    auditor = auditor or _AUDITOR
    with _LOCK:
        if round_idx is None:
            round_idx = _AUDIT_ROUNDS.get(source, 0) + 1
        _AUDIT_ROUNDS[source] = int(round_idx)
    by_dev = tree_replica_checksums(tree)
    for dev, cs in sorted(by_dev.items()):
        auditor.record(source, dev, int(round_idx), **cs)
    detail = auditor.round_detail(source, int(round_idx))
    sums = [e["checksum"] for e in detail.values()]
    crcs = {e["crc"] for e in detail.values()}
    return {"round": int(round_idx), "replicas": sorted(detail),
            "max_drift": (max(sums) - min(sums)) if len(sums) > 1 else 0.0,
            "bit_identical": len(crcs) <= 1}


# ------------------------------------------------------------ debug API

def debug_state() -> Dict[str, Any]:
    """What ``GET /debug/numerics`` returns: latest stat exports per
    (source, replica), every live sentinel's report, the drift-audit
    summary, and the latest fidelity-probe reports."""
    fid: Any = []
    try:
        from . import fidelity as obs_fidelity
        fid = obs_fidelity.latest_reports()
    except Exception:  # noqa: BLE001 — debug must not raise
        pass
    return {"stats": latest_stats(),
            "sentinels": [s.report() for s in live_sentinels()],
            "drift": drift_report(),
            "fidelity": fid}
