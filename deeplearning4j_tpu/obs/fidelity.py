"""Logit-fidelity probes — candidate-vs-reference model paths measured,
not guessed (ISSUE 13).

Every upcoming inference lever — flash vs XLA attention, bf16 vs fp32,
int8/fp8 KV cache, int8 weights, speculative drafts (ROADMAP item 3) —
is a *numerics trade*: it changes the logits a little in exchange for
bytes or latency. This module turns "a little" into recorded numbers:

- :class:`FidelityProbe` runs (or is handed) two logit tensors over the
  SAME inputs and reports per-position max-abs logit error, KL
  divergence of the predicted distributions, top-k set agreement, and
  the greedy-token-match prefix length — the acceptance oracle the
  spec-decode and quantized-KV PRs import (greedy spec-decode must be
  token-exact; a quantized cache must hold KL under a budget).
  Reports publish as ``dl4j_fidelity_*{kind}`` gauges and are kept for
  ``GET /debug/numerics`` and ``scripts/fidelity_report.py`` (which
  gates with ``--max-kl``).
- :func:`compare_trees` + :class:`MeasuredBound` +
  :func:`assert_trees_close` replace ad-hoc test tolerances: the bound
  asserted in a test is ``margin ×`` a RECORDED measurement (value,
  backend, date in ``source``), and a failure prints the probe's
  actual measured report instead of numpy's element dump.

All comparison math is host-side f64 numpy over logits that were
coming to host anyway (bench rows, tests) — the probe adds no device
work to the paths it judges.

Label discipline: ``dl4j_fidelity_*`` labels by ``kind`` only (the
probe pair's name, a small fixed vocabulary like ``flash_vs_xla``) —
``scripts/check_metric_names.py`` enforces.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List


def _as_positions(logits):
    """(…, V) → (N, V) f64 numpy, position order preserved (a (B, T, V)
    tensor flattens batch-major so per-sequence prefixes stay
    contiguous)."""
    import numpy as np
    a = np.asarray(logits, np.float64)
    if a.ndim == 1:
        a = a[None, :]
    return a.reshape(-1, a.shape[-1])


def _log_softmax(a):
    import numpy as np
    m = a.max(axis=-1, keepdims=True)
    z = a - m
    return z - np.log(np.exp(z).sum(axis=-1, keepdims=True))


def compare_logits(ref_logits, cand_logits, *, top_k: int = 5
                   ) -> Dict[str, Any]:
    """Fidelity report of candidate vs reference logits over the same
    inputs. Shapes must match ((T, V), (B, T, V), (N, V) — anything
    with a trailing vocab axis).

    - ``max_abs_err`` / ``mean_abs_err``: raw logit error (the number a
      kernel-equivalence claim quotes);
    - ``kl_mean`` / ``kl_max``: KL(ref ‖ cand) per position, nats —
      the distribution-level damage sampling actually sees;
    - ``topk_agreement``: mean |top-k(ref) ∩ top-k(cand)| / k;
    - ``greedy_match_frac`` and ``greedy_prefix_len``: argmax agreement
      overall and the longest matching prefix in position order — the
      spec-decode acceptance quantity.
    """
    import numpy as np
    ref = _as_positions(ref_logits)
    cand = _as_positions(cand_logits)
    if ref.shape != cand.shape:
        raise ValueError(f"shape mismatch: reference {ref.shape} vs "
                         f"candidate {cand.shape}")
    n, v = ref.shape
    k = max(1, min(int(top_k), v))
    err = np.abs(ref - cand)
    lp_ref = _log_softmax(ref)
    lp_cand = _log_softmax(cand)
    kl = (np.exp(lp_ref) * (lp_ref - lp_cand)).sum(axis=-1)
    kl = np.maximum(kl, 0.0)          # clamp -0.0 float noise
    # top-k set agreement per position
    tk_ref = np.argpartition(-ref, k - 1, axis=-1)[:, :k]
    tk_cand = np.argpartition(-cand, k - 1, axis=-1)[:, :k]
    agree = np.empty((n,), np.float64)
    for i in range(n):              # n is a probe length, not a corpus
        agree[i] = len(np.intersect1d(tk_ref[i], tk_cand[i],
                                      assume_unique=True)) / k
    greedy = ref.argmax(-1) == cand.argmax(-1)
    mismatches = np.nonzero(~greedy)[0]
    prefix = int(mismatches[0]) if mismatches.size else n
    return {
        "positions": int(n), "vocab": int(v), "top_k": int(k),
        "max_abs_err": float(err.max()),
        "mean_abs_err": float(err.mean()),
        "kl_mean": float(kl.mean()), "kl_max": float(kl.max()),
        "topk_agreement": float(agree.mean()),
        "greedy_match_frac": float(greedy.mean()),
        "greedy_prefix_len": prefix,
    }


# latest report per probe kind — /debug/numerics + fidelity_report
_LATEST: Dict[str, Dict[str, Any]] = {}
_LOCK = threading.Lock()


class FidelityProbe:
    """One named candidate-vs-reference comparison channel.

    ``kind`` names the pair (``flash_vs_xla``, ``bf16_vs_fp32``,
    ``int8kv_vs_fp32`` …) and is the ONLY metric label — keep it a
    small fixed vocabulary. ``compare`` takes precomputed logits;
    ``run`` calls the two paths itself over shared inputs."""

    def __init__(self, kind: str, *, top_k: int = 5, registry=None):
        self.kind = str(kind)
        self.top_k = int(top_k)
        self._registry = registry
        self._m_cache = None

    def _m(self):
        # cached per probe (the NumericsSentinel._m discipline) — a
        # probe wired into a bench or test loop observes repeatedly
        if self._m_cache is not None:
            return self._m_cache
        reg = self._registry
        if reg is None:
            from . import get_registry
            reg = get_registry()
        lab = ("kind",)
        self._m_cache = {
            "probes": reg.counter(
                "dl4j_fidelity_probes_total",
                "Fidelity-probe comparisons run, by probe kind",
                labelnames=lab),
            "max_abs_err": reg.gauge(
                "dl4j_fidelity_max_abs_err",
                "Max |candidate − reference| logit error over the "
                "probe's positions", labelnames=lab),
            "kl_mean": reg.gauge(
                "dl4j_fidelity_kl_mean",
                "Mean per-position KL(ref ‖ cand), nats",
                labelnames=lab),
            "kl_max": reg.gauge(
                "dl4j_fidelity_kl_max",
                "Max per-position KL(ref ‖ cand), nats",
                labelnames=lab),
            "topk_agreement": reg.gauge(
                "dl4j_fidelity_topk_agreement",
                "Mean top-k set agreement between the two paths",
                labelnames=lab),
            "greedy_match_frac": reg.gauge(
                "dl4j_fidelity_greedy_match_frac",
                "Fraction of positions where argmax agrees",
                labelnames=lab),
            "greedy_prefix": reg.gauge(
                "dl4j_fidelity_greedy_prefix",
                "Longest position prefix with matching greedy tokens",
                labelnames=lab),
        }
        return self._m_cache

    def compare(self, ref_logits, cand_logits, *, observe: bool = True
                ) -> Dict[str, Any]:
        report = compare_logits(ref_logits, cand_logits,
                                top_k=self.top_k)
        report["kind"] = self.kind
        report["ts"] = time.time()
        if observe:
            self.observe(report)
        return report

    def run(self, ref_fn: Callable, cand_fn: Callable, *inputs,
            observe: bool = True) -> Dict[str, Any]:
        """Run both paths over the same inputs and compare. The
        reference runs FIRST (so a candidate crash still leaves the
        reference logits computed for debugging)."""
        ref = ref_fn(*inputs)
        cand = cand_fn(*inputs)
        return self.compare(ref, cand, observe=observe)

    def observe(self, report: Dict[str, Any]):
        m = self._m()
        m["probes"].inc(kind=self.kind)
        for key, gauge_key in (("max_abs_err", "max_abs_err"),
                               ("kl_mean", "kl_mean"),
                               ("kl_max", "kl_max"),
                               ("topk_agreement", "topk_agreement"),
                               ("greedy_match_frac",
                                "greedy_match_frac"),
                               ("greedy_prefix_len", "greedy_prefix")):
            if key in report:
                m[gauge_key].set(float(report[key]), kind=self.kind)
        with _LOCK:
            _LATEST[self.kind] = dict(report)


def latest_reports() -> List[Dict[str, Any]]:
    """Every probe kind's most recent report, stable order."""
    with _LOCK:
        return [_LATEST[k] for k in sorted(_LATEST)]


def reset_reports():
    """Drop recorded reports (tests)."""
    with _LOCK:
        _LATEST.clear()


# ----------------------------------------------- measured test bounds

def compare_trees(ref_tree, got_tree) -> Dict[str, float]:
    """Element-wise error measurement over two matching pytrees (grads,
    params): max/mean abs error, max relative error (|Δ|/|ref|, zeros
    excluded), rms error, and the reference scale — the measurement a
    :class:`MeasuredBound` records and :func:`assert_trees_close`
    re-asserts."""
    import jax
    import numpy as np
    leaves_r = jax.tree_util.tree_leaves(ref_tree)
    leaves_g = jax.tree_util.tree_leaves(got_tree)
    if len(leaves_r) != len(leaves_g):
        raise ValueError("tree structures differ")
    max_abs = mean_num = mean_den = rms_num = 0.0
    max_rel = 0.0
    ref_absmax = 0.0
    for a, b in zip(leaves_r, leaves_g):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        d = np.abs(a - b)
        if d.size == 0:
            continue
        max_abs = max(max_abs, float(d.max()))
        mean_num += float(d.sum())
        rms_num += float((d * d).sum())
        mean_den += d.size
        ref_absmax = max(ref_absmax, float(np.abs(a).max()) if a.size
                         else 0.0)
        nz = np.abs(a) > 0
        if nz.any():
            max_rel = max(max_rel, float((d[nz] / np.abs(a[nz])).max()))
    return {
        "max_abs_err": max_abs,
        "mean_abs_err": mean_num / max(mean_den, 1.0),
        "rms_err": (rms_num / max(mean_den, 1.0)) ** 0.5,
        "max_rel_err": max_rel,
        "ref_absmax": ref_absmax,
    }


@dataclass(frozen=True)
class MeasuredBound:
    """A test tolerance that is a recorded measurement, not a magic
    constant: ``measured_abs`` / ``measured_rel`` are the errors
    actually observed when the bound was calibrated (``source`` says
    where and when), and the asserted tolerance is ``margin ×`` that —
    the margin is the only judgement call, and it is explicit."""

    measured_abs: float
    measured_rel: float
    source: str
    margin: float = 8.0

    @property
    def atol(self) -> float:
        return self.margin * self.measured_abs

    @property
    def rtol(self) -> float:
        return self.margin * self.measured_rel


def assert_trees_close(ref_tree, got_tree, bound: MeasuredBound,
                       what: str = "") -> Dict[str, float]:
    """allclose with measured tolerances: every element must satisfy
    ``|got − ref| ≤ bound.atol + bound.rtol·|ref|``. On failure the
    error message is the probe's measured report next to the recorded
    calibration — the drift is quantified, not just flagged. Returns
    the measurement (tests can additionally log or assert on it)."""
    import jax
    import numpy as np
    report = compare_trees(ref_tree, got_tree)
    worst = 0.0
    for a, b in zip(jax.tree_util.tree_leaves(ref_tree),
                    jax.tree_util.tree_leaves(got_tree)):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        if a.size == 0:
            continue
        excess = np.abs(a - b) - (bound.atol + bound.rtol * np.abs(a))
        worst = max(worst, float(excess.max()))
    if worst > 0:
        raise AssertionError(
            f"{what or 'trees'} drifted past the measured bound: "
            f"measured now {report}, bound = {bound.margin}x recorded "
            f"(abs {bound.measured_abs:g}, rel {bound.measured_rel:g}) "
            f"from {bound.source}; worst excess {worst:.3e}")
    return report
