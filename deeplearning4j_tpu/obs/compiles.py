"""Compile sentinel — retrace detection on the jitted entry points
(ISSUE 12).

A silent retrace storm erases any serving or memory win: one stray
weak-typed scalar or shape drift turns the "compiled once, reused
forever" contract into a per-call compile, and nothing in the metrics
plane would say so — throughput just craters. The sentinel makes
compilation a first-class observable:

- every jitted entry point that matters (MLN/CG train step, the
  engine's ``prefill`` / ``prefill_slot`` / ``decode_step`` /
  ``sample_tokens``, the ParallelWrapper step) is wrapped in a
  :class:`CompileSentinel`;
- each compile is counted per (fn, abstract signature)
  (``dl4j_compile_total{component=}``), timed
  (``dl4j_compile_seconds{component=}``) and deposited as a
  ``compile.<name>`` span on the process tracer;
- after ``mark_warm()`` any further compile is a RETRACE: it increments
  ``dl4j_compile_retraces_total{component=}`` and raises a
  ``RuntimeWarning`` — the regression tests assert the donated train
  step and the decode sweep are zero-recompile after warmup, and
  bucket-padded prefill compiles at most once per bucket.

Detection is the jit cache itself where available
(``fn._cache_size()`` growing across a call — exact, and O(1) on the
hot path), falling back to new-abstract-signature detection on
callables that don't expose a cache. The wrapper is transparent:
``lower``, ``__wrapped__`` and everything else delegate to the wrapped
function, so floor probes (``.lower()``) and ``fit_scanned``
(``step_fn.__wrapped__``) see the jit object they always saw.

Timing caveat, documented rather than hidden: a "compile" observation
spans the whole first call at that signature — trace + compile + first
execution — because jax gives no host-side hook between them. For the
retrace-storm failure mode that is the right number anyway (it is the
latency the caller actually lost).

Hot-path budget: a non-compiling call costs one ``_cache_size()`` read
and two clock reads; the sentinel self-times into
``overhead_seconds`` and the plane-wide <2% budget test covers it.
"""

from __future__ import annotations

import time
import warnings
from typing import Any, Callable, Dict, Optional, Tuple


def abstract_signature(args: tuple, kwargs: dict) -> Tuple:
    """Hashable (treedef, per-leaf shape/dtype) key — two calls with the
    same signature trace to the same jaxpr. Non-array leaves key by
    ``repr`` (the static-argument behaviour of jit itself)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))

    def one(x):
        shape = getattr(x, "shape", None)
        dt = getattr(x, "dtype", None)
        if shape is not None and dt is not None:
            return (tuple(shape), str(dt),
                    bool(getattr(x, "weak_type", False)))
        return ("static", repr(x))

    return (treedef, tuple(one(x) for x in leaves))


class CompileSentinel:
    """Transparent wrapper around one jitted callable that observes its
    compiles. Construct once next to the ``jax.jit`` call; invoke like
    the function it wraps."""

    def __init__(self, name: str, fn: Callable, *, registry=None):
        self.name = str(name)
        self._fn = fn
        self._registry = registry
        self.compiles = 0
        self.retraces_after_warm = 0
        self.warm = False
        self.signatures: Dict[Tuple, int] = {}
        self._overhead = 0.0
        self._last_size = self._cache_size()

    # ------------------------------------------------------- plumbing
    def __getattr__(self, item):
        # transparency: .lower (floor probes), .__wrapped__
        # (fit_scanned's scan body), ._cache_size, anything else
        if item == "_fn":        # guard: nothing may recurse before
            raise AttributeError(item)   # __init__ binds the target
        return getattr(self._fn, item)

    def _cache_size(self) -> Optional[int]:
        try:
            return int(self._fn._cache_size())
        except Exception:  # noqa: BLE001 — not a jit wrapper; fall back
            return None

    def _m(self):
        reg = self._registry
        if reg is None:
            from . import get_registry
            reg = get_registry()
        return (
            reg.counter(
                "dl4j_compile_total",
                "Compilations observed per jitted entry point",
                labelnames=("component",)),
            reg.histogram(
                "dl4j_compile_seconds",
                "Wall time of the call that compiled (trace + compile + "
                "first execution at that signature)",
                labelnames=("component",)),
            reg.counter(
                "dl4j_compile_retraces_total",
                "Compilations AFTER mark_warm() — each one is a retrace "
                "storm warning",
                labelnames=("component",)),
        )

    # ------------------------------------------------------ lifecycle
    def mark_warm(self) -> "CompileSentinel":
        """Declare warmup over: every compile from here on is a retrace
        (warned + counted). Arming is EXPLICIT — the caller decides
        when the working set of shapes is complete, because only the
        caller knows it (auto-arming after one cycle would false-alarm
        on the first prompt to hit a new, legitimate prefill bucket).
        ``engine.mark_warm()`` arms all four serving entry points at
        once; benches arm after their warm-up request, operators after
        their traffic's bucket sweep."""
        self.warm = True
        return self

    @property
    def overhead_seconds(self) -> float:
        """Cumulative sentinel bookkeeping cost, wrapped-call excluded
        (the MetricsListener self-timing discipline)."""
        return self._overhead

    def report(self) -> Dict[str, Any]:
        return {"name": self.name, "compiles": self.compiles,
                "signatures": len(self.signatures), "warm": self.warm,
                "retraces_after_warm": self.retraces_after_warm}

    # ----------------------------------------------------------- call
    def __call__(self, *args, **kwargs):
        t0 = time.perf_counter()
        before = self._last_size
        t_call = time.perf_counter()
        out = self._fn(*args, **kwargs)
        t_done = time.perf_counter()
        after = self._cache_size()
        self._last_size = after
        if before is not None and after is not None:
            compiled = after > before
            sig = abstract_signature(args, kwargs) if compiled else None
        else:
            # no cache introspection on this callable: a new abstract
            # signature is the best available compile signal (misses a
            # same-signature retrace; the jit-backed path catches those)
            sig = abstract_signature(args, kwargs)
            compiled = sig not in self.signatures
        if compiled:
            self._record_compile(sig, t_done - t_call)
        self._overhead += (t_call - t0) + (time.perf_counter() - t_done)
        return out

    def _record_compile(self, sig, dt: float):
        self.compiles += 1
        self.signatures[sig] = self.signatures.get(sig, 0) + 1
        c_total, c_secs, c_retr = self._m()
        c_total.inc(component=self.name)
        c_secs.observe(dt, component=self.name)
        try:
            from .spans import Span, derived_span_id, get_tracer
            tracer = get_tracer()
            trace_id = derived_span_id("dl4j_compile", self.name)
            tracer.add_span(Span(
                name=f"compile.{self.name}", trace_id=trace_id,
                span_id=derived_span_id(trace_id, self.compiles),
                start_ts=time.time() - dt, time_s=dt,
                attrs={"component": self.name,
                       "compile_index": self.compiles,
                       "retrace": self.warm}))
        except Exception:  # noqa: BLE001 — span export is decoration
            pass
        if self.warm:
            self.retraces_after_warm += 1
            c_retr.inc(component=self.name)
            warnings.warn(
                f"post-warmup retrace #{self.retraces_after_warm} of "
                f"{self.name!r} (compile {self.compiles}, "
                f"{dt * 1e3:.1f} ms): a shape/dtype/static-arg drifted — "
                "a retrace storm erases the compiled-once contract",
                RuntimeWarning, stacklevel=3)


def wrap_jit(name: str, fn: Callable, *, registry=None) -> CompileSentinel:
    """Construction shorthand: ``wrap_jit("decode_step", jax.jit(f))``."""
    return CompileSentinel(name, fn, registry=registry)
