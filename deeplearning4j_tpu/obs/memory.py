"""Memory census — HBM attribution over named components (ISSUE 12).

Every serving bench row's floor block says decode is MEMORY-bound, and
the two biggest ROADMAP levers (paged KV cache, ZeRO update sharding)
are memory plays: one must prove short requests stop paying ``max_len``
bytes, the other must prove a per-chip memory drop. Neither can be
sized or guarded without attribution — *whose* bytes are on the chip?

This module answers with two sources, combined:

- :func:`tree_bytes` — pytree attribution. Sums leaf ``nbytes`` over a
  named component (params, optimizer state, KV cache, workspace), which
  works on EVERY backend — the CPU tier-1 suite gets real numbers, not
  a silent gap (the ``MetricsListener._poll_memory`` degradation this
  PR fixes). Per-replica attribution reads each leaf's addressable
  shards, so an fsdp-sharded param tree reports what each device
  actually holds, not the logical size.
- :func:`device_memory_stats` — the allocator's own view
  (``device.memory_stats()``: bytes_in_use / peak_bytes_in_use /
  bytes_limit), present on TPU/GPU, gracefully ``None`` on CPU. The
  census carries BOTH: pytree bytes attribute, allocator bytes bound —
  the gap between them is fragmentation + XLA workspace, itself a
  number worth watching.

:func:`emit_census` publishes a census as
``dl4j_mem_component_bytes{component, replica}`` gauges on the process
registry and remembers the latest census per (source, replica) so
``GET /debug/memory`` on the UI server and ``scripts/mem_report.py``
can show the current attribution without re-walking live pytrees.

Label discipline (``scripts/check_metric_names.py`` enforces): the
``dl4j_mem_*`` / ``dl4j_kv_*`` / ``dl4j_compile_*`` plane may label by
``component`` and ``replica`` ONLY — component names are a small fixed
vocabulary (params / optimizer / kv_cache / grads / workspace / total),
never per-request identity.

No jax import at module load — and no package-relative import either:
like the registry, the census must be importable from the UI process
and bench subprocesses, and this file is additionally loaded STANDALONE
by file path (``scripts/refresh_readme_table.py`` borrows
:func:`format_bytes` without paying the package's jax import chain).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

# the small fixed component vocabulary — emit_census warns (via ValueError)
# on names outside it so dashboards aggregate a stable label set
KNOWN_COMPONENTS = ("params", "optimizer", "kv_cache", "grads",
                    "workspace", "states", "total")

_DEVICE_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                     "largest_alloc_size")


def format_bytes(v) -> str:
    """Human-readable bytes — the ONE implementation both
    ``scripts/mem_report.py`` and the README table renderer use, so a
    byte count never renders two ways."""
    if v is None:
        return "-"
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024 or unit == "GiB":
            return f"{int(v)} B" if unit == "B" else f"{v:,.1f} {unit}"
        v /= 1024
    return f"{v:,.1f} GiB"   # unreachable; keeps the signature total


def _leaf_nbytes(x) -> int:
    nb = getattr(x, "nbytes", None)
    if nb is not None:
        return int(nb)
    size = getattr(x, "size", None)
    dt = getattr(x, "dtype", None)
    if size is not None and dt is not None:
        return int(size) * int(getattr(dt, "itemsize", 0) or 0)
    return 0


def tree_bytes(tree) -> int:
    """Total bytes held by a pytree's array leaves (None leaves free)."""
    if tree is None:
        return 0
    import jax
    return sum(_leaf_nbytes(leaf)
               for leaf in jax.tree_util.tree_leaves(tree))


def component_bytes(components: Dict[str, Any]) -> Dict[str, int]:
    """{name: pytree} → {name: bytes}; a ``total`` row is appended."""
    out = {name: tree_bytes(tree) for name, tree in components.items()}
    out["total"] = sum(out.values())
    return out


def per_replica_bytes(tree) -> Dict[str, int]:
    """Bytes each addressable device actually holds of ``tree``.

    A sharded leaf contributes each shard's bytes to that shard's
    device; an unsharded/host leaf contributes everything to replica
    "0". This is what makes the ZeRO per-chip-memory-drop proof a
    gauge read instead of a hand calculation."""
    if tree is None:
        return {"0": 0}
    import jax
    acc: Dict[str, int] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for sh in shards:
                key = str(getattr(sh.device, "id", 0))
                acc[key] = acc.get(key, 0) + _leaf_nbytes(sh.data)
        else:
            acc["0"] = acc.get("0", 0) + _leaf_nbytes(leaf)
    return acc or {"0": 0}


def device_memory_stats(device=None) -> Optional[Dict[str, float]]:
    """The allocator's view for one device, or None where the backend
    has no ``memory_stats`` (CPU) — callers fall back to pytree sizes,
    they never go blind."""
    try:
        import jax
        dev = device or jax.local_devices()[0]
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 — absence is an expected backend trait
        return None
    if not stats:
        return None
    return {k: float(stats[k]) for k in _DEVICE_STAT_KEYS if k in stats}


# --------------------------------------------------------------- census

# latest census per (source, replica) — what /debug/memory serves
_CENSUSES: Dict[tuple, Dict[str, Any]] = {}
_LOCK = threading.Lock()


def emit_census(components: Dict[str, Any], *, replica: str = "0",
                source: str = "train", registry=None,
                per_replica: bool = False) -> Dict[str, Any]:
    """Attribute ``components`` ({name: pytree}) and publish.

    Sets ``dl4j_mem_component_bytes{component, replica}`` gauges,
    attaches the allocator stats when the backend has them (graceful
    absence on CPU — the pytree numbers stand alone), and records the
    census for ``GET /debug/memory``.

    ``registry`` is a :class:`~.registry.MetricsRegistry` (un-annotated
    on purpose: this module must load standalone by file path, so it
    imports nothing package-relative, not even for a type hint);
    ``None`` means the process-wide registry.

    With ``per_replica=True`` the GAUGES are per-device: each component
    split by the devices its shards actually live on (ParallelWrapper
    wiring — the per-chip number the ZeRO memory-drop proof reads).
    The aggregate numbers live in the returned census record's
    ``component_bytes``; they are deliberately NOT also written under
    ``replica`` — device ids start at "0" and would silently overwrite
    the aggregate row, leaving components that don't sum to ``total``.

    Returns the census record (plain data, JSON-able).
    """
    for name in components:
        if name not in KNOWN_COMPONENTS:
            raise ValueError(
                f"unknown memory component {name!r}: pick from "
                f"{KNOWN_COMPONENTS[:-1]} (a stable label vocabulary — "
                "extend KNOWN_COMPONENTS deliberately)")
    if registry is None:
        from . import get_registry
        registry = get_registry()
    gauge = registry.gauge(
        "dl4j_mem_component_bytes",
        "Device bytes attributed to a named component (pytree census; "
        "the allocator view rides the census record)",
        labelnames=("component", "replica"))
    by_comp = component_bytes(components)
    rep = str(replica)
    census: Dict[str, Any] = {
        "kind": "memcensus", "source": source, "replica": rep,
        "ts": time.time(), "component_bytes": by_comp,
    }
    if per_replica:
        split: Dict[str, Dict[str, int]] = {}
        for name, tree in components.items():
            for dev, nbytes in per_replica_bytes(tree).items():
                split.setdefault(dev, {})
                split[dev][name] = split[dev].get(name, 0) + nbytes
        for dev, comps in split.items():
            comps["total"] = sum(comps.values())
            for name, nbytes in comps.items():
                gauge.set(float(nbytes), component=name, replica=dev)
        census["per_replica_bytes"] = split
    else:
        for name, nbytes in by_comp.items():
            gauge.set(float(nbytes), component=name, replica=rep)
    stats = device_memory_stats()
    census["device"] = stats                  # None on CPU — explicit
    census["device_source"] = "memory_stats" if stats else "pytree"
    with _LOCK:
        _CENSUSES[(source, rep)] = census
    return census


def latest_censuses() -> List[Dict[str, Any]]:
    """Every (source, replica)'s most recent census, stable order."""
    with _LOCK:
        return [_CENSUSES[k] for k in sorted(_CENSUSES)]


def reset_censuses():
    """Drop recorded censuses (tests)."""
    with _LOCK:
        _CENSUSES.clear()


def debug_state() -> Dict[str, Any]:
    """What ``GET /debug/memory`` returns: the latest census per
    source/replica, the live allocator view, and the KV-residency
    accounting of every live scheduler (via its flight recorder's
    ``extra_state`` — the same hook /debug/serving reads)."""
    kv = []
    try:
        from .reqtrace import live_flight_recorders
        for fr in live_flight_recorders():
            if fr.extra_state is None:
                continue
            try:
                state = fr.extra_state()
            except Exception as e:  # noqa: BLE001 — debug must not raise
                state = {"error": repr(e)}
            if "kv" in state:
                kv.append({"replica": fr.replica, **state["kv"]})
    except Exception:  # noqa: BLE001 — debug must not raise
        pass
    return {"censuses": latest_censuses(),
            "device": device_memory_stats(),
            "kv": kv}
