"""Span tracer — nested wall-clock (optionally device-synced) timing
regions that stitch across process/thread boundaries.

A span records name, trace/span/parent ids, start timestamp, duration,
and free-form attrs. The current span rides a ``contextvars.ContextVar``
so nesting is automatic within a thread; across threads, processes, or
sockets the parent travels as a serialized ``SpanContext`` header
(``to_header`` / ``from_header`` — ``parallel/transport.py`` packs it
into wire frames, ``parallel/scaleout.py`` hands it to every worker so a
master round and its worker fits land in ONE trace tree).

Timing levels mirror ``utils/tracing.py``'s discipline: the default is
host wall-clock; pass/set a ``sync`` value (any jax pytree) and the span
calls ``jax.block_until_ready`` on it before taking the end timestamp,
so the span covers device work too (NB: through the axon tunnel only a
real host fetch syncs — see bench.py; on-chip sync spans are for local
backends). Export is JSONL, one record per span, carrying the same
``time_s`` key as tracing.py's profile records so existing tooling can
aggregate either stream:

    {"kind": "span", "name": ..., "trace_id": ..., "span_id": ...,
     "parent_id": ..., "start_ts": <epoch s>, "time_s": <duration s>,
     "synced": bool, "attrs": {...}}
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def derived_span_id(trace_id: str, *parts: Any) -> str:
    """Deterministic span id from (trace, parts) — lets two sides of a
    wire agree on a span's identity WITHOUT a round-trip (scaleout
    workers parent their fit spans to round k's id before the master has
    finished round k)."""
    h = hashlib.md5(":".join([trace_id, *map(str, parts)]).encode())
    return h.hexdigest()[:16]


@dataclass(frozen=True)
class SpanContext:
    trace_id: str
    span_id: str

    def to_header(self) -> str:
        return json.dumps({"trace_id": self.trace_id,
                           "span_id": self.span_id})

    @staticmethod
    def from_header(header: Optional[str]) -> Optional["SpanContext"]:
        if not header:
            return None
        try:
            d = json.loads(header)
            return SpanContext(str(d["trace_id"]), str(d["span_id"]))
        except (ValueError, KeyError, TypeError):
            return None


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    start_ts: float = 0.0
    time_s: float = 0.0
    synced: bool = False
    attrs: Dict[str, Any] = field(default_factory=dict)
    _sync: Any = None

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def set_sync(self, value: Any) -> "Span":
        """Register a jax value to block on before the end timestamp."""
        self._sync = value
        return self

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def record(self) -> dict:
        return {"kind": "span", "name": self.name,
                "trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "start_ts": self.start_ts,
                "time_s": self.time_s, "synced": self.synced,
                "attrs": self.attrs}


class Tracer:
    """Collects finished spans (bounded ring — never OOMs a long run;
    drops are counted, not silent) and owns the current-span context.
    The ring evicts the OLDEST spans: late spans are the enclosing ones
    (a job root closes last), and an exported tree must keep its root
    for the orphan-free stitching walk the tests perform."""

    def __init__(self, max_spans: int = 20000):
        self.max_spans = max_spans
        self.dropped = 0
        self._finished: "deque[Span]" = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._current: "contextvars.ContextVar[Optional[SpanContext]]" = \
            contextvars.ContextVar("dl4j_current_span", default=None)

    # ------------------------------------------------------ context
    def current_context(self) -> Optional[SpanContext]:
        return self._current.get()

    @contextlib.contextmanager
    def use_context(self, ctx: Optional[SpanContext]):
        """Adopt a remote parent (deserialized from a wire header) for
        the duration of the block — the receiving half of cross-
        transport propagation."""
        token = self._current.set(ctx)
        try:
            yield ctx
        finally:
            self._current.reset(token)

    # ------------------------------------------------------ spans
    @contextlib.contextmanager
    def span(self, name: str, attrs: Optional[Dict[str, Any]] = None,
             sync: Any = None, parent: Optional[SpanContext] = None,
             span_id: Optional[str] = None):
        parent_ctx = parent if parent is not None else self._current.get()
        trace_id = parent_ctx.trace_id if parent_ctx else _new_id()
        sp = Span(name=name, trace_id=trace_id,
                  span_id=span_id or _new_id(),
                  parent_id=parent_ctx.span_id if parent_ctx else None,
                  attrs=dict(attrs or {}), _sync=sync)
        token = self._current.set(sp.context)
        sp.start_ts = time.time()
        t0 = time.perf_counter()
        try:
            yield sp
        finally:
            self._current.reset(token)
            if sp._sync is not None:
                try:
                    import jax
                    jax.block_until_ready(sp._sync)
                    sp.synced = True
                except Exception:  # noqa: BLE001 — sync is best-effort
                    pass
            sp.time_s = time.perf_counter() - t0
            with self._lock:
                if len(self._finished) == self.max_spans:
                    self.dropped += 1   # deque(maxlen) evicts the oldest
                self._finished.append(sp)

    def add_span(self, sp: Span):
        """Record an externally-assembled span. The scaleout hub times a
        round across several handler threads (first frame -> close), so
        no single thread can hold the ``span()`` context manager open —
        it builds the Span by hand and deposits it here."""
        with self._lock:
            if len(self._finished) == self.max_spans:
                self.dropped += 1
            self._finished.append(sp)

    def add_spans(self, spans):
        """Deposit a batch of externally-assembled spans under ONE lock
        acquisition — what a request-trace assembly (root + prefills +
        per-token events, ``obs.reqtrace``) uses so a long generation's
        close-out doesn't pay the lock per token."""
        with self._lock:
            for sp in spans:
                if len(self._finished) == self.max_spans:
                    self.dropped += 1
                self._finished.append(sp)

    # ------------------------------------------------------ export
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def clear(self):
        with self._lock:
            self._finished.clear()
            self.dropped = 0

    def export_jsonl(self, path, clear: bool = False) -> int:
        """Append every finished span to ``path`` as JSONL; returns the
        number written. Ordered by completion time (children before
        parents, as in any post-order trace dump)."""
        with self._lock:
            spans = list(self._finished)
            if clear:
                self._finished.clear()
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "a") as f:
            for sp in spans:
                f.write(json.dumps(sp.record()) + "\n")
        return len(spans)


def load_spans(path) -> List[dict]:
    """Read a span JSONL file back (torn trailing line skipped, like
    ui.load_stats)."""
    out = []
    try:
        text = Path(path).read_text()
    except OSError:
        return out
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and rec.get("kind") == "span":
            out.append(rec)
    return out


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def span(name: str, **kw):
    """Module-level shorthand: ``with obs.span("round"): ...``"""
    return _tracer.span(name, **kw)
