"""Unified telemetry plane (ISSUE 6): one process-wide metrics registry
(counters / gauges / histograms, Prometheus text exposition on the UI
server's ``/metrics``), and a span tracer whose context propagates over
the scaleout wire so master rounds and worker fits stitch into one
trace tree.

Instrumented surfaces (all under the ``dl4j_`` namespace —
``scripts/check_metric_names.py`` lints the sites):

- ``nn.listeners.MetricsListener`` — train-step histogram, loss,
  examples/s, device memory.
- ``parallel.wrapper.ParallelInference`` — batch-occupancy gauge,
  queue-wait histogram (the serving plane inherits these).
- ``parallel.scaleout`` — round counters + stitched spans.
- ``kernels.autotune`` — per-candidate measurement provenance.
- ``bench.py`` — each row emits the same schema beside the record,
  including the ``floor`` roofline block (``obs.floors``, ISSUE 7).
- ``nn.listeners.ProfilingListener`` — per-layer time attribution
  (``obs.profiler``): ``dl4j_layer_time_ms`` + JSONL layer spans.
- ``serving.scheduler`` — the continuous-batching serving plane
  (ISSUE 10): ``dl4j_serving_*`` slot occupancy, TTFT / queue-wait /
  latency histograms, token + preemption counters, and
  ``serving.prefill`` / ``serving.decode`` spans.
- ``obs.reqtrace`` / ``obs.slo`` — the serving SLO plane (ISSUE 11):
  per-request lifecycle timelines stitched into the span tree, the
  ``dl4j_serving_itl_seconds`` inter-token-latency histogram, rolling
  ``dl4j_slo_*`` goodput/attainment/burn-rate gauges (``replica``-
  labeled), and the crash flight recorder behind ``/debug/serving``.
- ``obs.memory`` / ``obs.compiles`` — the memory & compile plane
  (ISSUE 12): pytree memory census over named components
  (``dl4j_mem_component_bytes{component, replica}``, allocator view
  attached where ``memory_stats`` exists, pytree fallback on CPU), KV
  residency accounting on the serving scheduler (``dl4j_kv_*``), and
  the :class:`CompileSentinel` retrace guard on every jitted entry
  point (``dl4j_compile_*``, post-warmup retraces warned). Forensics:
  ``GET /debug/memory``, census + residency records in flight-recorder
  dumps, ``scripts/mem_report.py``.
- ``obs.numerics`` / ``obs.fidelity`` — the numerics & fidelity plane
  (ISSUE 13): jitted one-pass tensor-stat engine
  (``dl4j_num_*{layer, kind}``), the :class:`NumericsSentinel`
  (warn/raise/skip-step on non-finite loss or grads + z-score
  loss-spike auto-dump through the flight recorder), cross-replica
  :class:`DriftAuditor` (``dl4j_replica_*`` — the ZeRO lockstep
  proof), and :class:`FidelityProbe` candidate-vs-reference logit
  comparisons (``dl4j_fidelity_*{kind}``, the spec-decode /
  quantized-KV acceptance oracle). Forensics: ``GET /debug/numerics``,
  ``scripts/fidelity_report.py``.
- ``obs.trend`` — the perf regression & trend plane (ISSUE 15): the
  longitudinal layer the other planes feed. Append-only bench ledger
  (``runs/perf_ledger.jsonl``) every ``bench.py`` capture appends a
  keyed record to, noise-aware change detection with bands from the
  *measured* IQR, two-cluster bimodality verdicts (the T=4096
  best-XLA debt), regression attribution (floor diff / retraces /
  layer spans → suspects), ``dl4j_trend_*{row, backend, verdict}``
  gauges. Forensics: ``GET /debug/trend``, ``scripts/perf_gate.py``
  (trend table + CI regression gate vs a pinned baseline).
"""

from .registry import (Counter, DEFAULT_BUCKETS, Gauge,  # noqa: F401
                       Histogram, MetricsRegistry)
from .spans import (Span, SpanContext, Tracer, derived_span_id,  # noqa: F401
                    get_tracer, load_spans, span)
from . import floors  # noqa: F401  (roofline floor engine, ISSUE 7)
from . import profiler  # noqa: F401  (per-layer attribution, ISSUE 7)
from . import memory  # noqa: F401  (memory census, ISSUE 12)
from .compiles import CompileSentinel  # noqa: F401  (retrace sentinel)
from .memory import (device_memory_stats, emit_census,  # noqa: F401
                     tree_bytes)

_registry = MetricsRegistry(namespace="dl4j")


def get_registry() -> MetricsRegistry:
    """The process-wide registry every built-in instrumentation site
    writes to and ``/metrics`` exposes."""
    return _registry


# imported after the registry exists: slo lazily resolves get_registry()
from .reqtrace import (FlightRecorder, RequestTrace,  # noqa: E402,F401
                       live_flight_recorders, load_flight_records)
from .slo import SLOConfig, SLOTracker  # noqa: E402,F401
from . import numerics  # noqa: E402,F401  (numerics plane, ISSUE 13)
from . import fidelity  # noqa: E402,F401  (fidelity probes, ISSUE 13)
from . import trend  # noqa: E402,F401  (perf trend plane, ISSUE 15)
from .numerics import (DriftAuditor, NumericsSentinel,  # noqa: E402,F401
                       audit_params, drift_report, emit_stats,
                       summarize)
from .fidelity import (FidelityProbe, MeasuredBound,  # noqa: E402,F401
                       assert_trees_close, compare_logits,
                       compare_trees)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_BUCKETS", "get_registry", "Span", "SpanContext",
           "Tracer", "get_tracer", "derived_span_id", "load_spans",
           "span", "FlightRecorder", "RequestTrace", "SLOConfig",
           "SLOTracker", "live_flight_recorders", "load_flight_records",
           "CompileSentinel", "device_memory_stats", "emit_census",
           "tree_bytes", "NumericsSentinel", "DriftAuditor",
           "FidelityProbe", "MeasuredBound", "assert_trees_close",
           "compare_logits", "compare_trees", "audit_params",
           "drift_report", "emit_stats", "summarize"]
