"""Per-request trace timelines + the serving flight recorder (ISSUE 11).

The serving-side analogue of the bench philosophy "every row explains
itself" (PR 7) applied to LIVE requests: the aggregate histograms
(`dl4j_serving_ttft_seconds`, ...) say *that* p99 moved; a
:class:`RequestTrace` says *why* — every lifecycle event of one request
(submit → queue → admit → prefill → each decode token → preempt /
requeue → finish / cancel / fail) with timestamps, so chunked prefill
and preemption can be tuned against held inter-token latency instead of
guessed (μ-cuDNN-style per-micro-step attribution, arXiv 1804.04806).

Three pieces:

- :class:`RequestTrace` — the append-only event timeline. Derives
  per-request TTFT, inter-token-latency samples (a preempted request's
  requeue gap IS an ITL sample — invisible to per-sweep timing), and a
  JSONL-able record. ``assemble_spans`` stitches the timeline into the
  process :class:`~.spans.Tracer` as a deterministic span tree
  (request root → one ``serving.prefill`` span per admission → token
  events), using the same ``derived_span_id`` machinery that stitches
  scaleout rounds — so a serving trace and a training trace export
  through one pipeline.
- :class:`FlightRecorder` — a bounded ring of the last N completed
  traces plus per-step scheduler snapshots (slot map, queue depth,
  occupancy). Dumped as JSONL on demand and automatically when the
  serve loop crashes (`ContinuousBatchingScheduler._fail_all`): a dying
  pool leaves a black box, not just failed futures. Live recorders
  self-register so the UI server can serve them at
  ``GET /debug/serving`` / ``GET /debug/requests``.
- :func:`load_flight_records` — torn-line-tolerant JSONL reader (the
  ``obs.spans.load_spans`` discipline) for postmortem tooling
  (``scripts/slo_report.py``).

Clocks: events are timestamped with ``time.perf_counter()`` (monotonic —
ITL math must never see a wall-clock step), anchored once per trace to
epoch time so exported spans carry the same ``start_ts`` semantics as
every other span.
"""

from __future__ import annotations

import json
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from .spans import Span, derived_span_id, get_tracer

# terminal event names — exactly one ends a trace
TERMINAL_EVENTS = ("finish", "cancel", "fail")


@dataclass
class RequestTrace:
    """Every lifecycle event of one serving request, timestamped.

    ``events`` is an append-only list of ``(name, ts, attrs)`` with
    monotonic ``ts``; ``t0_epoch``/``t0_perf`` anchor the monotonic
    clock to epoch time for span export.
    """

    request_id: int
    replica: str = "0"
    kind: str = "generate"       # RequestKind value (ISSUE 20)
    events: List[Tuple[str, float, Dict[str, Any]]] = field(
        default_factory=list)
    t0_epoch: float = field(default_factory=time.time)
    t0_perf: float = field(default_factory=time.perf_counter)

    # ------------------------------------------------------ recording
    def event(self, name: str, ts: Optional[float] = None,
              **attrs) -> float:
        """Append one lifecycle event; returns the timestamp used."""
        if ts is None:
            ts = time.perf_counter()
        self.events.append((name, ts, attrs))
        return ts

    def to_epoch(self, ts: float) -> float:
        return self.t0_epoch + (ts - self.t0_perf)

    # ----------------------------------------------------- accessors
    def first(self, name: str):
        for ev in self.events:
            if ev[0] == name:
                return ev
        return None

    def all(self, name: str):
        return [ev for ev in self.events if ev[0] == name]

    def terminal(self):
        for ev in reversed(self.events):
            if ev[0] in TERMINAL_EVENTS:
                return ev
        return None

    # ------------------------------------------------------- derived
    def token_timestamps(self) -> List[float]:
        return [ts for name, ts, _ in self.events if name == "token"]

    def itl_samples(self) -> List[float]:
        """Inter-token-latency samples: gaps between consecutive token
        events. Derived per REQUEST, not per sweep — the gap spanning a
        preempt → requeue → re-prefill interval is one (large) sample,
        exactly the stall the request's caller experienced."""
        ts = self.token_timestamps()
        return [b - a for a, b in zip(ts, ts[1:])]

    def ttft_s(self) -> Optional[float]:
        sub, tok = self.first("submit"), self.first("token")
        if sub is None or tok is None:
            return None
        return tok[1] - sub[1]

    def latency_s(self) -> Optional[float]:
        sub, end = self.first("submit"), self.terminal()
        if sub is None or end is None:
            return None
        return end[1] - sub[1]

    def finish_reason(self) -> Optional[str]:
        end = self.terminal()
        if end is None:
            return None
        if end[0] == "finish":
            return end[2].get("reason", "finish")
        return end[0]

    def n_tokens(self) -> int:
        return sum(1 for name, _, _ in self.events if name == "token")

    def preemptions(self) -> int:
        return sum(1 for name, _, _ in self.events if name == "preempt")

    def summary(self) -> Dict[str, Any]:
        """Compact per-request record — what the SLO engine consumes."""
        end = self.terminal()
        return {
            "request_id": self.request_id,
            "replica": self.replica,
            "kind": self.kind,
            "status": end[0] if end else "inflight",
            "reason": self.finish_reason(),
            "tokens": self.n_tokens(),
            "preemptions": self.preemptions(),
            "ttft_s": self.ttft_s(),
            "latency_s": self.latency_s(),
            "itl_s": [round(s, 6) for s in self.itl_samples()],
        }

    def to_record(self) -> Dict[str, Any]:
        return {"kind": "reqtrace", "request_id": self.request_id,
                "replica": self.replica, "request_kind": self.kind,
                "t0_epoch": self.t0_epoch,
                "summary": self.summary(),
                "events": [[name, round(ts - self.t0_perf, 6), attrs]
                           for name, ts, attrs in self.events]}

    # ---------------------------------------------------------- spans
    def trace_id(self) -> str:
        """Deterministic trace id for this request — re-assembling the
        same trace always rebuilds the same tree (the scaleout-round
        discipline). The epoch anchor is part of the derivation:
        request ids restart at 0 for every scheduler instance, and two
        schedulers in one process (bench builds several) must not mint
        colliding trees in the shared tracer."""
        return derived_span_id("dl4j_serving", self.replica,
                               self.request_id,
                               "%.6f" % self.t0_epoch)

    def assemble_spans(self, tracer=None) -> List[Span]:
        """Stitch the timeline into the tracer as one span tree:

            serving.request (root, submit → terminal)
              └─ serving.prefill (one per admission, k = 0, 1, ...)
                   └─ serving.token (zero-duration event per token)

        Built by hand and deposited via ``Tracer.add_span`` — the same
        path the scaleout hub uses for spans no single thread can hold
        open. Returns the spans it added (tests walk them)."""
        tracer = tracer or get_tracer()
        tid = self.trace_id()
        root_id = derived_span_id(tid, "request")
        sub = self.first("submit")
        end = self.terminal()
        t0 = sub[1] if sub else self.t0_perf
        t_end = end[1] if end else (self.events[-1][1] if self.events
                                    else t0)
        out: List[Span] = []
        prefill_k, cur_prefill = -1, root_id
        for name, ts, attrs in self.events:
            if name == "prefill":
                prefill_k += 1
                cur_prefill = derived_span_id(tid, "prefill", prefill_k)
                sp = Span(name="serving.prefill", trace_id=tid,
                          span_id=cur_prefill, parent_id=root_id,
                          start_ts=self.to_epoch(
                              ts - attrs.get("time_s", 0.0)),
                          time_s=attrs.get("time_s", 0.0),
                          attrs={"request": self.request_id,
                                 "admission": prefill_k, **attrs})
                out.append(sp)
            elif name == "token":
                # deterministic WITHOUT a hash: token events never cross
                # a process boundary, and a trace's close-out must stay
                # inside the <2% serving trace budget — an md5 per token
                # would be its single biggest cost
                i = attrs.get("i", 0)
                sp = Span(name="serving.token", trace_id=tid,
                          span_id="%st%04x" % (tid[:11], i),
                          parent_id=cur_prefill,
                          start_ts=self.to_epoch(ts), time_s=0.0,
                          attrs={"request": self.request_id, **attrs})
                out.append(sp)
        root = Span(name="serving.request", trace_id=tid, span_id=root_id,
                    start_ts=self.to_epoch(t0), time_s=t_end - t0,
                    attrs={"request": self.request_id,
                           "replica": self.replica,
                           "reason": self.finish_reason(),
                           "tokens": self.n_tokens(),
                           "preemptions": self.preemptions()})
        out.append(root)   # root last: children-before-parents, like
        tracer.add_spans(out)   # any post-order trace dump
        return out


# ---------------------------------------------------------------- recorder

_RECORDERS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()


def live_flight_recorders() -> List["FlightRecorder"]:
    """Every FlightRecorder still alive in this process, stable order —
    what the UI server's /debug endpoints enumerate."""
    return sorted(_RECORDERS, key=lambda fr: (fr.replica, fr.created_ts))


class FlightRecorder:
    """Bounded black box for one scheduler: the last N completed
    :class:`RequestTrace` records + per-step snapshots. All methods are
    thread-safe; everything is host-side deque appends (the <2% serving
    trace budget is tested, not aspirational)."""

    def __init__(self, capacity_requests: int = 256,
                 capacity_snapshots: int = 512, replica: str = "0",
                 crash_dump_path: Optional[str] = None):
        self.replica = str(replica)
        self.crash_dump_path = crash_dump_path
        self.created_ts = time.time()
        self.dumps = 0
        # the scheduler wires a live-state callback in here so
        # /debug/serving shows current occupancy/queue/SLO, not only
        # the recorded past
        self.extra_state: Optional[Callable[[], Dict[str, Any]]] = None
        self._requests: "deque[RequestTrace]" = deque(
            maxlen=capacity_requests)
        self._snapshots: "deque[Dict[str, Any]]" = deque(
            maxlen=capacity_snapshots)
        self._lock = threading.Lock()
        _RECORDERS.add(self)

    # ------------------------------------------------------ recording
    def record_request(self, trace: RequestTrace):
        with self._lock:
            self._requests.append(trace)

    def record_snapshot(self, **snap):
        snap.setdefault("kind", "snapshot")
        snap.setdefault("ts", time.time())
        snap.setdefault("replica", self.replica)
        with self._lock:
            self._snapshots.append(snap)

    # ----------------------------------------------------- inspection
    def requests(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._requests)

    def snapshots(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._snapshots)

    def debug_state(self) -> Dict[str, Any]:
        """What ``GET /debug/serving`` returns for this recorder."""
        with self._lock:
            last = self._snapshots[-1] if self._snapshots else None
            n_req, n_snap = len(self._requests), len(self._snapshots)
        state = {"replica": self.replica, "requests_recorded": n_req,
                 "snapshots_recorded": n_snap, "dumps": self.dumps,
                 "crash_dump_path": self.crash_dump_path,
                 "last_snapshot": last}
        if self.extra_state is not None:
            try:
                state.update(self.extra_state())
            except Exception as e:  # noqa: BLE001 — debug must not raise
                state["extra_state_error"] = repr(e)
        return state

    # ----------------------------------------------------------- dump
    def dump(self, path=None, reason: str = "on-demand") -> str:
        """Append the whole black box to ``path`` as JSONL (header,
        snapshots, request traces) and return the path written. Default
        path is the recorder's ``crash_dump_path`` or
        ``runs/serving_blackbox.jsonl``."""
        path = Path(path or self.crash_dump_path
                    or "runs/serving_blackbox.jsonl")
        path.parent.mkdir(parents=True, exist_ok=True)
        with self._lock:
            snaps = list(self._snapshots)
            traces = list(self._requests)
        header = {"kind": "flightrec", "replica": self.replica,
                  "reason": reason, "dumped_at": time.time(),
                  "n_snapshots": len(snaps), "n_requests": len(traces)}
        # memory plane (ISSUE 12): this replica's latest censuses ride
        # the dump so mem_report.py renders attribution AND waste from
        # one file — a crash postmortem answers "whose bytes" offline
        censuses = []
        try:
            from .memory import latest_censuses
            censuses = [c for c in latest_censuses()
                        if c.get("replica") == self.replica]
        except Exception:  # noqa: BLE001 — census is decoration
            pass
        with open(path, "a") as f:
            f.write(json.dumps(header) + "\n")
            for c in censuses:
                f.write(json.dumps(c) + "\n")
            for snap in snaps:
                f.write(json.dumps(snap) + "\n")
            for tr in traces:
                f.write(json.dumps(tr.to_record()) + "\n")
        self.dumps += 1
        return str(path)


def load_flight_records(path) -> List[dict]:
    """Read a flight-recorder JSONL back: torn trailing line skipped
    (a crash dump is by definition written by a dying process), unknown
    kinds ignored."""
    out: List[dict] = []
    try:
        text = Path(path).read_text()
    except OSError:
        return out
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and rec.get("kind") in (
                "flightrec", "snapshot", "reqtrace", "memcensus",
                "numerics", "fidelity"):
            out.append(rec)
    return out
