"""Roofline floor engine — every headline bench row explains itself.

ROADMAP item 5 ("floor-or-lever discipline"): a throughput number without
a hardware floor is indistinguishable from "stopped improving". This
module derives, for any jitted step function, the two quantities a
roofline account needs —

- **flops**: total floating-point work per step,
- **bytes**: HBM/memory traffic per step,

preferring XLA's own cost model (``lowered.compile().cost_analysis()``,
the ground truth the paper-era Caffe-con-Troll proportion-of-peak tables
were built from) and falling back to a jaxpr-walk estimator
(``utils/tracing.trace_ops``: analytic MXU flops keyed on layer shapes,
bytes from per-primitive output sizes) when a backend omits or truncates
the cost model. The fallback is load-bearing: TPU backends behind the
axon tunnel have returned empty cost tables mid-session, and a floor
block must degrade to ``source="estimated"`` — never crash a bench row.

Combined with the per-backend peak table below, the costs become a
compute/memory roofline::

    compute_floor_ms = flops / peak_flops
    memory_floor_ms  = bytes / peak_bytes_per_s
    floor_ms         = max(...)          # the binding resource
    pct_of_floor     = floor_ms / measured_step_ms

``pct_of_floor`` ≥ ~0.85 means the row is within the 15% floor-or-lever
band (verdict ``ok``); below it the row owes a named lever (verdict
``lever``). Values > 1 are possible and meaningful: XLA's fusion can
beat the cost model's un-fused byte count (the measured ResNet step runs
*below* the cost-analysis HBM floor — docs/PERF.md).

CPU entries in the peak table are NOMINAL order-of-magnitude host values
so the whole pipeline is exercised by tier-1 CPU tests; a CPU
``pct_of_floor`` is a plumbing check, not a performance claim
(``peaks_nominal: true`` marks such blocks).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

# Per-backend attainable peaks. flops keyed by compute dtype: f32 matmuls
# run at ~half the bf16 MXU rate (same normalization bench.py applies to
# its MFU audit gate).
PEAKS: Dict[str, dict] = {
    "tpu": {
        "flops": {"bf16": 197e12, "f32": 98.5e12},  # v5e public spec
        "bytes_per_s": 819e9,                       # v5e HBM bandwidth
        "source": "TPU v5e public spec (bf16 MXU peak, HBM BW)",
    },
    "cpu": {
        # Nominal host-class numbers (order of magnitude for a modern
        # server core count); present so tier-1 CPU tests exercise the
        # floor pipeline end-to-end. Marked nominal in every block.
        "flops": {"bf16": 1.0e12, "f32": 0.5e12},
        "bytes_per_s": 50e9,
        "source": "nominal host values (CI plumbing, not a perf claim)",
        "nominal": True,
    },
}


def backend_peaks(backend: Optional[str] = None) -> Optional[dict]:
    """Peak entry for ``backend`` (default: the current jax backend).
    Unknown backends return None — callers emit a floor block without
    floor_ms rather than inventing a peak."""
    if backend is None:
        try:
            import jax
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 — no backend, no peaks
            return None
    return PEAKS.get(backend)


def estimate_costs(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """Fallback estimator: analytic flops (jaxpr dot/conv walk — exact
    layer shapes, scan trip counts multiplied) and bytes as the sum of
    every primitive's output size plus the inputs read. Overestimates
    traffic relative to a fused XLA executable (every intermediate is
    counted at memory once), which is the conservative direction for a
    floor: an estimated memory floor is an upper bound on the real one."""
    import math

    import jax

    from ..utils.tracing import trace_ops

    records = trace_ops(fn, *args, **kwargs)
    flops = float(sum(r.flops for r in records))
    bytes_out = float(sum(r.bytes_out for r in records))
    in_bytes = 0.0
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
        in_bytes += math.prod(shape or (1,)) * itemsize
    return {"flops": flops, "bytes": bytes_out + in_bytes}


def _cost_analysis_of(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """{flops, bytes} from XLA's compiled-executable cost model; keys
    absent when the backend omits them. Never raises."""
    import jax

    try:
        lowered = fn.lower(*args, **kwargs) if hasattr(fn, "lower") \
            else jax.jit(fn).lower(*args, **kwargs)
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = dict(ca) if ca else {}
    except Exception:  # noqa: BLE001 — backend withheld the cost model
        return {}
    out = {}
    flops = ca.get("flops")
    if flops is not None and flops > 0:
        out["flops"] = float(flops)
    byts = ca.get("bytes accessed")
    if byts is not None and byts > 0:
        out["bytes"] = float(byts)
    return out


def hlo_costs(fn: Callable, *args, **kwargs) -> Dict[str, Any]:
    """{flops, bytes, source, flops_source, bytes_source} for one step.

    ``fn`` may be a jitted function (its ``.lower`` is used, hitting the
    same lowering the step actually runs) or any traceable callable;
    args may be real arrays or ``jax.ShapeDtypeStruct``s — nothing is
    executed.

    Provenance rules:
    - **bytes**: the compiled executable's "bytes accessed" when
      present (it sees fusion; the estimator can only overcount), else
      the estimator.
    - **flops**: the LARGER of compiled and analytic. XLA's cost
      analysis counts a ``lax.scan`` body ONCE regardless of trip count
      (measured: the 8-block scanned transformer step reports ~10x low,
      which would flip its roofline from compute- to memory-bound),
      while the jaxpr walk multiplies trip counts; taking the max keeps
      whichever accounting actually saw the work.
    - ``source`` is ``"cost_analysis"`` only when BOTH fields come from
      the compiled executable, else ``"estimated"``; per-field
      ``flops_source`` / ``bytes_source`` carry the detail.

    Never raises: a total derivation failure returns ``{"error": ...}``
    for the caller to record."""
    ca = _cost_analysis_of(fn, *args, **kwargs)
    try:
        est = estimate_costs(fn, *args, **kwargs)
    except Exception as e:  # noqa: BLE001 — never crash a bench row
        if not ca:
            return {"error": f"cost derivation failed: "
                             f"{type(e).__name__}: {e}"[:300]}
        est = None
    out: Dict[str, Any] = {}
    ca_fl, est_fl = ca.get("flops"), est["flops"] if est else None
    if ca_fl is not None and (est_fl is None or ca_fl >= est_fl):
        out["flops"], fl_src = ca_fl, "cost_analysis"
    elif est_fl is not None:
        out["flops"], fl_src = est_fl, "estimated"
        if ca_fl is not None:
            out["flops_cost_analysis"] = ca_fl   # the undercount, kept
            # for the record (scan-body-once accounting)
    else:
        return {"error": "no flops from cost_analysis or estimator"}
    if "bytes" in ca:
        out["bytes"], by_src = ca["bytes"], "cost_analysis"
    elif est is not None:
        out["bytes"], by_src = est["bytes"], "estimated"
    else:
        return {"error": "no bytes from cost_analysis or estimator"}
    out["flops_source"], out["bytes_source"] = fl_src, by_src
    out["source"] = ("cost_analysis"
                     if fl_src == by_src == "cost_analysis"
                     else "estimated")
    return out


def floor_block(costs: Dict[str, Any], *, step_ms: Optional[float] = None,
                dtype: str = "bf16", backend: Optional[str] = None,
                ok_threshold: float = 0.85) -> Dict[str, Any]:
    """Assemble the ``floor`` block a bench row carries.

    ``costs`` is ``hlo_costs`` output. ``step_ms`` (measured marginal
    step) yields ``pct_of_floor`` + the lever-or-ok verdict; omit it for
    a floor table with no measurement yet (docs use)."""
    if "error" in costs:
        return {"na": costs["error"]}
    block: Dict[str, Any] = {
        "flops": int(costs["flops"]),
        "bytes": int(costs["bytes"]),
        "source": costs.get("source", "estimated"),
    }
    peaks = backend_peaks(backend)
    if peaks is None:
        block["na"] = "no peak table for backend"
        return block
    peak_flops = peaks["flops"].get(dtype) or peaks["flops"]["f32"]
    block["peak_flops"] = peak_flops
    block["peak_bytes_per_s"] = peaks["bytes_per_s"]
    if peaks.get("nominal"):
        block["peaks_nominal"] = True
    compute_ms = block["flops"] / peak_flops * 1e3
    memory_ms = block["bytes"] / peaks["bytes_per_s"] * 1e3
    block["compute_floor_ms"] = round(compute_ms, 4)
    block["memory_floor_ms"] = round(memory_ms, 4)
    block["floor_ms"] = round(max(compute_ms, memory_ms), 4)
    block["binding_resource"] = ("compute" if compute_ms >= memory_ms
                                 else "memory")
    if step_ms is not None and step_ms > 0 and block["floor_ms"] > 0:
        pct = block["floor_ms"] / step_ms
        block["pct_of_floor"] = round(pct, 4)
        block["verdict"] = "ok" if pct >= ok_threshold else "lever"
    return block


def emit_floor_metrics(config: str, block: Dict[str, Any], registry=None):
    """Mirror a floor block into the dl4j_ registry so a live /metrics
    scrape and the bench artifact read identical names. Returns the
    {name: value} map the bench row embeds; {} for na-blocks."""
    if not block or "floor_ms" not in block:
        return {}
    if registry is None:
        from . import get_registry
        registry = get_registry()
    out = {}
    registry.gauge(
        "dl4j_bench_floor_ms",
        "Roofline floor (max of compute/memory) for a bench row",
        labelnames=("config",)).set(block["floor_ms"], config=config)
    out["dl4j_bench_floor_ms"] = block["floor_ms"]
    if "pct_of_floor" in block:
        registry.gauge(
            "dl4j_bench_pct_of_floor",
            "floor_ms / measured step: 1.0 = at the roofline floor",
            labelnames=("config",)).set(block["pct_of_floor"], config=config)
        out["dl4j_bench_pct_of_floor"] = block["pct_of_floor"]
    return out


def shape_probe(tree):
    """args → ShapeDtypeStructs: lets a builder capture a lowering probe
    BEFORE its buffers are donated (lowering needs shapes, not data)."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
        if hasattr(a, "shape") else a, tree)
