"""Perf regression & trend plane (ISSUE 15) — the longitudinal layer.

Every other observability plane (floors, SLO, memory, numerics) explains
a SINGLE capture; nothing watched the numbers *across* captures, so a
regression was invisible until a human reread the README, and the
T=4096 best-XLA bimodality (82–152k tokens/s across sessions,
docs/PERF.md) lived as a prose debt with no machine verdict. This
module is the TVM-autotune discipline (PAPERS.md, arXiv 1802.04799 —
measured, persisted cost records beat one-shot eyeballs) applied to
every headline bench row:

- **Ledger** (``runs/perf_ledger.jsonl``): append-only JSONL every
  ``bench.py`` capture feeds. Appends are a single ``O_APPEND`` write
  of one whole line (atomic at these sizes), and the loader tolerates
  a torn trailing line — the ``obs.spans.load_spans`` discipline. Each
  record is keyed by (row, backend, host fingerprint, git sha) and
  carries the capture's median, relative IQR, raw
  ``step_time_ms_samples``, ``pct_of_floor``, compile/retrace
  counters, and (for inference rows) the slo/memory block scalars.
- **Change detection** (:func:`classify_capture`): verdicts for a new
  capture against the ledger history with noise bands derived from the
  *measured* IQR — the PR 13 ``MeasuredBound`` philosophy applied to
  throughput: the band is ``margin × max(measured rel-IQR, floor)``,
  and the margin is the only judgement call. Verdicts: ``stable`` /
  ``improved`` / ``regressed`` / ``unstable`` / ``bimodal``.
- **Bimodality** (:func:`split_clusters` + :func:`series_split`): a
  largest-gap two-cluster split test over the retained samples, with a
  RECURRENCE requirement — one capture's own sample set splitting, or
  a chronological series that keeps alternating between the modes. A
  series that stepped to a new level and stayed there is a *regime
  change* (baseline = where it settled), never two "clusters" a later
  regression could hide inside. ``bimodal`` rows report per-cluster
  medians instead of a meaningless pooled median; the recorded T=4096
  best-XLA session set (:data:`T4096_BEST_XLA_SAMPLES`) finally gets a
  first-class verdict this way.
- **Attribution** (:func:`attribute`): on ``regressed``, auto-diff the
  floor block (flops/bytes moved → model change), the compile counters
  (retraces appeared), and per-layer profiler spans between baseline
  and current into a ``suspects`` list.
- **Export**: verdict counts and pct-vs-baseline as ``dl4j_trend_*``
  gauges (labels: row / backend / verdict only —
  ``scripts/check_metric_names.py`` enforces it) behind
  ``GET /debug/trend`` on the UI server.

``scripts/perf_gate.py`` is the offline driver: ledger → per-row trend
table, exit 1 on an out-of-band regression vs a pinned baseline
(``runs/perf_baseline.json``), ``--backfill`` to seed five rounds of
real history from BENCH_r01–r05.json + bench_secondary.json.

No jax import anywhere in this module: like ``obs.memory`` it is
standalone-importable by file path, so the scripts run without pulling
the full package in. The registry export is a lazy, optional import.
"""

from __future__ import annotations

import json
import math
import os
import platform
import statistics
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------- paths

_REPO = Path(__file__).resolve().parents[2]


def ledger_path() -> Path:
    """Default ledger location; ``DL4J_TREND_LEDGER`` overrides (tests,
    backfill rehearsals)."""
    return Path(os.environ.get("DL4J_TREND_LEDGER",
                               _REPO / "runs" / "perf_ledger.jsonl"))


def baseline_path() -> Path:
    return Path(os.environ.get("DL4J_TREND_BASELINE",
                               _REPO / "runs" / "perf_baseline.json"))


def host_fingerprint() -> str:
    """Coarse host identity: CPU-derived numbers drift with the host
    (README: sandbox CPU is not a stable reference), so off-TPU
    comparisons only pool entries from the SAME fingerprint."""
    return f"{platform.node()}:{platform.machine()}:{os.cpu_count()}"


# ------------------------------------------------------------- the ledger

def append_record(rec: Dict[str, Any],
                  path: Optional[os.PathLike] = None) -> float:
    """Append one record as one whole line with a single ``O_APPEND``
    write — atomic at these sizes, so two bench subprocesses can never
    interleave bytes — and return the elapsed seconds (the <2%-of-a-row
    budget is self-timed and pinned in tests/test_trend.py)."""
    p = Path(path) if path is not None else ledger_path()
    t0 = time.perf_counter()
    p.parent.mkdir(parents=True, exist_ok=True)
    line = json.dumps(rec, separators=(",", ":"),
                      sort_keys=True, default=str) + "\n"
    fd = os.open(p, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line.encode())
    finally:
        os.close(fd)
    return time.perf_counter() - t0


def load_ledger(path: Optional[os.PathLike] = None) -> List[Dict[str, Any]]:
    """Every parseable record, in append order. A torn trailing line (a
    capture process dying mid-write, or a reader racing the writer) is
    skipped, never fatal — the ``load_spans`` discipline."""
    p = Path(path) if path is not None else ledger_path()
    out: List[Dict[str, Any]] = []
    try:
        text = p.read_text()
    except (FileNotFoundError, OSError):
        return out
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue     # torn line
        if isinstance(rec, dict):
            out.append(rec)
    return out


_LOWER_BETTER_UNITS = ("ms",)


def higher_is_better(unit: Optional[str]) -> bool:
    """Polarity from the row's own unit: latency rows ("ms", "ms/step",
    "ms p50 (batch 1)") regress UP, throughput rows regress DOWN."""
    u = (unit or "").strip().lower()
    return not any(u == m or u.startswith(m + "/") or u.startswith(m + " ")
                   for m in _LOWER_BETTER_UNITS)


def ledger_record(row: str, rec: Dict[str, Any],
                  source: str = "bench.py") -> Optional[Dict[str, Any]]:
    """Map a bench record onto the keyed ledger schema. Returns None for
    a record with no measured value (errors / skips never enter the
    ledger — the --refresh never-overwrite-verified discipline)."""
    if not isinstance(rec, dict) or rec.get("value") is None:
        return None
    entry: Dict[str, Any] = {
        "kind": "perf",
        "row": row,
        "backend": rec.get("backend") or "unknown",
        "host": host_fingerprint(),
        "git_sha": rec.get("git_sha"),
        "captured_at": rec.get("captured_at"),
        "unit": rec.get("unit"),
        "value": rec.get("value"),
        "source": source,
    }
    if rec.get("step_time_ms") is not None:
        entry["step_time_ms"] = rec["step_time_ms"]
    # raw retained samples: the sub-ms stability path keeps per-pair
    # step times; TTFT rows keep per-rep wall samples (already ms)
    samples = rec.get("step_time_ms_samples") or rec.get("ttft_ms_samples")
    if samples:
        entry["step_time_ms_samples"] = list(samples)
    for k in ("iqr_rel", "unstable", "bimodal", "cluster_medians_ms",
              "timing_valid", "mfu"):
        if rec.get(k) is not None:
            entry[k] = rec[k]
    fl = rec.get("floor")
    if isinstance(fl, dict) and "na" not in fl:
        entry["floor"] = {k: fl[k] for k in
                          ("flops", "bytes", "pct_of_floor",
                           "binding_resource", "source")
                          if fl.get(k) is not None}
        if fl.get("pct_of_floor") is not None:
            entry["pct_of_floor"] = fl["pct_of_floor"]
    slo = rec.get("slo")
    if isinstance(slo, dict) and "na" not in slo:
        entry["slo"] = {k: slo[k] for k in
                        ("goodput", "itl_p99_ms", "ttft_p99_ms",
                         "error_rate", "met")
                        if slo.get(k) is not None}
    mem = rec.get("memory")
    if isinstance(mem, dict) and "na" not in mem:
        compact = {k: mem[k] for k in
                   ("kv_waste_ratio", "bytes_per_resident_token",
                    "peak_bytes") if mem.get(k) is not None}
        if mem.get("retraces_after_warm") is not None:
            entry["retraces_after_warm"] = mem["retraces_after_warm"]
        if compact:
            entry["memory"] = compact
    if isinstance(rec.get("layers"), dict):
        entry["layers"] = rec["layers"]
    # paged-attention kernel-vs-XLA A/B (ISSUE 17): both arms' rates,
    # the promotion verdict and the fidelity bound ride in the ledger so
    # the trend plane can watch the kernel's margin across captures
    ab = rec.get("paged_kernel_ab")
    if isinstance(ab, dict) and "na" not in ab:
        compact = {k: ab[k] for k in
                   ("verdict", "promoted", "speedup_kernel_over_gather",
                    "fidelity_kl_max", "greedy_match_frac", "cost_record")
                   if ab.get(k) is not None}
        for arm in ("gather", "kernel"):
            a = ab.get(arm)
            if isinstance(a, dict):
                compact[arm] = {k: a[k] for k in
                                ("step_time_ms", "tokens_per_s",
                                 "pct_of_floor") if a.get(k) is not None}
        if compact:
            entry["paged_kernel_ab"] = compact
    return entry


# -------------------------------------------------- two-cluster split test

# Documented cross-session captures of the t4096 b4 best-XLA arm
# (bf16-scores remat-full), tokens/s — the bimodality carried as prose
# ("82–152k across sessions", docs/PERF.md §long-context, VERDICT r5
# item 2) since r5. The recorded session extremes ARE the evidence the
# debt was filed on; the split test below turns them into a first-class
# verdict with per-cluster medians instead of a 1.9×-spread pooled one.
T4096_BEST_XLA_SAMPLES = (82000.0, 152000.0)
T4096_BEST_XLA_ROW = "transformer_long_best_xla"

MIN_REL_GAP = 0.20          # clusters must sit ≥20% apart (≫ any band)
MAX_CLUSTER_REL_SPREAD = 0.10   # and each be internally tight


def split_clusters(values: Sequence[float],
                   min_rel_gap: float = MIN_REL_GAP,
                   max_cluster_rel_spread: float = MAX_CLUSTER_REL_SPREAD,
                   min_cluster: int = 1,
                   ) -> Optional[Dict[str, Any]]:
    """Largest-gap two-cluster split over positive samples. Returns the
    split description when the samples genuinely live in two modes —
    cluster medians ≥ ``min_rel_gap`` apart (relative to their
    midpoint) with each cluster's own spread ≤
    ``max_cluster_rel_spread`` — else None. Ordinary capture noise
    (spread ≪ gap threshold) never splits; a single outlier forms a
    singleton cluster, which is why :func:`classify_capture` only
    calls a row bimodal when the HISTORY splits (a lone new low
    sample is a regression, not a mode), and why callers judging ONE
    capture's sample set (``bench.measure_stable``) pass
    ``min_cluster=2`` — within one capture a mode must RECUR, or a
    lone tunnel-jitter outlier among k samples would read as one."""
    vals = sorted(float(v) for v in values
                  if v is not None and math.isfinite(v) and v > 0)
    if len(vals) < max(2, 2 * min_cluster):
        return None
    gaps = [vals[i + 1] - vals[i] for i in range(len(vals) - 1)]
    i = max(range(len(gaps)), key=gaps.__getitem__)
    lo, hi = vals[:i + 1], vals[i + 1:]
    lo_med, hi_med = statistics.median(lo), statistics.median(hi)
    mid = 0.5 * (lo_med + hi_med)
    if mid <= 0:
        return None
    rel_gap = (hi_med - lo_med) / mid

    def rel_spread(cluster: List[float], med: float) -> float:
        return (cluster[-1] - cluster[0]) / med if med > 0 else math.inf

    if rel_gap < min_rel_gap:
        return None
    if len(lo) < min_cluster or len(hi) < min_cluster:
        return None
    if rel_spread(lo, lo_med) > max_cluster_rel_spread \
            or rel_spread(hi, hi_med) > max_cluster_rel_spread:
        return None
    return {
        "lo_median": lo_med, "hi_median": hi_med,
        "lo_n": len(lo), "hi_n": len(hi),
        "rel_gap": round(rel_gap, 4),
    }


def nearest_cluster(split: Dict[str, Any], value: float) -> float:
    """The cluster median a value belongs to (pct-vs-baseline for a
    bimodal row quotes against its OWN mode, not the pooled median)."""
    lo, hi = split["lo_median"], split["hi_median"]
    return lo if abs(value - lo) <= abs(value - hi) else hi


def cluster_transitions(ordered_values: Sequence[float],
                        split: Dict[str, Any]) -> int:
    """How many times a CHRONOLOGICAL series switches cluster. This is
    what separates bimodality from a regime change: a series that
    visits one mode, moves to the other, and never returns (≤1
    transition — e.g. the r02→r05 doubling of several bench rows) is
    an improvement/regression that STUCK, and its honest baseline is
    the latest regime; a series that keeps alternating (≥2
    transitions) has no single regime — that is ``bimodal``. Without
    this check, every big accepted improvement would pin as a
    'cluster' and a later regression back to the old level would pass
    the gate inside it."""
    assign = [abs(v - split["lo_median"]) > abs(v - split["hi_median"])
              for v in ordered_values]
    return sum(1 for a, b in zip(assign, assign[1:]) if a != b)


def latest_regime(ordered_values: Sequence[float],
                  split: Dict[str, Any]) -> List[float]:
    """The trailing run of same-cluster values — the current regime a
    monotone regime-change series has settled into."""
    vals = list(ordered_values)
    assign = [abs(v - split["lo_median"]) > abs(v - split["hi_median"])
              for v in vals]
    cut = len(vals) - 1
    while cut > 0 and assign[cut - 1] == assign[-1]:
        cut -= 1
    return vals[cut:]


# --------------------------------------------------- noise-aware verdicts

BAND_MARGIN = 1.5     # × the measured rel-IQR — the one judgement call
BAND_MIN = 0.05       # floor: same-config captures repeat within ~1-2%
                      # on ≥10ms rows (docs/PERF.md §LeNet), 5% is slack
UNSTABLE_REL_IQR = 0.25   # bench.py's own sub-ms instability threshold


def noise_band(hist_iqr_rels: Sequence[float],
               cur_iqr_rel: Optional[float] = None,
               band_min: float = BAND_MIN,
               margin: float = BAND_MARGIN) -> float:
    """The MeasuredBound philosophy applied to throughput: the allowed
    relative deviation is ``margin ×`` the measured relative IQR (the
    worse of history and current capture), floored at ``band_min`` so a
    suspiciously quiet history can't make 1% noise a 'regression'."""
    measured = [r for r in list(hist_iqr_rels) + [cur_iqr_rel]
                if isinstance(r, (int, float)) and math.isfinite(r)]
    return margin * max([band_min] + measured)


def series_values(entries: Sequence[Dict[str, Any]]) -> List[float]:
    """Per-capture observations for the split/band tests: an entry
    contributes its retained per-session samples when it has them
    (``value_samples`` — the backfilled T=4096 evidence), else its
    single captured value."""
    out: List[float] = []
    for e in entries:
        samples = e.get("value_samples")
        if samples:
            out.extend(float(s) for s in samples)
        elif e.get("value") is not None:
            out.append(float(e["value"]))
    return out


def series_split(entries: Sequence[Dict[str, Any]]
                 ) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
    """Is this SERIES bimodal at all? Two ways to earn the verdict:

    - **within-capture**: one entry's own retained sample set splits
      (same sha, same session era — no regime-change reading exists;
      the recorded T=4096 session set and a bimodal ``measure_stable``
      capture both land here);
    - **across-captures**: the chronological per-capture values split
      AND keep alternating (≥2 cluster transitions) — recurrence, not
      a one-way regime change.
    """
    for e in entries:
        sp = split_clusters(e.get("value_samples") or ())
        if sp is not None:
            return sp, "within-capture"
    vals = series_values(entries)
    sp = split_clusters(vals)
    if sp is not None and cluster_transitions(vals, sp) >= 2:
        return sp, "across-captures"
    return None, None


def classify_capture(history: Sequence[float], current: float, *,
                     higher_better: bool = True,
                     cur_iqr_rel: Optional[float] = None,
                     hist_iqr_rels: Sequence[float] = (),
                     band_min: float = BAND_MIN,
                     margin: float = BAND_MARGIN) -> Dict[str, Any]:
    """Judge one new capture against the ledger history.

    Order matters: a history that itself keeps ALTERNATING between two
    modes makes the row ``bimodal`` (the current capture is assigned
    to its nearest cluster and judged against THAT median — the stable
    denominator the pooled median never was); a history that split
    once and stuck (≤1 transition) is a regime change, and the capture
    is judged against the LATEST regime's median; a current capture
    whose own samples are too spread is ``unstable``; otherwise the
    capture is in/out of the measured noise band around the history
    median. A lone new sample far from a tight history is
    ``regressed`` / ``improved``, never ``bimodal`` — one observation
    is an event, two recurrences are a mode."""
    hist = [float(v) for v in history
            if v is not None and math.isfinite(v)]
    out: Dict[str, Any] = {
        "verdict": "no_baseline", "baseline": None,
        "pct_vs_baseline": None, "band_rel": None,
        "n_history": len(hist),
    }
    if not hist:
        return out
    band = noise_band(hist_iqr_rels, cur_iqr_rel, band_min, margin)
    out["band_rel"] = round(band, 4)
    split = split_clusters(hist) if len(hist) >= 2 else None
    if split is not None:
        if cluster_transitions(hist, split) >= 2:
            baseline = nearest_cluster(split, current)
            out.update(verdict="bimodal", baseline=baseline,
                       clusters=[split["lo_median"],
                                 split["hi_median"]],
                       split=split)
            if baseline:
                out["pct_vs_baseline"] = round(
                    (current - baseline) / baseline, 4)
            return out
        # regime change that stuck: judge against where it settled
        hist = latest_regime(hist, split)
    baseline = statistics.median(hist)
    out["baseline"] = baseline
    if baseline:
        out["pct_vs_baseline"] = round((current - baseline) / baseline, 4)
    if cur_iqr_rel is not None and cur_iqr_rel > UNSTABLE_REL_IQR:
        out["verdict"] = "unstable"
        return out
    hist_spread = ((max(hist) - min(hist)) / baseline
                   if baseline and len(hist) > 1 else 0.0)
    if hist_spread > max(2 * band, UNSTABLE_REL_IQR):
        # wildly spread history that does NOT split into clean modes:
        # no stable denominator exists and no band verdict is honest
        out["verdict"] = "unstable"
        return out
    pct = out["pct_vs_baseline"]
    if pct is None or abs(pct) <= band:
        out["verdict"] = "stable"
    elif (pct < 0) == higher_better:
        out["verdict"] = "regressed"
    else:
        out["verdict"] = "improved"
    return out


# -------------------------------------------------- attribution drill-down

FLOOR_DIFF_REL = 0.02      # flops/bytes moved ≥2% → the model changed
LAYER_DIFF_REL = 0.10      # a layer span moved ≥10% → named suspect


def _rel_delta(a, b) -> Optional[float]:
    try:
        a, b = float(a), float(b)
    except (TypeError, ValueError):
        return None
    if not a:
        return None
    return (b - a) / a


def attribute(baseline: Dict[str, Any],
              current: Dict[str, Any]) -> List[str]:
    """The regression drill-down: diff the recorded evidence between
    the baseline and current ledger entries into human-readable
    suspects, most structural first. Order of checks: a floor-block
    move means the PROGRAM changed (different flops/bytes = different
    model — any timing delta follows from that); retraces mean the
    compile cache stopped holding; a layer-span move names the layer;
    an SLO/KV move localizes it to the serving path; an empty list
    falls back to environment suspects (host/sha changed)."""
    suspects: List[str] = []
    bf, cf = baseline.get("floor") or {}, current.get("floor") or {}
    for quantity in ("flops", "bytes"):
        d = _rel_delta(bf.get(quantity), cf.get(quantity))
        if d is not None and abs(d) >= FLOOR_DIFF_REL:
            suspects.append(
                f"model change: floor {quantity}/step moved "
                f"{bf[quantity]:.3g} → {cf[quantity]:.3g} ({d:+.1%}) — "
                "the program being timed is different")
    br = baseline.get("retraces_after_warm") or 0
    cr = current.get("retraces_after_warm") or 0
    if cr > br:
        suspects.append(
            f"retraces appeared: {cr} post-warm compile(s) vs {br} at "
            "baseline — a shape/signature started missing the jit cache")
    bl, cl = baseline.get("layers") or {}, current.get("layers") or {}
    movers = []
    for layer in sorted(set(bl) & set(cl)):
        d = _rel_delta(bl[layer], cl[layer])
        if d is not None and abs(d) >= LAYER_DIFF_REL:
            movers.append((abs(d), layer, d))
    for _, layer, d in sorted(movers, reverse=True)[:3]:
        suspects.append(
            f"layer span {layer!r} moved {d:+.1%} "
            f"({bl[layer]:.3g} → {cl[layer]:.3g} ms)")
    bs, cs = baseline.get("slo") or {}, current.get("slo") or {}
    d = _rel_delta(bs.get("itl_p99_ms"), cs.get("itl_p99_ms"))
    if d is not None and d >= LAYER_DIFF_REL:
        suspects.append(f"serving ITL p99 grew {d:+.1%} "
                        f"({bs['itl_p99_ms']} → {cs['itl_p99_ms']} ms)")
    bm, cm = baseline.get("memory") or {}, current.get("memory") or {}
    d = _rel_delta(bm.get("kv_waste_ratio"), cm.get("kv_waste_ratio"))
    if d is not None and d >= LAYER_DIFF_REL:
        suspects.append(f"kv waste grew {d:+.1%} "
                        f"({bm['kv_waste_ratio']} → "
                        f"{cm['kv_waste_ratio']})")
    if not suspects:
        env = []
        if baseline.get("host") != current.get("host"):
            env.append(f"host changed ({baseline.get('host')} → "
                       f"{current.get('host')})")
        if baseline.get("git_sha") != current.get("git_sha"):
            env.append(f"sha {baseline.get('git_sha')} → "
                       f"{current.get('git_sha')}")
        suspects.append(
            "no attributable change in recorded evidence"
            + (" — " + "; ".join(env) if env else
               " — same host and sha: session/tunnel noise"))
    return suspects


# ----------------------------------------------------- the trend table

HISTORY_WINDOW = 12    # recent captures the verdict pools


def _comparable(entries: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Off-TPU numbers are only comparable on the SAME host (sandbox
    CPU drifts between sessions — README serving-table caveat): filter
    a non-tpu series to the latest entry's host fingerprint."""
    if not entries:
        return entries
    last = entries[-1]
    if last.get("backend") == "tpu":
        return entries
    host = last.get("host")
    return [e for e in entries if e.get("host") == host]


def trend_table(records: Sequence[Dict[str, Any]],
                window: int = HISTORY_WINDOW) -> Dict[str, Dict[str, Any]]:
    """Replay a ledger into one verdict row per (row, backend) key:
    latest value, history stats, the capture verdict of the LATEST
    entry vs its predecessors, the series-level split, and — when the
    verdict is ``regressed`` — the attribution suspects."""
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("kind") != "perf" or rec.get("row") is None:
            continue
        if rec.get("timing_valid") is False:
            # a capture its own MFU audit rejected (or a backfilled
            # pre-methodology record, e.g. the r01 97k-img/s headline)
            # stays in the ledger but never enters a verdict pool
            continue
        groups.setdefault((rec["row"], rec.get("backend") or "unknown"),
                          []).append(rec)
    out: Dict[str, Dict[str, Any]] = {}
    for (row, backend), entries in sorted(groups.items()):
        entries = _comparable(entries)[-window:]
        if not entries:
            continue
        cur = entries[-1]
        hist = entries[:-1]
        hist_vals = series_values(hist)
        cur_vals = series_values([cur])
        cur_val = cur_vals[-1] if cur_vals else None
        unit = cur.get("unit")
        hb = higher_is_better(unit)
        hist_iqrs = [e["iqr_rel"] for e in hist
                     if e.get("iqr_rel") is not None]
        hist_split, hist_split_kind = series_split(hist)
        if cur_val is None:
            verdict: Dict[str, Any] = {"verdict": "no_baseline"}
        elif hist_split is not None:
            # the HISTORY is already bimodal (a within-capture sample
            # split, or recurring alternation): judge the new capture
            # against its nearest mode, never the pooled median
            near = nearest_cluster(hist_split, cur_val)
            verdict = {
                "verdict": "bimodal", "baseline": near,
                "pct_vs_baseline": round((cur_val - near) / near, 4)
                if near else None,
                "clusters": [hist_split["lo_median"],
                             hist_split["hi_median"]],
                "split": {**hist_split, "kind": hist_split_kind},
                "band_rel": round(noise_band(hist_iqrs,
                                             cur.get("iqr_rel")), 4),
                "n_history": len(hist_vals),
            }
        else:
            verdict = classify_capture(
                hist_vals, cur_val, higher_better=hb,
                cur_iqr_rel=cur.get("iqr_rel"),
                hist_iqr_rels=hist_iqrs)
        # series-level split over EVERYTHING retained (incl. the
        # current capture): the "is this row bimodal at all" question
        # the T=4096 debt asks, distinct from the capture verdict —
        # a within-capture sample split or a recurring (alternating)
        # cross-capture split, never a one-way regime change
        split, split_kind = series_split(entries)
        if split is not None and verdict["verdict"] in ("stable",
                                                        "unstable",
                                                        "no_baseline"):
            verdict["verdict"] = "bimodal"
            verdict["clusters"] = [split["lo_median"],
                                   split["hi_median"]]
            verdict["split"] = {**split, "kind": split_kind}
            if cur_val is not None:
                near = nearest_cluster(split, cur_val)
                verdict["baseline"] = near
                verdict["pct_vs_baseline"] = round(
                    (cur_val - near) / near, 4) if near else None
        entry = {
            "row": row, "backend": backend, "unit": unit,
            "value": cur_val,
            "captured_at": cur.get("captured_at"),
            "git_sha": cur.get("git_sha"),
            "n_captures": len(entries),
            "higher_is_better": hb,
            **verdict,
        }
        if verdict["verdict"] == "regressed" and hist:
            entry["suspects"] = attribute(hist[-1], cur)
        out[f"{row}|{backend}"] = entry
    return out


# -------------------------------------------------------------- metrics

def emit_trend_metrics(table: Dict[str, Dict[str, Any]]) -> None:
    """Mirror a replayed trend table into the process registry:
    ``dl4j_trend_pct_vs_baseline{row, backend}`` per row and
    ``dl4j_trend_verdicts{verdict}`` counts. Lazy optional import —
    this module stays standalone-loadable; a process without the obs
    package just skips the mirror. Instruments are re-fetched through
    get-or-create every call (NOT cached): a replay happens once per
    gate/debug request, never per step, and a cached handle would
    survive a registry reset as an orphan."""
    try:
        from deeplearning4j_tpu.obs import get_registry
        reg = get_registry()
    except Exception:  # noqa: BLE001 — standalone script use
        return
    pct_g = reg.gauge("dl4j_trend_pct_vs_baseline",
                      "Latest capture vs ledger baseline (fraction; "
                      "bimodal rows quote vs their nearest cluster)",
                      labelnames=("row", "backend"))
    verdict_g = reg.gauge("dl4j_trend_verdicts",
                          "Rows at each trend verdict after the last "
                          "replay", labelnames=("verdict",))
    counts: Dict[str, int] = {}
    for entry in table.values():
        counts[entry["verdict"]] = counts.get(entry["verdict"], 0) + 1
        if entry.get("pct_vs_baseline") is not None:
            pct_g.set(entry["pct_vs_baseline"],
                      row=entry["row"], backend=entry["backend"])
    for v in ("stable", "improved", "regressed", "unstable", "bimodal",
              "no_baseline"):
        verdict_g.set(counts.get(v, 0), verdict=v)


def debug_state() -> Dict[str, Any]:
    """What ``GET /debug/trend`` returns: the ledger replayed fresh
    (bench captures append from subprocesses, so in-process caching
    would serve stale verdicts) plus verdict counts. Never raises."""
    p = ledger_path()
    try:
        records = load_ledger(p)
        table = trend_table(records)
    except Exception as e:  # noqa: BLE001 — debug must not raise
        return {"ledger_path": str(p), "error": repr(e)}
    counts: Dict[str, int] = {}
    for entry in table.values():
        counts[entry["verdict"]] = counts.get(entry["verdict"], 0) + 1
    try:
        emit_trend_metrics(table)
    except Exception:  # noqa: BLE001 — gauge mirror is decoration
        pass
    return {"ledger_path": str(p), "n_records": len(records),
            "verdict_counts": counts, "rows": table}


# ------------------------------------------------------ README trend cell

def trend_cell(row: str, backend: Optional[str],
               records: Optional[Sequence[Dict[str, Any]]] = None,
               band_min: float = BAND_MIN) -> str:
    """The README trend column: ▲/▼/≈ with % vs the previous
    same-backend capture, tolerant of a missing or partial ledger
    (no ledger / <2 captures → em-dash). The arrow encodes
    BETTER/WORSE, not raw direction — a TTFT row that got 30% slower
    is ▼ even though its millisecond value went up, so a latency
    regression can never render like a throughput gain."""
    try:
        if records is None:
            records = load_ledger()
        entries = [r for r in records
                   if r.get("kind") == "perf" and r.get("row") == row
                   and (backend is None or r.get("backend") == backend)
                   and r.get("value") is not None
                   and r.get("timing_valid") is not False]
        entries = _comparable(entries)
        if len(entries) < 2:
            return "—"
        prev, cur = float(entries[-2]["value"]), float(entries[-1]["value"])
        if not prev:
            return "—"
        pct = (cur - prev) / prev
        if abs(pct) <= band_min:
            return f"≈ ({pct:+.1%})"
        better = (pct > 0) == higher_is_better(entries[-1].get("unit"))
        arrow = "▲" if better else "▼"
        return f"{arrow} {pct:+.1%}"
    except Exception:  # noqa: BLE001 — a decoration must not break the table
        return "—"
