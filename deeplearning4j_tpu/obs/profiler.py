"""Per-layer profiler — attributes step wall-time to named layer spans.

The donated jitted train step is ONE fused XLA executable: host code
cannot see where its milliseconds go. This profiler runs a separate
*attribution pass* over the same layer math — forward layer-by-layer via
``jax.vjp`` (which also records each layer's pullback), then backward
layer-by-layer by replaying the pullbacks in reverse — timing every
layer with a PR-6 ``Span`` and a device sync, so ≥90% of the pass's wall
time lands in named per-layer spans with a forward/backward split.

This is the OpProfiler-style interpreted account (utils/tracing.py level
2), not the hot path: the pass pays per-layer dispatch and loses
cross-layer fusion, so its *absolute* total differs from the jitted
step; its value is the per-layer *shares* (the layer map that names
which layer owns a regression) plus the ``jax.named_scope`` annotations
threaded through both networks' layer apply, which label the fused
executable's ops for XLA-level tools (tensorboard xprof) with the SAME
names this profiler uses for its spans — ``layer_i.<Type>`` /
``<node>.<Type>``, ``.loss`` suffix on the output tail — so an
exact-name join between xprof op metadata, ``dl4j_layer_time_ms``
labels, and JSONL spans works.

Exports: per-layer ``Span`` records (JSONL via the shared tracer) and a
``dl4j_layer_time_ms`` histogram labeled (layer, direction) in the
process-wide registry. ``nn.listeners.ProfilingListener`` wires this
into ``fit()`` at a configurable frequency.
"""

from __future__ import annotations

from typing import Any, Dict, List

# layer times span ~µs (a LeNet dense on CPU) to seconds (a profiled
# ResNet conv stack): exponential ms buckets 0.001 ms .. ~8.4 s
LAYER_MS_BUCKETS = tuple(1e-3 * (2.0 ** i) for i in range(24))


def _sync(x):
    import jax
    try:
        jax.block_until_ready(x)
    except Exception:  # noqa: BLE001 — sync is best-effort off-CPU
        pass


def _one(dtype):
    import jax.numpy as jnp
    return jnp.ones((), dtype)


def _layer_rows(spans) -> List[Dict[str, Any]]:
    """Fold forward/<name> + backward/<name> span pairs into rows."""
    rows: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []
    for sp in spans:
        direction, _, lname = sp.name.partition("/")
        if direction not in ("forward", "backward") or not lname:
            continue
        if lname not in rows:
            rows[lname] = {"layer": lname, "forward_ms": 0.0,
                           "backward_ms": 0.0}
            order.append(lname)
        rows[lname][f"{direction}_ms"] = round(
            rows[lname][f"{direction}_ms"] + sp.time_s * 1e3, 4)
    return [rows[k] for k in order]


def _report(model, root, spans) -> Dict[str, Any]:
    layers = _layer_rows(spans)
    accounted = sum(r["forward_ms"] + r["backward_ms"] for r in layers)
    total = root.time_s * 1e3
    return {
        "model": type(model).__name__,
        "total_ms": round(total, 4),
        "accounted_ms": round(accounted, 4),
        "accounted_frac": round(accounted / total, 4) if total > 0 else None,
        "layers": layers,
        "trace_id": root.trace_id,
        # THIS pass's span records only — so a JSONL exporter can append
        # exactly one pass per call instead of re-dumping the tracer's
        # whole ring (which holds every earlier pass too)
        "span_records": [sp.record() for sp in spans] + [root.record()],
        "note": "interpreted per-layer attribution pass (per-layer "
                "dispatch, no cross-layer fusion); shares are the "
                "signal, the jitted step's absolute time is "
                "dl4j_train_step_seconds",
    }


def profile_mln_step(net, ds, *, tracer=None, rng=None) -> Dict[str, Any]:
    """One attributed train-step pass over a MultiLayerNetwork.

    Returns a report dict (total/accounted ms, per-layer forward/backward
    rows); the spans land in ``tracer`` (default: the process tracer)."""
    import jax
    import jax.numpy as jnp

    from ..nn.layers.core import LossLayer, OutputLayer
    from ..nn.layers.samediff_layer import SameDiffOutputLayer
    from ..nn.layers.wrappers import unwrap
    from .spans import get_tracer

    tracer = tracer or get_tracer()
    rng = jax.random.PRNGKey(0) if rng is None else rng
    x = jnp.asarray(ds.features)
    y = jnp.asarray(ds.labels)
    fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
    lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)

    n = len(net.layers)
    spans = []
    with tracer.span("profile_step",
                     attrs={"model": type(net).__name__}) as root:
        entries = []          # (lname, pullback) in forward order
        h = x
        loss = None
        for i, layer in enumerate(net.layers):
            key = f"layer_{i}"
            ul = unwrap(layer)
            last_is_loss = i == n - 1 and isinstance(
                ul, (OutputLayer, LossLayer, SameDiffOutputLayer))
            # same names the named_scope threading stamps on the fused
            # executable (MLN._apply_one / _loss), so spans and xprof
            # metadata join exactly
            lname = f"layer_{i}.{type(ul).__name__}" + (
                ".loss" if last_is_loss else "")
            if last_is_loss:
                # the output layer's forward IS the loss computation
                # (net._loss stops before it and calls compute_loss on
                # the pre-activation) — profile exactly that
                def f_loss(p, hh, _i=i, _ul=ul):
                    if _i in net._preprocessors:
                        hh = net._preprocessors[_i](hh)
                    if isinstance(_ul, LossLayer):
                        return _ul.compute_loss(hh, y, mask=lmask)
                    return _ul.compute_loss(p, hh, y, mask=lmask)

                with tracer.span(f"forward/{lname}") as sp:
                    loss, pullback = jax.vjp(f_loss, net.params[key], h)
                    _sync(loss)
            else:
                def f(p, hh, _i=i, _key=key):
                    ns = {}
                    h2, _ = net._apply_one(
                        _i, {_key: p}, net.states, hh, ns, train=True,
                        rng=rng, fmask=fmask, lmask=lmask,
                        stop_before_output=False)
                    return h2

                with tracer.span(f"forward/{lname}") as sp:
                    h, pullback = jax.vjp(f, net.params[key], h)
                    _sync(h)
            spans.append(sp)
            entries.append((lname, pullback))

        ct = _one(loss.dtype) if loss is not None else jnp.ones_like(h)
        for lname, pullback in reversed(entries):
            with tracer.span(f"backward/{lname}") as sp:
                _dp, ct = pullback(ct)
                _sync(ct)
            spans.append(sp)
    return _report(net, root, spans)


def _accum(cts: dict, name: str, val):
    cts[name] = val if name not in cts else cts[name] + val


def profile_cg_step(net, ds, *, tracer=None, rng=None) -> Dict[str, Any]:
    """One attributed train-step pass over a ComputationGraph: forward in
    topo order (one vjp per node), backward in reverse topo order with
    cotangents accumulated across fan-out. Output nodes profile their
    ``compute_loss`` as ``<name>.<Type>.loss`` (the same name
    CG._loss's named_scope stamps on the fused executable)."""
    import jax
    import jax.numpy as jnp

    from ..data.dataset import MultiDataSet
    from ..nn.layers.core import LossLayer, OutputLayer
    from ..nn.layers.samediff_layer import SameDiffOutputLayer
    from ..nn.layers.wrappers import unwrap
    from .spans import get_tracer

    tracer = tracer or get_tracer()
    rng = jax.random.PRNGKey(0) if rng is None else rng
    if isinstance(ds, MultiDataSet):
        feats, labs = ds.features, ds.labels
        fmask = None if ds.features_masks is None else ds.features_masks[0]
        lmask = None if ds.labels_masks is None else ds.labels_masks[0]
    else:
        feats, labs = [ds.features], [ds.labels]
        fmask, lmask = ds.features_mask, ds.labels_mask
    inputs = {n_: jnp.asarray(f) for n_, f in zip(net.conf.inputs, feats)}
    labels = {n_: jnp.asarray(l) for n_, l in zip(net.conf.outputs, labs)}
    fmask = None if fmask is None else jnp.asarray(fmask)
    lmask = None if lmask is None else jnp.asarray(lmask)

    spans = []
    with tracer.span("profile_step",
                     attrs={"model": type(net).__name__}) as root:
        acts = dict(inputs)
        entries = []          # (name, pullback, input_names)
        for idx, name in enumerate(net.conf.topo_order):
            node = net.conf.nodes[name]
            in_names = list(node.inputs)
            lname = f"{name}.{type(unwrap(node.op)).__name__}".replace(
                "/", "_")   # matches CG._apply_node's named_scope

            def f(p, *ins, _idx=idx, _name=name, _in=tuple(in_names)):
                local = {k: v for k, v in zip(_in, ins)}
                pre, ns = {}, {}
                net._apply_node(
                    _idx, _name, {_name: p}, net.states, local, pre, ns,
                    train=True, rng=rng, fmask=fmask, lmask=lmask,
                    stop_at_output_preact=True)
                return local[_name]

            with tracer.span(f"forward/{lname}") as sp:
                out, pullback = jax.vjp(
                    f, net.params[name], *[acts[i] for i in in_names])
                _sync(out)
            spans.append(sp)
            acts[name] = out
            entries.append((name, lname, pullback, in_names))

        # output nodes: loss forward (their params engage here, not above)
        loss_entries = []
        for o in net.conf.outputs:
            op = unwrap(net.conf.nodes[o].op)
            w = net.output_loss_weights.get(o, 1.0)
            yo = labels[o]
            oname = f"{o}.{type(op).__name__}.loss".replace("/", "_")

            def f_loss(p, pre, _op=op, _w=w, _y=yo):
                if isinstance(_op, LossLayer):
                    return _w * _op.compute_loss(pre, _y, mask=lmask)
                return _w * _op.compute_loss(p, pre, _y, mask=lmask)

            with tracer.span(f"forward/{oname}") as sp:
                loss_o, lvjp = jax.vjp(f_loss, net.params[o], acts[o])
                _sync(loss_o)
            spans.append(sp)
            loss_entries.append((o, oname, lvjp, loss_o))

        cts: Dict[str, Any] = {}
        for o, oname, lvjp, loss_o in loss_entries:
            with tracer.span(f"backward/{oname}") as sp:
                _dp, dpre = lvjp(_one(loss_o.dtype))
                _sync(dpre)
            spans.append(sp)
            _accum(cts, o, dpre)
        input_names = set(net.conf.inputs)
        for name, lname, pullback, in_names in reversed(entries):
            ct = cts.pop(name, None)
            if ct is None:      # output never consumed → zero cotangent
                continue
            with tracer.span(f"backward/{lname}") as sp:
                outs = pullback(ct)
                _sync(outs)
            spans.append(sp)
            for n_, d in zip(in_names, outs[1:]):
                if n_ not in input_names:
                    _accum(cts, n_, d)
    return _report(net, root, spans)


def profile_step(net, ds, *, tracer=None, rng=None) -> Dict[str, Any]:
    """Dispatch on network type (MultiLayerNetwork / ComputationGraph)."""
    from ..nn.computation_graph import ComputationGraph
    if isinstance(net, ComputationGraph):
        return profile_cg_step(net, ds, tracer=tracer, rng=rng)
    return profile_mln_step(net, ds, tracer=tracer, rng=rng)


def observe_report(report: Dict[str, Any], registry=None) -> None:
    """Feed a profile report into the registry: one ``dl4j_layer_time_ms``
    histogram observation per (layer, direction), plus the accounted
    fraction gauge tests and dashboards read."""
    if registry is None:
        from . import get_registry
        registry = get_registry()
    hist = registry.histogram(
        "dl4j_layer_time_ms",
        "Per-layer attributed time (interpreted profile pass)",
        labelnames=("layer", "direction"), buckets=LAYER_MS_BUCKETS)
    for row in report["layers"]:
        hist.observe(row["forward_ms"], layer=row["layer"],
                     direction="forward")
        if row["backward_ms"]:
            hist.observe(row["backward_ms"], layer=row["layer"],
                         direction="backward")
    if report.get("accounted_frac") is not None:
        registry.gauge(
            "dl4j_profile_accounted_fraction",
            "Share of the profile pass's wall time inside named layer "
            "spans (target ≥0.9)").set(report["accounted_frac"])
