"""Serving SLO engine (ISSUE 11): declarative targets, rolling-window
attainment, burn rate, and goodput accounting over request lifecycles.

A serving plane optimized for raw tokens/s will happily starve tail
requests; the decode-slot sweep (ROADMAP item 1) must optimize GOODPUT —
requests completing *within* SLO per second — so the verdict has to live
beside the throughput number. :class:`SLOConfig` declares the targets;
:class:`SLOTracker` consumes per-request lifecycle records
(:class:`~.reqtrace.RequestTrace` summaries), keeps a rolling window,
and exports:

- ``dl4j_slo_goodput_ratio{replica=}`` — in-SLO completions / all
  SLO-eligible requests in the window,
- ``dl4j_slo_ttft_attainment{replica=}`` / ``dl4j_slo_itl_attainment``
  — fraction of requests meeting each latency target,
- ``dl4j_slo_error_rate{replica=}`` — failed / eligible,
- ``dl4j_slo_burn_rate{replica=}`` — error-budget consumption rate
  (1.0 = exactly spending the budget the quantile objective allows;
  >1 = burning toward violation),
- ``dl4j_slo_window_requests{replica=}`` — window population.

Semantics (documented here, asserted in tests/test_slo.py):

- A request meets the **TTFT target** iff ``ttft_s <= cfg.ttft_s``.
- A request meets the **ITL target** iff EVERY inter-token gap is
  ``<= cfg.itl_s`` — worst-gap, not average: one 2 s stall mid-stream
  is exactly what a streaming caller notices, and it is how a
  preemption requeue gap shows up. Requests with <2 tokens have no
  gaps and meet the target vacuously.
- **Good** = finished (not failed) AND both targets met. **Cancelled**
  requests are excluded from the window entirely (the client walked
  away; serving latency verdicts don't apply). **Failed** requests
  count against goodput and error rate.
- The window prunes by the LATEST observed timestamp (not wall clock),
  so offline replay of a flight-recorder dump (scripts/slo_report.py)
  and a live tracker share one code path.

``replica`` labels every gauge (default "0") — ROADMAP item 2's
load-aware router reads per-replica goodput unchanged.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional


@dataclass(frozen=True)
class SLOConfig:
    """Declarative serving targets. ``quantile`` is the attainment
    objective (0.99 → "p99 within target", error budget 1%)."""

    ttft_s: float = 1.0          # submit → first token
    itl_s: float = 0.25          # worst inter-token gap
    quantile: float = 0.99       # attainment objective
    max_error_rate: float = 0.01  # failed / eligible ceiling
    window_s: float = 300.0      # rolling window span
    window_max: int = 4096       # hard cap on window population

    def __post_init__(self):
        if not 0.0 < self.quantile < 1.0:
            raise ValueError(f"quantile {self.quantile} outside (0, 1)")
        if self.ttft_s <= 0 or self.itl_s <= 0:
            raise ValueError("ttft_s / itl_s targets must be positive")


def _quantile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


class SLOTracker:
    """Rolling-window SLO accounting over request lifecycle records.

    Feed it completed :class:`~.reqtrace.RequestTrace` objects
    (``observe``) or plain summary dicts (``observe_summary`` — the
    offline-replay path). ``report()`` returns the verdict dict
    ``bench.py`` embeds beside inference rows."""

    def __init__(self, config: Optional[SLOConfig] = None,
                 replica: str = "0", registry=None):
        """``registry`` — None: export gauges to the process registry;
        False: no gauge export (offline replay); else: that registry."""
        self.config = config or SLOConfig()
        self.replica = str(replica)
        self._registry = registry
        # (ts, summary, good, ttft_ok, itl_ok, failed); pruned manually
        # (horizon + window_max) so the running counters below stay in
        # lockstep — gauge export is O(1), not a window re-scan
        self._window: deque = deque()
        self._counts = {"good": 0, "ttft_ok": 0, "itl_ok": 0,
                        "failed": 0}
        self._lock = threading.Lock()
        self._latest_ts = 0.0
        self._total_seen = 0
        self._gauges = None   # instrument handles, cached on first export

    # ------------------------------------------------------- ingest
    def observe(self, trace, ts: Optional[float] = None):
        """Account one completed request (RequestTrace or summary)."""
        summary = trace.summary() if hasattr(trace, "summary") else dict(
            trace)
        return self.observe_summary(summary, ts=ts)

    def observe_summary(self, summary: Dict[str, Any],
                        ts: Optional[float] = None):
        status = summary.get("status", "finish")
        if status == "cancel":
            return None          # client walked away: SLO-ineligible
        cfg = self.config
        failed = status == "fail"
        ttft = summary.get("ttft_s")
        itl = summary.get("itl_s") or []
        ttft_ok = ttft is not None and ttft <= cfg.ttft_s
        itl_ok = all(s <= cfg.itl_s for s in itl)
        good = (not failed) and ttft_ok and itl_ok
        if ts is None:
            ts = time.time()
        with self._lock:
            self._window.append((ts, summary, good, ttft_ok, itl_ok,
                                 failed))
            self._counts["good"] += good
            self._counts["ttft_ok"] += ttft_ok
            self._counts["itl_ok"] += itl_ok
            self._counts["failed"] += failed
            self._latest_ts = max(self._latest_ts, ts)
            self._total_seen += 1
            self._prune_locked()
            counts = dict(self._counts, n=len(self._window))
        self._export_gauges(counts)
        return good

    def _prune_locked(self):
        horizon = self._latest_ts - self.config.window_s
        while self._window and (
                self._window[0][0] < horizon
                or len(self._window) > self.config.window_max):
            _, _, good, ttft_ok, itl_ok, failed = self._window.popleft()
            self._counts["good"] -= good
            self._counts["ttft_ok"] -= ttft_ok
            self._counts["itl_ok"] -= itl_ok
            self._counts["failed"] -= failed

    # ------------------------------------------------------ verdicts
    def _stats(self):
        with self._lock:
            rows = list(self._window)
        n = len(rows)
        if n == 0:
            return None
        ttfts = sorted(s.get("ttft_s") for _, s, *_ in rows
                       if s.get("ttft_s") is not None)
        itls = sorted(x for _, s, *_ in rows
                      for x in (s.get("itl_s") or []))
        # per-kind census (ISSUE 20): the multi-workload request plane
        # labels every summary with its RequestKind; a summary without
        # one (pre-ISSUE-20 dumps) counts as "generate"
        by_kind: Dict[str, Dict[str, int]] = {}
        for _, s, good, _, _, failed in rows:
            k = by_kind.setdefault(str(s.get("kind", "generate")),
                                   {"requests": 0, "good": 0,
                                    "failed": 0})
            k["requests"] += 1
            k["good"] += good
            k["failed"] += failed
        return {
            "n": n,
            "good": sum(1 for r in rows if r[2]),
            "ttft_ok": sum(1 for r in rows if r[3]),
            "itl_ok": sum(1 for r in rows if r[4]),
            "failed": sum(1 for r in rows if r[5]),
            "ttfts": ttfts, "itls": itls,
            "by_kind": by_kind,
            "span_s": rows[-1][0] - rows[0][0],
        }

    @property
    def latest_ts(self) -> float:
        """Timestamp of the newest observation (0.0 before any). The
        window prunes by THIS, not wall clock — a consumer comparing
        against wall time (the fleet router's staleness guard) can tell
        a fresh verdict from one frozen since traffic moved away."""
        with self._lock:
            return self._latest_ts

    def goodput(self) -> Optional[float]:
        st = self._stats()
        return None if st is None else st["good"] / st["n"]

    def error_rate(self) -> Optional[float]:
        st = self._stats()
        return None if st is None else st["failed"] / st["n"]

    def _burn(self, good: int, n: int) -> float:
        """Error-budget consumption: violating fraction over the budget
        the quantile objective allows (0.99 → 1% budget). 1.0 = spending
        the budget exactly; sustained >1 = the SLO will be missed. ONE
        definition — report(), the gauge export and the accessor must
        never drift apart."""
        return (1.0 - good / n) / (1.0 - self.config.quantile)

    def burn_rate(self) -> Optional[float]:
        st = self._stats()
        return None if st is None else self._burn(st["good"], st["n"])

    def report(self) -> Dict[str, Any]:
        """The verdict dict: targets, window stats, per-dimension
        attainment + observed quantiles, goodput, burn rate, and a
        single ``met`` bool. Embedded by bench.py inference rows."""
        cfg = self.config
        out: Dict[str, Any] = {"targets": asdict(cfg),
                               "replica": self.replica}
        st = self._stats()
        if st is None:
            out.update({"window": {"requests": 0}, "goodput": None,
                        "met": None})
            return out
        n = st["n"]
        q = cfg.quantile
        goodput = st["good"] / n
        error_rate = st["failed"] / n
        out["window"] = {"requests": n, "failed": st["failed"],
                         "span_s": round(st["span_s"], 3),
                         "total_seen": self._total_seen}
        out["ttft"] = {
            "p50_s": _quantile(st["ttfts"], 0.50),
            "p99_s": _quantile(st["ttfts"], 0.99),
            "attainment": st["ttft_ok"] / n}
        out["itl"] = {
            "p50_s": _quantile(st["itls"], 0.50),
            "p99_s": _quantile(st["itls"], 0.99),
            "samples": len(st["itls"]),
            "attainment": st["itl_ok"] / n}
        out["goodput"] = goodput
        out["error_rate"] = error_rate
        out["burn_rate"] = self._burn(st["good"], n)
        out["met"] = bool(goodput >= q
                          and error_rate <= cfg.max_error_rate)
        # per-kind goodput breakdown (ISSUE 20) — what
        # scripts/slo_report.py renders under the replica table
        out["by_kind"] = {
            kind: {"requests": c["requests"], "good": c["good"],
                   "failed": c["failed"],
                   "goodput": c["good"] / c["requests"]}
            for kind, c in sorted(st["by_kind"].items())}
        return out

    # ------------------------------------------------------- gauges
    def _reg(self):
        if self._registry is not None:
            return self._registry
        from . import get_registry   # lazy: obs package init order
        return get_registry()

    def _make_gauges(self, reg):
        """Instrument handles, registered once and held (the
        MetricsListener precedent for long-lived holders — per-observe
        re-registration would dominate the close-out budget)."""
        return {
            "goodput": reg.gauge(
                "dl4j_slo_goodput_ratio",
                "In-SLO completions / eligible requests "
                "(rolling window)", labelnames=("replica",)),
            "ttft": reg.gauge(
                "dl4j_slo_ttft_attainment",
                "Fraction of windowed requests meeting the TTFT target",
                labelnames=("replica",)),
            "itl": reg.gauge(
                "dl4j_slo_itl_attainment",
                "Fraction of windowed requests whose every inter-token "
                "gap meets the ITL target", labelnames=("replica",)),
            "errors": reg.gauge(
                "dl4j_slo_error_rate",
                "Failed / eligible requests in the window",
                labelnames=("replica",)),
            "burn": reg.gauge(
                "dl4j_slo_burn_rate",
                "Error-budget consumption rate (1.0 = spending the "
                "quantile objective's budget exactly)",
                labelnames=("replica",)),
            "window": reg.gauge(
                "dl4j_slo_window_requests",
                "Requests in the rolling SLO window",
                labelnames=("replica",)),
        }

    def _export_gauges(self, st=None):
        """Mirror the rolling verdict onto the telemetry plane from the
        O(1) running counters (no window re-scan — the serving trace
        budget pays for this on every request close-out). Never fatal —
        the tracker's dict report is the source of truth."""
        if self._registry is False:
            return                      # offline replay: dicts only
        if st is None:
            with self._lock:
                st = dict(self._counts, n=len(self._window))
        if not st["n"]:
            return
        try:
            if self._gauges is None:
                self._gauges = self._make_gauges(self._reg())
            g = self._gauges
            n = st["n"]
            r = self.replica
            g["goodput"].set(st["good"] / n, replica=r)
            g["ttft"].set(st["ttft_ok"] / n, replica=r)
            g["itl"].set(st["itl_ok"] / n, replica=r)
            g["errors"].set(st["failed"] / n, replica=r)
            g["burn"].set(self._burn(st["good"], n), replica=r)
            g["window"].set(n, replica=r)
        except Exception:  # noqa: BLE001 — telemetry mirror only
            pass
