"""deeplearning4j_tpu.import_ — model import (deeplearning4j-modelimport)."""

from .keras import (KerasLambdaLayer, clear_custom_layers,
                    import_keras_model, import_keras_sequential,
                    register_custom_layer, register_lambda)
