"""Keras .h5 model import — Sequential + Functional.

Reference parity: ``deeplearning4j-modelimport``
(``KerasModelImport.importKerasSequentialModelAndWeights`` /
``importKerasModelAndWeights``). Reads the HDF5 `model_config` JSON and
weight groups directly with h5py (no TF/Keras execution), builds our
MultiLayerNetwork (Sequential) or ComputationGraph (Functional), and maps
weights with the layout conversions:

- Dense kernel (in, out) → ours (in, out) as-is
- Conv2D kernel (kh, kw, cin, cout) → HWIO as-is (both NHWC)
- Conv2DTranspose kernel (kh, kw, cout, cin) → transposed to HWIO
- DepthwiseConv2D / SeparableConv2D depthwise kernel (kh, kw, cin, mult)
  → reshaped (kh, kw, 1, cin*mult); output-channel order cin*mult+m matches
- LSTM kernels: keras gate order [i, f, c, o] → ours [i, f, o, g(c)]
- GRU kernels: keras [z, r, h] → ours [r, z, n]; reset_after bias → `rb`
- BatchNorm: gamma/beta/moving_mean/moving_variance → params + state

Functional (keras 2 AND keras 3 inbound-node formats) becomes a
ComputationGraph: merge layers → Merge/ElementWise vertices, Flatten →
CnnToFeedForward preprocessor vertex.
"""

from __future__ import annotations

import json
from contextlib import contextmanager as _contextmanager
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.conf import NeuralNetConfiguration
from ..nn.layers.base import InputType, Layer
from ..nn.layers.conv import (Convolution1DLayer, Convolution3DLayer,
                              ConvolutionLayer, Cropping1D, Cropping2D,
                              Cropping3D, Deconvolution2D, Deconvolution3D,
                              DepthwiseConvolution2D, GlobalPoolingLayer,
                              SeparableConvolution2D, Subsampling1DLayer,
                              Subsampling3DLayer, SubsamplingLayer,
                              Upsampling1D, Upsampling2D, Upsampling3D,
                              ZeroPadding1DLayer, ZeroPadding3DLayer,
                              ZeroPaddingLayer)
from ..nn.layers.core import (ActivationLayer, AlphaDropout, DenseLayer,
                              DropoutLayer, EmbeddingSequenceLayer,
                              GaussianDropout, GaussianNoise, PReLULayer,
                              SpatialDropout)
from ..nn.layers.norm import BatchNormalization, LayerNormalization
from ..nn.layers.recurrent import (GRU, LSTM, Bidirectional, ConvLSTM2D,
                                   SimpleRnn)
from ..nn.multi_layer_network import MultiLayerNetwork
from ..nn.preprocessors import CnnToFeedForwardPreProcessor
from ..nn.vertices import (ElementWiseVertex, MergeVertex, PreprocessorVertex)

_ACT = {"relu": "relu", "sigmoid": "sigmoid", "tanh": "tanh",
        "softmax": "softmax", "linear": "identity", "elu": "elu",
        "selu": "selu", "gelu": "gelu", "softplus": "softplus",
        "softsign": "softsign", "swish": "swish", "silu": "swish",
        "hard_sigmoid": "hardsigmoid", "leaky_relu": "leakyrelu",
        "relu6": "relu6", "mish": "mish", "exponential": "identity"}

_ELEMENTWISE = {"Add": "add", "Subtract": "sub", "Multiply": "mul",
                "Average": "avg", "Maximum": "max"}

# --------------------------------------------- custom layer / Lambda registry
# Reference parity: KerasLayer.registerCustomLayer(name, class) and
# KerasLambdaLayer — Lambda bodies don't serialize portably, so (exactly
# like the reference requires a SameDiffLambdaLayer) the user registers a
# function for each Lambda layer NAME before importing.
_CUSTOM_LAYERS: Dict[str, Any] = {}
_LAMBDAS: Dict[str, Any] = {}


def register_custom_layer(class_name: str, factory, assign_weights=None):
    """Register ``factory(keras_layer_config_dict) -> Layer`` for a keras
    ``class_name`` the importer doesn't map (reference registerCustomLayer).

    For custom layers WITH trainable weights, also pass
    ``assign_weights(layer, params_dict, state_dict, weight_arrays)`` —
    importing a weighted custom layer without it raises rather than
    silently keeping random init."""
    _CUSTOM_LAYERS[class_name] = (factory, assign_weights)


def register_lambda(layer_name: str, fn):
    """Register the jax function for a keras ``Lambda`` layer, keyed by the
    LAYER NAME (reference KerasLayer.registerLambdaLayer). ``fn(x) -> y``
    must be jax-traceable; output shape is inferred via eval_shape."""
    _LAMBDAS[layer_name] = fn


def clear_custom_layers():
    _CUSTOM_LAYERS.clear()
    _LAMBDAS.clear()


@dataclass
class KerasLambdaLayer(Layer):
    """Parameter-free layer wrapping a user-registered jax function — our
    SameDiffLambdaLayer analogue."""

    fn: Any = None
    lambda_name: str = ""

    def init(self, key, input_shape):
        # probe dynamic (None) dims — common for variable-length RNN input —
        # then restore None where the fn preserved the probed extent
        probe = tuple(4 if d is None else d for d in input_shape)
        try:
            out = jax.eval_shape(
                self.fn, jax.ShapeDtypeStruct((1,) + probe, jnp.float32))
        except Exception as e:  # noqa: BLE001 — surface as an import error
            raise ValueError(
                f"Lambda '{self.lambda_name}': output-shape inference failed "
                f"for input shape {input_shape}: {e}") from e
        out_shape = tuple(out.shape[1:])
        if len(out_shape) == len(probe):
            out_shape = tuple(
                None if d is None and o == p else o
                for d, p, o in zip(input_shape, probe, out_shape))
        return {}, {}, out_shape

    def apply(self, params, state, x, ctx):
        return self.fn(x), state

    def has_params(self):
        return False


def _act(cfg):
    return _ACT.get(cfg.get("activation", "linear"), "identity")


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _one(v):
    return v[0] if isinstance(v, (list, tuple)) else v


def _trip(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


def _map_layer(kcfg: dict):
    """keras layer config dict → our layer (or None for structural layers)."""
    cls = kcfg["class_name"]
    c = kcfg["config"]
    if cls in _CUSTOM_LAYERS:              # user registry wins (reference
        factory, assign = _CUSTOM_LAYERS[cls]   # registerCustomLayer)
        layer = factory(kcfg)
        layer._keras_custom = cls
        layer._keras_assign = assign
        return layer
    if cls == "Lambda":
        name = c.get("name", "")
        if name not in _LAMBDAS:
            raise NotImplementedError(
                f"Lambda layer '{name}': python lambda bodies don't "
                "serialize portably — register_lambda("
                f"{name!r}, fn) before importing (the reference requires "
                "a SameDiffLambdaLayer the same way)")
        return KerasLambdaLayer(fn=_LAMBDAS[name], lambda_name=name)
    if cls == "Dense":
        return DenseLayer(n_out=c["units"], activation=_act(c),
                          has_bias=c.get("use_bias", True))
    if cls == "Conv2D":
        pad = c.get("padding", "valid")
        return ConvolutionLayer(
            n_out=c["filters"], kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            dilation=_pair(c.get("dilation_rate", 1)),
            convolution_mode="same" if pad == "same" else "truncate",
            padding=0, activation=_act(c), has_bias=c.get("use_bias", True))
    if cls == "Conv2DTranspose":
        pad = c.get("padding", "valid")
        return Deconvolution2D(
            n_out=c["filters"], kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            convolution_mode="same" if pad == "same" else "truncate",
            padding=0, activation=_act(c), has_bias=c.get("use_bias", True))
    if cls == "SeparableConv2D":
        pad = c.get("padding", "valid")
        return SeparableConvolution2D(
            n_out=c["filters"], kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            depth_multiplier=c.get("depth_multiplier", 1),
            convolution_mode="same" if pad == "same" else "truncate",
            padding=0, activation=_act(c), has_bias=c.get("use_bias", True))
    if cls == "DepthwiseConv2D":
        pad = c.get("padding", "valid")
        return DepthwiseConvolution2D(
            kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            depth_multiplier=c.get("depth_multiplier", 1),
            convolution_mode="same" if pad == "same" else "truncate",
            padding=0, activation=_act(c), has_bias=c.get("use_bias", True))
    if cls == "Conv1D":
        pad = c.get("padding", "valid")
        return Convolution1DLayer(
            n_out=c["filters"], kernel_size=_one(c["kernel_size"]),
            stride=_one(c.get("strides", 1)),
            dilation=_one(c.get("dilation_rate", 1)),
            convolution_mode="same" if pad == "same" else "truncate",
            padding=0, activation=_act(c), has_bias=c.get("use_bias", True))
    if cls == "Conv3D":
        pad = c.get("padding", "valid")
        return Convolution3DLayer(
            n_out=c["filters"], kernel_size=_trip(c["kernel_size"]),
            stride=_trip(c.get("strides", 1)),
            dilation=_trip(c.get("dilation_rate", 1)),
            convolution_mode="same" if pad == "same" else "truncate",
            padding=0, activation=_act(c), has_bias=c.get("use_bias", True))
    if cls == "Conv3DTranspose":
        pad = c.get("padding", "valid")
        return Deconvolution3D(
            n_out=c["filters"], kernel_size=_trip(c["kernel_size"]),
            stride=_trip(c.get("strides", 1)),
            convolution_mode="same" if pad == "same" else "truncate",
            padding=0, activation=_act(c), has_bias=c.get("use_bias", True))
    if cls == "ConvLSTM2D":
        return ConvLSTM2D(
            n_out=c["filters"], kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            convolution_mode=("same" if c.get("padding", "valid") == "same"
                              else "truncate"),
            activation=_act({"activation": c.get("activation", "tanh")}),
            gate_activation=_ACT.get(c.get("recurrent_activation", "sigmoid"),
                                     "sigmoid"),
            forget_gate_bias=(1.0 if c.get("unit_forget_bias", True) else 0.0),
            return_sequences=c.get("return_sequences", False),
            has_bias=c.get("use_bias", True))
    if cls in ("MaxPooling3D", "AveragePooling3D"):
        pad = c.get("padding", "valid")
        return Subsampling3DLayer(
            kernel_size=_trip(c.get("pool_size", 2)),
            stride=_trip(c.get("strides") or c.get("pool_size", 2)),
            pooling_type="max" if cls.startswith("Max") else "avg",
            convolution_mode="same" if pad == "same" else "truncate")
    if cls == "UpSampling3D":
        return Upsampling3D(size=_trip(c.get("size", 2)))
    if cls == "ZeroPadding3D":
        return ZeroPadding3DLayer(padding=c.get("padding", 1))
    if cls == "Cropping3D":
        return Cropping3D(cropping=c.get("cropping", 1))
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        pad = c.get("padding", "valid")
        return SubsamplingLayer(
            kernel_size=_pair(c.get("pool_size", 2)),
            stride=_pair(c.get("strides") or c.get("pool_size", 2)),
            pooling_type="max" if cls.startswith("Max") else "avg",
            convolution_mode="same" if pad == "same" else "truncate")
    if cls in ("MaxPooling1D", "AveragePooling1D"):
        pad = c.get("padding", "valid")
        return Subsampling1DLayer(
            kernel_size=_one(c.get("pool_size", 2)),
            stride=_one(c.get("strides") or c.get("pool_size", 2)),
            pooling_type="max" if cls.startswith("Max") else "avg",
            convolution_mode="same" if pad == "same" else "truncate")
    if cls in ("GlobalAveragePooling3D", "GlobalAveragePooling2D",
               "GlobalAveragePooling1D"):
        return GlobalPoolingLayer(pooling_type="avg")
    if cls in ("GlobalMaxPooling3D", "GlobalMaxPooling2D",
               "GlobalMaxPooling1D"):
        return GlobalPoolingLayer(pooling_type="max")
    if cls == "UpSampling2D":
        return Upsampling2D(size=_pair(c.get("size", 2)))
    if cls == "UpSampling1D":
        return Upsampling1D(size=_one(c.get("size", 2)))
    if cls == "ZeroPadding2D":
        return ZeroPaddingLayer(padding=c.get("padding", (1, 1)))
    if cls == "ZeroPadding1D":
        return ZeroPadding1DLayer(padding=c.get("padding", 1))
    if cls == "Cropping2D":
        return Cropping2D(cropping=c.get("cropping", (1, 1)))
    if cls == "Cropping1D":
        return Cropping1D(cropping=c.get("cropping", 1))
    if cls == "Dropout":
        return DropoutLayer(rate=c["rate"])
    if cls == "SpatialDropout2D":
        return SpatialDropout(rate=c["rate"])
    if cls == "GaussianDropout":
        return GaussianDropout(rate=c["rate"])
    if cls == "GaussianNoise":
        return GaussianNoise(stddev=c.get("stddev", 0.1))
    if cls == "AlphaDropout":
        return AlphaDropout(rate=c["rate"])
    if cls == "Activation":
        return ActivationLayer(activation=_act(c))
    if cls == "ReLU":
        return ActivationLayer(activation="relu")
    if cls == "LeakyReLU":
        return ActivationLayer(activation="leakyrelu")
    if cls == "ELU":
        return ActivationLayer(activation="elu")
    if cls == "Softmax":
        return ActivationLayer(activation="softmax")
    if cls == "PReLU":
        return PReLULayer()
    if cls == "BatchNormalization":
        return BatchNormalization(eps=c.get("epsilon", 1e-3),
                                  decay=c.get("momentum", 0.99))
    if cls == "LayerNormalization":
        return LayerNormalization(eps=c.get("epsilon", 1e-3))
    if cls == "Embedding":
        return EmbeddingSequenceLayer(n_in=c["input_dim"], n_out=c["output_dim"])
    if cls == "Reshape":
        from ..nn.layers.core import ReshapeLayer
        return ReshapeLayer(target_shape=tuple(c["target_shape"]))
    if cls == "Permute":
        from ..nn.layers.core import PermuteLayer
        return PermuteLayer(dims=tuple(c["dims"]))
    if cls == "RepeatVector":
        from ..nn.layers.wrappers import RepeatVector
        return RepeatVector(n=c["n"])
    if cls == "TimeDistributed":
        from ..nn.layers.wrappers import TimeDistributedLayer
        inner_cls = c["layer"].get("class_name")
        inner = _map_layer(c["layer"])
        if inner is None:
            raise NotImplementedError(
                f"TimeDistributed({inner_cls}): structural inner layers "
                "(Flatten/InputLayer) have no per-timestep meaning")
        # the fold-time-into-batch wrapper is shape-generic, so spatial
        # inners (Conv2D per frame — upstream KerasTimeDistributed's Cnn3D
        # special case) map the same way as feed-forward ones
        return TimeDistributedLayer(layer=inner)
    if cls in ("LSTM", "GRU", "SimpleRNN"):
        if cls == "LSTM":
            rnn = LSTM(n_out=c["units"],
                       activation=_act({"activation": c.get("activation", "tanh")}),
                       gate_activation=_ACT.get(c.get("recurrent_activation",
                                                      "sigmoid"), "sigmoid"),
                       forget_gate_bias=0.0)
        elif cls == "GRU":
            rnn = GRU(n_out=c["units"],
                      gate_activation=_ACT.get(c.get("recurrent_activation",
                                                     "sigmoid"), "sigmoid"),
                      reset_after=c.get("reset_after", True))
        else:
            rnn = SimpleRnn(n_out=c["units"],
                            activation=_act({"activation": c.get("activation", "tanh")}))
        if not c.get("return_sequences", False):
            from ..nn.layers.recurrent import LastTimeStep
            return LastTimeStep(rnn)
        return rnn
    if cls == "Bidirectional":
        sub = c["layer"]
        subc = dict(sub["config"])
        last_step = not subc.get("return_sequences", False)
        subc["return_sequences"] = True  # wrapper, not inner, takes last step
        inner = _map_layer({"class_name": sub["class_name"], "config": subc})
        mode = c.get("merge_mode", "concat")
        if mode == "sum":
            mode = "add"
        if mode is None:
            raise NotImplementedError(
                "Bidirectional merge_mode=None (separate outputs) is not "
                "supported; use concat/sum/ave/mul")
        if mode not in ("concat", "add", "mul", "ave", "average"):
            raise NotImplementedError(f"Bidirectional merge_mode '{mode}'")
        if mode == "ave":
            mode = "average"
        return Bidirectional(fwd=inner, mode=mode, last_step=last_step)
    if cls == "Flatten":
        return None  # auto preprocessor inserts the reshape
    if cls in ("InputLayer",):
        return None
    raise NotImplementedError(
        f"Keras layer '{cls}' not mapped yet — register_custom_layer("
        f"{cls!r}, factory) can supply a mapping (reference "
        "KerasLayer.registerCustomLayer)")


def _keras_input_type(kcfg):
    c = kcfg["config"]
    shape = c.get("batch_input_shape") or c.get("batch_shape")
    if shape is None:
        return None
    dims = tuple(d for d in shape[1:])
    if len(dims) == 4:  # (T,H,W,C) ConvLSTM sequences or (D,H,W,C) volumes
        return InputType.convolutional_3d(*dims)
    if len(dims) == 3:
        return InputType.convolutional(*dims)
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    return None


def _lstm_reorder(k, units):
    """keras [i, f, c, o] gate columns → ours [i, f, o, g]."""
    i, f, cc, o = (k[:, j * units:(j + 1) * units] for j in range(4))
    return np.concatenate([i, f, o, cc], axis=1)


def _gru_reorder(k, units):
    """keras [z, r, h] gate columns → ours [r, z, n]."""
    z, r, hh = (k[:, j * units:(j + 1) * units] for j in range(3))
    return np.concatenate([r, z, hh], axis=1)


def _convlstm_reorder(k, units):
    """keras ConvLSTM gate blocks [i, f, c, o] (last axis) → ours [i, f, o, g]."""
    i, f, cc, o = (k[..., j * units:(j + 1) * units] for j in range(4))
    return np.concatenate([i, f, o, cc], axis=-1)


def _depthwise_reshape(k):
    """keras (kh, kw, cin, mult) → lax HWIO (kh, kw, 1, cin*mult); keras's
    output-channel order cin*mult + m matches feature_group_count=cin."""
    kh, kw, cin, mult = k.shape
    return k.reshape(kh, kw, 1, cin * mult)


def _set_layer_weights(layer, pdict: Dict, sdict: Dict, ws: List[np.ndarray]):
    """Write one keras layer's weight list into our (params, state) dicts."""
    from ..nn.layers.recurrent import LastTimeStep
    from ..nn.layers.wrappers import TimeDistributedLayer
    if isinstance(layer, LastTimeStep):  # return_sequences=False wrapper
        layer = layer.inner
    if isinstance(layer, TimeDistributedLayer):   # weights live on the inner
        layer = layer.layer
    assign = getattr(layer, "_keras_assign", None)
    if assign is not None:
        assign(layer, pdict, sdict, ws)
        return
    if getattr(layer, "_keras_custom", None) and ws:
        raise ValueError(
            f"custom layer '{layer._keras_custom}' has {len(ws)} weight "
            "arrays in the h5 file but no assign_weights hook — importing "
            "would silently keep random init; pass register_custom_layer("
            f"{layer._keras_custom!r}, factory, assign_weights=...)")
    if isinstance(layer, KerasLambdaLayer):
        return  # parameter-free by construction
    if isinstance(layer, Bidirectional):
        # h5 weight_names order: forward [kernel, rec, bias] then backward
        half = len(ws) // 2
        _set_layer_weights(layer.fwd, pdict["fwd"], sdict.get("fwd", {}),
                           ws[:half])
        _set_layer_weights(layer.fwd, pdict["bwd"], sdict.get("bwd", {}),
                           ws[half:])
        return
    if isinstance(layer, DenseLayer):
        pdict["W"] = jnp.asarray(ws[0])
        if layer.has_bias and len(ws) > 1:
            pdict["b"] = jnp.asarray(ws[1])
    elif isinstance(layer, Deconvolution2D):
        # keras (kh,kw,cout,cin), gradient-of-conv semantics (flipped kernel)
        # → our unflipped HWIO conv_transpose: flip spatial + swap I/O
        pdict["W"] = jnp.asarray(np.transpose(ws[0][::-1, ::-1], (0, 1, 3, 2)))
        if layer.has_bias and len(ws) > 1:
            pdict["b"] = jnp.asarray(ws[1])
    elif isinstance(layer, SeparableConvolution2D):
        pdict["dW"] = jnp.asarray(_depthwise_reshape(ws[0]))
        pdict["pW"] = jnp.asarray(ws[1])
        if layer.has_bias and len(ws) > 2:
            pdict["b"] = jnp.asarray(ws[2])
    elif isinstance(layer, DepthwiseConvolution2D):
        pdict["W"] = jnp.asarray(_depthwise_reshape(ws[0]))
        if layer.has_bias and len(ws) > 1:
            pdict["b"] = jnp.asarray(ws[1])
    elif isinstance(layer, Deconvolution3D):
        # keras (kd,kh,kw,cout,cin) gradient-of-conv (flipped) → our
        # unflipped DHWIO conv_transpose: flip spatial + swap I/O
        pdict["W"] = jnp.asarray(
            np.transpose(ws[0][::-1, ::-1, ::-1], (0, 1, 2, 4, 3)))
        if layer.has_bias and len(ws) > 1:
            pdict["b"] = jnp.asarray(ws[1])
    elif isinstance(layer, ConvLSTM2D):
        units = layer.n_out
        kernel, rec, bias = ws[:3]
        pdict["W"] = jnp.asarray(_convlstm_reorder(kernel, units))
        pdict["RW"] = jnp.asarray(_convlstm_reorder(rec, units))
        if layer.has_bias and len(ws) > 2:
            pdict["b"] = jnp.asarray(
                _convlstm_reorder(bias[None, :], units)[0])
    elif isinstance(layer, (ConvolutionLayer, Convolution1DLayer,
                            Convolution3DLayer)):
        pdict["W"] = jnp.asarray(ws[0])  # HWIO / TIO / DHWIO as-is
        if layer.has_bias and len(ws) > 1:
            pdict["b"] = jnp.asarray(ws[1])
    elif isinstance(layer, BatchNormalization):
        gamma, beta, mean, var = ws[:4]
        pdict["gamma"] = jnp.asarray(gamma)
        pdict["beta"] = jnp.asarray(beta)
        sdict["mean"] = jnp.asarray(mean)
        sdict["var"] = jnp.asarray(var)
    elif isinstance(layer, LayerNormalization):
        pdict["gamma"] = jnp.asarray(ws[0])
        if len(ws) > 1:
            pdict["beta"] = jnp.asarray(ws[1])
    elif isinstance(layer, LSTM):
        units = layer.n_out
        kernel, rec, bias = ws[:3]
        pdict["W"] = jnp.asarray(_lstm_reorder(kernel, units))
        pdict["RW"] = jnp.asarray(_lstm_reorder(rec, units))
        if bias.ndim == 2:  # keras can stack [input_bias, recurrent_bias]
            bias = bias.sum(axis=0)
        pdict["b"] = jnp.asarray(_lstm_reorder(bias[None, :], units)[0])
    elif isinstance(layer, GRU):
        units = layer.n_out
        kernel, rec, bias = ws[:3]
        pdict["W"] = jnp.asarray(_gru_reorder(kernel, units))
        pdict["RW"] = jnp.asarray(_gru_reorder(rec, units))
        if bias.ndim == 2:  # reset_after=True: [input_bias, recurrent_bias]
            pdict["b"] = jnp.asarray(_gru_reorder(bias[0][None, :], units)[0])
            pdict["rb"] = jnp.asarray(_gru_reorder(bias[1][None, :], units)[0])
        else:
            pdict["b"] = jnp.asarray(_gru_reorder(bias[None, :], units)[0])
    elif isinstance(layer, SimpleRnn):
        pdict["W"] = jnp.asarray(ws[0])
        pdict["RW"] = jnp.asarray(ws[1])
        if len(ws) > 2:
            pdict["b"] = jnp.asarray(ws[2])
    elif isinstance(layer, PReLULayer):
        pdict["alpha"] = jnp.asarray(ws[0])
    elif isinstance(layer, EmbeddingSequenceLayer):
        pdict["W"] = jnp.asarray(ws[0])


def _weight_arrays(model_weights, lname):
    import h5py
    grp = model_weights[lname]
    names = [n.decode() if isinstance(n, bytes) else n
             for n in grp.attrs.get("weight_names", [])]
    if names:
        return [np.asarray(grp[n]) for n in names]
    found = []  # keras3 style: nested 'vars' datasets, integer-named

    def visit(name, obj):
        if isinstance(obj, h5py.Dataset):
            found.append((name, obj))
    grp.visititems(visit)

    # visititems yields lexicographic order ('10' < '2'); sort integer-like
    # path segments numerically so layers with 10+ variables stay ordered
    def sort_key(item):
        return tuple((0, int(seg)) if seg.isdigit() else (1, seg)
                     for seg in item[0].split("/"))

    return [np.asarray(obj) for _, obj in sorted(found, key=sort_key)]


def _assign_weights(net: MultiLayerNetwork, model_weights, layer_names_in_order):
    """Copy weight arrays from the h5 group into net params/states."""
    for i, (layer, lname) in enumerate(zip(net.layers, layer_names_in_order)):
        if lname is None:
            continue
        ws = _weight_arrays(model_weights, lname)
        if not ws:
            continue
        key = f"layer_{i}"
        _set_layer_weights(layer, net.params[key], net.states[key], ws)
    net._invalidate()


_KERAS_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "sparse_mcxent",
    "binary_crossentropy": "binary_xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mae", "mae": "mae",
    "hinge": "hinge", "squared_hinge": "squared_hinge",
    "kl_divergence": "kl_divergence",
    "kullback_leibler_divergence": "kl_divergence",
    "poisson": "poisson", "cosine_similarity": "cosine_proximity",
}


def _keras_to_snake(name: str) -> str:
    """keras.src to_snake_case: the rule behind v3 auto variable paths
    ('Conv2D' → 'conv2d', 'BatchNormalization' → 'batch_normalization')."""
    import re
    name = re.sub(r"\W+", "", name)
    name = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub(r"([a-z])([A-Z])", r"\1_\2", name).lower()


def _v3_auto_paths(layer_cfgs) -> Dict[str, str]:
    """Config layer name → the auto path keras-v3 keys its weights h5 by.

    model.weights.h5 groups are 'layers/<snake(class)>[_<k>]' in CREATION
    order per base name — the config's explicit layer names never appear
    (verified empirically, keras 3.13). Regenerating the counter sequence
    over the config's layer list (skipping InputLayer, which saves no
    group) reproduces the mapping."""
    counts: Dict[str, int] = {}
    out: Dict[str, str] = {}
    for kc in layer_cfgs:
        if kc["class_name"] == "InputLayer":
            continue
        base = _keras_to_snake(kc["class_name"])
        k = counts.get(base, 0)
        counts[base] = k + 1
        out[kc["config"]["name"]] = base if k == 0 else f"{base}_{k}"
    return out


class _V3Weights:
    """Presents a keras-v3 weights h5 with the legacy name-keyed interface
    the assignment code uses (config layer name → h5 group with vars/)."""

    def __init__(self, h5file, name_map: Dict[str, str]):
        self._layers = h5file.get("layers")
        self._map = name_map

    def keys(self):
        if self._layers is None:
            return []
        return [cfg_name for cfg_name, auto in self._map.items()
                if auto in self._layers]

    def __contains__(self, k):
        return self._layers is not None and self._map.get(k) in self._layers

    def __getitem__(self, k):
        return self._layers[self._map[k]]


@_contextmanager
def _model_source(path):
    """Context manager: (f-like with .attrs, weights-group-like) for BOTH
    the legacy .h5 layout and the keras-v3 .keras zip archive
    (config.json + model.weights.h5 + metadata.json)."""
    import io
    import types
    import zipfile as _zip

    import h5py

    if _zip.is_zipfile(path):
        with _zip.ZipFile(path) as zf:
            if "config.json" not in set(zf.namelist()):
                raise ValueError(f"{path} is a zip but not a .keras "
                                 "archive (no config.json)")
            cfg = json.loads(zf.read("config.json"))
            attrs = {"model_config": json.dumps(cfg)}
            if cfg.get("compile_config"):
                attrs["training_config"] = json.dumps(cfg["compile_config"])
            inner = cfg["config"]
            layer_cfgs = inner["layers"] if isinstance(inner, dict) else inner
            with h5py.File(io.BytesIO(zf.read("model.weights.h5")),
                           "r") as hf:
                yield (types.SimpleNamespace(attrs=attrs),
                       _V3Weights(hf, _v3_auto_paths(layer_cfgs)))
    else:
        with h5py.File(path, "r") as f:
            yield f, (f["model_weights"] if "model_weights" in f else f)


def _h5_training_loss(f) -> Optional[str]:
    """The compiled loss from the h5 training_config attr, mapped to our
    loss name (reference enforceTrainingConfig path)."""
    raw = f.attrs.get("training_config")
    if raw is None:
        return None
    try:
        tc = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
        loss = tc.get("loss")
        if isinstance(loss, dict):        # keras-3 serialized loss object
            loss = (loss.get("config", {}) or {}).get("name") \
                or loss.get("class_name")
        if isinstance(loss, str):
            key = loss.lower()
            # CamelCase class names -> snake ("CategoricalCrossentropy")
            import re as _re
            key = _re.sub(r"(?<!^)(?=[A-Z])", "_",
                          loss).lower() if loss != key else key
            return _KERAS_LOSSES.get(key)
    except Exception:   # noqa: BLE001 — absent/odd config = inference-only
        return None
    return None


def import_keras_sequential(path, input_shape=None, loss=None):
    """KerasModelImport.importKerasSequentialModelAndWeights analogue.

    When the h5 carries a compiled loss (training_config) — or `loss=` is
    given — a trailing Dense becomes an OutputLayer with that loss, so the
    imported net is trainable with fit() (the reference's
    enforceTrainingConfig behavior). Without either, the import is
    inference-only like an uncompiled keras save.
    """
    from ..nn.layers.core import OutputLayer
    with _model_source(path) as (f, wg):
        raw = f.attrs["model_config"]
        cfg = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
        if cfg["class_name"] != "Sequential":
            raise ValueError("use import_keras_model for Functional models")
        layer_cfgs = cfg["config"]["layers"] if isinstance(cfg["config"], dict) \
            else cfg["config"]
        loss = loss or _h5_training_loss(f)
        b = NeuralNetConfiguration.builder().list()
        names = []
        itype = None
        mapped = []
        for kc in layer_cfgs:
            if itype is None:
                itype = _keras_input_type(kc)
            lyr = _map_layer(kc)
            if lyr is not None:
                mapped.append((lyr, kc["config"]["name"]))
        explicit_loss = loss is not None
        if loss is not None and mapped:
            # Dense + separate Activation('softmax'/...) is a common keras
            # ending: fold the activation into the converted OutputLayer
            if (len(mapped) >= 2 and isinstance(mapped[-1][0], ActivationLayer)
                    and type(mapped[-2][0]) is DenseLayer):
                act_layer, _ = mapped.pop()
                last, nm = mapped[-1]
                mapped[-1] = (OutputLayer(
                    n_out=last.n_out, activation=act_layer.activation,
                    has_bias=last.has_bias, loss=loss), nm)
            elif type(mapped[-1][0]) is DenseLayer:
                last, nm = mapped[-1]
                mapped[-1] = (OutputLayer(
                    n_out=last.n_out, activation=last.activation,
                    has_bias=last.has_bias, loss=loss), nm)
            elif explicit_loss:
                raise ValueError(
                    f"loss={loss!r} was requested but the model's last "
                    f"layer is {type(mapped[-1][0]).__name__}, not Dense — "
                    "cannot build a trainable OutputLayer head")
            else:
                import warnings
                warnings.warn(
                    "h5 carries a compiled loss but the final layer is "
                    f"{type(mapped[-1][0]).__name__}; importing "
                    "inference-only", stacklevel=2)
        for lyr, nm in mapped:
            b.layer(lyr)
            names.append(nm)
        if itype is not None:
            b.set_input_type(itype)
        net = MultiLayerNetwork(b.build())
        net.init(tuple(itype[1]) if itype else tuple(input_shape))
        present = set(wg.keys())
        _assign_weights(net, wg, [n if n in present else None for n in names])
    return net


# ------------------------------------------------------------- functional --

def _inbound_names(kcfg) -> List[str]:
    """Input node names, handling BOTH the keras-2 nested-list format
    ([[['name', 0, 0, {}], ...]]) and the keras-3 __keras_tensor__ format."""
    out: List[str] = []

    def walk(o):
        if isinstance(o, dict):
            if o.get("class_name") == "__keras_tensor__":
                out.append(o["config"]["keras_history"][0])
                return
            for v in o.values():
                walk(v)
        elif isinstance(o, (list, tuple)):
            if (len(o) >= 3 and isinstance(o[0], str)
                    and isinstance(o[1], int) and isinstance(o[2], int)):
                out.append(o[0])  # keras2 ['name', node_idx, tensor_idx, ...]
                return
            for v in o:
                walk(v)

    walk(kcfg.get("inbound_nodes", []))
    return out


def _io_names(spec) -> List[str]:
    """config['input_layers'] / ['output_layers'] → names. Either a single
    ['name', 0, 0] or a list of them."""
    if not spec:
        return []
    if isinstance(spec[0], str):
        return [spec[0]]
    return [s[0] for s in spec]


def import_keras_model(path):
    """KerasModelImport.importKerasModelAndWeights analogue: Functional
    keras model → ComputationGraph."""
    from ..nn.computation_graph import ComputationGraph

    with _model_source(path) as (f, wg):
        raw = f.attrs["model_config"]
        cfg = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
        if cfg["class_name"] == "Sequential":
            raise ValueError("use import_keras_sequential for Sequential models")
        c = cfg["config"]
        inputs = _io_names(c["input_layers"])
        outputs = _io_names(c["output_layers"])
        b = NeuralNetConfiguration.builder().graph_builder()
        b.add_inputs(*inputs)
        input_shapes: Dict[str, tuple] = {}
        layer_names: Dict[str, Any] = {}  # graph node name → keras group name
        for kc in c["layers"]:
            cls = kc["class_name"]
            name = kc["config"]["name"]
            if cls == "InputLayer":
                it = _keras_input_type(kc)
                if it is not None:
                    input_shapes[name] = tuple(it[1])
                continue
            inbound = _inbound_names(kc)
            if cls in _ELEMENTWISE:
                b.add_vertex(name, ElementWiseVertex(op=_ELEMENTWISE[cls]),
                             *inbound)
            elif cls == "Concatenate":
                b.add_vertex(name, MergeVertex(axis=kc["config"].get("axis", -1)),
                             *inbound)
            elif cls == "Flatten":
                b.add_vertex(name,
                             PreprocessorVertex(CnnToFeedForwardPreProcessor()),
                             *inbound)
            else:
                layer = _map_layer(kc)
                if layer is None:
                    raise NotImplementedError(
                        f"structural keras layer '{cls}' not supported in "
                        f"functional import")
                b.add_layer(name, layer, *inbound)
                layer_names[name] = layer
        b.set_outputs(*outputs)
        net = ComputationGraph(b.build())
        net.init([input_shapes[i] for i in inputs])
        present = set(wg.keys())
        for name, layer in layer_names.items():
            if name not in present:
                continue
            ws = _weight_arrays(wg, name)
            if not ws:
                continue
            _set_layer_weights(layer, net.params[name], net.states[name], ws)
        net._train_step = None  # drop jit caches compiled pre-assignment
        net._infer_fn = None
    return net
