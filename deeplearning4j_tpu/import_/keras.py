"""Keras .h5 model import — Sequential + Functional subset.

Reference parity: ``deeplearning4j-modelimport``
(``KerasModelImport.importKerasSequentialModelAndWeights`` /
``importKerasModelAndWeights``). Reads the HDF5 `model_config` JSON and
weight groups directly with h5py (no TF/Keras execution), builds our
MultiLayerNetwork (Sequential) or ComputationGraph (Functional), and maps
weights with the layout conversions:

- Dense kernel (in, out) → ours (in, out) as-is
- Conv2D kernel (kh, kw, cin, cout) → HWIO as-is (both NHWC)
- LSTM kernels: keras gate order [i, f, c, o] → ours [i, f, o, g(c)]
- BatchNorm: gamma/beta/moving_mean/moving_variance → params + state
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

import jax.numpy as jnp
import numpy as np

from ..nn.conf import NeuralNetConfiguration
from ..nn.layers.base import InputType
from ..nn.layers.conv import (ConvolutionLayer, GlobalPoolingLayer,
                              SubsamplingLayer, Upsampling2D, ZeroPaddingLayer)
from ..nn.layers.core import (ActivationLayer, DenseLayer, DropoutLayer,
                              EmbeddingSequenceLayer, OutputLayer)
from ..nn.layers.norm import BatchNormalization, LayerNormalization
from ..nn.layers.recurrent import GRU, LSTM, Bidirectional
from ..nn.multi_layer_network import MultiLayerNetwork

_ACT = {"relu": "relu", "sigmoid": "sigmoid", "tanh": "tanh",
        "softmax": "softmax", "linear": "identity", "elu": "elu",
        "selu": "selu", "gelu": "gelu", "softplus": "softplus",
        "softsign": "softsign", "swish": "swish", "silu": "swish",
        "hard_sigmoid": "hardsigmoid", "leaky_relu": "leakyrelu",
        "relu6": "relu6", "mish": "mish", "exponential": "identity"}


def _act(cfg):
    return _ACT.get(cfg.get("activation", "linear"), "identity")


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _map_layer(kcfg: dict):
    """keras layer config dict → our layer (or None for structural layers)."""
    cls = kcfg["class_name"]
    c = kcfg["config"]
    if cls == "Dense":
        return DenseLayer(n_out=c["units"], activation=_act(c),
                          has_bias=c.get("use_bias", True))
    if cls == "Conv2D":
        pad = c.get("padding", "valid")
        return ConvolutionLayer(
            n_out=c["filters"], kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            dilation=_pair(c.get("dilation_rate", 1)),
            convolution_mode="same" if pad == "same" else "truncate",
            padding=0, activation=_act(c), has_bias=c.get("use_bias", True))
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        pad = c.get("padding", "valid")
        return SubsamplingLayer(
            kernel_size=_pair(c.get("pool_size", 2)),
            stride=_pair(c.get("strides") or c.get("pool_size", 2)),
            pooling_type="max" if cls.startswith("Max") else "avg",
            convolution_mode="same" if pad == "same" else "truncate")
    if cls in ("GlobalAveragePooling2D", "GlobalAveragePooling1D"):
        return GlobalPoolingLayer(pooling_type="avg")
    if cls in ("GlobalMaxPooling2D", "GlobalMaxPooling1D"):
        return GlobalPoolingLayer(pooling_type="max")
    if cls == "UpSampling2D":
        return Upsampling2D(size=_pair(c.get("size", 2)))
    if cls == "ZeroPadding2D":
        return ZeroPaddingLayer(padding=c.get("padding", (1, 1)))
    if cls == "Dropout":
        return DropoutLayer(rate=c["rate"])
    if cls == "Activation":
        return ActivationLayer(activation=_act(c))
    if cls == "ReLU":
        return ActivationLayer(activation="relu")
    if cls == "LeakyReLU":
        return ActivationLayer(activation="leakyrelu")
    if cls == "BatchNormalization":
        return BatchNormalization(eps=c.get("epsilon", 1e-3),
                                  decay=c.get("momentum", 0.99))
    if cls == "LayerNormalization":
        return LayerNormalization(eps=c.get("epsilon", 1e-3))
    if cls == "Embedding":
        return EmbeddingSequenceLayer(n_in=c["input_dim"], n_out=c["output_dim"])
    if cls == "LSTM":
        return LSTM(n_out=c["units"], activation=_act({"activation": c.get("activation", "tanh")}),
                    gate_activation=_ACT.get(c.get("recurrent_activation", "sigmoid"), "sigmoid"),
                    forget_gate_bias=0.0)
    if cls == "GRU":
        return GRU(n_out=c["units"])
    if cls == "Bidirectional":
        inner = _map_layer(c["layer"])
        return Bidirectional(fwd=inner, mode=c.get("merge_mode", "concat"))
    if cls == "Flatten":
        return None  # auto preprocessor inserts the reshape
    if cls in ("InputLayer",):
        return None
    raise NotImplementedError(f"Keras layer '{cls}' not mapped yet")


def _keras_input_type(kcfg):
    c = kcfg["config"]
    shape = c.get("batch_input_shape") or c.get("batch_shape")
    if shape is None:
        return None
    dims = tuple(d for d in shape[1:])
    if len(dims) == 3:
        return InputType.convolutional(*dims)
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    return None


def _lstm_reorder(k, units):
    """keras [i, f, c, o] gate columns → ours [i, f, o, g]."""
    i, f, cc, o = (k[:, j * units:(j + 1) * units] for j in range(4))
    return np.concatenate([i, f, o, cc], axis=1)


def _assign_weights(net: MultiLayerNetwork, model_weights, layer_names_in_order):
    """Copy weight arrays from the h5 group into net params/states."""
    import h5py

    def arrays_for(lname):
        grp = model_weights[lname]
        names = [n.decode() if isinstance(n, bytes) else n
                 for n in grp.attrs.get("weight_names", [])]
        if names:
            return [np.asarray(grp[n]) for n in names]
        # keras3 style: nested 'vars' datasets
        out = []

        def visit(_, obj):
            if isinstance(obj, h5py.Dataset):
                out.append(np.asarray(obj))
        grp.visititems(visit)
        return out

    for i, (layer, lname) in enumerate(zip(net.layers, layer_names_in_order)):
        if lname is None:
            continue
        ws = arrays_for(lname)
        if not ws:
            continue
        key = f"layer_{i}"
        if isinstance(layer, (DenseLayer,)):
            layer_params = {"W": jnp.asarray(ws[0])}
            if layer.has_bias and len(ws) > 1:
                layer_params["b"] = jnp.asarray(ws[1])
            net.params[key].update(layer_params)
        elif isinstance(layer, ConvolutionLayer):
            net.params[key]["W"] = jnp.asarray(ws[0])
            if layer.has_bias and len(ws) > 1:
                net.params[key]["b"] = jnp.asarray(ws[1])
        elif isinstance(layer, BatchNormalization):
            gamma, beta, mean, var = ws[:4]
            net.params[key]["gamma"] = jnp.asarray(gamma)
            net.params[key]["beta"] = jnp.asarray(beta)
            net.states[key]["mean"] = jnp.asarray(mean)
            net.states[key]["var"] = jnp.asarray(var)
        elif isinstance(layer, LSTM):
            units = layer.n_out
            kernel, rec, bias = ws[:3]
            net.params[key]["W"] = jnp.asarray(_lstm_reorder(kernel, units))
            net.params[key]["RW"] = jnp.asarray(_lstm_reorder(rec, units))
            net.params[key]["b"] = jnp.asarray(
                _lstm_reorder(bias[None, :], units)[0])
        elif isinstance(layer, EmbeddingSequenceLayer):
            net.params[key]["W"] = jnp.asarray(ws[0])
    net._invalidate()


def import_keras_sequential(path, input_shape=None):
    """KerasModelImport.importKerasSequentialModelAndWeights analogue."""
    import h5py
    with h5py.File(path, "r") as f:
        raw = f.attrs["model_config"]
        cfg = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
        if cfg["class_name"] != "Sequential":
            raise ValueError("use import_keras_model for Functional models")
        layer_cfgs = cfg["config"]["layers"] if isinstance(cfg["config"], dict) \
            else cfg["config"]
        b = NeuralNetConfiguration.builder().list()
        names = []
        itype = None
        for kc in layer_cfgs:
            if itype is None:
                itype = _keras_input_type(kc)
            lyr = _map_layer(kc)
            if lyr is not None:
                b.layer(lyr)
                names.append(kc["config"]["name"])
        if itype is not None:
            b.set_input_type(itype)
        net = MultiLayerNetwork(b.build())
        net.init(tuple(itype[1]) if itype else tuple(input_shape))
        wg = f["model_weights"] if "model_weights" in f else f
        present = set(wg.keys())
        _assign_weights(net, wg, [n if n in present else None for n in names])
    return net
