"""Hyperparameter spaces — parity with Arbiter's
``org.deeplearning4j.arbiter.optimize.api.ParameterSpace`` family
(ContinuousParameterSpace, IntegerParameterSpace, DiscreteParameterSpace)
and the grid/random candidate generators.

A search space is a flat dict ``name -> ParameterSpace``; a candidate is
the sampled dict. Model-construction stays a user callable (the lite
replacement for Arbiter's MultiLayerSpace config-template machinery).
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


class ParameterSpace:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError

    def grid(self, n: int) -> List[Any]:
        """n representative values for grid search."""
        raise NotImplementedError

    def mutate(self, value, rng: np.random.Generator):
        """Genetic-search mutation — default: resample the gene."""
        return self.sample(rng)


class ContinuousParameterSpace(ParameterSpace):
    def __init__(self, low: float, high: float, log_scale: bool = False):
        if log_scale and low <= 0:
            raise ValueError("log_scale requires low > 0")
        self.low, self.high, self.log_scale = float(low), float(high), log_scale

    def sample(self, rng):
        if self.log_scale:
            return float(np.exp(rng.uniform(math.log(self.low), math.log(self.high))))
        return float(rng.uniform(self.low, self.high))

    def grid(self, n):
        if self.log_scale:
            return [float(v) for v in np.exp(np.linspace(
                math.log(self.low), math.log(self.high), n))]
        return [float(v) for v in np.linspace(self.low, self.high, n)]

    def mutate(self, value, rng):
        """Local gaussian step (10% of the span); log-scale steps in log
        space — keeps evolution's fine-convergence while sample() handles
        exploration."""
        if self.log_scale:
            lo, hi = math.log(self.low), math.log(self.high)
            lv = math.log(value) + rng.normal(0.0, 0.1 * (hi - lo))
            return float(math.exp(min(max(lv, lo), hi)))
        v = value + rng.normal(0.0, 0.1 * (self.high - self.low))
        return float(min(max(v, self.low), self.high))


class IntegerParameterSpace(ParameterSpace):
    def __init__(self, low: int, high: int):
        self.low, self.high = int(low), int(high)

    def sample(self, rng):
        return int(rng.integers(self.low, self.high + 1))

    def grid(self, n):
        vals = np.unique(np.linspace(self.low, self.high, n).round().astype(int))
        return [int(v) for v in vals]

    def mutate(self, value, rng):
        step = 1 if rng.random() < 0.5 else -1
        return int(min(max(value + step, self.low), self.high))


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(len(self.values)))]

    def grid(self, n):
        return list(self.values)


class FixedValue(ParameterSpace):
    def __init__(self, value: Any):
        self.value = value

    def sample(self, rng):
        return self.value

    def grid(self, n):
        return [self.value]


# -------------------------------------------------------------- generators
class CandidateGenerator:
    """Yields candidate dicts; exhausted generators stop iteration."""

    def __init__(self, space: Dict[str, ParameterSpace]):
        self.space = dict(space)

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        raise NotImplementedError


class RandomSearchGenerator(CandidateGenerator):
    """Reference ``RandomSearchGenerator`` — endless iid samples."""

    def __init__(self, space, seed: int = 0, max_candidates: Optional[int] = None):
        super().__init__(space)
        self.seed = seed
        self.max_candidates = max_candidates

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        n = 0
        while self.max_candidates is None or n < self.max_candidates:
            yield {k: s.sample(rng) for k, s in self.space.items()}
            n += 1


class GridSearchCandidateGenerator(CandidateGenerator):
    """Reference ``GridSearchCandidateGenerator`` — cartesian product of
    per-dimension grids; order 'sequential' or 'random' (shuffled)."""

    def __init__(self, space, discretization_count: int = 5,
                 mode: str = "sequential", seed: int = 0):
        super().__init__(space)
        self.discretization_count = discretization_count
        self.mode = mode
        self.seed = seed

    def __iter__(self):
        keys = list(self.space)
        axes = [self.space[k].grid(self.discretization_count) for k in keys]
        combos = list(itertools.product(*axes))
        if self.mode == "random":
            np.random.default_rng(self.seed).shuffle(combos)
        for combo in combos:
            yield dict(zip(keys, combo))


class GeneticSearchCandidateGenerator(CandidateGenerator):
    """Evolutionary candidate search — parity with Arbiter's
    ``GeneticSearchCandidateGenerator`` (+ its selection / crossover /
    mutation operators collapsed into tournament selection, per-gene uniform
    crossover, and resample-mutation on the typed ParameterSpaces directly,
    so no numeric chromosome encoding layer is needed).

    Feedback loop: ``OptimizationRunner`` calls :meth:`report` after scoring
    each candidate (the upstream generator receives results the same way).
    Until ``population_size`` scored results exist, candidates are random
    samples; afterwards each candidate is bred from two tournament-selected
    parents.
    """

    def __init__(self, space, population_size: int = 12,
                 tournament_size: int = 3, mutation_prob: float = 0.15,
                 crossover_prob: float = 0.85, max_candidates: int = 50,
                 seed: int = 0, minimize: bool = True):
        super().__init__(space)
        if population_size < 2:
            raise ValueError("population_size must be >= 2")
        self.population_size = population_size
        self.tournament_size = tournament_size
        self.mutation_prob = mutation_prob
        self.crossover_prob = crossover_prob
        self.max_candidates = max_candidates
        self.seed = seed
        self.minimize = minimize
        self._scored: List[tuple] = []   # (candidate dict, score)

    # ---- runner feedback -------------------------------------------------
    def report(self, candidate: Dict[str, Any], score: float,
               minimize: Optional[bool] = None):
        """Record a scored candidate; the breeding pool keeps the best
        ``population_size`` seen so far."""
        if minimize is not None:
            self.minimize = minimize
        if not math.isfinite(score):
            return
        self._scored.append((dict(candidate), float(score)))
        self._scored.sort(key=lambda cs: cs[1] if self.minimize else -cs[1])
        del self._scored[self.population_size:]

    # ---- breeding --------------------------------------------------------
    def _tournament(self, rng) -> Dict[str, Any]:
        k = min(self.tournament_size, len(self._scored))
        picks = rng.choice(len(self._scored), size=k, replace=False)
        best = min(picks, key=lambda i: self._scored[i][1]) if self.minimize \
            else max(picks, key=lambda i: self._scored[i][1])
        return self._scored[best][0]

    def _breed(self, rng) -> Dict[str, Any]:
        pa, pb = self._tournament(rng), self._tournament(rng)
        child = {}
        for k, s in self.space.items():
            va, vb = pa[k], pb[k]
            if rng.random() < self.mutation_prob:
                child[k] = s.mutate(va, rng)        # local step (or resample)
            elif rng.random() < self.crossover_prob:
                # arithmetic crossover (upstream ArithmeticCrossover) only on
                # ranged spaces — a convex blend stays inside the range.
                # Discrete/Fixed genes must stay MEMBERS of the space, so
                # they get a uniform parent pick instead.
                if isinstance(s, ContinuousParameterSpace):
                    u = rng.random()
                    child[k] = u * va + (1 - u) * vb
                elif isinstance(s, IntegerParameterSpace):
                    u = rng.random()
                    child[k] = round(u * va + (1 - u) * vb)
                else:
                    child[k] = va if rng.random() < 0.5 else vb
            else:
                child[k] = va
        return child

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        for _ in range(self.max_candidates):
            if len(self._scored) < self.population_size:
                yield {k: s.sample(rng) for k, s in self.space.items()}
            else:
                yield self._breed(rng)
