"""Hyperparameter spaces — parity with Arbiter's
``org.deeplearning4j.arbiter.optimize.api.ParameterSpace`` family
(ContinuousParameterSpace, IntegerParameterSpace, DiscreteParameterSpace)
and the grid/random candidate generators.

A search space is a flat dict ``name -> ParameterSpace``; a candidate is
the sampled dict. Model-construction stays a user callable (the lite
replacement for Arbiter's MultiLayerSpace config-template machinery).
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


class ParameterSpace:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError

    def grid(self, n: int) -> List[Any]:
        """n representative values for grid search."""
        raise NotImplementedError


class ContinuousParameterSpace(ParameterSpace):
    def __init__(self, low: float, high: float, log_scale: bool = False):
        if log_scale and low <= 0:
            raise ValueError("log_scale requires low > 0")
        self.low, self.high, self.log_scale = float(low), float(high), log_scale

    def sample(self, rng):
        if self.log_scale:
            return float(np.exp(rng.uniform(math.log(self.low), math.log(self.high))))
        return float(rng.uniform(self.low, self.high))

    def grid(self, n):
        if self.log_scale:
            return [float(v) for v in np.exp(np.linspace(
                math.log(self.low), math.log(self.high), n))]
        return [float(v) for v in np.linspace(self.low, self.high, n)]


class IntegerParameterSpace(ParameterSpace):
    def __init__(self, low: int, high: int):
        self.low, self.high = int(low), int(high)

    def sample(self, rng):
        return int(rng.integers(self.low, self.high + 1))

    def grid(self, n):
        vals = np.unique(np.linspace(self.low, self.high, n).round().astype(int))
        return [int(v) for v in vals]


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(len(self.values)))]

    def grid(self, n):
        return list(self.values)


class FixedValue(ParameterSpace):
    def __init__(self, value: Any):
        self.value = value

    def sample(self, rng):
        return self.value

    def grid(self, n):
        return [self.value]


# -------------------------------------------------------------- generators
class CandidateGenerator:
    """Yields candidate dicts; exhausted generators stop iteration."""

    def __init__(self, space: Dict[str, ParameterSpace]):
        self.space = dict(space)

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        raise NotImplementedError


class RandomSearchGenerator(CandidateGenerator):
    """Reference ``RandomSearchGenerator`` — endless iid samples."""

    def __init__(self, space, seed: int = 0, max_candidates: Optional[int] = None):
        super().__init__(space)
        self.seed = seed
        self.max_candidates = max_candidates

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        n = 0
        while self.max_candidates is None or n < self.max_candidates:
            yield {k: s.sample(rng) for k, s in self.space.items()}
            n += 1


class GridSearchCandidateGenerator(CandidateGenerator):
    """Reference ``GridSearchCandidateGenerator`` — cartesian product of
    per-dimension grids; order 'sequential' or 'random' (shuffled)."""

    def __init__(self, space, discretization_count: int = 5,
                 mode: str = "sequential", seed: int = 0):
        super().__init__(space)
        self.discretization_count = discretization_count
        self.mode = mode
        self.seed = seed

    def __iter__(self):
        keys = list(self.space)
        axes = [self.space[k].grid(self.discretization_count) for k in keys]
        combos = list(itertools.product(*axes))
        if self.mode == "random":
            np.random.default_rng(self.seed).shuffle(combos)
        for combo in combos:
            yield dict(zip(keys, combo))
