"""Optimization runner — parity with Arbiter's
``OptimizationConfiguration`` + ``LocalOptimizationRunner`` (execute a
candidate generator against a score function, track results, stop on
termination conditions) and its ``TerminationCondition`` family.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .space import CandidateGenerator


# ----------------------------------------------------- termination conditions
class TerminationCondition:
    def initialize(self, runner: "OptimizationRunner"):
        pass

    def terminate(self, runner: "OptimizationRunner") -> bool:
        raise NotImplementedError


class MaxCandidatesCondition(TerminationCondition):
    def __init__(self, max_candidates: int):
        self.max_candidates = max_candidates

    def terminate(self, runner):
        return len(runner.results) >= self.max_candidates


class MaxTimeCondition(TerminationCondition):
    def __init__(self, seconds: float):
        self.seconds = seconds
        self._t0 = None

    def initialize(self, runner):
        self._t0 = time.time()

    def terminate(self, runner):
        return time.time() - self._t0 >= self.seconds


class BestScoreCondition(TerminationCondition):
    """Stop once the best score crosses a threshold."""

    def __init__(self, threshold: float):
        self.threshold = threshold

    def terminate(self, runner):
        best = runner.best_result()
        if best is None:
            return False
        return (best.score <= self.threshold if runner.minimize
                else best.score >= self.threshold)


# --------------------------------------------------------------- result record
@dataclass
class CandidateResult:
    index: int
    candidate: Dict[str, Any]
    score: float
    duration_s: float
    extra: Any = None


class OptimizationRunner:
    """execute(): pull candidates, score them, keep results + the best.

    ``score_fn(candidate: dict) -> float`` or ``-> (float, extra)`` — the
    user's train-and-evaluate closure (Arbiter's ScoreFunction + TaskCreator
    collapsed into one callable).
    """

    def __init__(self, generator: CandidateGenerator,
                 score_fn: Callable[[Dict[str, Any]], Any],
                 minimize: bool = True,
                 termination_conditions: Optional[List[TerminationCondition]] = None,
                 on_result: Optional[Callable[[CandidateResult], None]] = None):
        from .space import RandomSearchGenerator
        self.generator = generator
        self.score_fn = score_fn
        self.minimize = minimize
        self.conditions = termination_conditions or []
        self.on_result = on_result
        self.results: List[CandidateResult] = []
        if (not self.conditions and isinstance(generator, RandomSearchGenerator)
                and generator.max_candidates is None):
            raise ValueError(
                "unbounded RandomSearchGenerator needs a termination condition "
                "(or set max_candidates)")

    def execute(self) -> Optional[CandidateResult]:
        for c in self.conditions:
            c.initialize(self)
        for i, candidate in enumerate(self.generator):
            if any(c.terminate(self) for c in self.conditions):
                break
            t0 = time.time()
            out = self.score_fn(candidate)
            score, extra = out if isinstance(out, tuple) else (out, None)
            res = CandidateResult(i, candidate, float(score),
                                  time.time() - t0, extra)
            self.results.append(res)
            report = getattr(self.generator, "report", None)
            if report is not None:   # genetic search closes its feedback loop
                report(candidate, res.score, self.minimize)
            if self.on_result:
                self.on_result(res)
        return self.best_result()

    def best_result(self) -> Optional[CandidateResult]:
        import math
        valid = [r for r in self.results if not math.isnan(r.score)]
        if not valid:
            return None
        key = (lambda r: r.score) if self.minimize else (lambda r: -r.score)
        return min(valid, key=key)
