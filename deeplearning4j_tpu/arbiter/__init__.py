"""deeplearning4j_tpu.arbiter — Arbiter-lite hyperparameter search."""

from .runner import (BestScoreCondition, CandidateResult,
                     MaxCandidatesCondition, MaxTimeCondition,
                     OptimizationRunner, TerminationCondition)
from .space import (CandidateGenerator, ContinuousParameterSpace,
                    DiscreteParameterSpace, FixedValue,
                    GeneticSearchCandidateGenerator,
                    GridSearchCandidateGenerator, IntegerParameterSpace,
                    ParameterSpace, RandomSearchGenerator)
