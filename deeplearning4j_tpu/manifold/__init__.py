"""deeplearning4j_tpu.manifold — dimensionality reduction for visualisation.

Parity with ``deeplearning4j-manifold`` (``BarnesHutTsne``).
"""

from .tsne import TSNE, BarnesHutTsne
