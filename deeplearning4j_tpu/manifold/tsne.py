"""t-SNE — parity with ``deeplearning4j-manifold``'s
``org.deeplearning4j.plot.BarnesHutTsne`` (perplexity-calibrated input
affinities, early exaggeration, momentum + per-dimension gains).

TPU-first redesign: the reference approximates the repulsive forces with
a Barnes-Hut quadtree on the CPU because O(N²) is hostile to scalar
cores. On TPU the O(N²) kernels ARE the fast path — pairwise distances,
the student-t Q matrix, and both force sums are dense matmul/broadcast
ops that ride the MXU/VPU, so this implementation computes them exactly
(no theta approximation) with the whole optimisation loop, including the
per-row perplexity bisection, inside one jitted ``lax`` program. For the
embedding sizes t-SNE is used for (10³–10⁴ points) exact beats
tree-approximate on this hardware.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


def _pairwise_sq_dists(x):
    n2 = jnp.sum(jnp.square(x), axis=1)
    d = n2[:, None] + n2[None, :] - 2.0 * (x @ x.T)
    return jnp.maximum(d, 0.0)


def _conditional_probs(d2, perplexity, iters=50):
    """Per-row precision (beta) bisection to hit log2(perplexity) entropy —
    vectorised over ALL rows at once (reference computeGaussianPerplexity)."""
    n = d2.shape[0]
    target = jnp.log(perplexity)
    eye = jnp.eye(n, dtype=bool)

    def entropy_and_p(beta):
        logits = -d2 * beta[:, None]
        logits = jnp.where(eye, -jnp.inf, logits)
        p = jax.nn.softmax(logits, axis=1)
        # Shannon entropy H = log Z + beta * <d2>
        h = -jnp.sum(jnp.where(p > 1e-12, p * jnp.log(p), 0.0), axis=1)
        return h, p

    def body(carry, _):
        beta, lo, hi = carry
        h, _ = entropy_and_p(beta)
        too_high = h > target          # entropy too high → raise precision
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2.0, (lo + hi) / 2.0)
        return (beta, lo, hi), None

    init = (jnp.ones(n), jnp.zeros(n), jnp.full(n, jnp.inf))
    (beta, _, _), _ = jax.lax.scan(body, init, None, length=iters)
    _, p = entropy_and_p(beta)
    return p


@dataclass
class TSNE:
    """Exact t-SNE with the reference's optimisation schedule."""

    n_components: int = 2
    perplexity: float = 30.0
    learning_rate: float = 200.0
    n_iter: int = 500               # reference maxIter
    early_exaggeration: float = 12.0
    exaggeration_iters: int = 100   # reference stopLyingIteration
    momentum: float = 0.5
    final_momentum: float = 0.8
    momentum_switch: int = 250      # reference switchMomentumIteration
    min_gain: float = 0.01
    seed: int = 0

    def fit_transform(self, x) -> np.ndarray:
        x = jnp.asarray(x, jnp.float32)
        n = x.shape[0]
        if n < 4:
            raise ValueError(f"need >= 4 points, got {n}")
        perp = min(self.perplexity, (n - 1) / 3.0)

        d2 = _pairwise_sq_dists(x)
        p_cond = _conditional_probs(d2, perp)
        p = (p_cond + p_cond.T) / (2.0 * n)       # symmetrised joint P
        p = jnp.maximum(p, 1e-12)

        key = jax.random.PRNGKey(self.seed)
        y0 = 1e-4 * jax.random.normal(key, (n, self.n_components))
        cfg = self

        @jax.jit
        def optimize(p, y0):
            eye = jnp.eye(n, dtype=bool)

            def grad_kl(y, p_eff):
                num = 1.0 / (1.0 + _pairwise_sq_dists(y))   # student-t kernel
                num = jnp.where(eye, 0.0, num)
                q = jnp.maximum(num / jnp.sum(num), 1e-12)
                pq = (p_eff - q) * num                       # (N, N)
                # 4 Σ_j pq_ij (y_i - y_j)  — dense matmul form
                g = 4.0 * (jnp.diag(jnp.sum(pq, axis=1)) - pq) @ y
                kl = jnp.sum(p_eff * jnp.log(p_eff / q))
                return g, kl

            def body(i, carry):
                y, vel, gains = carry
                p_eff = jnp.where(i < cfg.exaggeration_iters,
                                  p * cfg.early_exaggeration, p)
                g, _ = grad_kl(y, p_eff)
                mom = jnp.where(i < cfg.momentum_switch,
                                cfg.momentum, cfg.final_momentum)
                # per-dimension gains (reference BarnesHutTsne.update)
                same_sign = jnp.sign(g) == jnp.sign(vel)
                gains = jnp.maximum(
                    jnp.where(same_sign, gains * 0.8, gains + 0.2),
                    cfg.min_gain)
                vel = mom * vel - cfg.learning_rate * gains * g
                y = y + vel
                return (y - jnp.mean(y, axis=0), vel, gains)

            y, _, _ = jax.lax.fori_loop(
                0, cfg.n_iter, body,
                (y0, jnp.zeros_like(y0), jnp.ones_like(y0)))
            _, kl = grad_kl(y, p)
            return y, kl

        y, kl = optimize(p, y0)
        self.kl_divergence_ = float(kl)
        return np.asarray(y)


BarnesHutTsne = TSNE  # reference class-name alias (exact-repulsion variant)
