"""Generation engine for the zoo Transformer-LM: jitted KV-cache prefill
and single-token decode, plus greedy/temperature/top-k sampling.

Two device entry points, both compiled once per shape and reused for the
life of the engine:

- ``prefill`` — runs the prompt through the ordinary block stack (the
  SAME ``apply_blocks`` the training forward uses, ``return_kv=True``),
  writes every layer's k/v into the cache, and returns ONLY the last
  valid position's logits (``(B, V)`` — never the ``(B, T, V)`` tensor a
  generation step doesn't need; at T=4096/V=32k that tensor alone is
  0.5 GB f32).
- ``decode_step`` — one token per slot: embed at each slot's own
  position cursor, scan the stacked blocks with the cache riding the
  scan's xs/ys (layer l's k/v slab is consumed and re-emitted in place),
  attend causally against the cache under a per-slot length mask. The
  cache argument is DONATED, so the decode loop never holds two copies
  of the K/V HBM.

Correctness is anchored the ``rnn_time_step`` way (tests/test_serving.py):
prefill+decode logits must match the full forward at every position
within fp tolerance — the cache is an optimization, never a different
model.

Single-chip inference path: MoE (`n_experts`) and ring attention are
training-parallelism features with no single-token analogue here and are
rejected at construction. Prefill inherits the model's own attention
gating (`flash_engages`), so a TPU prefill at flash-sized T runs the
pallas kernel exactly like the training forward.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..zoo import transformer as tfm
from . import kvcache

# prompt lengths are padded up to one of these before the jitted
# per-slot prefill runs, so mixed-length traffic compiles a handful of
# kernels instead of one per distinct prompt length (clipped to the
# engine's max_len; max_len itself is always a bucket)
DEFAULT_PREFILL_BUCKETS = (32, 128, 512, 1024, 2048, 4096, 8192)

_NEG_INF = -1e30  # mask value: finite, softmax-safe in f32


def sample_tokens(key, logits, temperature, top_k):
    """Vectorized next-token sampling: (B, V) f32 logits, per-slot
    ``temperature`` (B,) and ``top_k`` (B,) — a slot with
    ``temperature <= 0`` decodes greedily (argmax, key unused), one with
    ``top_k > 0`` samples only among its k highest logits. Per-slot
    knobs make one jitted sampler serve a mixed-request decode sweep.
    """
    logits = logits.astype(jnp.float32)
    v = logits.shape[-1]
    temperature = jnp.asarray(temperature, jnp.float32).reshape(-1)
    top_k = jnp.asarray(top_k, jnp.int32).reshape(-1)
    # top-k filter: threshold at each row's k-th largest logit
    desc = jnp.sort(logits, axis=-1)[:, ::-1]
    kk = jnp.clip(jnp.where(top_k > 0, top_k, v), 1, v)
    thresh = jnp.take_along_axis(desc, (kk - 1)[:, None], axis=-1)
    filtered = jnp.where(logits >= thresh, logits, _NEG_INF)
    scaled = filtered / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def _cached_attention(cfg, q, k, v, pos):
    """Single-token attention against the cache: q (B, H, Dh) vs
    k/v (B, S, H, Dh), each slot masked to its own length (positions
    ``<= pos[b]`` — pos is the index the current token was just written
    at). Scores accumulate f32 regardless of cache dtype; out-of-range
    cache rows never contribute."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhd,bshd->bhs",
                        (q.astype(jnp.float32) * scale),
                        k.astype(jnp.float32))
    s = k.shape[1]
    mask = jnp.arange(s)[None, :] <= pos[:, None]          # (B, S)
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", probs, v.astype(jnp.float32))
    return out.astype(cfg.dtype)


class GenerationEngine:
    """Prefill/decode engine bound to one (cfg, params) pair.

    The engine owns the jitted functions; callers own the cache pytree
    (``init_cache``) and thread it through ``prefill`` / ``decode_step``
    — the functional style every other step in this codebase uses, so
    the cache composes with donation and with schedulers that interleave
    prefill and decode on one pool.
    """

    def __init__(self, cfg, params, *, max_len: Optional[int] = None,
                 prefill_buckets=DEFAULT_PREFILL_BUCKETS):
        if getattr(cfg, "n_experts", 0):
            raise NotImplementedError(
                "GenerationEngine is dense-only: MoE expert dispatch has "
                "no single-token decode path yet (train MoE via the GSPMD "
                "path; see ROADMAP)")
        if cfg.use_ring_attention:
            raise NotImplementedError(
                "ring attention is a sequence-parallel TRAINING path; the "
                "decode step attends one token against a local cache — "
                "construct the engine with use_ring_attention=False")
        self.cfg = cfg
        self.params = params
        self.max_len = int(cfg.max_seq if max_len is None else max_len)
        if self.max_len > cfg.max_seq:
            raise ValueError(
                f"max_len {self.max_len} exceeds cfg.max_seq="
                f"{cfg.max_seq}: no position rows past the table")
        self.prefill_buckets = tuple(sorted(
            {min(b, self.max_len) for b in prefill_buckets} | {self.max_len}))
        # jit once; cache (argnum 1 after params) donated on every path.
        # Each entry point is wrapped in a CompileSentinel (ISSUE 12):
        # compiles are counted/timed per abstract signature, and after
        # mark_warm() any further compile is a warned retrace — the
        # zero-recompile-after-warmup contract the regression tests pin.
        # The sentinel is transparent (.lower etc. delegate), so floor
        # probes keep working on eng._decode unchanged.
        from ..obs.compiles import CompileSentinel
        self._decode = CompileSentinel(
            "decode_step", jax.jit(self._decode_raw, donate_argnums=(1,)))
        self._prefill = CompileSentinel(
            "prefill", jax.jit(self._prefill_raw, donate_argnums=(1,)))
        self._prefill_slot = CompileSentinel(
            "prefill_slot", jax.jit(self._prefill_slot_raw,
                                    donate_argnums=(1,)))
        self._sample = CompileSentinel("sample_tokens",
                                       jax.jit(sample_tokens))
        self.sentinels = {s.name: s for s in (
            self._decode, self._prefill, self._prefill_slot, self._sample)}

    # ------------------------------------------------------------ cache
    def init_cache(self, n_slots: int):
        return kvcache.init_cache(self.cfg, n_slots, self.max_len)

    def refresh(self, params):
        """Swap in new params (e.g. after more training). Compiled fns
        are shape-keyed, so no retrace as long as shapes match."""
        self.params = params
        return self

    # -------------------------------------------------- compile plane
    def mark_warm(self):
        """Declare warmup over on every sentinel: the decode sweep and
        the bucketed prefills seen so far are the working set; any
        compile after this is a warned retrace (ISSUE 12)."""
        for s in self.sentinels.values():
            s.mark_warm()
        return self

    def compile_report(self):
        """{entry point: {compiles, signatures, retraces_after_warm}} —
        what the retrace regression tests and ``/debug/memory`` read."""
        return {name: s.report() for name, s in self.sentinels.items()}

    # ----------------------------------------------------- device fns
    def _prefill_trunk(self, params, tokens):
        """Shared prompt pass: embedded tokens through the block stack
        with per-layer k/v capture. Returns (hidden, k, v)."""
        cfg = self.cfg
        x = tfm.embed(params, cfg, tokens)
        x, _, (ks, vs) = tfm.apply_blocks(params["blocks"], cfg, x,
                                          return_kv=True)
        return x, ks, vs

    def _prefill_raw(self, params, cache, tokens, lengths):
        """Whole-pool prefill: tokens (B, T) — B must equal the cache's
        slot count — lengths (B,) valid-prefix lengths (padding rows
        beyond a row's length leave garbage k/v that the pos mask never
        exposes). Returns (last-position logits (B, V) f32, cache)."""
        x, ks, vs = self._prefill_trunk(params, tokens)
        k_cache = lax.dynamic_update_slice(
            cache["k"], ks.astype(cache["k"].dtype), (0, 0, 0, 0, 0))
        v_cache = lax.dynamic_update_slice(
            cache["v"], vs.astype(cache["v"].dtype), (0, 0, 0, 0, 0))
        b, t = tokens.shape
        last = jnp.clip(lengths - 1, 0, t - 1)
        x_last = x[jnp.arange(b), last]
        logits = tfm.head_logits_rows(params, self.cfg, x_last)
        return logits, {"k": k_cache, "v": v_cache,
                        "pos": lengths.astype(jnp.int32)}

    def _prefill_slot_raw(self, params, cache, tokens, length, slot):
        """Admit ONE request into slot ``slot`` of a live pool: tokens
        (1, T_bucket) padded prompt, ``length`` its true length. Only
        this slot's cache rows and cursor change — in-flight neighbours
        are untouched, which is what lets admission interleave with
        decode on the same cache."""
        x, ks, vs = self._prefill_trunk(params, tokens)
        k_cache = lax.dynamic_update_slice(
            cache["k"], ks.astype(cache["k"].dtype), (0, slot, 0, 0, 0))
        v_cache = lax.dynamic_update_slice(
            cache["v"], vs.astype(cache["v"].dtype), (0, slot, 0, 0, 0))
        t = tokens.shape[1]
        x_last = x[0, jnp.clip(length - 1, 0, t - 1)]
        logits = tfm.head_logits_rows(params, self.cfg, x_last[None])[0]
        pos = cache["pos"].at[slot].set(length.astype(jnp.int32))
        return logits, {"k": k_cache, "v": v_cache, "pos": pos}

    def _decode_raw(self, params, cache, tokens):
        """One decode step for the whole pool: tokens (B,) int32 → next
        logits (B, V) f32 + advanced cache. Each slot writes its token's
        k/v at its own cursor and attends to its own prefix; a slot past
        capacity drops the write (scatter OOB is a no-op) and its output
        is garbage the scheduler must mask — capacity accounting is the
        scheduler's admission-time job, not a per-step branch here."""
        cfg = self.cfg
        pos = cache["pos"]
        b = tokens.shape[0]
        h_, dh = cfg.n_heads, cfg.head_dim
        x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
        x = x * math.sqrt(cfg.d_model)
        pos_rows = jnp.take(params["pos_embed"],
                            jnp.clip(pos, 0, cfg.max_seq - 1), axis=0)
        x = x + pos_rows.astype(cfg.dtype)                     # (B, d)

        def block(x, xs):
            blk, kl, vl = xs
            hh = tfm._rmsnorm(x, blk["ln1"])
            qkv = hh @ blk["wqkv"].astype(hh.dtype)            # (B, 3h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, h_, dh)
            kl = kl.at[jnp.arange(b), pos].set(
                k.reshape(b, h_, dh).astype(kl.dtype))
            vl = vl.at[jnp.arange(b), pos].set(
                v.reshape(b, h_, dh).astype(vl.dtype))
            a = _cached_attention(cfg, q, kl, vl, pos).reshape(b, h_ * dh)
            x = x + a @ blk["wo"].astype(hh.dtype)
            h2 = tfm._rmsnorm(x, blk["ln2"])
            m = jax.nn.gelu(h2 @ blk["w_in"].astype(h2.dtype)) \
                @ blk["w_out"].astype(h2.dtype)
            return x + m, (kl, vl)

        x, (k_new, v_new) = lax.scan(block, x,
                                     (params["blocks"], cache["k"],
                                      cache["v"]))
        logits = tfm.head_logits_rows(params, cfg, x)
        return logits, {"k": k_new, "v": v_new, "pos": pos + 1}

    # ------------------------------------------------------- host API
    def prefill(self, cache, tokens, lengths=None):
        """Prefill the whole pool. ``tokens`` (B, T) with B == cache
        slots; ``lengths`` (B,) defaults to the full T per row."""
        tokens = jnp.asarray(tokens, jnp.int32)
        if tokens.ndim != 2:
            raise ValueError(f"prefill wants (B, T) token ids, got shape "
                             f"{tokens.shape}")
        if tokens.shape[1] > self.max_len:
            raise ValueError(f"prompt length {tokens.shape[1]} exceeds the "
                             f"cache capacity max_len={self.max_len}")
        if tokens.shape[0] != kvcache.cache_slots(cache):
            raise ValueError(
                f"prefill batch {tokens.shape[0]} != cache slots "
                f"{kvcache.cache_slots(cache)} (use prefill_slot for "
                "single-request admission)")
        if lengths is None:
            lengths = jnp.full((tokens.shape[0],), tokens.shape[1],
                               jnp.int32)
        return self._prefill(self.params, cache, tokens,
                             jnp.asarray(lengths, jnp.int32))

    def prefill_slot(self, cache, tokens, slot: int):
        """Admit one 1-D prompt into ``slot``; pads to the next prefill
        bucket so mixed lengths reuse a few compiled kernels. Returns
        (last logits (V,), cache)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.shape[0]
        if n < 1:
            raise ValueError("empty prompt")
        if n > self.max_len:
            raise ValueError(f"prompt length {n} exceeds cache capacity "
                             f"max_len={self.max_len}")
        bucket = next(b for b in self.prefill_buckets if b >= n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        return self._prefill_slot(self.params, cache, jnp.asarray(padded),
                                  jnp.int32(n), jnp.int32(slot))

    def decode_step(self, cache, tokens):
        """One token for every slot: tokens (B,) → (logits (B, V), cache).
        The passed cache is DONATED — keep only the returned one."""
        return self._decode(self.params, cache,
                            jnp.asarray(tokens, jnp.int32).reshape(-1))

    def sample(self, key, logits, temperature=0.0, top_k=0):
        """Next tokens from (B, V) logits; scalar knobs broadcast to the
        pool, vectors give per-slot control."""
        bsz = logits.shape[0]
        temperature = jnp.broadcast_to(
            jnp.asarray(temperature, jnp.float32), (bsz,))
        top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (bsz,))
        return self._sample(key, logits, temperature, top_k)

    def generate(self, prompt_ids, max_new_tokens=32, *, key=None,
                 temperature=0.0, top_k=0, eos_id=None):
        """One-shot batched generation: prefill the prompt(s), then
        sample/decode up to ``max_new_tokens``. Returns generated ids
        (prompt excluded) as numpy — ``(B, n)`` (rows past their eos are
        padded with ``eos_id``) or ``(n,)`` for a 1-D prompt."""
        ids = np.asarray(prompt_ids, np.int32)
        squeeze = ids.ndim == 1
        if squeeze:
            ids = ids[None, :]
        if ids.ndim != 2 or ids.shape[1] < 1:
            raise ValueError(f"prompt_ids must be (T,) or (B, T) with "
                             f"T >= 1, got shape {ids.shape}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        bsz, t = ids.shape
        # the last sampled token is never written back, hence the -1
        if t + max_new_tokens - 1 > self.max_len:
            raise ValueError(
                f"prompt ({t}) + max_new_tokens ({max_new_tokens}) - 1 "
                f"exceeds cache capacity max_len={self.max_len}")
        if key is None:
            key = jax.random.PRNGKey(0)
        cache = self.init_cache(bsz)
        logits, cache = self.prefill(cache, ids)
        out = np.zeros((bsz, max_new_tokens), np.int32)
        done = np.zeros((bsz,), bool)
        pad = 0 if eos_id is None else int(eos_id)
        n = 0
        for i in range(max_new_tokens):
            key, sub = jax.random.split(key)
            toks = np.asarray(self.sample(sub, logits, temperature, top_k))
            out[:, i] = np.where(done, pad, toks)
            n = i + 1
            if eos_id is not None:
                done |= (toks == eos_id)
                if done.all():
                    break
            if i + 1 < max_new_tokens:
                logits, cache = self.decode_step(cache, jnp.asarray(toks))
        out = out[:, :n]
        return out[0] if squeeze else out
