"""Generation engine for the zoo Transformer-LM: jitted KV-cache prefill
and single-token decode, plus greedy/temperature/top-k sampling.

Two device entry points, both compiled once per shape and reused for the
life of the engine:

- ``prefill`` — runs the prompt through the ordinary block stack (the
  SAME ``apply_blocks`` the training forward uses, ``return_kv=True``),
  writes every layer's k/v into the cache, and returns ONLY the last
  valid position's logits (``(B, V)`` — never the ``(B, T, V)`` tensor a
  generation step doesn't need; at T=4096/V=32k that tensor alone is
  0.5 GB f32).
- ``decode_step`` — one token per slot: embed at each slot's own
  position cursor, scan the stacked blocks with the cache riding the
  scan's xs/ys (layer l's k/v slab is consumed and re-emitted in place),
  attend causally against the cache under a per-slot length mask. The
  cache argument is DONATED, so the decode loop never holds two copies
  of the K/V HBM.

Correctness is anchored the ``rnn_time_step`` way (tests/test_serving.py):
prefill+decode logits must match the full forward at every position
within fp tolerance — the cache is an optimization, never a different
model.

Single-chip inference path: MoE (`n_experts`) and ring attention are
training-parallelism features with no single-token analogue here and are
rejected at construction. Prefill inherits the model's own attention
gating (`flash_engages`), so a TPU prefill at flash-sized T runs the
pallas kernel exactly like the training forward.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..zoo import transformer as tfm
from . import kvcache

# prompt lengths are padded up to one of these before the jitted
# per-slot prefill runs, so mixed-length traffic compiles a handful of
# kernels instead of one per distinct prompt length (clipped to the
# engine's max_len; max_len itself is always a bucket)
DEFAULT_PREFILL_BUCKETS = (32, 128, 512, 1024, 2048, 4096, 8192)

_NEG_INF = -1e30  # mask value: finite, softmax-safe in f32


def sample_tokens(key, logits, temperature, top_k):
    """Vectorized next-token sampling: (B, V) f32 logits, per-slot
    ``temperature`` (B,) and ``top_k`` (B,) — a slot with
    ``temperature <= 0`` decodes greedily (argmax, key unused), one with
    ``top_k > 0`` samples only among its k highest logits. Per-slot
    knobs make one jitted sampler serve a mixed-request decode sweep.
    """
    logits = logits.astype(jnp.float32)
    v = logits.shape[-1]
    temperature = jnp.asarray(temperature, jnp.float32).reshape(-1)
    top_k = jnp.asarray(top_k, jnp.int32).reshape(-1)
    # top-k filter: threshold at each row's k-th largest logit
    desc = jnp.sort(logits, axis=-1)[:, ::-1]
    kk = jnp.clip(jnp.where(top_k > 0, top_k, v), 1, v)
    thresh = jnp.take_along_axis(desc, (kk - 1)[:, None], axis=-1)
    filtered = jnp.where(logits >= thresh, logits, _NEG_INF)
    scaled = filtered / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def sample_tokens_masked(key, logits, temperature, top_k, mask):
    """CONSTRAINED-decoding sampler (ISSUE 20): masked logits through
    the SAME ``sample_tokens`` body. ``mask`` is (B, V) bool — a
    disallowed token's logit drops to the mask floor BEFORE the top-k
    threshold and the greedy argmax, so every sampled (or greedy)
    token lies inside the mask. An all-true mask is the identity:
    bit-identical to ``sample_tokens`` on the same operands."""
    masked = jnp.where(mask, logits.astype(jnp.float32), _NEG_INF)
    return sample_tokens(key, masked, temperature, top_k)


def _wload(blk, name, dt):
    """One layer weight in compute dtype. A quantized block stack
    (ISSUE 19, ``serving.quant.quantized_params``) stores int8 values
    plus a per-output-channel scale under ``name + "_scale"``; the
    dequant happens here, on the fly, so storage is int8 and the
    matvec math stays bf16 — identical call sites either way."""
    w = blk[name]
    s = blk.get(name + "_scale")
    if s is None:
        return w.astype(dt)
    return (w.astype(jnp.float32) * s.astype(jnp.float32)).astype(dt)


def _cached_attention(cfg, q, k, v, pos):
    """Single-token attention against the cache: q (B, H, Dh) vs
    k/v (B, S, H, Dh), each slot masked to its own length (positions
    ``<= pos[b]`` — pos is the index the current token was just written
    at). Scores accumulate f32 regardless of cache dtype; out-of-range
    cache rows never contribute."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhd,bshd->bhs",
                        (q.astype(jnp.float32) * scale),
                        k.astype(jnp.float32))
    s = k.shape[1]
    mask = jnp.arange(s)[None, :] <= pos[:, None]          # (B, S)
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", probs, v.astype(jnp.float32))
    return out.astype(cfg.dtype)


class GenerationEngine:
    """Prefill/decode engine bound to one (cfg, params) pair.

    The engine owns the jitted functions; callers own the cache pytree
    (``init_cache``) and thread it through ``prefill`` / ``decode_step``
    — the functional style every other step in this codebase uses, so
    the cache composes with donation and with schedulers that interleave
    prefill and decode on one pool.
    """

    def __init__(self, cfg, params, *, max_len: Optional[int] = None,
                 prefill_buckets=DEFAULT_PREFILL_BUCKETS,
                 prefill_chunk: Optional[int] = None,
                 paged_kernel: Optional[str] = None,
                 quant_kv: Optional[str] = None,
                 quant_weights: Optional[str] = None):
        if getattr(cfg, "n_experts", 0):
            raise NotImplementedError(
                "GenerationEngine is dense-only: MoE expert dispatch has "
                "no single-token decode path yet (train MoE via the GSPMD "
                "path; see ROADMAP)")
        if cfg.use_ring_attention:
            raise NotImplementedError(
                "ring attention is a sequence-parallel TRAINING path; the "
                "decode step attends one token against a local cache — "
                "construct the engine with use_ring_attention=False")
        self.cfg = cfg
        self.params = params
        self.max_len = int(cfg.max_seq if max_len is None else max_len)
        if self.max_len > cfg.max_seq:
            raise ValueError(
                f"max_len {self.max_len} exceeds cfg.max_seq="
                f"{cfg.max_seq}: no position rows past the table")
        self.prefill_buckets = tuple(sorted(
            {min(b, self.max_len) for b in prefill_buckets} | {self.max_len}))
        # chunked prefill (ISSUE 14): one chunk never exceeds this many
        # prompt tokens; chunks pad to the bucket subset at or below it
        # (≤ 1 compile per chunk bucket — the retrace contract)
        self.chunk_len = int(min(
            kvcache.DEFAULT_PREFILL_CHUNK if prefill_chunk is None
            else prefill_chunk, self.max_len))
        if self.chunk_len < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.chunk_buckets = tuple(sorted(
            {min(b, self.chunk_len) for b in self.prefill_buckets}
            | {self.chunk_len}))
        # jit once; cache (argnum 1 after params) donated on every path.
        # Each entry point is wrapped in a CompileSentinel (ISSUE 12):
        # compiles are counted/timed per abstract signature, and after
        # mark_warm() any further compile is a warned retrace — the
        # zero-recompile-after-warmup contract the regression tests pin.
        # The sentinel is transparent (.lower etc. delegate), so floor
        # probes keep working on eng._decode unchanged.
        from ..obs.compiles import CompileSentinel
        self._decode = CompileSentinel(
            "decode_step", jax.jit(self._decode_raw, donate_argnums=(1,)))
        self._prefill = CompileSentinel(
            "prefill", jax.jit(self._prefill_raw, donate_argnums=(1,)))
        self._prefill_slot = CompileSentinel(
            "prefill_slot", jax.jit(self._prefill_slot_raw,
                                    donate_argnums=(1,)))
        self._sample = CompileSentinel("sample_tokens",
                                       jax.jit(sample_tokens))
        # paged entry points (ISSUE 14): same donation discipline — the
        # page pool is updated in place for the life of the cache
        self._decode_paged = CompileSentinel(
            "decode_paged", jax.jit(self._decode_paged_raw,
                                    donate_argnums=(1,)))
        # pallas paged-attention variant (ISSUE 17): same signature and
        # donation, attention fused in-kernel instead of gathered.
        # Which one decode_step dispatches is a per-geometry verdict
        # from the fidelity-gated promotion race (_paged_kernel_choice)
        self._decode_paged_kernel = CompileSentinel(
            "decode_paged_kernel",
            jax.jit(functools.partial(self._decode_paged_raw,
                                      use_kernel=True),
                    donate_argnums=(1,)))
        # paged_kernel pins the dispatch mode (off|on|auto|race); None
        # defers to $DL4J_PAGED_KERNEL, default "auto" (race on TPU,
        # gather elsewhere — see kernels.paged_attention.decide)
        self.paged_kernel_mode = paged_kernel
        self._paged_plan = {}            # geometry key -> kernel|gather
        # quantization plane (ISSUE 19): per-mode dispatch verdicts —
        # quant_kv/quant_weights pin the mode (off|on|auto|race); None
        # defers to $DL4J_QUANT_KV / $DL4J_QUANT_W, default "auto"
        # (race on TPU, bf16 elsewhere — serving.quant.decide_*). The
        # int8 block stack is built lazily on the first decode that
        # wants it, never at construction.
        self.quant_kv_mode = quant_kv
        self.quant_weights_mode = quant_weights
        self._wchoice: Optional[str] = None   # "int8" | "bf16"
        self._qparams = None
        self._prefill_chunk = CompileSentinel(
            "prefill_chunk", jax.jit(self._prefill_chunk_raw,
                                     donate_argnums=(1,)))
        # speculative-decode verify (ISSUE 19): the SAME chunked-prefill
        # body, but the head runs over EVERY row — the draft's k
        # proposals are judged from one dispatch's (C, V) logits
        self._verify_chunk = CompileSentinel(
            "verify_chunk",
            jax.jit(functools.partial(self._prefill_chunk_raw,
                                      all_logits=True),
                    donate_argnums=(1,)))
        self._copy_page = CompileSentinel(
            "copy_page", jax.jit(self._copy_page_raw,
                                 donate_argnums=(0,)))
        # multi-workload request plane (ISSUE 20): the EMBED hidden-row
        # chunk and the CONSTRAINED masked sampler — same bodies as
        # their unmasked/logit siblings, pre-warmed by the scheduler so
        # a new workload never retraces mid-serve
        self._embed_chunk = CompileSentinel(
            "embed_chunk",
            jax.jit(functools.partial(self._prefill_chunk_raw,
                                      return_hidden=True),
                    donate_argnums=(1,)))
        self._sample_masked = CompileSentinel(
            "sample_tokens_masked", jax.jit(sample_tokens_masked))
        self.sentinels = {s.name: s for s in (
            self._decode, self._prefill, self._prefill_slot, self._sample,
            self._decode_paged, self._decode_paged_kernel,
            self._prefill_chunk, self._verify_chunk, self._copy_page,
            self._embed_chunk, self._sample_masked)}

    # ------------------------------------------------------------ cache
    def init_cache(self, n_slots: int):
        return kvcache.init_cache(self.cfg, n_slots, self.max_len)

    def init_paged_cache(self, n_slots: int, n_pages: int,
                         page_len: int = kvcache.DEFAULT_PAGE_LEN,
                         quantized: Optional[bool] = None):
        """Allocate the paged pool. ``quantized=None`` lets the
        fidelity-gated quant_kv promotion decide per geometry (ISSUE
        19, ``serving.quant.decide_kv``) — off everywhere the race
        does not run or win, so callers that never opt in keep the
        bf16 pool byte-for-byte."""
        if quantized is None:
            from . import quant
            quantized = quant.decide_kv(self, n_slots, n_pages,
                                        page_len) == "int8"
        return kvcache.init_paged_cache(self.cfg, n_slots, n_pages,
                                        page_len, self.max_len,
                                        quantized=bool(quantized))

    def refresh(self, params):
        """Swap in new params (e.g. after more training). Compiled fns
        are shape-keyed, so no retrace as long as shapes match. The
        quantized block stack (ISSUE 19) is derived state: drop it so
        the next decode re-quantizes the fresh values."""
        self.params = params
        self._qparams = None
        return self

    def _decode_params(self):
        """Params the decode matvecs run with: the int8 block stack
        when the quant_w promotion picked it (ISSUE 19), else the full
        ones. Resolved lazily ONCE per engine — the race itself needs
        the jitted decode, so this cannot happen at construction."""
        if self._wchoice is None:
            from . import quant
            self._wchoice = quant.decide_weights(self)
        if self._wchoice == "int8":
            if self._qparams is None:
                from . import quant
                self._qparams = quant.quantized_params(self.params)
            return self._qparams
        return self.params

    # -------------------------------------------------- compile plane
    def mark_warm(self):
        """Declare warmup over on every sentinel: the decode sweep and
        the bucketed prefills seen so far are the working set; any
        compile after this is a warned retrace (ISSUE 12)."""
        for s in self.sentinels.values():
            s.mark_warm()
        return self

    def compile_report(self):
        """{entry point: {compiles, signatures, retraces_after_warm}} —
        what the retrace regression tests and ``/debug/memory`` read."""
        return {name: s.report() for name, s in self.sentinels.items()}

    # ----------------------------------------------------- device fns
    def _prefill_trunk(self, params, tokens):
        """Shared prompt pass: embedded tokens through the block stack
        with per-layer k/v capture. Returns (hidden, k, v)."""
        cfg = self.cfg
        x = tfm.embed(params, cfg, tokens)
        x, _, (ks, vs) = tfm.apply_blocks(params["blocks"], cfg, x,
                                          return_kv=True)
        return x, ks, vs

    def _prefill_raw(self, params, cache, tokens, lengths):
        """Whole-pool prefill: tokens (B, T) — B must equal the cache's
        slot count — lengths (B,) valid-prefix lengths (padding rows
        beyond a row's length leave garbage k/v that the pos mask never
        exposes). Returns (last-position logits (B, V) f32, cache)."""
        x, ks, vs = self._prefill_trunk(params, tokens)
        k_cache = lax.dynamic_update_slice(
            cache["k"], ks.astype(cache["k"].dtype), (0, 0, 0, 0, 0))
        v_cache = lax.dynamic_update_slice(
            cache["v"], vs.astype(cache["v"].dtype), (0, 0, 0, 0, 0))
        b, t = tokens.shape
        last = jnp.clip(lengths - 1, 0, t - 1)
        x_last = x[jnp.arange(b), last]
        logits = tfm.head_logits_rows(params, self.cfg, x_last)
        return logits, {"k": k_cache, "v": v_cache,
                        "pos": lengths.astype(jnp.int32)}

    def _prefill_slot_raw(self, params, cache, tokens, length, slot):
        """Admit ONE request into slot ``slot`` of a live pool: tokens
        (1, T_bucket) padded prompt, ``length`` its true length. Only
        this slot's cache rows and cursor change — in-flight neighbours
        are untouched, which is what lets admission interleave with
        decode on the same cache."""
        x, ks, vs = self._prefill_trunk(params, tokens)
        k_cache = lax.dynamic_update_slice(
            cache["k"], ks.astype(cache["k"].dtype), (0, slot, 0, 0, 0))
        v_cache = lax.dynamic_update_slice(
            cache["v"], vs.astype(cache["v"].dtype), (0, slot, 0, 0, 0))
        t = tokens.shape[1]
        x_last = x[0, jnp.clip(length - 1, 0, t - 1)]
        logits = tfm.head_logits_rows(params, self.cfg, x_last[None])[0]
        pos = cache["pos"].at[slot].set(length.astype(jnp.int32))
        return logits, {"k": k_cache, "v": v_cache, "pos": pos}

    def _decode_raw(self, params, cache, tokens):
        """One decode step for the whole pool: tokens (B,) int32 → next
        logits (B, V) f32 + advanced cache. Each slot writes its token's
        k/v at its own cursor and attends to its own prefix; a slot past
        capacity drops the write (scatter OOB is a no-op) and its output
        is garbage the scheduler must mask — capacity accounting is the
        scheduler's admission-time job, not a per-step branch here."""
        cfg = self.cfg
        pos = cache["pos"]
        b = tokens.shape[0]
        x = self._embed_rows(params, tokens, pos)
        x, kv = self._blocks_with_cache(
            params, cache, x,
            write=lambda kl, rows: kl.at[jnp.arange(b), pos].set(
                rows.astype(kl.dtype)),
            attend=lambda q, kl, vl: _cached_attention(cfg, q, kl, vl,
                                                       pos))
        logits = tfm.head_logits_rows(params, cfg, x)
        return logits, dict(kv, pos=pos + 1)

    def _embed_rows(self, params, tokens, pos):
        """Embed one token row per sequence at its own position —
        the shared prologue of every cached entry point."""
        cfg = self.cfg
        x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
        x = x * math.sqrt(cfg.d_model)
        pos_rows = jnp.take(params["pos_embed"],
                            jnp.clip(pos, 0, cfg.max_seq - 1), axis=0)
        return x + pos_rows.astype(cfg.dtype)

    def _blocks_with_cache(self, params, cache, x, *, write, attend):
        """The ONE transformer block body every cached entry point
        (dense decode, paged decode, chunked prefill) runs — they
        differ ONLY in how k/v rows land in the layer cache
        (``write(layer_cache, rows) -> layer_cache``) and how the
        rows' queries see the cache (``attend(q, kl, vl) ->
        (rows, H, Dh)``). Keeping the norm/qkv/residual/MLP math in
        one place is what makes the paged-vs-dense bitwise-equivalence
        contract a structural property, not a maintenance promise.

        A quantized pool (ISSUE 19) threads its per-row scale arrays
        through the same scan: each layer's cache then travels as a
        ``(rows, scales)`` pair through ``write``/``attend``, and the
        closures own the quantize-on-append / dequantize-on-gather.
        Raw compute-dtype rows go INTO ``write`` on every path — the
        storage cast lives in the closure beside the scatter it feeds.
        Returns (block-stack output rows, cache k/v update dict)."""
        cfg = self.cfg
        n = x.shape[0]
        h_, dh = cfg.n_heads, cfg.head_dim
        quant = kvcache.is_quantized(cache)

        def block(x, xs):
            if quant:
                blk, kl, vl, ks, vs = xs
                kc, vc = (kl, ks), (vl, vs)
            else:
                blk, kc, vc = xs
            hh = tfm._rmsnorm(x, blk["ln1"])
            qkv = hh @ _wload(blk, "wqkv", hh.dtype)           # (n, 3h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(n, h_, dh)
            kc = write(kc, k.reshape(n, h_, dh))
            vc = write(vc, v.reshape(n, h_, dh))
            a = attend(q, kc, vc).reshape(n, h_ * dh)
            x = x + a @ _wload(blk, "wo", hh.dtype)
            h2 = tfm._rmsnorm(x, blk["ln2"])
            m = jax.nn.gelu(h2 @ _wload(blk, "w_in", h2.dtype)) \
                @ _wload(blk, "w_out", h2.dtype)
            if quant:
                return x + m, (kc[0], vc[0], kc[1], vc[1])
            return x + m, (kc, vc)

        if quant:
            x, (k_new, v_new, ks_new, vs_new) = lax.scan(
                block, x, (params["blocks"], cache["k"], cache["v"],
                           cache["k_scale"], cache["v_scale"]))
            return x, {"k": k_new, "v": v_new,
                       "k_scale": ks_new, "v_scale": vs_new}
        x, (k_new, v_new) = lax.scan(block, x,
                                     (params["blocks"], cache["k"],
                                      cache["v"]))
        return x, {"k": k_new, "v": v_new}

    def _decode_paged_raw(self, params, cache, tokens, use_kernel=False):
        """One decode step over a block-paged pool (ISSUE 14): same
        contract as ``_decode_raw`` — tokens (B,) → (logits (B, V) f32,
        advanced cache) — but each slot's k/v rows live in the pages its
        table maps. The write scatters the token's k/v into
        (page, offset); attention gathers the slot's fixed-width table
        row (pads to the pool sentinel, so the gather SHAPE never
        changes — page-table growth is data, not a retrace). A slot
        whose write position falls on an unmapped/sentinel entry drops
        the write (scatter OOB is a no-op — same contract as the dense
        path's past-capacity drop); keeping every position mapped is
        the scheduler's page-accounting job.

        ``use_kernel=True`` (the ``decode_paged_kernel`` entry point,
        ISSUE 17) swaps ONLY the attend closure for the fused pallas
        paged-attention kernel — page-table indirection via scalar
        prefetch, no materialized gather; writes, block math and logits
        are byte-identical to the gather path by construction
        (``_blocks_with_cache`` is shared)."""
        cfg = self.cfg
        pos = cache["pos"]
        table = cache["pages"]                       # (B, P) int32
        b = tokens.shape[0]
        h_, dh = cfg.n_heads, cfg.head_dim
        npg, plen = cache["k"].shape[1], cache["k"].shape[2]
        per_slot = table.shape[1]
        # write coordinates: logical page -> pool page via the table;
        # past-capacity or unmapped -> sentinel npg (scatter drops)
        lp = pos // plen                              # (B,)
        ent = table[jnp.arange(b), jnp.clip(lp, 0, per_slot - 1)]
        ent = jnp.where(lp < per_slot, ent, npg)
        off = pos % plen
        x = self._embed_rows(params, tokens, pos)
        quant = kvcache.is_quantized(cache)

        if use_kernel:
            if quant:
                raise NotImplementedError(
                    "the pallas paged-attention kernel reads bf16 pages; "
                    "a quantized pool decodes via the gather path "
                    "(decode_step routes it there automatically)")
            from ..kernels.paged_attention import paged_attention as _pa

            def attend(q, kl, vl):
                return _pa(q, kl, vl, table, pos)
        elif quant:
            from . import quant as quantmod

            def attend(q, kc, vc):
                # dequantize at gather: int8 pages × per-row-per-head
                # scales → f32 rows, same clamp-the-sentinel contract
                kl, ks = kc
                vl, vs = vc
                s = per_slot * plen
                kg = kl[table].reshape(b, s, h_, dh).astype(jnp.float32) \
                    * ks[table].reshape(b, s, h_)[..., None]
                vg = vl[table].reshape(b, s, h_, dh).astype(jnp.float32) \
                    * vs[table].reshape(b, s, h_)[..., None]
                return _cached_attention(cfg, q, kg, vg, pos)
        else:
            def attend(q, kl, vl):
                # gather each slot's pages: sentinel entries clamp to
                # the last pool page — garbage the pos mask never
                # exposes
                kg = kl[table].reshape(b, per_slot * plen, h_, dh)
                vg = vl[table].reshape(b, per_slot * plen, h_, dh)
                return _cached_attention(cfg, q, kg, vg, pos)

        if quant:
            def write(kc, rows):
                # quantize at append (ISSUE 19): the scale scatters to
                # the same (page, offset) the int8 row does
                arr, sc = kc
                qr, s = quantmod.quantize_rows(rows)
                return (arr.at[ent, off].set(qr),
                        sc.at[ent, off].set(s))
        else:
            def write(kl, rows):
                return kl.at[ent, off].set(rows.astype(kl.dtype))

        x, kv = self._blocks_with_cache(params, cache, x,
                                        write=write, attend=attend)
        logits = tfm.head_logits_rows(params, cfg, x)
        return logits, dict(kv, pos=pos + 1, pages=table)

    def _prefill_chunk_raw(self, params, cache, tokens, start, length,
                           slot, all_logits=False, return_hidden=False):
        """One chunked-prefill dispatch (ISSUE 14): tokens (1, C_bucket)
        — the slot's context rows ``[start, start+length)`` padded to a
        chunk bucket — written into the slot's mapped pages, with the
        chunk's queries attending causally against everything the slot
        holds (earlier chunks' pages + this chunk's own rows). Returns
        (last-valid-row logits (V,), cache); the scheduler uses the
        logits only on the FINAL chunk (they are the TTFT sample).
        Rows past ``length`` are padding: their writes drop (sentinel
        page) and their outputs are garbage nothing reads.

        ``all_logits=True`` is the speculative-decode verify variant
        (ISSUE 19, the ``verify_chunk`` entry point): the head runs
        over EVERY row — (C_bucket, V) — so one dispatch judges all k
        draft proposals; rows past ``length`` are garbage the caller
        slices off."""
        cfg = self.cfg
        table = cache["pages"]
        npg, plen = cache["k"].shape[1], cache["k"].shape[2]
        per_slot = table.shape[1]
        h_, dh = cfg.n_heads, cfg.head_dim
        tok = tokens[0]                                  # (C,)
        c = tok.shape[0]
        gpos = start + jnp.arange(c, dtype=jnp.int32)    # global positions
        valid = jnp.arange(c) < length
        row = table[slot]                                # (P,)
        lp = gpos // plen
        ent = row[jnp.clip(lp, 0, per_slot - 1)]
        ent = jnp.where(valid & (lp < per_slot), ent, npg)
        off = gpos % plen
        # positions via _embed_rows' clipped take, NOT a dynamic
        # slice: a padded tail past max_seq must clamp row-wise
        # (garbage rows) without shifting the VALID rows' positions
        # the way a clamped dynamic_slice start would
        x = self._embed_rows(params, tok, gpos)          # (C, d)
        s_len = per_slot * plen
        mask = jnp.arange(s_len)[None, :] <= gpos[:, None]   # (C, S)
        quant = kvcache.is_quantized(cache)

        def _chunk_attention(q, kg, vg):
            # the chunk's C queries attend causally over the ONE
            # slot's gathered pages (earlier chunks + own rows) — the
            # multi-row analogue of the decode paths' single-row
            # _cached_attention
            scale = 1.0 / math.sqrt(dh)
            scores = jnp.einsum("qhd,shd->qhs",
                                (q.astype(jnp.float32) * scale),
                                kg.astype(jnp.float32))
            scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1)
            return jnp.einsum("qhs,shd->qhd", probs,
                              vg.astype(jnp.float32)).astype(cfg.dtype)

        if quant:
            from . import quant as quantmod

            def attend(q, kc, vc):
                kl, ks = kc
                vl, vs = vc
                kg = kl[row].reshape(s_len, h_, dh).astype(jnp.float32) \
                    * ks[row].reshape(s_len, h_)[..., None]
                vg = vl[row].reshape(s_len, h_, dh).astype(jnp.float32) \
                    * vs[row].reshape(s_len, h_)[..., None]
                return _chunk_attention(q, kg, vg)

            def write(kc, rows):
                arr, sc = kc
                qr, s = quantmod.quantize_rows(rows)
                return (arr.at[ent, off].set(qr),
                        sc.at[ent, off].set(s))
        else:
            def attend(q, kl, vl):
                return _chunk_attention(q, kl[row].reshape(s_len, h_, dh),
                                        vl[row].reshape(s_len, h_, dh))

            def write(kl, rows):
                return kl.at[ent, off].set(rows.astype(kl.dtype))

        x, kv = self._blocks_with_cache(params, cache, x,
                                        write=write, attend=attend)
        if return_hidden:
            # EMBED variant (ISSUE 20): the post-ln_f hidden rows the
            # pooling reduces host-side — (C_bucket, d) f32, rows past
            # ``length`` garbage the caller slices off. No head matmul:
            # an embedding request never needs the (C, V) logits.
            logits = tfm.hidden_rows(params, cfg, x)
        elif all_logits:
            logits = tfm.head_logits_rows(params, cfg, x)    # (C, V)
        else:
            x_last = x[jnp.clip(length - 1, 0, c - 1)]
            logits = tfm.head_logits_rows(params, cfg, x_last[None])[0]
        pos = cache["pos"].at[slot].set((start + length).astype(jnp.int32))
        return logits, dict(kv, pos=pos, pages=table)

    @staticmethod
    def _copy_page_raw(cache, src, dst):
        """Copy-on-write page split (ISSUE 16): duplicate pool page
        ``src``'s k/v rows (every layer) into page ``dst``. Scalar
        src/dst are traced operands, so ONE compile covers every split;
        the cache is donated — the copy lands in place in the pool. A
        quantized pool's scale arrays share the page axis, so the same
        two-slice move carries them and CoW splits stay exact (ISSUE
        19: scales ride sharing untouched)."""
        out = dict(cache)
        for name in ("k", "v", "k_scale", "v_scale"):
            a = cache.get(name)
            if a is None:
                continue
            page = jax.lax.dynamic_slice_in_dim(a, src, 1, axis=1)
            out[name] = jax.lax.dynamic_update_slice_in_dim(
                a, page, dst, axis=1)
        return out

    # ------------------------------------------------------- host API
    def copy_page(self, cache, src: int, dst: int):
        """Duplicate pool page ``src`` into ``dst`` (paged cache only) —
        the device half of a CoW split, after ``PageTable.cow`` remapped
        the table entry. The cache is DONATED; keep only the return."""
        if not kvcache.is_paged(cache):
            raise ValueError("copy_page needs a paged cache")
        npg = kvcache.n_pages(cache)
        if not (0 <= int(src) < npg and 0 <= int(dst) < npg):
            raise ValueError(f"page copy {src}->{dst} outside the "
                             f"{npg}-page pool")
        return self._copy_page(cache, jnp.int32(src), jnp.int32(dst))

    def prefill(self, cache, tokens, lengths=None):
        """Prefill the whole pool. ``tokens`` (B, T) with B == cache
        slots; ``lengths`` (B,) defaults to the full T per row."""
        if kvcache.is_paged(cache):
            raise ValueError(
                "prefill is the dense-pool path; a paged cache admits "
                "via prefill_chunk (its rows live in mapped pages, not "
                "per-slot lanes)")
        tokens = jnp.asarray(tokens, jnp.int32)
        if tokens.ndim != 2:
            raise ValueError(f"prefill wants (B, T) token ids, got shape "
                             f"{tokens.shape}")
        if tokens.shape[1] > self.max_len:
            raise ValueError(f"prompt length {tokens.shape[1]} exceeds the "
                             f"cache capacity max_len={self.max_len}")
        if tokens.shape[0] != kvcache.cache_slots(cache):
            raise ValueError(
                f"prefill batch {tokens.shape[0]} != cache slots "
                f"{kvcache.cache_slots(cache)} (use prefill_slot for "
                "single-request admission)")
        if lengths is None:
            lengths = jnp.full((tokens.shape[0],), tokens.shape[1],
                               jnp.int32)
        return self._prefill(self.params, cache, tokens,
                             jnp.asarray(lengths, jnp.int32))

    def prefill_slot(self, cache, tokens, slot: int):
        """Admit one 1-D prompt into ``slot``; pads to the next prefill
        bucket so mixed lengths reuse a few compiled kernels. Returns
        (last logits (V,), cache)."""
        if kvcache.is_paged(cache):
            raise ValueError(
                "prefill_slot is the dense-pool admission path; a paged "
                "cache admits via prefill_chunk (writing by slot index "
                "would land in an arbitrary pool page)")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.shape[0]
        if n < 1:
            raise ValueError("empty prompt")
        if n > self.max_len:
            raise ValueError(f"prompt length {n} exceeds cache capacity "
                             f"max_len={self.max_len}")
        bucket = next(b for b in self.prefill_buckets if b >= n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        return self._prefill_slot(self.params, cache, jnp.asarray(padded),
                                  jnp.int32(n), jnp.int32(slot))

    def _paged_kernel_choice(self, cache) -> str:
        """``"kernel"`` or ``"gather"`` for this cache geometry —
        resolved ONCE per (pool shape, dtype, table shape) via the
        fidelity-gated promotion race (``kernels.paged_attention``) and
        memoized, so the decode hot loop never re-decides. The race's
        probe caches share the live cache's abstract shapes, so losing
        a race never costs the serve loop a retrace."""
        key = (cache["k"].shape, str(jnp.dtype(cache["k"].dtype)),
               cache["pages"].shape)
        got = self._paged_plan.get(key)
        if got is None:
            from ..kernels.paged_attention import decide
            got = decide(self, cache)
            self._paged_plan[key] = got
        return got

    def decode_step(self, cache, tokens):
        """One token for every slot: tokens (B,) → (logits (B, V), cache).
        Dispatches on the cache layout — dense slots, or the block-paged
        pool (ISSUE 14) via either the XLA gather path or the promoted
        pallas kernel (ISSUE 17, ``_paged_kernel_choice``) — behind one
        call site; the passed cache is DONATED either way, keep only
        the returned one. A quantized pool (ISSUE 19) always takes the
        gather path — dequant lives in its attend closure, which the
        pallas kernel has no analogue for — and the weights the matvecs
        load come from ``_decode_params`` (int8 when promoted)."""
        if kvcache.is_paged(cache):
            if kvcache.is_quantized(cache):
                fn = self._decode_paged
            else:
                fn = (self._decode_paged_kernel
                      if self._paged_kernel_choice(cache) == "kernel"
                      else self._decode_paged)
        else:
            fn = self._decode
        return fn(self._decode_params(), cache,
                  jnp.asarray(tokens, jnp.int32).reshape(-1))

    def prefill_chunk(self, cache, tokens, slot: int, start: int = 0):
        """Write one chunk of a slot's context into its mapped pages
        (paged cache only): ``tokens`` are the context rows
        ``[start, start+len)``, at most ``prefill_chunk`` of them, and
        every position up to ``start+len`` must already be mapped by
        the slot's page table (the scheduler's job); ``chunk_len`` caps
        one chunk's tokens. Pads to a chunk
        bucket (≤ 1 compile per bucket). Returns (last logits (V,),
        cache) — the logits matter only on the final chunk."""
        if not kvcache.is_paged(cache):
            raise ValueError("prefill_chunk needs a paged cache "
                             "(init_paged_cache); dense pools admit via "
                             "prefill_slot")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.shape[0]
        if n < 1:
            raise ValueError("empty chunk")
        if n > self.chunk_len:
            raise ValueError(f"chunk of {n} tokens exceeds chunk_len="
                             f"{self.chunk_len}")
        if start + n > self.max_len:
            raise ValueError(f"chunk ends at {start + n}, past cache "
                             f"capacity max_len={self.max_len}")
        bucket = next(b for b in self.chunk_buckets if b >= n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        return self._prefill_chunk(self.params, cache, jnp.asarray(padded),
                                   jnp.int32(start), jnp.int32(n),
                                   jnp.int32(slot))

    def verify_chunk(self, cache, tokens, slot: int, start: int):
        """Speculative-decode verify (ISSUE 19): run ``tokens`` — the
        last accepted token followed by the draft's proposals — through
        the chunked-prefill body at positions ``[start, start+len)``
        and return ALL row logits ``((C_bucket, V) f32, cache)``; row i
        is the next-token distribution after ``tokens[:i+1]``, so one
        dispatch judges every proposal. Rows are WRITTEN into the
        slot's mapped pages as they go — the caller rolls back the
        rejected tail (``PageTable.trim`` + a pos rewind). Runs with
        ``_decode_params`` — the verify logits must be the ones
        ``decode_step`` would have produced, or greedy spec decode
        loses bit-identity with ``generate()``."""
        if not kvcache.is_paged(cache):
            raise ValueError("verify_chunk needs a paged cache: rollback "
                             "is a page-table operation")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.shape[0]
        if n < 1:
            raise ValueError("empty verify chunk")
        if n > self.chunk_len:
            raise ValueError(f"verify chunk of {n} tokens exceeds "
                             f"chunk_len={self.chunk_len}")
        if start + n > self.max_len:
            raise ValueError(f"verify chunk ends at {start + n}, past "
                             f"cache capacity max_len={self.max_len}")
        bucket = next(b for b in self.chunk_buckets if b >= n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        return self._verify_chunk(self._decode_params(), cache,
                                  jnp.asarray(padded), jnp.int32(start),
                                  jnp.int32(n), jnp.int32(slot))

    def embed_chunk(self, cache, tokens, slot: int, start: int = 0):
        """EMBED workload chunk (ISSUE 20): the ``prefill_chunk`` body
        with the head swapped for the post-``ln_f`` hidden rows —
        returns ``((C_bucket, d) f32 hidden rows, cache)``; rows past
        ``len(tokens)`` are padding garbage the caller slices off. KV
        rows are written into the slot's mapped pages exactly like a
        prefill chunk (same bucketing, ≤ 1 compile per bucket)."""
        if not kvcache.is_paged(cache):
            raise ValueError("embed_chunk needs a paged cache "
                             "(init_paged_cache)")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.shape[0]
        if n < 1:
            raise ValueError("empty chunk")
        if n > self.chunk_len:
            raise ValueError(f"chunk of {n} tokens exceeds chunk_len="
                             f"{self.chunk_len}")
        if start + n > self.max_len:
            raise ValueError(f"chunk ends at {start + n}, past cache "
                             f"capacity max_len={self.max_len}")
        bucket = next(b for b in self.chunk_buckets if b >= n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        return self._embed_chunk(self.params, cache, jnp.asarray(padded),
                                 jnp.int32(start), jnp.int32(n),
                                 jnp.int32(slot))

    def sample(self, key, logits, temperature=0.0, top_k=0):
        """Next tokens from (B, V) logits; scalar knobs broadcast to the
        pool, vectors give per-slot control."""
        bsz = logits.shape[0]
        temperature = jnp.broadcast_to(
            jnp.asarray(temperature, jnp.float32), (bsz,))
        top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (bsz,))
        return self._sample(key, logits, temperature, top_k)

    def sample_masked(self, key, logits, temperature=0.0, top_k=0,
                      mask=None):
        """CONSTRAINED-decoding sampler (ISSUE 20): ``mask`` (B, V) or
        (V,) bool — True admits the token. ``mask=None`` falls through
        to the plain sampler (same compiled fn GENERATE uses)."""
        if mask is None:
            return self.sample(key, logits, temperature, top_k)
        bsz = logits.shape[0]
        temperature = jnp.broadcast_to(
            jnp.asarray(temperature, jnp.float32), (bsz,))
        top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (bsz,))
        mask = jnp.broadcast_to(jnp.asarray(mask, bool),
                                (bsz, logits.shape[-1]))
        return self._sample_masked(key, logits, temperature, top_k, mask)

    def generate(self, prompt_ids, max_new_tokens=32, *, key=None,
                 temperature=0.0, top_k=0, eos_id=None):
        """One-shot batched generation: prefill the prompt(s), then
        sample/decode up to ``max_new_tokens``. Returns generated ids
        (prompt excluded) as numpy — ``(B, n)`` (rows past their eos are
        padded with ``eos_id``) or ``(n,)`` for a 1-D prompt."""
        ids = np.asarray(prompt_ids, np.int32)
        squeeze = ids.ndim == 1
        if squeeze:
            ids = ids[None, :]
        if ids.ndim != 2 or ids.shape[1] < 1:
            raise ValueError(f"prompt_ids must be (T,) or (B, T) with "
                             f"T >= 1, got shape {ids.shape}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        bsz, t = ids.shape
        # the last sampled token is never written back, hence the -1
        if t + max_new_tokens - 1 > self.max_len:
            raise ValueError(
                f"prompt ({t}) + max_new_tokens ({max_new_tokens}) - 1 "
                f"exceeds cache capacity max_len={self.max_len}")
        if key is None:
            key = jax.random.PRNGKey(0)
        cache = self.init_cache(bsz)
        logits, cache = self.prefill(cache, ids)
        out = np.zeros((bsz, max_new_tokens), np.int32)
        done = np.zeros((bsz,), bool)
        pad = 0 if eos_id is None else int(eos_id)
        n = 0
        for i in range(max_new_tokens):
            key, sub = jax.random.split(key)
            toks = np.asarray(self.sample(sub, logits, temperature, top_k))
            out[:, i] = np.where(done, pad, toks)
            n = i + 1
            if eos_id is not None:
                done |= (toks == eos_id)
                if done.all():
                    break
            if i + 1 < max_new_tokens:
                logits, cache = self.decode_step(cache, jnp.asarray(toks))
        out = out[:, :n]
        return out[0] if squeeze else out
